"""Network + async helpers.

Capability parity with reference ``utils/network.py:11-40`` (pooled client
session, error responder) and ``utils/async_helpers.py:9-50``
(sync->async bridge), plus the network-info / master-IP heuristics of
reference ``distributed.py:93-207``.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import socket
import threading
from typing import Any, Dict, List, Optional

import aiohttp

from comfyui_distributed_tpu.utils.logging import debug_log, log

import weakref

_sessions: "weakref.WeakKeyDictionary[asyncio.AbstractEventLoop, aiohttp.ClientSession]" = (
    weakref.WeakKeyDictionary())
_session_lock = threading.Lock()


async def get_client_session() -> aiohttp.ClientSession:
    """Shared pooled session (reference ``utils/network.py:14-22``).

    One session per event loop, keyed weakly by the loop object itself: an
    aiohttp session is bound to the loop that created it, and id()-keying
    would alias a dead loop's session onto a new loop allocated at the same
    address."""
    loop = asyncio.get_running_loop()
    with _session_lock:
        sess = _sessions.get(loop)
        if sess is None or sess.closed:
            connector = aiohttp.TCPConnector(limit=100, limit_per_host=30)
            sess = aiohttp.ClientSession(connector=connector)
            _sessions[loop] = sess
        return sess


async def cleanup_client_session() -> None:
    loop = asyncio.get_running_loop()
    with _session_lock:
        sess = _sessions.pop(loop, None)
    if sess is not None and not sess.closed:
        await sess.close()


def handle_api_error(request, error: Exception, status: int = 500):
    """JSON error responder (reference ``utils/network.py:28-33``)."""
    from aiohttp import web
    log(f"API error on {getattr(request, 'path', '?')}: {error}")
    return web.json_response({"status": "error", "message": str(error)},
                             status=status)


def run_async_in_loop(coro, loop: asyncio.AbstractEventLoop,
                      timeout: Optional[float] = None):
    """Run a coroutine on a foreign event loop from sync code and block for the
    result (reference ``run_async_in_server_loop``,
    ``utils/async_helpers.py:9-50``).  Raises if called *on* that loop's
    thread, which would deadlock — the hazard SURVEY.md §5 flags."""
    try:
        running = asyncio.get_running_loop()
    except RuntimeError:
        running = None
    if running is loop:
        raise RuntimeError("run_async_in_loop called from the target loop; "
                           "await the coroutine instead")
    fut = asyncio.run_coroutine_threadsafe(coro, loop)
    try:
        return fut.result(timeout=timeout)
    except concurrent.futures.TimeoutError:
        fut.cancel()
        raise TimeoutError(f"coroutine timed out after {timeout}s")


async def post_form_with_retry(url: str, make_form, timeout: float,
                               max_retries: Optional[int] = None,
                               what: str = "upload") -> None:
    """POST a multipart form with exponential backoff, retrying any error
    including 404 (the queue-not-ready race the reference's tile sender
    retries through, ``distributed_upscale.py:618-665``).  ``make_form``
    is a zero-arg factory — FormData payloads are single-use."""
    from comfyui_distributed_tpu.utils import constants as C
    retries = max_retries if max_retries is not None else C.SEND_MAX_RETRIES
    session = await get_client_session()
    delay = C.SEND_BACKOFF_BASE
    for attempt in range(retries):
        try:
            async with session.post(
                    url, data=make_form(),
                    timeout=aiohttp.ClientTimeout(total=timeout)) as resp:
                if resp.status == 200:
                    return
                body = await resp.text()
                raise RuntimeError(f"{what} {resp.status}: {body[:100]}")
        except Exception as e:  # noqa: BLE001 - retry transport + status
            if attempt == retries - 1:
                raise
            debug_log(f"{what} retry {attempt + 1}: {e}")
            await asyncio.sleep(delay)
            delay = min(delay * 2, C.SEND_BACKOFF_CAP)


# --- host IP discovery (reference distributed.py:93-207) --------------------

def get_network_ips() -> List[str]:
    """Enumerate candidate host IPs (reference ``get_network_ips``,
    ``distributed.py:98-152``): getaddrinfo + UDP-connect trick."""
    ips: List[str] = []
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None,
                                       family=socket.AF_INET):
            ip = info[4][0]
            if ip not in ips:
                ips.append(ip)
    except socket.gaierror:
        pass
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            ip = s.getsockname()[0]
            if ip not in ips:
                ips.append(ip)
        finally:
            s.close()
    except OSError:
        pass
    if "127.0.0.1" not in ips:
        ips.append("127.0.0.1")
    return ips


def _private_rank(ip: str) -> int:
    """Private-range preference (reference ``get_recommended_ip``,
    ``distributed.py:154-207``): 192.168 > 10. > 172.16-31 > other > loopback."""
    if ip.startswith("192.168."):
        return 0
    if ip.startswith("10."):
        return 1
    if ip.startswith("172."):
        try:
            second = int(ip.split(".")[1])
            if 16 <= second <= 31:
                return 2
        except (IndexError, ValueError):
            pass
    if ip.startswith("127."):
        return 9
    return 5


def get_recommended_ip() -> str:
    ips = get_network_ips()
    return sorted(ips, key=_private_rank)[0]


def network_info() -> Dict[str, Any]:
    ips = get_network_ips()
    return {"ips": ips, "recommended_ip": get_recommended_ip(),
            "hostname": socket.gethostname()}


def find_free_port(host: str = "127.0.0.1") -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port
