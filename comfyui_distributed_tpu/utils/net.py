"""Network + async helpers.

Capability parity with reference ``utils/network.py:11-40`` (pooled client
session, error responder) and ``utils/async_helpers.py:9-50``
(sync->async bridge), plus the network-info / master-IP heuristics of
reference ``distributed.py:93-207``.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import socket
import threading
import time
from typing import Any, Dict, List, Optional

import aiohttp

from comfyui_distributed_tpu.utils.logging import debug_log, log

import weakref

_sessions: "weakref.WeakKeyDictionary[asyncio.AbstractEventLoop, aiohttp.ClientSession]" = (
    weakref.WeakKeyDictionary())
_session_lock = threading.Lock()


async def get_client_session() -> aiohttp.ClientSession:
    """Shared pooled session (reference ``utils/network.py:14-22``).

    One session per event loop, keyed weakly by the loop object itself: an
    aiohttp session is bound to the loop that created it, and id()-keying
    would alias a dead loop's session onto a new loop allocated at the same
    address."""
    loop = asyncio.get_running_loop()
    with _session_lock:
        sess = _sessions.get(loop)
        if sess is None or sess.closed:
            connector = aiohttp.TCPConnector(limit=100, limit_per_host=30)
            sess = aiohttp.ClientSession(connector=connector)
            _sessions[loop] = sess
        return sess


async def cleanup_client_session() -> None:
    loop = asyncio.get_running_loop()
    with _session_lock:
        sess = _sessions.pop(loop, None)
    if sess is not None and not sess.closed:
        await sess.close()


def handle_api_error(request, error: Exception, status: int = 500):
    """JSON error responder (reference ``utils/network.py:28-33``)."""
    from aiohttp import web
    log(f"API error on {getattr(request, 'path', '?')}: {error}")
    return web.json_response({"status": "error", "message": str(error)},
                             status=status)


def run_async_in_loop(coro, loop: asyncio.AbstractEventLoop,
                      timeout: Optional[float] = None):
    """Run a coroutine on a foreign event loop from sync code and block for the
    result (reference ``run_async_in_server_loop``,
    ``utils/async_helpers.py:9-50``).  Raises if called *on* that loop's
    thread, which would deadlock — the hazard SURVEY.md §5 flags."""
    try:
        running = asyncio.get_running_loop()
    except RuntimeError:
        running = None
    if running is loop:
        raise RuntimeError("run_async_in_loop called from the target loop; "
                           "await the coroutine instead")
    fut = asyncio.run_coroutine_threadsafe(coro, loop)
    try:
        return fut.result(timeout=timeout)
    except concurrent.futures.TimeoutError:
        fut.cancel()
        raise TimeoutError(f"coroutine timed out after {timeout}s")


def _retry_after_hint(headers) -> Optional[float]:
    """Parse a Retry-After header (delta-seconds form only — the HTTP
    date form isn't worth a date parser on this hot path) into a
    bounded sleep, or None."""
    from comfyui_distributed_tpu.utils import constants as C
    raw = (headers or {}).get("Retry-After")
    if raw is None:
        return None
    try:
        return min(max(float(raw), 0.0), C.RETRY_AFTER_CAP_S)
    except (TypeError, ValueError):
        return None


def backoff_delays(retries: int, rng=None) -> List[float]:
    """The jittered exponential backoff schedule ``post_form_with_retry``
    sleeps between attempts: ``min(base * 2^k, cap) * uniform[1-j, 1]``.

    Jitter exists for the fleet, not the caller: when one master restart
    fails every worker's in-flight send at the same instant, a fixed
    cadence re-synchronizes all their retries into periodic thundering
    herds — exactly the overload signature the chaos harness provokes.
    Pure function (injectable ``rng``) so the de-synchronization is
    testable."""
    import random as _random

    from comfyui_distributed_tpu.utils import constants as C
    rng = rng or _random
    out = []
    delay = C.SEND_BACKOFF_BASE
    for _ in range(max(retries - 1, 0)):
        out.append(delay * rng.uniform(1.0 - C.SEND_JITTER_FRACTION, 1.0))
        delay = min(delay * 2, C.SEND_BACKOFF_CAP)
    return out


async def post_form_with_retry(url: str, make_form, timeout: float,
                               max_retries: Optional[int] = None,
                               what: str = "upload",
                               headers: Optional[Dict[str, str]] = None
                               ) -> None:
    """POST a multipart form with jittered exponential backoff, retrying
    any error including 404 (the queue-not-ready race the reference's
    tile sender retries through, ``distributed_upscale.py:618-665``).
    ``make_form`` is a zero-arg factory — FormData payloads are
    single-use.  ``headers`` rides every attempt (the worker->master
    data-plane hop carries its traceparent here so the master can stitch
    the job's distributed trace together).

    Overload behavior (ISSUE 9): each attempt's wall clock is capped at
    ``SEND_ATTEMPT_TIMEOUT_CAP`` so one black-holed connection can't eat
    the whole retry budget; a ``Retry-After`` header on a 429/503
    response overrides the computed backoff (the server's drain-rate
    hint beats our exponential guess); and the chaos harness may drop or
    delay an attempt here — the client-side half of a flaky network."""
    from comfyui_distributed_tpu.utils import chaos as chaos_mod
    from comfyui_distributed_tpu.utils import constants as C
    retries = max_retries if max_retries is not None else C.SEND_MAX_RETRIES
    delays = backoff_delays(retries)
    attempt_timeout = min(timeout, C.SEND_ATTEMPT_TIMEOUT_CAP)
    for attempt in range(retries):
        retry_after = None
        try:
            cm = chaos_mod.get_chaos()
            if cm.active:
                extra = cm.client_edge(url, what=what)  # may raise (drop)
                if extra > 0:
                    await asyncio.sleep(extra)
            # re-acquire per attempt: a peer's cleanup can close the
            # shared session mid-retry (get_client_session then hands
            # out a fresh one) — holding one reference across the loop
            # would turn a transient close into N guaranteed failures
            session = await get_client_session()
            async with session.post(
                    url, data=make_form(), headers=headers or None,
                    timeout=aiohttp.ClientTimeout(
                        total=attempt_timeout)) as resp:
                if resp.status == 200:
                    return
                if resp.status in (429, 503):
                    retry_after = _retry_after_hint(resp.headers)
                body = await resp.text()
                raise RuntimeError(f"{what} {resp.status}: {body[:100]}")
        except Exception as e:  # noqa: BLE001 - retry transport + status
            if attempt == retries - 1:
                raise
            debug_log(f"{what} retry {attempt + 1}: {e}")
            # honor the server's shed hint when it's LONGER than our
            # backoff: a 429'd sender hammering at its own cadence is
            # the retry storm the hint exists to prevent
            await asyncio.sleep(max(delays[attempt], retry_after or 0.0))


# --- overlapped host-IO pool -------------------------------------------------

class HostIOPool:
    """Bounded encoder/uploader pool: device->host fetches, PNG/tensor
    encodes and disk writes move here so job N's host edge overlaps job
    N+1's device compute (JAX's async dispatch makes the overlap free
    once nothing synchronizes on the executor thread).

    Bounded on purpose: ``max_pending`` in-flight tasks, then ``submit``
    blocks the producer — device compute can outrun a slow disk/NIC
    without buffering unbounded decoded batches in host RAM."""

    def __init__(self, max_workers: Optional[int] = None,
                 max_pending: Optional[int] = None):
        import concurrent.futures
        import os

        from comfyui_distributed_tpu.utils import constants as C
        max_workers = max_workers or int(os.environ.get(
            C.HOSTIO_THREADS_ENV, C.HOSTIO_THREADS_DEFAULT))
        max_pending = max_pending or int(os.environ.get(
            C.HOSTIO_PENDING_ENV, C.HOSTIO_PENDING_DEFAULT))
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, max_workers),
            thread_name_prefix="dtpu-hostio")
        self._slots = threading.BoundedSemaphore(max(1, max_pending))
        self._pending = 0  # guarded-by: self._idle
        self._idle = threading.Condition(threading.Lock())

    @property
    def pending(self) -> int:
        with self._idle:
            return self._pending

    def submit(self, fn, *args, stage: Optional[str] = None):
        """Schedule ``fn(*args)`` on the pool; returns a Future.

        The submitting thread's transfer attribution (workflow node +
        per-run sinks) AND its request-trace span context are captured and
        re-entered in the worker, so the deferred d2h still lands in the
        run's ledger and deferred stage spans still attach to the job's
        trace; ``stage`` times the task into the pipeline stage
        timeline."""
        from comfyui_distributed_tpu.utils import trace as trace_mod
        captured = trace_mod.capture_transfer_context()
        captured_span = trace_mod.capture_span_context()
        self._slots.acquire()
        with self._idle:
            self._pending += 1

        def run():
            try:
                with trace_mod.transfer_context(captured), \
                        trace_mod.use_span(captured_span):
                    if stage:
                        with trace_mod.stage(stage):
                            return fn(*args)
                    return fn(*args)
            finally:
                with self._idle:
                    self._pending -= 1
                    if self._pending == 0:
                        self._idle.notify_all()
                self._slots.release()

        return self._pool.submit(run)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted task finished; False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._idle:
            while self._pending > 0:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._idle.wait(timeout=remaining)
        return True

    def shutdown(self, wait: bool = True) -> None:
        self._pool.shutdown(wait=wait, cancel_futures=not wait)


# --- wire-format negotiation -------------------------------------------------

# master_url -> (negotiated upload content type, tensor codec); one
# probe per master per process (a fallen-back master stays PNG until
# reset_wire_cache()).
_wire_formats: Dict[str, tuple] = {}
_wire_lock = threading.Lock()


def reset_wire_cache() -> None:
    with _wire_lock:
        _wire_formats.clear()


def wire_codec(master_url: str) -> str:
    """The tensor codec negotiated with ``master_url`` (after
    :func:`negotiate_wire_format` ran); zlib — the floor every build
    decodes — when nothing is cached."""
    with _wire_lock:
        return _wire_formats.get(master_url, ("", "zlib"))[1]


async def negotiate_wire_format(master_url: str) -> str:
    """The upload content type to use toward ``master_url``.

    Probes ``GET /distributed/wire_formats`` once with an ``Accept``
    header naming the raw-tensor type; a master that lists it back gets
    raw-tensor uploads in the best codec BOTH sides support (the
    response's ``tensor_codecs`` ∩ ours — a zstd-capable worker must
    never send zstd at a deflate-only master), anything else (404 from
    an older build, network error, ``DTPU_WIRE=png``) falls back to PNG
    — the always-compatible reference wire."""
    import os

    from comfyui_distributed_tpu.utils import constants as C
    from comfyui_distributed_tpu.utils.image import tensor_codecs
    if os.environ.get(C.WIRE_FORMAT_ENV, "").lower() in ("png", "0", "off"):
        return "image/png"
    with _wire_lock:
        cached = _wire_formats.get(master_url)
    if cached is not None:
        return cached[0]
    fmt, codec = "image/png", "zlib"
    try:
        session = await get_client_session()
        async with session.get(
                f"{master_url}/distributed/wire_formats",
                headers={"Accept": C.TENSOR_WIRE_CONTENT_TYPE},
                timeout=aiohttp.ClientTimeout(total=5)) as r:
            if r.status == 200:
                body = await r.json()
                if C.TENSOR_WIRE_CONTENT_TYPE in body.get("formats", []):
                    fmt = C.TENSOR_WIRE_CONTENT_TYPE
                    # peers predating codec negotiation decode zlib only
                    theirs = body.get("tensor_codecs", ["zlib"])
                    codec = next((c for c in tensor_codecs()
                                  if c in theirs), "zlib")
    except Exception as e:  # noqa: BLE001 - negotiation must never fail a job
        debug_log(f"wire negotiation with {master_url} failed ({e}); "
                  f"falling back to PNG")
    with _wire_lock:
        _wire_formats[master_url] = (fmt, codec)
    debug_log(f"wire format for {master_url}: {fmt} ({codec})")
    return fmt


# --- host IP discovery (reference distributed.py:93-207) --------------------

def get_network_ips() -> List[str]:
    """Enumerate candidate host IPs (reference ``get_network_ips``,
    ``distributed.py:98-152``): getaddrinfo + UDP-connect trick."""
    ips: List[str] = []
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None,
                                       family=socket.AF_INET):
            ip = info[4][0]
            if ip not in ips:
                ips.append(ip)
    except socket.gaierror:
        pass
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            ip = s.getsockname()[0]
            if ip not in ips:
                ips.append(ip)
        finally:
            s.close()
    except OSError:
        pass
    if "127.0.0.1" not in ips:
        ips.append("127.0.0.1")
    return ips


def _private_rank(ip: str) -> int:
    """Private-range preference (reference ``get_recommended_ip``,
    ``distributed.py:154-207``): 192.168 > 10. > 172.16-31 > other > loopback."""
    if ip.startswith("192.168."):
        return 0
    if ip.startswith("10."):
        return 1
    if ip.startswith("172."):
        try:
            second = int(ip.split(".")[1])
            if 16 <= second <= 31:
                return 2
        except (IndexError, ValueError):
            pass
    if ip.startswith("127."):
        return 9
    return 5


def get_recommended_ip() -> str:
    ips = get_network_ips()
    return sorted(ips, key=_private_rank)[0]


def network_info() -> Dict[str, Any]:
    ips = get_network_ips()
    return {"ips": ips, "recommended_ip": get_recommended_ip(),
            "hostname": socket.gethostname()}


def find_free_port(host: str = "127.0.0.1") -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port
