"""Structured logging.

Capability parity with reference ``utils/logging.py:21-28`` (``log`` /
``debug_log`` with a config-gated debug tier) but without the reference's
read-the-config-file-on-every-call behaviour — debug state is a process-local
flag refreshed by the config layer on load/save.

``DTPU_LOG_JSON=1`` switches every line to one JSON object stamped with
the active request-trace correlation fields (``trace_id``/``span_id``/
``prompt_id`` from ``utils.trace.current_trace_ids``), so a log
aggregator can join log lines to the flight-recorder trace of the job
that emitted them.  Toggleable at runtime via :func:`set_json_logs`.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

_PREFIX = "[DistributedTPU]"
_LOG_JSON_ENV = "DTPU_LOG_JSON"   # mirrored in utils.constants.LOG_JSON_ENV
                                  # (kept literal here: logging sits below
                                  # constants-importing modules)


class _JsonFormatter(logging.Formatter):
    """One JSON object per line with trace correlation fields."""

    def format(self, record: logging.LogRecord) -> str:
        out = {"ts": round(record.created, 6),
               "level": record.levelname.lower(),
               "msg": record.getMessage()}
        try:
            # lazy import: trace sits above logging in the utils
            # dependency order (same pattern as Timer below)
            from comfyui_distributed_tpu.utils.trace import \
                current_trace_ids
            ids = current_trace_ids()
        except Exception:  # noqa: BLE001 - logging must never raise
            ids = None
        if ids:
            out.update(ids)
        return json.dumps(out, ensure_ascii=False, default=str)


_PLAIN_FORMATTER = logging.Formatter("%(message)s")
_JSON_FORMATTER = _JsonFormatter()

_logger = logging.getLogger("comfyui_distributed_tpu")
if not _logger.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter("%(message)s"))
    _logger.addHandler(_h)
    _logger.setLevel(logging.INFO)
    _logger.propagate = False

_json_enabled = False


def set_json_logs(enabled: bool) -> None:
    """Swap the handler formatter between plain and JSON mode (start
    value from DTPU_LOG_JSON)."""
    global _json_enabled
    _json_enabled = bool(enabled)
    fmt = _JSON_FORMATTER if _json_enabled else _PLAIN_FORMATTER
    for h in _logger.handlers:
        h.setFormatter(fmt)


def json_logs_enabled() -> bool:
    return _json_enabled


if os.environ.get(_LOG_JSON_ENV, "").strip().lower() \
        in ("1", "true", "yes", "on"):
    set_json_logs(True)

_ENV_DEBUG = os.environ.get("DISTRIBUTED_TPU_DEBUG")
_env_forced = (_ENV_DEBUG is not None
               and _ENV_DEBUG.strip().lower() not in ("", "0", "false", "no", "off"))
_debug_enabled = _env_forced


def set_debug(enabled: bool) -> None:
    """Toggle the debug tier (called by the config layer on load/save).

    The DISTRIBUTED_TPU_DEBUG env var is an explicit user request and wins
    over config-driven toggling — config can only *enable* on top of it."""
    global _debug_enabled
    _debug_enabled = bool(enabled) or _env_forced


def debug_enabled() -> bool:
    return _debug_enabled


def log(message: str) -> None:
    """Always-on log line (reference ``log``, ``utils/logging.py:21-23``)."""
    _logger.info("%s %s", _PREFIX, message)


def debug_log(message: str) -> None:
    """Debug-tier log line (reference ``debug_log``, ``utils/logging.py:25-28``)."""
    if _debug_enabled:
        _logger.info("%s [DEBUG] %s", _PREFIX, message)


class Timer:
    """Phase wall-clock timer — the observability the reference lacks (SURVEY §5).

    Usage::

        with Timer("gather") as t: ...
        t.elapsed_s
    """

    def __init__(self, name: str, emit: bool = True):
        self.name = name
        self.emit = emit
        self.elapsed_s = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed_s = time.perf_counter() - self._t0
        if self.emit:
            debug_log(f"phase[{self.name}] {self.elapsed_s * 1e3:.1f} ms")
        # feed the process-wide phase aggregator (lazy import: trace sits
        # above logging in the utils dependency order)
        from comfyui_distributed_tpu.utils.trace import GLOBAL_PHASES
        GLOBAL_PHASES.record(self.name, self.elapsed_s)
        return False
