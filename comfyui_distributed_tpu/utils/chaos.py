"""Chaos fault-injection harness (ISSUE 9).

A control plane that *survives* hostile conditions has to be exercised
under them: dropped and delayed HTTP edges, spurious 5xx, frozen
heartbeats, corrupted uploads, killed workers.  This module is the one
switchboard for injecting those faults — env-driven for benches
(``DTPU_CHAOS`` JSON spec), programmatic for tests
(:func:`set_chaos`) — so the injection sites stay dumb one-liners:

- ``utils/net.post_form_with_retry`` calls :meth:`ChaosMonkey.client_edge`
  before each attempt (drop -> simulated transport error the retry loop
  handles; delay -> added latency);
- ``server/app.py`` installs :func:`middleware` so matching inbound
  routes can be 5xx'd or delayed a fraction of the time (the
  server-side half of a flaky network);
- ``server/app.py``'s upload decoder runs payloads through
  :meth:`ChaosMonkey.corrupt` (a corrupted tile must fail decode, 500,
  and be retried clean — exercising idempotent redelivery);
- ``runtime/cluster.HeartbeatSender`` consults
  :meth:`ChaosMonkey.heartbeat_frozen` (a frozen heartbeat expires the
  worker's lease while its process is alive — the suspect/rehome edge).

Determinism: one ``random.Random`` seeded from the spec (``"seed"`` or
``DTPU_CHAOS_SEED``), so a failing chaos run replays.  Every injection
bumps a ``chaos_*`` event counter surfaced on both metrics surfaces;
with no spec configured the fast path is a single ``is None`` check.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Any, Dict, Optional

from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils import trace as trace_mod
from comfyui_distributed_tpu.utils.logging import debug_log, log


class ChaosDropError(ConnectionError):
    """A chaos-dropped client edge (retryable transport failure)."""


class ChaosMonkey:
    """One parsed injection spec + its deterministic RNG + counters."""

    def __init__(self, spec: Optional[Dict[str, Any]] = None):
        spec = dict(spec or {})
        self.spec = spec
        self.drop_pct = float(spec.get("drop_pct", 0) or 0)
        self.delay_pct = float(spec.get("delay_pct", 0) or 0)
        self.delay_s = float(spec.get("delay_s",
                                      C.CHAOS_DELAY_DEFAULT_S) or 0)
        self.http_5xx_pct = float(spec.get("http_5xx_pct", 0) or 0)
        self.corrupt_pct = float(spec.get("corrupt_pct", 0) or 0)
        # True freezes every sender; a list freezes only those worker ids
        fh = spec.get("freeze_heartbeats", False)
        self.freeze_all = fh is True
        self.freeze_ids = set(str(x) for x in fh) \
            if isinstance(fh, (list, tuple, set)) else set()
        self.routes = tuple(spec.get("routes")
                            or C.CHAOS_DEFAULT_ROUTES)
        seed = spec.get("seed", os.environ.get(C.CHAOS_SEED_ENV))
        self._rng = random.Random(int(seed) if seed is not None
                                  else None)
        self._lock = threading.Lock()

    @property
    def active(self) -> bool:
        return bool(self.drop_pct or self.delay_pct or self.http_5xx_pct
                    or self.corrupt_pct or self.freeze_all
                    or self.freeze_ids)

    def _roll(self, pct: float) -> bool:
        if pct <= 0:
            return False
        with self._lock:
            return self._rng.uniform(0, 100) < pct

    def _bump(self, kind: str, what: str) -> None:
        trace_mod.GLOBAL_COUNTERS.bump(f"chaos_{kind}")
        # when the injection hits inside a traced operation (a tile
        # send's upload span, a collector drain), pin it into the job's
        # trace so `cli trace` shows WHERE the chaos landed
        sp = trace_mod.capture_span_context()
        if sp is not None:
            now = time.time()
            trace_mod.event_span(f"chaos_{kind}", now, now, parent=sp,
                                 attrs={"target": str(what)[:120]})
        debug_log(f"chaos: injected {kind} on {what}")

    # -- client-side HTTP edge (post_form_with_retry) -------------------------

    def client_edge(self, url: str, what: str = "send") -> float:
        """Called before each send attempt.  Raises
        :class:`ChaosDropError` for a dropped edge; returns the extra
        delay (seconds, 0 for none) the caller should sleep — returned
        rather than slept here because the call sites are async."""
        if self._roll(self.drop_pct):
            self._bump("drop", f"{what} {url}")
            raise ChaosDropError(f"chaos: dropped {what} to {url}")
        if self._roll(self.delay_pct):
            self._bump("delay", f"{what} {url}")
            return max(self.delay_s, 0.0)
        return 0.0

    # -- server-side HTTP edge (aiohttp middleware) ---------------------------

    def route_matches(self, path: str) -> bool:
        return any(path.startswith(r) for r in self.routes)

    def server_edge(self, path: str):
        """(status_or_None, delay_s) for an inbound request on a
        matching route: 503 a fraction, delay a fraction, else pass."""
        if not self.route_matches(path):
            return None, 0.0
        if self._roll(self.http_5xx_pct):
            self._bump("5xx", path)
            return 503, 0.0
        if self._roll(self.delay_pct):
            self._bump("delay", path)
            return None, max(self.delay_s, 0.0)
        return None, 0.0

    # -- payload corruption (upload decode edge) ------------------------------

    def corrupt(self, data: bytes, what: str = "upload") -> bytes:
        """Maybe flip bytes in an upload payload.  The decoder then
        fails, the server 500s, and the sender's retry re-delivers the
        clean payload — the corruption is per-delivery, not sticky."""
        if not data or not self._roll(self.corrupt_pct):
            return data
        self._bump("corrupt", what)
        # stomp a window in the middle: headers AND checksums must not
        # be able to hide it
        mid = len(data) // 2
        return data[:mid] + bytes(b ^ 0xFF
                                  for b in data[mid:mid + 16]) \
            + data[mid + 16:]

    # -- worker lifecycle -----------------------------------------------------

    def heartbeat_frozen(self, worker_id: str) -> bool:
        if self.freeze_all or str(worker_id) in self.freeze_ids:
            trace_mod.GLOBAL_COUNTERS.bump("chaos_heartbeat_frozen")
            return True
        return False

    def snapshot(self) -> Dict[str, Any]:
        return {
            "active": self.active,
            "drop_pct": self.drop_pct,
            "delay_pct": self.delay_pct,
            "delay_s": self.delay_s,
            "http_5xx_pct": self.http_5xx_pct,
            "corrupt_pct": self.corrupt_pct,
            "freeze_heartbeats": (True if self.freeze_all
                                  else sorted(self.freeze_ids)),
            "routes": list(self.routes),
            "injected": {
                k.split("chaos_", 1)[1]: v
                for k, v in trace_mod.GLOBAL_COUNTERS.snapshot().items()
                if k.startswith("chaos_")},
        }


_IDLE = ChaosMonkey()          # the zero-spec fast path (never active)
_current: ChaosMonkey = _IDLE
_current_from_env = False
_env_raw_seen = ""
_install_lock = threading.Lock()


def set_chaos(spec: Optional[Dict[str, Any]]) -> ChaosMonkey:
    """Install an injection spec programmatically (tests/bench);
    ``None`` disarms.  Returns the active monkey."""
    global _current, _current_from_env
    with _install_lock:
        _current = ChaosMonkey(spec) if spec else _IDLE
        _current_from_env = False
        if _current.active:
            log(f"chaos: armed {json.dumps(spec, sort_keys=True)}")
        return _current


def get_chaos() -> ChaosMonkey:
    """The active monkey.  The DTPU_CHAOS env is re-parsed only when its
    raw value changes (a :func:`set_chaos` installation survives an
    untouched env), so the per-edge cost with chaos off is one env read
    + one string compare."""
    global _current, _current_from_env, _env_raw_seen
    raw = os.environ.get(C.CHAOS_ENV) or ""
    if raw != _env_raw_seen:
        with _install_lock:
            _env_raw_seen = raw
            if raw:
                try:
                    spec = json.loads(raw)
                    _current = ChaosMonkey(spec
                                           if isinstance(spec, dict)
                                           else {})
                    _current_from_env = True
                    if _current.active:
                        log(f"chaos: armed from {C.CHAOS_ENV}")
                except ValueError:
                    log(f"chaos: bad {C.CHAOS_ENV} JSON; ignoring")
                    _current, _current_from_env = _IDLE, False
            elif _current_from_env:
                # the env spec was cleared; a programmatic spec stays
                _current, _current_from_env = _IDLE, False
    return _current


def middleware():
    """The aiohttp middleware factory ``server/app.py`` installs: 503 or
    delay a fraction of inbound requests on matching routes.  With no
    spec armed the overhead is the env-change check."""
    import asyncio

    from aiohttp import web

    @web.middleware
    async def chaos_middleware(request, handler):
        cm = get_chaos()
        if cm.active:
            status, delay = cm.server_edge(request.path)
            if delay > 0:
                await asyncio.sleep(delay)
            if status is not None:
                return web.json_response(
                    {"error": "chaos: injected failure"}, status=status)
        return await handler(request)

    return chaos_middleware
