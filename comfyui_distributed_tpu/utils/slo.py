"""SLO burn-rate engine (ISSUE 18).

Declarative per-tenant-class objectives (``DTPU_SLO_SPEC``) evaluated
over multi-window rolling rings, fed by the server's finalize path —
the answer to "are we burning the paid error budget *right now*", which
neither the monotonic tenant counters nor the all-time latency
histograms can give.

Spec grammar (one line, env-friendly)::

    DTPU_SLO_SPEC = class:obj[,obj...][;class:obj...]
    obj           = pNN<DUR | completion>RATIO
    DUR           = float seconds, optional 's'/'ms' suffix

e.g. ``paid:p95<2s,completion>0.999;free:p95<10s``.  A latency
objective ``pNN<T`` means "at most (100-NN)% of requests may take
longer than T"; ``completion>R`` means "at least fraction R of requests
must finalize ok".  Malformed parts are logged and skipped — a typo'd
spec must not take the server down.

Burn rate is the classic SRE ratio: observed bad fraction over the
window divided by the budgeted bad fraction.  Burn 1.0 = spending the
budget exactly as fast as allowed; >1.0 = the objective fails if the
window's behavior persists.  Two windows per tenant (fast ~5m, slow
~1h, both env-tunable) keep the signal both prompt and flap-resistant —
the Gorilla lesson applied to SLOs: operational telemetry is only
useful cheap, bounded and recent, so samples live in fixed-size rings
pruned by age, never an unbounded log.

Surfaces: ``GET /distributed/slo``, ``dtpu_slo_burn_rate`` /
``dtpu_slo_budget_remaining`` gauges on ``/distributed/metrics.prom``,
``cli slo``, and (``DTPU_AUTOSCALE_SLO=1``) the autoscaler's scale-up
pressure.
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils.logging import log

_OBJ_RE = re.compile(r"^p(\d{1,2}(?:\.\d+)?)<([0-9.]+)(ms|s)?$")
_COMPLETION_RE = re.compile(r"^completion>(0?\.\d+|1(?:\.0+)?)$")


class Objective:
    """One parsed objective (plain record)."""

    __slots__ = ("kind", "quantile", "threshold_s", "min_ratio",
                 "budget_frac", "raw")

    def __init__(self, kind: str, raw: str,
                 quantile: float = 0.0, threshold_s: float = 0.0,
                 min_ratio: float = 0.0):
        self.kind = kind              # "latency" | "completion"
        self.raw = raw
        self.quantile = quantile      # latency: target quantile in (0,1)
        self.threshold_s = threshold_s
        self.min_ratio = min_ratio    # completion: required ok fraction
        # the budgeted bad fraction the burn rate divides by
        self.budget_frac = (1.0 - quantile) if kind == "latency" \
            else (1.0 - min_ratio)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"kind": self.kind, "raw": self.raw,
                               "budget_frac": round(self.budget_frac, 6)}
        if self.kind == "latency":
            out["quantile"] = self.quantile
            out["threshold_s"] = self.threshold_s
        else:
            out["min_ratio"] = self.min_ratio
        return out


def _parse_objective(part: str) -> Optional[Objective]:
    part = part.strip()
    m = _OBJ_RE.match(part)
    if m is not None:
        q = float(m.group(1)) / 100.0
        if not 0.0 < q < 1.0:
            return None
        thr = float(m.group(2))
        if m.group(3) == "ms":
            thr /= 1000.0
        if thr <= 0.0:
            return None
        return Objective("latency", part, quantile=q, threshold_s=thr)
    m = _COMPLETION_RE.match(part)
    if m is not None:
        ratio = float(m.group(1))
        if not 0.0 < ratio < 1.0:
            return None
        return Objective("completion", part, min_ratio=ratio)
    return None


def parse_slo_spec(raw: Optional[str]) -> Dict[str, List[Objective]]:
    """``DTPU_SLO_SPEC`` -> {tenant_class: [Objective, ...]}; malformed
    pieces are logged once and skipped."""
    out: Dict[str, List[Objective]] = {}
    for clause in (raw or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        cls, sep, body = clause.partition(":")
        cls = cls.strip()
        if not sep or not cls:
            log(f"slo: ignoring malformed spec clause {clause!r}")
            continue
        objs: List[Objective] = []
        for part in body.split(","):
            if not part.strip():
                continue
            obj = _parse_objective(part)
            if obj is None:
                log(f"slo: ignoring malformed objective {part!r} "
                    f"for class {cls!r}")
                continue
            objs.append(obj)
        if objs:
            out.setdefault(cls, []).extend(objs)
    return out


class _WindowRing:
    """Bounded recent-completions ring for ONE (tenant, window): samples
    ``(t_mono, duration_s, ok)`` pruned by age on every read/write.
    Caller (the engine) holds the engine lock."""

    __slots__ = ("window_s", "samples")

    def __init__(self, window_s: float, maxlen: int = C.SLO_RING_MAX):
        self.window_s = float(window_s)
        self.samples: deque = deque(maxlen=maxlen)

    def record(self, now: float, duration_s: float, ok: bool) -> None:
        self.prune(now)
        self.samples.append((now, float(duration_s), bool(ok)))

    def prune(self, now: float) -> None:
        cutoff = now - self.window_s
        dq = self.samples
        while dq and dq[0][0] < cutoff:
            dq.popleft()

    def stats(self, now: float) -> Dict[str, Any]:
        self.prune(now)
        durs = sorted(d for _, d, _ in self.samples)
        n = len(durs)
        ok = sum(1 for _, _, o in self.samples if o)

        def pct(q: float) -> float:
            if not n:
                return 0.0
            return durs[min(int(q * n), n - 1)]

        return {"count": n, "ok": ok,
                "ok_ratio": (ok / n) if n else 1.0,
                "p50_s": round(pct(0.50), 6),
                "p95_s": round(pct(0.95), 6),
                "p99_s": round(pct(0.99), 6),
                "durations": durs}


WINDOW_NAMES = ("fast", "slow")


class SLOEngine:
    """Multi-window burn-rate evaluation over the parsed spec
    (thread-safe: finalizer threads record, scrape surfaces read)."""

    def __init__(self, spec: Dict[str, List[Objective]],
                 fast_s: Optional[float] = None,
                 slow_s: Optional[float] = None):
        self.spec = spec
        try:
            self.fast_s = float(
                os.environ.get(C.SLO_FAST_WINDOW_ENV,
                               C.SLO_FAST_WINDOW_DEFAULT)) \
                if fast_s is None else float(fast_s)
        except ValueError:
            self.fast_s = C.SLO_FAST_WINDOW_DEFAULT
        try:
            self.slow_s = float(
                os.environ.get(C.SLO_SLOW_WINDOW_ENV,
                               C.SLO_SLOW_WINDOW_DEFAULT)) \
                if slow_s is None else float(slow_s)
        except ValueError:
            self.slow_s = C.SLO_SLOW_WINDOW_DEFAULT
        self._lock = threading.Lock()
        # tenant -> {"fast": ring, "slow": ring}
        self._rings: Dict[str, Dict[str, _WindowRing]] = {}  # guarded-by: self._lock

    @classmethod
    def from_env(cls) -> "SLOEngine":
        return cls(parse_slo_spec(os.environ.get(C.SLO_SPEC_ENV)))

    @property
    def enabled(self) -> bool:
        return bool(self.spec)

    # dtpu-lint: holds[self._lock]
    def _tenant_rings(self, tenant: str) -> Dict[str, _WindowRing]:
        rings = self._rings.get(tenant)
        if rings is None:
            rings = self._rings[tenant] = {
                "fast": _WindowRing(self.fast_s),
                "slow": _WindowRing(self.slow_s)}
        return rings

    def record(self, tenant: str, duration_s: float, ok: bool,
               now: Optional[float] = None) -> None:
        """One finalized prompt (any status) into both windows.  A cheap
        no-op when no spec is configured."""
        if not self.enabled:
            return
        now = time.monotonic() if now is None else now
        with self._lock:
            for ring in self._tenant_rings(str(tenant)).values():
                ring.record(now, duration_s, ok)

    def latency_threshold(self, tenant: str) -> Optional[float]:
        """The tightest latency objective threshold for ``tenant`` (the
        slo_breach trace-event bar), or None."""
        thrs = [o.threshold_s for o in self.spec.get(str(tenant), ())
                if o.kind == "latency"]
        return min(thrs) if thrs else None

    @staticmethod
    def _objective_burn(obj: Objective, stats: Dict[str, Any]) -> float:
        n = stats["count"]
        if not n or obj.budget_frac <= 0.0:
            return 0.0
        if obj.kind == "latency":
            bad = sum(1 for d in stats["durations"]
                      if d > obj.threshold_s)
        else:
            bad = n - stats["ok"]
        return (bad / n) / obj.budget_frac

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Full snapshot for ``GET /distributed/slo`` / ``cli slo``."""
        now = time.monotonic() if now is None else now
        tenants: Dict[str, Any] = {}
        with self._lock:
            classes = set(self.spec) | set(self._rings)
            for cls in sorted(classes):
                objs = self.spec.get(cls, [])
                rings = self._tenant_rings(cls)
                windows: Dict[str, Any] = {}
                for wname in WINDOW_NAMES:
                    stats = rings[wname].stats(now)
                    burns = {o.raw: round(self._objective_burn(o, stats),
                                          4)
                             for o in objs}
                    stats.pop("durations")
                    windows[wname] = {
                        **stats,
                        "window_s": rings[wname].window_s,
                        "burn_rates": burns,
                        "burn_rate": max(burns.values()) if burns
                        else 0.0}
                slow_burn = windows["slow"]["burn_rate"]
                tenants[cls] = {
                    "objectives": [o.to_dict() for o in objs],
                    "windows": windows,
                    "budget_remaining": round(
                        max(0.0, 1.0 - slow_burn), 4)}
        return {"enabled": self.enabled,
                "fast_window_s": self.fast_s,
                "slow_window_s": self.slow_s,
                "tenants": tenants}

    def burn_rate(self, tenant: str, window: str = "fast",
                  now: Optional[float] = None) -> float:
        """Max objective burn for one tenant/window (autoscaler hook);
        0.0 when unconfigured or sample-free."""
        objs = self.spec.get(str(tenant))
        if not objs:
            return 0.0
        now = time.monotonic() if now is None else now
        with self._lock:
            stats = self._tenant_rings(str(tenant))[window].stats(now)
        return max(self._objective_burn(o, stats) for o in objs)

    def prom_families(self) -> List[Tuple[str, str, str,
                                          List[Tuple[Dict, float]]]]:
        """The gauge families ``/distributed/metrics.prom`` appends."""
        if not self.enabled:
            return []
        snap = self.evaluate()
        burn_samples: List[Tuple[Dict, float]] = []
        budget_samples: List[Tuple[Dict, float]] = []
        for cls, t in snap["tenants"].items():
            if not t["objectives"]:
                continue
            for wname in WINDOW_NAMES:
                burn_samples.append((
                    {"tenant": cls, "window": wname},
                    round(t["windows"][wname]["burn_rate"], 6)))
            budget_samples.append(({"tenant": cls},
                                   t["budget_remaining"]))
        return [
            ("dtpu_slo_burn_rate", "gauge",
             "Error-budget burn rate per tenant class and window "
             "(>1: objective failing at this window's rate).",
             burn_samples),
            ("dtpu_slo_budget_remaining", "gauge",
             "Remaining slow-window error budget fraction per tenant "
             "class.", budget_samples),
        ]

    def reset(self) -> None:
        with self._lock:
            self._rings.clear()


def autoscale_slo_armed() -> bool:
    return str(os.environ.get(C.AUTOSCALE_SLO_ENV, "0")).strip().lower() \
        in ("1", "true", "yes", "on")
