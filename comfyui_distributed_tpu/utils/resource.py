"""Cluster resource telemetry plane (ISSUE 5).

PR 3 answered "where did this job spend its *time*"; this module answers
"what did it *cost in memory*, and is the fleet healthy".  On real TPUs
HBM exhaustion is the dominant serving failure mode (vLLM, SOSP 2023:
memory management — not kernels — bounds serving capacity), and nothing
in the codebase read ``device.memory_stats()`` until now.

Pieces:

- :func:`device_memory_snapshot` — ``bytes_in_use``/``peak_bytes_in_use``
  summed over the local devices via ``memory_stats()``, with a host-RSS
  fallback on backends that return ``None`` (the CPU backend in this
  container) so every environment reports *something* honest, tagged
  with its ``source``;
- :func:`host_rss_bytes` — psutil when available, ``/proc/self/statm``
  else, ``resource.getrusage`` peak as the last resort;
- :class:`RingTimeseries` — a bounded in-memory (t, value) ring per
  series.  The Gorilla (VLDB 2015) observation we take is the *model*,
  not the codec: operational timeseries are only useful when cheap,
  fixed-cost, and recent — a ring of the last ``DTPU_RES_RING`` samples
  per series, queried from process memory, no external TSDB;
- :class:`ResourceMonitor` — a daemon sampling thread
  (``DTPU_RES_INTERVAL_S``) feeding the rings: device memory, host RSS,
  queue depth (callback-provided), and a device-utilization estimate
  derived from the PR 2/3 stage timeline (the ``compute`` stage's
  wall-clock delta over the sample interval — the software proxy for
  "how busy was the device between these two samples");
- :func:`resource_prom_families` — the gauge families both Prometheus
  surfaces render: the per-process ``/distributed/metrics.prom`` (no
  label) and the federated ``/distributed/cluster/metrics.prom``
  (``worker_id``-labelled, one series per participant).

Everything here is host-side Python outside the jitted programs — the
telemetry bench (``bench.py --phase telemetry``) proves monitor-on vs
monitor-off throughput stays within noise with zero new jit traces.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils.logging import debug_log

# series names every monitor samples (rings + gauges + prom families)
SERIES = ("device_bytes_in_use", "device_peak_bytes", "host_rss_bytes",
          "utilization", "queue_depth", "cache_bytes")


# --- probes ------------------------------------------------------------------

_psutil_proc = None


def host_rss_bytes() -> int:
    """Current resident set size of this process, in bytes."""
    global _psutil_proc
    try:
        import psutil
        if _psutil_proc is None:
            _psutil_proc = psutil.Process()
        return int(_psutil_proc.memory_info().rss)
    except Exception:  # noqa: BLE001 - psutil optional / may race exit
        pass
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * (os.sysconf("SC_PAGE_SIZE")
                        if hasattr(os, "sysconf") else 4096)
    except (OSError, ValueError, IndexError):
        pass
    import resource as _res
    # ru_maxrss is the PEAK (KB on Linux) — better than nothing
    return int(_res.getrusage(_res.RUSAGE_SELF).ru_maxrss) * 1024


def host_rss_peak_bytes() -> int:
    """Peak RSS (``ru_maxrss``) — the host-side high-water mark."""
    import resource as _res
    return int(_res.getrusage(_res.RUSAGE_SELF).ru_maxrss) * 1024


def device_memory_snapshot() -> Dict[str, Any]:
    """Device memory now: ``{"bytes_in_use", "peak_bytes_in_use",
    "bytes_limit", "n_devices", "source"}``.

    Sums ``memory_stats()`` over the local devices.  Backends whose
    devices report ``None`` (CPU here; some PJRT plugins) fall back to
    host RSS (current) / ``ru_maxrss`` (peak) with ``source:
    "host_rss"`` — the numbers stay meaningful (the CPU "device" IS host
    memory) and callers can tell which regime they're reading."""
    in_use = peak = limit = 0
    n = 0
    try:
        import jax
        for d in jax.local_devices():
            try:
                ms = d.memory_stats()
            except Exception:  # noqa: BLE001 - per-device stats optional
                ms = None
            if not ms:
                continue
            in_use += int(ms.get("bytes_in_use", 0))
            peak += int(ms.get("peak_bytes_in_use",
                               ms.get("bytes_in_use", 0)))
            limit += int(ms.get("bytes_limit", 0))
            n += 1
    except Exception as e:  # noqa: BLE001 - jax may be mid-init elsewhere
        debug_log(f"device memory probe failed: {e}")
    if n:
        return {"bytes_in_use": in_use, "peak_bytes_in_use": peak,
                "bytes_limit": limit or None, "n_devices": n,
                "source": "memory_stats"}
    rss = host_rss_bytes()
    return {"bytes_in_use": rss,
            "peak_bytes_in_use": max(host_rss_peak_bytes(), rss),
            "bytes_limit": None, "n_devices": 0, "source": "host_rss"}


def _cache_bytes() -> int:
    """Reuse-plane residency (ISSUE 13): the caches are LRU-bounded by
    DTPU_CACHE_* budgets, and sampling their total into a ring puts the
    residency next to RSS/HBM on every surface the monitor feeds.
    Never constructs the plane just to measure it."""
    try:
        from comfyui_distributed_tpu.runtime import reuse as reuse_mod
        return reuse_mod.cache_bytes_total()
    except Exception:  # noqa: BLE001 - telemetry must never fail a sample
        return 0


def snapshot_now(queue_depth: Optional[int] = None,
                 utilization: Optional[float] = None) -> Dict[str, Any]:
    """One full resource sample (the heartbeat/federation wire shape)."""
    mem = device_memory_snapshot()
    return {
        "t": time.time(),
        "device_bytes_in_use": mem["bytes_in_use"],
        "device_peak_bytes": mem["peak_bytes_in_use"],
        "device_bytes_limit": mem["bytes_limit"],
        "host_rss_bytes": host_rss_bytes(),
        "utilization": utilization,
        "queue_depth": queue_depth,
        "cache_bytes": _cache_bytes(),
        "source": mem["source"],
    }


# --- bounded ring timeseries -------------------------------------------------

class RingTimeseries:
    """Bounded (t, value) ring for one series (thread-safe).

    Fixed memory, newest-wins: the Gorilla in-memory block model without
    the XOR codec (at our sample rates the floats are already cheap; the
    bounded-ring + recent-window query semantics are what matter)."""

    __slots__ = ("name", "maxlen", "_ring", "_lock", "total_samples")

    def __init__(self, name: str, maxlen: int):
        self.name = str(name)
        self.maxlen = max(int(maxlen), 1)
        self._ring: deque = deque(maxlen=self.maxlen)  # guarded-by: self._lock
        self._lock = threading.Lock()
        self.total_samples = 0                         # guarded-by: self._lock

    def append(self, t: float, value: float) -> None:
        with self._lock:
            self._ring.append((float(t), float(value)))
            self.total_samples += 1

    def values(self) -> List[Tuple[float, float]]:
        with self._lock:
            return list(self._ring)

    def last(self) -> Optional[Tuple[float, float]]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            vals = [v for _, v in self._ring]
        if not vals:
            return {"n": 0, "last": None, "min": None, "max": None,
                    "mean": None}
        return {"n": len(vals), "last": vals[-1], "min": min(vals),
                "max": max(vals),
                "mean": round(sum(vals) / len(vals), 4)}


# --- the monitor -------------------------------------------------------------

class ResourceMonitor:
    """Periodic resource sampler feeding bounded ring timeseries.

    ``queue_depth_fn`` (optional) supplies the serving queue depth;
    utilization is derived from :data:`trace.GLOBAL_STAGES`'s ``compute``
    total between consecutive samples.  ``start()``/``stop()`` manage a
    daemon thread; ``sample_once()`` works without one (tests, one-shot
    probes).  Restartable: stop() then start() spawns a fresh thread."""

    def __init__(self, interval: Optional[float] = None,
                 ring: Optional[int] = None,
                 queue_depth_fn: Optional[Callable[[], int]] = None):
        if interval is None:
            try:
                interval = float(os.environ.get(C.RES_INTERVAL_ENV,
                                                C.RES_INTERVAL_DEFAULT))
            except ValueError:
                interval = C.RES_INTERVAL_DEFAULT
        if ring is None:
            try:
                ring = int(os.environ.get(C.RES_RING_ENV,
                                          C.RES_RING_DEFAULT))
            except ValueError:
                ring = C.RES_RING_DEFAULT
        self.interval = max(float(interval), 0.01)
        self.ring_max = max(int(ring), 1)
        self.queue_depth_fn = queue_depth_fn
        self.series: Dict[str, RingTimeseries] = {
            name: RingTimeseries(name, self.ring_max) for name in SERIES}
        # sample_once runs on BOTH the monitor thread and on-demand
        # callers (latest() from the heartbeat thread before the first
        # interval) — the sample state below is lock-guarded
        self._latest: Optional[Dict[str, Any]] = None  # guarded-by: self._lock
        self._util_mark: Optional[Tuple[float, float]] = None  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.n_samples = 0                             # guarded-by: self._lock

    # -- sampling -------------------------------------------------------------

    def _utilization(self, now: float) -> Optional[float]:
        """Compute-stage wall-clock delta over the sample interval —
        the device-busy-fraction estimate the PR 2/3 stage timeline
        makes possible.  None until two samples exist."""
        from comfyui_distributed_tpu.utils.trace import GLOBAL_STAGES
        hist = GLOBAL_STAGES.histograms().get("compute")
        total = 0.0
        if hist is not None:
            _, total, _ = hist.prom_series()
        # swap under the lock: two concurrent sample_once calls (monitor
        # thread + a heartbeat's on-demand latest()) racing the unguarded
        # swap could both anchor on the same mark and double-count the
        # compute delta
        with self._lock:
            mark, self._util_mark = self._util_mark, (now, total)
        if mark is None:
            return None
        dt = now - mark[0]
        if dt <= 0:
            return None
        return max(0.0, min(1.0, (total - mark[1]) / dt))

    def sample_once(self) -> Dict[str, Any]:
        now = time.monotonic()
        qd = None
        if self.queue_depth_fn is not None:
            try:
                qd = int(self.queue_depth_fn())
            except Exception:  # noqa: BLE001 - depth source may be torn down
                qd = None
        snap = snapshot_now(queue_depth=qd,
                            utilization=self._utilization(now))
        t = snap["t"]
        self.series["device_bytes_in_use"].append(
            t, snap["device_bytes_in_use"])
        self.series["device_peak_bytes"].append(t, snap["device_peak_bytes"])
        self.series["host_rss_bytes"].append(t, snap["host_rss_bytes"])
        self.series["cache_bytes"].append(t, snap["cache_bytes"])
        if snap["utilization"] is not None:
            self.series["utilization"].append(t, snap["utilization"])
        if qd is not None:
            self.series["queue_depth"].append(t, qd)
        with self._lock:
            self._latest = snap
            self.n_samples += 1
        return snap

    def latest(self) -> Dict[str, Any]:
        """Most recent sample; samples on demand when none exists yet
        (a heartbeat must never ship an empty snapshot)."""
        with self._lock:
            snap = self._latest
        return snap if snap is not None else self.sample_once()

    # -- thread lifecycle -----------------------------------------------------

    def start(self) -> None:
        t = self._thread
        if t is not None and t.is_alive():
            if not self._stop.is_set():
                return
            # stop() doesn't join: the old thread may still be draining
            # its final wait().  Join it here so a stop();start() pair
            # can't see the dying thread as "alive", skip the spawn, and
            # leave the monitor permanently dead.
            t.join(timeout=self.interval + 2.0)
            if t.is_alive():
                # Still blocked in a probe (backend init can take
                # seconds on a real TPU).  Spawning now would put two
                # samplers on the same rings; leave the stop flag set so
                # the old thread exits after its probe and a later
                # start() completes the restart.
                debug_log("resource monitor restart deferred: "
                          "old sampler still draining")
                return
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dtpu-resmon")
        self._thread.start()

    def stop(self, join: bool = False) -> None:
        self._stop.set()
        t = self._thread
        if join and t is not None and t.is_alive():
            t.join(timeout=2.0)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        stop = self._stop
        # first sample up front: it may initialize the JAX backend
        # (seconds on a real TPU), and paying that here keeps it off
        # whoever calls latest() first — e.g. the heartbeat thread,
        # whose first beat races this thread's first interval
        try:
            self.sample_once()
        except Exception as e:  # noqa: BLE001 - monitor must survive
            debug_log(f"resource sample failed: {e}")
        while not stop.wait(self.interval):
            try:
                self.sample_once()
            except Exception as e:  # noqa: BLE001 - monitor must survive
                debug_log(f"resource sample failed: {e}")

    # -- introspection --------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The JSON metrics block: config, counters, latest sample, and
        per-series ring stats (not the raw points — see series_tail)."""
        with self._lock:
            latest = dict(self._latest) if self._latest else None
            n = self.n_samples
        return {"interval_s": self.interval, "ring_max": self.ring_max,
                "running": self.running, "n_samples": n,
                "latest": latest,
                "series": {name: ring.stats()
                           for name, ring in self.series.items()}}

    def series_tail(self, name: str,
                    n: Optional[int] = None) -> List[Tuple[float, float]]:
        ring = self.series.get(name)
        if ring is None:
            return []
        vals = ring.values()
        return vals[-n:] if n else vals


# --- process-global monitor --------------------------------------------------

_MONITOR: Optional[ResourceMonitor] = None
_monitor_lock = threading.Lock()


def resource_enabled() -> bool:
    return os.environ.get(C.RESOURCE_ENV, "1").lower() \
        not in ("0", "false", "off")


def _weak_callable(fn: Optional[Callable[[], int]]
                   ) -> Optional[Callable[[], int]]:
    """Bound methods are held via WeakMethod so the process-global
    monitor never pins a dead owner (ServerStates come and go; the
    monitor doesn't).  A collected owner raises, which sample_once
    treats as "no depth source".  Plain callables pass through."""
    if fn is None or not hasattr(fn, "__self__"):
        return fn
    import weakref
    ref = weakref.WeakMethod(fn)

    def call() -> int:
        m = ref()
        if m is None:
            raise ReferenceError("queue-depth source was collected")
        return m()
    return call


def install_monitor(queue_depth_fn: Optional[Callable[[], int]] = None
                    ) -> Optional[ResourceMonitor]:
    """Start (or return) the process-global monitor.  ONE sampling
    thread per process regardless of how many ServerStates exist
    (loopback tests/benches run several): memory and RSS are process
    facts; only the queue-depth callback is rebound to the most recent
    caller.  ``DTPU_RESOURCE=0`` disables entirely (returns None)."""
    global _MONITOR
    if not resource_enabled():
        return None
    queue_depth_fn = _weak_callable(queue_depth_fn)
    with _monitor_lock:
        if _MONITOR is None:
            _MONITOR = ResourceMonitor(queue_depth_fn=queue_depth_fn)
            _MONITOR.start()
        elif queue_depth_fn is not None:
            _MONITOR.queue_depth_fn = queue_depth_fn
        if not _MONITOR.running:
            _MONITOR.start()
        return _MONITOR


def get_monitor() -> Optional[ResourceMonitor]:
    return _MONITOR


def _host_only_snapshot() -> Dict[str, Any]:
    """A sample that cannot touch the device (no jax import): host RSS
    stands in for the device fields, the same regime the CPU fallback
    reports.  Used when a caller must not risk blocking behind backend
    initialization."""
    rss = host_rss_bytes()
    return {
        "t": time.time(),
        "device_bytes_in_use": rss,
        "device_peak_bytes": max(host_rss_peak_bytes(), rss),
        "device_bytes_limit": None,
        "host_rss_bytes": rss,
        "utilization": None,
        "queue_depth": None,
        "cache_bytes": _cache_bytes(),
        "source": "host_rss",
    }


def fleet_sample() -> Dict[str, Any]:
    """The snapshot a heartbeat ships / the federation merge uses for
    "self": the monitor's latest when one exists; a device-free host
    snapshot while a running monitor hasn't produced its first sample
    yet (its thread may be seconds deep in backend init — the heartbeat
    thread must never block behind that inline); a fresh sample only
    when no monitor thread exists to race."""
    mon = _MONITOR
    if mon is not None:
        try:
            with mon._lock:
                snap = mon._latest
            if snap is not None:
                return dict(snap)
            if mon.running:
                return _host_only_snapshot()
            return mon.latest()
        except Exception as e:  # noqa: BLE001 - never fail a heartbeat
            debug_log(f"fleet sample via monitor failed: {e}")
    return snapshot_now()


# --- Prometheus gauge families -----------------------------------------------

def resource_prom_families(
        snapshots: Dict[str, Optional[Dict[str, Any]]],
        ages: Optional[Dict[str, Optional[float]]] = None
) -> List[Tuple[str, str, str, List[Tuple[Dict, float]]]]:
    """Gauge families for one or many participants, in the ``extra``
    shape :func:`trace.prometheus_text` renders.  Key ``""`` emits
    unlabelled series (the per-process exposition); any other key
    becomes a ``worker_id`` label (the federated exposition)."""
    gauges = [
        ("dtpu_res_device_bytes_in_use",
         "Device (HBM) bytes in use; host RSS on backends without "
         "memory_stats.", "device_bytes_in_use"),
        ("dtpu_res_device_peak_bytes",
         "Peak device bytes in use (high-water mark).",
         "device_peak_bytes"),
        ("dtpu_res_host_rss_bytes",
         "Host resident set size in bytes.", "host_rss_bytes"),
        ("dtpu_res_utilization_ratio",
         "Device-busy fraction estimated from the compute-stage "
         "timeline.", "utilization"),
        ("dtpu_res_queue_depth",
         "Prompts queued or executing at sample time.", "queue_depth"),
        ("dtpu_res_cache_bytes",
         "Bytes resident in the cross-request reuse caches.",
         "cache_bytes"),
    ]
    fams = []
    for fam, help_text, key in gauges:
        samples = []
        for wid, snap in sorted(snapshots.items()):
            if not snap or snap.get(key) is None:
                continue
            # snapshots arrive over the wire from workers (heartbeats,
            # pull-through) — one version-skewed peer shipping "n/a"
            # must cost its row, not the whole fleet exposition
            try:
                value = float(snap[key])
            except (TypeError, ValueError):
                continue
            labels = {"worker_id": wid} if wid else {}
            samples.append((labels, value))
        if samples:
            fams.append((fam, "gauge", help_text, samples))
    if ages:
        samples = [({"worker_id": wid} if wid else {}, round(float(age), 3))
                   for wid, age in sorted(ages.items()) if age is not None]
        if samples:
            fams.append(
                ("dtpu_res_snapshot_age_seconds", "gauge",
                 "Age of the participant's retained resource snapshot.",
                 samples))
    return fams
