"""Tracing / profiling / request-scoped telemetry subsystem.

The reference has NONE (SURVEY.md §5: "Tracing / profiling: ABSENT" — its
only timing is a preflight elapsed-ms debug line, ``gpupanel.js:1502``).
Here profiling is a first-class subsystem:

- phase wall-clock aggregation (:class:`PhaseStats`) fed by
  ``utils.logging.Timer`` and the executor's per-node timings, surfaced on
  ``GET /distributed/metrics`` — now with fixed-bucket latency histograms
  and p50/p95/p99 per phase (:class:`LatencyHistogram`), also rendered as
  Prometheus text by :func:`prometheus_text` for ``/distributed/metrics.prom``;
- **request-scoped distributed tracing** (Dapper-style: low-overhead,
  always-on, propagated via RPC metadata): a :class:`Span` model
  (``trace_id``/``span_id``/``parent_id``) with a contextvar-carried
  current span (async-task- and thread-correct), snapshot/reattach
  (:func:`capture_span_context`) mirroring the transfer context so spans
  survive the HostIOPool handoff, W3C-``traceparent`` helpers for the
  distributed HTTP edges, and a bounded per-job flight recorder
  (:class:`FlightRecorder`) behind ``GET /distributed/trace/<prompt_id>``;
- XLA/device traces via ``jax.profiler`` (viewable in TensorBoard /
  Perfetto), driven by ``POST /distributed/profile/start`` + ``/stop`` or
  the :func:`trace` context manager;
- host<->device transfer accounting (:class:`TransferStats`): every device
  edge in the ops layer reports bytes through :func:`record_transfer`,
  attributed to the executing workflow node (:func:`node_scope`) — the
  software-measurable proxy for "tensors never leave HBM";
- retrace/compile counters (:class:`RetraceStats`) fed by
  ``jax.monitoring`` events: a steady-state serving process must report
  ZERO new traces on a repeated workflow (``install_jax_monitoring``).

Telemetry never touches traced code paths: spans and histograms are pure
host-side Python around (never inside) the jitted programs, so tracing-on
vs tracing-off must show zero retrace delta (``bench.py --phase
observability`` proves the overhead stays within noise).
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils.logging import log


class LatencyHistogram:
    """Fixed-bucket latency histogram with percentile estimation.

    Prometheus-shaped: per-bucket counts over
    :data:`constants.HISTOGRAM_BUCKETS_S` plus an overflow (+Inf) bucket,
    with sum/count/max — enough for ``_bucket``/``_sum``/``_count`` series
    AND interpolated p50/p95/p99 without storing samples (thread-safe).

    Buckets optionally carry OpenMetrics exemplars: ``record(...,
    trace_id=...)`` remembers the latest (trace_id, value, wall-clock)
    that landed in each bucket, so the ``.prom`` exposition can link a
    slow bucket straight to a flight-recorder / capture-file trace."""

    __slots__ = ("bounds", "counts", "overflow", "count", "sum_s", "max_s",
                 "exemplars", "_lock")

    def __init__(self, bounds: Tuple[float, ...] = C.HISTOGRAM_BUCKETS_S):
        self.bounds = tuple(bounds)
        self.counts = [0] * len(self.bounds)  # guarded-by: self._lock
        self.overflow = 0                     # guarded-by: self._lock
        self.count = 0                        # guarded-by: self._lock
        self.sum_s = 0.0                      # guarded-by: self._lock
        self.max_s = 0.0                      # guarded-by: self._lock
        # bucket index (len(bounds) = overflow) -> (trace_id, value, ts)
        self.exemplars: Dict[int, Tuple[str, float, float]] = {}  # guarded-by: self._lock
        self._lock = threading.Lock()

    def record(self, seconds: float,
               trace_id: Optional[str] = None) -> None:
        s = max(float(seconds), 0.0)
        with self._lock:
            self.count += 1
            self.sum_s += s
            self.max_s = max(self.max_s, s)
            idx = len(self.bounds)
            for i, le in enumerate(self.bounds):
                if s <= le:
                    self.counts[i] += 1
                    idx = i
                    break
            else:
                self.overflow += 1
            if trace_id:
                self.exemplars[idx] = (str(trace_id), s, time.time())

    def cumulative(self) -> List[Tuple[float, int]]:
        """``[(le, cumulative_count), ..., (inf, total)]`` — the
        Prometheus ``_bucket`` series."""
        return self.prom_series()[0]

    def prom_series(self) -> Tuple[List[Tuple[float, int]], float, int]:
        """``(buckets, sum, count)`` read under ONE lock acquisition —
        the Prometheus invariant (+Inf bucket == _count) must hold even
        against a concurrent record() mid-scrape."""
        with self._lock:
            out, cum = [], 0
            for le, n in zip(self.bounds, self.counts):
                cum += n
                out.append((le, cum))
            out.append((float("inf"), cum + self.overflow))
            return out, self.sum_s, self.count

    def exemplars_snapshot(self) -> Dict[int, Tuple[str, float, float]]:
        """Bucket-index -> (trace_id, value, unix_ts) under the lock."""
        with self._lock:
            return dict(self.exemplars)

    # dtpu-lint: holds[self._lock]
    def _percentile(self, q: float) -> float:
        """Caller holds the lock.  Linear interpolation inside the bucket
        holding the target rank; the overflow bucket interpolates toward
        the observed max."""
        if self.count == 0:
            return 0.0
        target = max(min(q, 1.0), 0.0) * self.count
        cum, lo = 0, 0.0
        for le, n in zip(self.bounds, self.counts):
            if n and cum + n >= target:
                frac = (target - cum) / n
                hi = min(le, self.max_s) if self.max_s > 0 else le
                return min(lo + (max(hi, lo) - lo) * frac, self.max_s)
            cum += n
            lo = le
        if self.overflow:
            frac = (target - cum) / self.overflow
            hi = max(self.max_s, lo)
            return lo + (hi - lo) * frac
        return self.max_s

    def percentile(self, q: float) -> float:
        """Estimated q-quantile (q in [0, 1])."""
        with self._lock:
            return self._percentile(q)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            count, sum_s, max_s = self.count, self.sum_s, self.max_s
            return {"count": count, "total_s": sum_s, "max_s": max_s,
                    "mean_s": sum_s / count if count else 0.0,
                    "p50_s": self._percentile(0.50),
                    "p95_s": self._percentile(0.95),
                    "p99_s": self._percentile(0.99)}


class PhaseStats:
    """Aggregated per-phase wall-clock (thread-safe).

    Historically count/total/max only; each phase now carries a
    :class:`LatencyHistogram`, so ``snapshot()`` additionally reports
    mean and p50/p95/p99 and :meth:`histograms` feeds the Prometheus
    ``_bucket`` series.  The legacy keys (``count``/``total_s``/``max_s``)
    are preserved — existing readers (bench, tests) keep working."""

    def __init__(self) -> None:
        self._stats: Dict[str, LatencyHistogram] = {}  # guarded-by: self._lock
        self._lock = threading.Lock()

    def _hist(self, phase: str) -> LatencyHistogram:
        with self._lock:
            h = self._stats.get(phase)
            if h is None:
                h = self._stats[phase] = LatencyHistogram()
            return h

    def record(self, phase: str, seconds: float,
               trace_id: Optional[str] = None) -> None:
        self._hist(phase).record(seconds, trace_id=trace_id)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            items = list(self._stats.items())
        return {k: h.snapshot() for k, h in items}

    def histograms(self) -> Dict[str, LatencyHistogram]:
        with self._lock:
            return dict(self._stats)

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


# process-wide sink the Timer class reports into
GLOBAL_PHASES = PhaseStats()


@contextmanager
def phase(name: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        GLOBAL_PHASES.record(name, time.perf_counter() - t0)


# --- pipeline stage timeline -------------------------------------------------

# Per-job stage wall-clock for the overlapped serving pipeline
# (queue_wait / coalesced_batch / compute / d2h / encode / upload).
# Separate from GLOBAL_PHASES so /distributed/metrics can expose the
# pipeline timeline as its own coherent block: stage totals here overlap
# in wall-clock (that is the point), so summing them against a run's
# wall time yields the device-idle-fraction estimate bench.py reports.
GLOBAL_STAGES = PhaseStats()


# Per-node-type op wall-clock (the executor records every node execution
# here by class_type): the latency histogram behind the
# dtpu_node_seconds Prometheus family and the "nodes" metrics block.
GLOBAL_NODES = PhaseStats()


@contextmanager
def stage(name: str):
    """Time one pipeline stage into :data:`GLOBAL_STAGES`.

    When a request trace is active (``current_span()``), the stage is ALSO
    recorded as a child span of the same name, so the flight recorder's
    per-job tree shows exactly where the wall-clock went — the aggregate
    histogram and the per-job trace are fed by one instrumentation
    point."""
    t0 = time.perf_counter()
    sp = _begin_span(name)
    try:
        yield
    except BaseException:
        if sp is not None:
            sp.set_status("error")
        raise
    finally:
        GLOBAL_STAGES.record(name, time.perf_counter() - t0)
        _end_span(sp)


class CounterStats:
    """Named monotonic counters (thread-safe) — scheduler/wire events."""

    def __init__(self) -> None:
        self._counts: Dict[str, int] = {}  # guarded-by: self._lock
        self._lock = threading.Lock()

    def bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + int(n)

    def get(self, name: str) -> int:
        with self._lock:
            return int(self._counts.get(name, 0))

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


# coalesced_batches / coalesced_prompts / exec_runs / wire_tensor_msgs /
# wire_png_msgs / wire_bytes ... — the scheduler and wire layers bump,
# /distributed/metrics and bench.py --phase pipeline read
GLOBAL_COUNTERS = CounterStats()


class GaugeStats:
    """Named level gauges (thread-safe) — current-state values the
    counters can't express (a monotonic bump has no "now there are N"):
    parked continuous-batching rows, residency occupancy, ...  Setters
    publish, the metrics surfaces read."""

    def __init__(self) -> None:
        self._values: Dict[str, float] = {}  # guarded-by: self._lock
        self._lock = threading.Lock()

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._values[name] = float(value)

    def get(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return float(self._values.get(name, default))

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._values)

    def reset(self) -> None:
        with self._lock:
            self._values.clear()


# cb_parked (latent paging, ISSUE 17) ... — level views next to the
# monotonic counters on the same metrics surfaces
GLOBAL_GAUGES = GaugeStats()


def pipeline_snapshot() -> Dict[str, Any]:
    """The serving-pipeline block of /distributed/metrics."""
    return {"stages": GLOBAL_STAGES.snapshot(),
            "counters": GLOBAL_COUNTERS.snapshot(),
            "gauges": GLOBAL_GAUGES.snapshot()}


# --- device/XLA tracing ------------------------------------------------------

_trace_lock = threading.Lock()
_trace_dir: Optional[str] = None


def start_device_trace(out_dir: Optional[str] = None) -> str:
    """Begin a ``jax.profiler`` trace (TensorBoard/Perfetto format)."""
    global _trace_dir
    import jax
    with _trace_lock:
        if _trace_dir is not None:
            raise RuntimeError(f"trace already running -> {_trace_dir}")
        out_dir = out_dir or os.path.join(
            os.getcwd(), "traces", time.strftime("%Y%m%d-%H%M%S"))
        os.makedirs(out_dir, exist_ok=True)
        jax.profiler.start_trace(out_dir)
        _trace_dir = out_dir
        log(f"device trace started -> {out_dir}")
        return out_dir


def stop_device_trace() -> str:
    global _trace_dir
    import jax
    with _trace_lock:
        if _trace_dir is None:
            raise RuntimeError("no trace running")
        out = _trace_dir
        try:
            jax.profiler.stop_trace()
        finally:
            # a raising stop_trace must still clear the state: leaving
            # _trace_dir set would wedge every later start_device_trace
            # with "trace already running" for the life of the process
            _trace_dir = None
        log(f"device trace stopped -> {out}")
        return out


def trace_status() -> Dict[str, Any]:
    with _trace_lock:
        return {"running": _trace_dir is not None, "dir": _trace_dir}


@contextmanager
def device_trace(out_dir: Optional[str] = None):
    d = start_device_trace(out_dir)
    try:
        yield d
    finally:
        stop_device_trace()


# --- host<->device transfer accounting ---------------------------------------

class TransferStats:
    """Per-label host<->device transfer byte/call counters (thread-safe).

    Labels are workflow node ids when a :func:`node_scope` is active,
    ``"_unattributed"`` otherwise.  Directions: ``d2h`` (device fetch —
    the expensive edge the tensor plane exists to eliminate) and ``h2d``
    (host put)."""

    def __init__(self) -> None:
        self._stats: Dict[str, Dict[str, float]] = {}  # guarded-by: self._lock
        self._lock = threading.Lock()

    def record(self, direction: str, nbytes: int,
               label: Optional[str] = None) -> None:
        key = label or "_unattributed"
        with self._lock:
            s = self._stats.setdefault(
                key, {"d2h_bytes": 0, "d2h_calls": 0,
                      "h2d_bytes": 0, "h2d_calls": 0})
            s[f"{direction}_bytes"] += int(nbytes)
            s[f"{direction}_calls"] += 1

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._stats.items()}

    def total(self, direction: str) -> int:
        with self._lock:
            return sum(int(v[f"{direction}_bytes"])
                       for v in self._stats.values())

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


# process-wide sink (feeds /distributed/metrics); executors push a per-run
# sink on top so ExecutionResult can report per-node transfers for just
# that run
GLOBAL_TRANSFERS = TransferStats()

_transfer_state = threading.local()


def _sinks() -> List[TransferStats]:
    return getattr(_transfer_state, "sinks", None) or []


@contextmanager
def transfer_sink(sink: TransferStats):
    """Additionally record this thread's transfers into ``sink`` (the
    executor's per-run accounting)."""
    stack = getattr(_transfer_state, "sinks", None)
    if stack is None:
        stack = _transfer_state.sinks = []
    stack.append(sink)
    try:
        yield sink
    finally:
        stack.remove(sink)


@contextmanager
def node_scope(node_id: str):
    """Attribute transfers recorded inside the block to a workflow node."""
    prev = getattr(_transfer_state, "node", None)
    _transfer_state.node = str(node_id)
    try:
        yield
    finally:
        _transfer_state.node = prev


def current_node() -> Optional[str]:
    return getattr(_transfer_state, "node", None)


def capture_transfer_context() -> tuple:
    """Snapshot this thread's transfer attribution (node label + per-run
    sinks) so deferred host work keeps reporting into the run that
    spawned it.  The sinks/node state is thread-local; without this, a
    d2h fetch moved onto the encoder pool would vanish from the
    run-local ``ExecutionResult.transfers`` ledger."""
    return (current_node(), list(_sinks()))


@contextmanager
def transfer_context(captured: tuple):
    """Re-enter a :func:`capture_transfer_context` snapshot on another
    thread (the host-IO pool's worker)."""
    node, sinks = captured
    prev_node = getattr(_transfer_state, "node", None)
    stack = getattr(_transfer_state, "sinks", None)
    if stack is None:
        stack = _transfer_state.sinks = []
    added = [s for s in sinks if s not in stack]
    stack.extend(added)
    _transfer_state.node = node
    try:
        yield
    finally:
        _transfer_state.node = prev_node
        for s in added:
            stack.remove(s)


def record_transfer(direction: str, nbytes: int) -> None:
    """Report one host<->device edge (``direction`` in {"d2h", "h2d"}) from
    the ops layer; attribution and per-run fan-out happen here."""
    label = current_node()
    GLOBAL_TRANSFERS.record(direction, nbytes, label)
    for sink in _sinks():
        sink.record(direction, nbytes, label)


# --- retrace / compile counters ----------------------------------------------

class RetraceStats:
    """Monotonic counters over ``jax.monitoring`` events (thread-safe).

    ``traces`` counts jaxpr traces (every cache-missed jit call),
    ``compiles`` counts backend (XLA) compilations — with the persistent
    compilation cache warm, a retrace can hit the disk cache and skip the
    backend compile, so the two differ."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.traces = 0    # guarded-by: self._lock
        self.compiles = 0  # guarded-by: self._lock

    def bump(self, what: str) -> None:
        with self._lock:
            setattr(self, what, getattr(self, what) + 1)

    def mark(self) -> Dict[str, int]:
        with self._lock:
            return {"traces": self.traces, "compiles": self.compiles}

    def since(self, mark: Dict[str, int]) -> Dict[str, int]:
        with self._lock:
            return {"traces": self.traces - mark["traces"],
                    "compiles": self.compiles - mark["compiles"]}


GLOBAL_RETRACES = RetraceStats()

_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_monitoring_installed = False
_monitoring_lock = threading.Lock()


def install_jax_monitoring() -> None:
    """Register the (process-global, idempotent) ``jax.monitoring``
    listener feeding :data:`GLOBAL_RETRACES`.  Cheap to call per run."""
    global _monitoring_installed
    with _monitoring_lock:
        if _monitoring_installed:
            return
        import jax.monitoring as monitoring

        def on_duration(name: str, duration: float, **kw) -> None:
            if name == _TRACE_EVENT:
                GLOBAL_RETRACES.bump("traces")
            elif name == _COMPILE_EVENT:
                GLOBAL_RETRACES.bump("compiles")

        monitoring.register_event_duration_secs_listener(on_duration)
        _monitoring_installed = True


def counters_snapshot() -> Dict[str, Any]:
    """One payload for /distributed/metrics and bench artifacts."""
    return {"transfers": GLOBAL_TRANSFERS.snapshot(),
            "retraces": GLOBAL_RETRACES.mark()}


# --- request-scoped distributed tracing (spans) ------------------------------
#
# Dapper-lite: always-on, low-overhead, propagated through RPC metadata.
# A span is a named timed interval with a trace_id shared by every span of
# one job (across processes) and a parent_id forming the tree.  The
# current span rides a contextvar — correct across asyncio task
# boundaries (each task gets a context copy at creation) and explicit
# across thread handoffs via capture_span_context()/use_span(), the span
# analog of capture_transfer_context.

_tracing_enabled = os.environ.get(C.TRACE_ENV, "1").lower() \
    not in ("0", "false", "off")


def set_tracing(enabled: bool) -> None:
    """Process-wide span-creation switch (env ``DTPU_TRACE`` start value).
    Aggregate metrics (phases/stages/counters) are unaffected — this
    gates only the per-request span machinery."""
    global _tracing_enabled
    _tracing_enabled = bool(enabled)


def tracing_enabled() -> bool:
    return _tracing_enabled


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


class Span:
    """One timed interval of a request trace.

    ``parent`` is the in-process parent Span (None for a local root);
    ``parent_id`` may be set without a parent object when the parent
    lives in another process (the inbound traceparent case)."""

    __slots__ = ("trace_id", "span_id", "parent", "parent_id", "name",
                 "attrs", "start_s", "end_s", "status", "error", "_token")

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 parent: Optional["Span"] = None,
                 parent_id: Optional[str] = None,
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = str(name)
        self.parent = parent
        if parent is not None:
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        else:
            self.trace_id = trace_id or new_trace_id()
            self.parent_id = parent_id
        self.span_id = new_span_id()
        self.attrs: Dict[str, Any] = dict(attrs or {})
        self.start_s = time.time()
        self.end_s: Optional[float] = None
        self.status = "ok"
        self.error: Optional[str] = None
        self._token: Any = None  # contextvar token while current

    def set_status(self, status: str, error: Optional[str] = None) -> None:
        self.status = status
        if error is not None:
            self.error = str(error)[:500]

    def end(self, status: Optional[str] = None) -> None:
        if self.end_s is not None:
            return  # idempotent: double-end keeps the first timing
        if status is not None:
            self.status = status
        self.end_s = time.time()
        GLOBAL_TRACES.on_end(self)

    def to_dict(self, provisional: bool = False) -> Dict[str, Any]:
        end = self.end_s if self.end_s is not None else time.time()
        d = {"trace_id": self.trace_id, "span_id": self.span_id,
             "parent_id": self.parent_id, "name": self.name,
             "start_s": round(self.start_s, 6), "end_s": round(end, 6),
             "duration_s": round(end - self.start_s, 6),
             "status": self.status}
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.error:
            d["error"] = self.error
        if provisional and self.end_s is None:
            d["provisional"] = True
        return d


_SPAN_VAR: "contextvars.ContextVar[Optional[Span]]" = \
    contextvars.ContextVar("dtpu_current_span", default=None)


def current_span() -> Optional[Span]:
    return _SPAN_VAR.get()


def current_trace_ids() -> Optional[Dict[str, str]]:
    """``{"trace_id", "span_id", "prompt_id"?}`` for the active span — the
    correlation fields the JSON log mode stamps on every line."""
    sp = _SPAN_VAR.get()
    if sp is None:
        return None
    out = {"trace_id": sp.trace_id, "span_id": sp.span_id}
    node: Optional[Span] = sp
    while node is not None:
        pid = node.attrs.get("prompt_id")
        if pid:
            out["prompt_id"] = str(pid)
            break
        node = node.parent
    return out


def start_span(name: str, trace_id: Optional[str] = None,
               parent: Optional[Span] = None,
               parent_id: Optional[str] = None,
               attrs: Optional[Dict[str, Any]] = None) -> Optional[Span]:
    """Open a span (a root when no parent is given).  Returns None with
    tracing disabled — every consumer treats the span as optional."""
    if not _tracing_enabled:
        return None
    sp = Span(name, trace_id=trace_id, parent=parent, parent_id=parent_id,
              attrs=attrs)
    GLOBAL_TRACES.on_start(sp)
    return sp


def _begin_span(name: str, **attrs: Any) -> Optional[Span]:
    """Child of the current span, set as current; None when no trace is
    active (stray stages outside a job never create orphan spans)."""
    parent = _SPAN_VAR.get()
    if parent is None or not _tracing_enabled:
        return None
    sp = Span(name, parent=parent, attrs=attrs or None)
    GLOBAL_TRACES.on_start(sp)
    sp._token = _SPAN_VAR.set(sp)
    return sp


def _end_span(sp: Optional[Span]) -> None:
    if sp is None:
        return
    token, sp._token = sp._token, None
    if token is not None:
        try:
            _SPAN_VAR.reset(token)
        except ValueError:
            # reset from a different context (thread/task migrated the
            # span) — clearing by value keeps the var consistent
            if _SPAN_VAR.get() is sp:
                _SPAN_VAR.set(sp.parent)
    sp.end()


@contextmanager
def span(name: str, **attrs: Any):
    """Child span of the current span, current within the block; yields
    None (and records nothing) when no trace is active."""
    sp = _begin_span(name, **attrs)
    try:
        yield sp
    except BaseException as e:
        if sp is not None:
            sp.set_status("error", repr(e))
        raise
    finally:
        _end_span(sp)


@contextmanager
def use_span(sp: Optional[Span]):
    """Make ``sp`` the current span for the block WITHOUT ending it on
    exit (the span's owner ends it) — the reattach half of the
    cross-thread handoff, and how the exec loop parents a run under the
    job span created at enqueue time."""
    if sp is None:
        yield None
        return
    token = _SPAN_VAR.set(sp)
    try:
        yield sp
    finally:
        _SPAN_VAR.reset(token)


def capture_span_context() -> Optional[Span]:
    """Snapshot this thread's/task's span context for reattachment on
    another thread (``with use_span(captured): ...``) — mirrors
    :func:`capture_transfer_context` for the HostIOPool handoff."""
    return _SPAN_VAR.get()


def event_span(name: str, start_s: float, end_s: float,
               parent: Optional[Span] = None,
               trace_id: Optional[str] = None,
               parent_id: Optional[str] = None,
               attrs: Optional[Dict[str, Any]] = None,
               status: str = "ok") -> Optional[Dict[str, Any]]:
    """Record an already-finished interval as a span (queue_wait measured
    at pop time, an inbound upload measured by the handler).  Accepts a
    parent Span or raw (trace_id, parent_id) for remote parents."""
    if not _tracing_enabled:
        return None
    if parent is not None:
        trace_id, parent_id = parent.trace_id, parent.span_id
    if not trace_id:
        return None
    d = {"trace_id": trace_id, "span_id": new_span_id(),
         "parent_id": parent_id, "name": str(name),
         "start_s": round(start_s, 6), "end_s": round(end_s, 6),
         "duration_s": round(max(end_s - start_s, 0.0), 6),
         "status": status}
    if attrs:
        d["attrs"] = dict(attrs)
    GLOBAL_TRACES.add(trace_id, d)
    return d


# --- W3C traceparent (the propagation header) --------------------------------

def format_traceparent(sp: Span) -> str:
    """``00-<trace_id>-<span_id>-01`` (W3C trace-context, sampled)."""
    return f"00-{sp.trace_id}-{sp.span_id}-01"


def traceparent_headers(sp: Optional[Span] = None) -> Dict[str, str]:
    """Headers dict carrying the current (or given) span's traceparent;
    empty when no trace is active — callers merge unconditionally."""
    sp = sp if sp is not None else _SPAN_VAR.get()
    if sp is None or not _tracing_enabled:
        return {}
    return {C.TRACEPARENT_HEADER: format_traceparent(sp)}


def parse_traceparent(header: Optional[str]
                      ) -> Optional[Tuple[str, str]]:
    """``(trace_id, parent_span_id)`` from a traceparent header, or None
    on anything malformed (propagation must never fail a request)."""
    if not header:
        return None
    parts = str(header).strip().split("-")
    if len(parts) < 4:
        return None
    _, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


# --- flight recorder ---------------------------------------------------------

class FlightRecorder:
    """Bounded ring of recent completed job traces + the accumulation
    buffer for in-flight ones.

    Spans land here as they finish (``on_end``) or arrive from a peer
    (``ingest`` — the worker ships its spans on the final data-plane
    POST); ``commit(prompt_id, trace_id)`` moves a trace into the ring
    when its job finalizes.  Late arrivals for a committed trace are
    appended to the ring entry, so a straggler tile's spans still reach
    the postmortem.  Everything is bounded: spans per trace
    (``TRACE_MAX_SPANS``), in-flight traces, and the ring itself
    (``DTPU_TRACE_RING``)."""

    def __init__(self, max_traces: Optional[int] = None,
                 max_spans: int = C.TRACE_MAX_SPANS):
        self._lock = threading.Lock()
        self.max_traces = max_traces if max_traces is not None else \
            max(1, int(os.environ.get(C.TRACE_RING_ENV,
                                      C.TRACE_RING_DEFAULT)))
        self.max_spans = max_spans
        # trace_id -> {span_id: span dict} for in-flight traces
        self._active: "OrderedDict[str, Dict[str, Dict]]" = \
            OrderedDict()                       # guarded-by: self._lock
        # trace_id -> [open Span] (exported provisionally mid-flight)
        self._open: Dict[str, List[Span]] = {}  # guarded-by: self._lock
        # prompt_id -> committed record (the ring)
        self._jobs: "OrderedDict[str, Dict[str, Any]]" = \
            OrderedDict()                       # guarded-by: self._lock
        # committed trace -> prompt
        self._by_trace: Dict[str, str] = {}     # guarded-by: self._lock
        self.dropped_spans = 0                  # guarded-by: self._lock
        self.evictions = 0                      # guarded-by: self._lock

    # -- span sinks ---------------------------------------------------------

    def on_start(self, sp: Span) -> None:
        with self._lock:
            self._open.setdefault(sp.trace_id, []).append(sp)

    def on_end(self, sp: Span) -> None:
        with self._lock:
            opens = self._open.get(sp.trace_id)
            if opens is not None:
                try:
                    opens.remove(sp)
                except ValueError:
                    pass
                if not opens:
                    del self._open[sp.trace_id]
        self.add(sp.trace_id, sp.to_dict())

    def add(self, trace_id: str, span_dict: Dict[str, Any]) -> None:
        """Insert/replace one span dict (keyed by span_id: a provisional
        remote span is superseded by its final version)."""
        with self._lock:
            pid = self._by_trace.get(trace_id)
            if pid is not None:
                rec = self._jobs.get(pid)
                if rec is not None and (
                        span_dict["span_id"] in rec["_ids"]
                        or len(rec["spans"]) < self.max_spans):
                    if span_dict["span_id"] in rec["_ids"]:
                        rec["spans"] = [span_dict
                                        if s["span_id"] ==
                                        span_dict["span_id"] else s
                                        for s in rec["spans"]]
                    else:
                        rec["spans"].append(span_dict)
                        rec["_ids"].add(span_dict["span_id"])
                else:
                    self.dropped_spans += 1
                return
            spans = self._active.get(trace_id)
            if spans is None:
                # bound the in-flight buffer too: a flood of orphan
                # traces (e.g. remote spans for jobs this process never
                # commits) must not grow without limit
                while len(self._active) >= 4 * self.max_traces:
                    self._active.popitem(last=False)
                spans = self._active[trace_id] = {}
            if span_dict["span_id"] in spans \
                    or len(spans) < self.max_spans:
                spans[span_dict["span_id"]] = span_dict
            else:
                self.dropped_spans += 1

    def ingest(self, span_dicts: List[Dict[str, Any]]) -> int:
        """Merge spans shipped from a peer process (dicts with their own
        trace_id); malformed entries are skipped, count kept is
        returned."""
        kept = 0
        for d in span_dicts or []:
            if not isinstance(d, dict):
                continue
            tid, sid = d.get("trace_id"), d.get("span_id")
            if not tid or not sid:
                continue
            self.add(str(tid), d)
            kept += 1
        return kept

    def export(self, trace_id: str,
               include_open: bool = True) -> List[Dict[str, Any]]:
        """The trace's spans as dicts — finished ones plus (optionally)
        still-open ones with a provisional end, for shipping to the
        master before the local job span closes."""
        with self._lock:
            pid = self._by_trace.get(trace_id)
            if pid is not None and pid in self._jobs:
                out = list(self._jobs[pid]["spans"])
            else:
                out = list(self._active.get(trace_id, {}).values())
            opens = list(self._open.get(trace_id, ())) if include_open \
                else []
        out.extend(sp.to_dict(provisional=True) for sp in opens)
        return out

    # -- job lifecycle ------------------------------------------------------

    def commit(self, prompt_id: str, trace_id: str, status: str = "ok",
               root_span_id: Optional[str] = None,
               duration_s: Optional[float] = None) -> None:
        """Seal a job's trace into the ring under its prompt id.

        A trace_id may legitimately commit under more than one prompt id
        in ONE process (single-process loopback: the worker-role job and
        the master's fan-out job share the trace and the recorder) — the
        later commit absorbs the earlier record's spans so whichever
        prompt id the client holds resolves to the full tree."""
        evicted_total = 0
        with self._lock:
            by_id = dict(self._active.pop(trace_id, {}))
            prev_pid = self._by_trace.get(trace_id)
            if prev_pid is not None and prev_pid != str(prompt_id):
                prev = self._jobs.get(prev_pid)
                if prev is not None:
                    for s in prev["spans"]:
                        by_id.setdefault(s["span_id"], s)
            spans = list(by_id.values())
            rec = {"prompt_id": str(prompt_id), "trace_id": trace_id,
                   "status": status, "root_span_id": root_span_id,
                   "duration_s": duration_s, "finished_at": time.time(),
                   "spans": spans,
                   "_ids": set(by_id)}
            self._jobs[str(prompt_id)] = rec
            self._jobs.move_to_end(str(prompt_id))
            self._by_trace[trace_id] = str(prompt_id)
            # snapshot for the exporter inside the lock: a late-arrival
            # add() may mutate rec["spans"] the moment we release
            export_rec = {k: v for k, v in rec.items() if k != "_ids"}
            export_rec["spans"] = list(spans)
            while len(self._jobs) > self.max_traces:
                _, old = self._jobs.popitem(last=False)
                # only unmap the trace if the mapping still points at the
                # evicted record: after a dual-commit (loopback), the
                # newer prompt's record owns the mapping and must keep
                # receiving late arrivals
                if self._by_trace.get(old["trace_id"]) \
                        == old["prompt_id"]:
                    self._by_trace.pop(old["trace_id"], None)
                self.evictions += 1
                evicted_total = self.evictions
        if evicted_total:
            GLOBAL_COUNTERS.bump("trace_evictions")
            # no-silent-caps: the ring forgetting history is normal but
            # must be visible — one line per N, not one per trace
            if evicted_total % C.TRACE_EVICT_LOG_EVERY == 0:
                log(f"flight recorder: {evicted_total} committed traces "
                    f"evicted from the {self.max_traces}-entry ring "
                    f"(raise {C.TRACE_RING_ENV} or set "
                    f"{C.TRACE_EXPORT_DIR_ENV} for durable capture)")
        # durable capture plane (ISSUE 18): committed traces stream to
        # the capture files; a no-op unless DTPU_TRACE_EXPORT_DIR is set.
        # This runs on the finalizer/executor threads (never the event
        # loop) and outside the recorder lock — the exporter has its own.
        from comfyui_distributed_tpu.utils import trace_export
        trace_export.on_commit(export_rec)
        # critical-path analytics plane (ISSUE 20): armed only while a
        # baseline profile is configured; disarmed it costs one env read
        from comfyui_distributed_tpu.utils import trace_analysis
        trace_analysis.on_commit(export_rec)

    def get(self, prompt_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._jobs.get(str(prompt_id))
            if rec is None:
                return None
            out = {k: v for k, v in rec.items() if k != "_ids"}
            out["spans"] = sorted(rec["spans"],
                                  key=lambda s: s.get("start_s", 0.0))
            out["n_spans"] = len(out["spans"])
            return out

    def index(self) -> List[Dict[str, Any]]:
        """Newest-first job summaries for ``GET /distributed/traces``."""
        with self._lock:
            return [{"prompt_id": rec["prompt_id"],
                     "trace_id": rec["trace_id"],
                     "status": rec["status"],
                     "duration_s": rec["duration_s"],
                     "finished_at": rec["finished_at"],
                     "n_spans": len(rec["spans"])}
                    for rec in reversed(self._jobs.values())]

    def records(self) -> List[Dict[str, Any]]:
        """All committed job records, oldest first, shaped like
        :meth:`get` (sorted span-dict lists) — the cross-trace
        analytics plane's bulk read (ISSUE 20)."""
        with self._lock:
            out = []
            for rec in self._jobs.values():
                r = {k: v for k, v in rec.items() if k != "_ids"}
                r["spans"] = sorted(rec["spans"],
                                    key=lambda s: s.get("start_s", 0.0))
                out.append(r)
            return out

    def breakdown(self, trace_id: str) -> Dict[str, float]:
        """Per-span-name total seconds for one trace — the slow-job log's
        one-line stage summary."""
        out: Dict[str, float] = {}
        for s in self.export(trace_id, include_open=False):
            out[s["name"]] = round(
                out.get(s["name"], 0.0) + float(s.get("duration_s", 0.0)),
                6)
        return out

    def size(self) -> int:
        with self._lock:
            return len(self._jobs)

    def eviction_count(self) -> int:
        with self._lock:
            return self.evictions

    def reset(self) -> None:
        with self._lock:
            self._active.clear()
            self._open.clear()
            self._jobs.clear()
            self._by_trace.clear()
            self.dropped_spans = 0
            self.evictions = 0


GLOBAL_TRACES = FlightRecorder()


def build_span_tree(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Nest span dicts by parent_id: returns the root list, each node a
    copy with a ``children`` list (start-time ordered).  Spans whose
    parent is unknown (a remote hop that never shipped) surface as
    additional roots rather than vanishing."""
    nodes = {s["span_id"]: {**s, "children": []}
             for s in sorted(spans, key=lambda s: s.get("start_s", 0.0))}
    roots: List[Dict[str, Any]] = []
    for node in nodes.values():
        parent = nodes.get(node.get("parent_id") or "")
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    return roots


# --- Prometheus text exposition ----------------------------------------------

def _prom_escape(value: Any) -> str:
    return (str(value).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _prom_labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_prom_escape(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _prom_num(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _render_histogram_family(lines: List[str], family: str, help_text: str,
                             stats: PhaseStats, label_key: str) -> None:
    hists = stats.histograms()
    lines.append(f"# HELP {family} {help_text}")
    lines.append(f"# TYPE {family} histogram")
    for name in sorted(hists):
        base = {label_key: name}
        h = hists[name]
        buckets, sum_s, count = h.prom_series()
        exemplars = h.exemplars_snapshot()
        for i, (le, cum) in enumerate(buckets):
            le_s = "+Inf" if le == float("inf") else _prom_num(le)
            line = (f"{family}_bucket"
                    f"{_prom_labels({**base, 'le': le_s})} {cum}")
            ex = exemplars.get(i)
            if ex is not None:
                # OpenMetrics exemplar: the last sample that landed in
                # THIS (non-cumulative) bucket, linking it to a trace
                tid, val, ts = ex
                line += (f' # {{trace_id="{_prom_escape(tid)}"}} '
                         f"{_prom_num(val)} {round(ts, 3)}")
            lines.append(line)
        lines.append(f"{family}_sum{_prom_labels(base)} {repr(sum_s)}")
        lines.append(f"{family}_count{_prom_labels(base)} {count}")


def prometheus_text(extra: Optional[List[Tuple[str, str, str,
                                               List[Tuple[Dict, float]]]]]
                    = None) -> str:
    """Render the telemetry state as Prometheus text exposition format
    (v0.0.4): stage/phase/node latency histograms (``_bucket``/``_sum``/
    ``_count``), event counters, transfer byte counters, jit
    trace/compile counters and the flight-recorder gauge.  ``extra`` adds
    caller families as ``(name, type, help, [(labels, value), ...])`` —
    the server layer appends its prompt/image counters and queue gauge."""
    lines: List[str] = []
    _render_histogram_family(
        lines, "dtpu_stage_seconds",
        "Serving-pipeline stage wall-clock (overlapping stages).",
        GLOBAL_STAGES, "stage")
    _render_histogram_family(
        lines, "dtpu_phase_seconds",
        "Internal phase wall-clock (Timer sink).",
        GLOBAL_PHASES, "phase")
    _render_histogram_family(
        lines, "dtpu_node_seconds",
        "Per-workflow-node-type op execution seconds.",
        GLOBAL_NODES, "node_type")

    lines.append("# HELP dtpu_events_total Scheduler/wire/pipeline event "
                 "counters.")
    lines.append("# TYPE dtpu_events_total counter")
    for name, value in sorted(GLOBAL_COUNTERS.snapshot().items()):
        lines.append(f"dtpu_events_total{_prom_labels({'event': name})} "
                     f"{int(value)}")

    lines.append("# HELP dtpu_transfer_bytes_total Host<->device transfer "
                 "bytes by direction.")
    lines.append("# TYPE dtpu_transfer_bytes_total counter")
    for direction in ("d2h", "h2d"):
        lines.append(
            f"dtpu_transfer_bytes_total"
            f"{_prom_labels({'direction': direction})} "
            f"{GLOBAL_TRANSFERS.total(direction)}")

    retr = GLOBAL_RETRACES.mark()
    lines.append("# HELP dtpu_jit_traces_total Jaxpr traces observed "
                 "(cache-missed jit calls).")
    lines.append("# TYPE dtpu_jit_traces_total counter")
    lines.append(f"dtpu_jit_traces_total {retr['traces']}")
    lines.append("# HELP dtpu_xla_compiles_total Backend (XLA) "
                 "compilations observed.")
    lines.append("# TYPE dtpu_xla_compiles_total counter")
    lines.append(f"dtpu_xla_compiles_total {retr['compiles']}")

    lines.append("# HELP dtpu_trace_ring_size Completed job traces held "
                 "by the flight recorder.")
    lines.append("# TYPE dtpu_trace_ring_size gauge")
    lines.append(f"dtpu_trace_ring_size {GLOBAL_TRACES.size()}")

    lines.append("# HELP dtpu_trace_evictions_total Committed traces "
                 "pushed out of the flight-recorder ring.")
    lines.append("# TYPE dtpu_trace_evictions_total counter")
    lines.append(f"dtpu_trace_evictions_total "
                 f"{GLOBAL_TRACES.eviction_count()}")

    _append_prom_families(lines, extra or [])
    return "\n".join(lines) + "\n"


def _append_prom_families(lines: List[str],
                          families: List[Tuple[str, str, str,
                                               List[Tuple[Dict, float]]]]
                          ) -> None:
    for name, typ, help_text, samples in families:
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {typ}")
        for labels, value in samples:
            lines.append(f"{name}{_prom_labels(labels)} {_prom_num(value)}")


def render_prom_families(families: List[Tuple[str, str, str,
                                              List[Tuple[Dict, float]]]]
                         ) -> str:
    """Standalone Prometheus text for caller-supplied families only (the
    federated cluster exposition renders fleet gauges without duplicating
    this process's histograms)."""
    lines: List[str] = []
    _append_prom_families(lines, families)
    return "\n".join(lines) + "\n"


def reset_aggregate_metrics() -> Dict[str, Any]:
    """POST /distributed/metrics/reset core: clear the process-wide
    aggregate sinks (phases, stages, node timings, counters, transfers)
    so benches and multi-phase test runs stop inheriting cross-run
    telemetry.  Retrace counters are monotonic observations of
    jax.monitoring and are NOT reset (readers diff marks); the flight
    recorder keeps its per-job history unless asked."""
    before = {"phases": len(GLOBAL_PHASES.snapshot()),
              "stages": len(GLOBAL_STAGES.snapshot()),
              "nodes": len(GLOBAL_NODES.snapshot()),
              "counters": len(GLOBAL_COUNTERS.snapshot()),
              "transfer_labels": len(GLOBAL_TRANSFERS.snapshot())}
    GLOBAL_PHASES.reset()
    GLOBAL_STAGES.reset()
    GLOBAL_NODES.reset()
    GLOBAL_COUNTERS.reset()
    GLOBAL_TRANSFERS.reset()
    return before
