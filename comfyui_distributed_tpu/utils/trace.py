"""Tracing / profiling subsystem.

The reference has NONE (SURVEY.md §5: "Tracing / profiling: ABSENT" — its
only timing is a preflight elapsed-ms debug line, ``gpupanel.js:1502``).
Here profiling is a first-class subsystem:

- phase wall-clock aggregation (:class:`PhaseStats`) fed by
  ``utils.logging.Timer`` and the executor's per-node timings, surfaced on
  ``GET /distributed/metrics``;
- XLA/device traces via ``jax.profiler`` (viewable in TensorBoard /
  Perfetto), driven by ``POST /distributed/profile/start`` + ``/stop`` or
  the :func:`trace` context manager.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Optional

from comfyui_distributed_tpu.utils.logging import log


class PhaseStats:
    """Aggregated per-phase wall-clock: count/total/max (thread-safe)."""

    def __init__(self) -> None:
        self._stats: Dict[str, Dict[str, float]] = {}
        self._lock = threading.Lock()

    def record(self, phase: str, seconds: float) -> None:
        with self._lock:
            s = self._stats.setdefault(
                phase, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            s["count"] += 1
            s["total_s"] += seconds
            s["max_s"] = max(s["max_s"], seconds)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._stats.items()}

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


# process-wide sink the Timer class reports into
GLOBAL_PHASES = PhaseStats()


@contextmanager
def phase(name: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        GLOBAL_PHASES.record(name, time.perf_counter() - t0)


# --- device/XLA tracing ------------------------------------------------------

_trace_lock = threading.Lock()
_trace_dir: Optional[str] = None


def start_device_trace(out_dir: Optional[str] = None) -> str:
    """Begin a ``jax.profiler`` trace (TensorBoard/Perfetto format)."""
    global _trace_dir
    import jax
    with _trace_lock:
        if _trace_dir is not None:
            raise RuntimeError(f"trace already running -> {_trace_dir}")
        out_dir = out_dir or os.path.join(
            os.getcwd(), "traces", time.strftime("%Y%m%d-%H%M%S"))
        os.makedirs(out_dir, exist_ok=True)
        jax.profiler.start_trace(out_dir)
        _trace_dir = out_dir
        log(f"device trace started -> {out_dir}")
        return out_dir


def stop_device_trace() -> str:
    global _trace_dir
    import jax
    with _trace_lock:
        if _trace_dir is None:
            raise RuntimeError("no trace running")
        jax.profiler.stop_trace()
        out = _trace_dir
        _trace_dir = None
        log(f"device trace stopped -> {out}")
        return out


def trace_status() -> Dict[str, Any]:
    with _trace_lock:
        return {"running": _trace_dir is not None, "dir": _trace_dir}


@contextmanager
def device_trace(out_dir: Optional[str] = None):
    d = start_device_trace(out_dir)
    try:
        yield d
    finally:
        stop_device_trace()
