"""Tracing / profiling subsystem.

The reference has NONE (SURVEY.md §5: "Tracing / profiling: ABSENT" — its
only timing is a preflight elapsed-ms debug line, ``gpupanel.js:1502``).
Here profiling is a first-class subsystem:

- phase wall-clock aggregation (:class:`PhaseStats`) fed by
  ``utils.logging.Timer`` and the executor's per-node timings, surfaced on
  ``GET /distributed/metrics``;
- XLA/device traces via ``jax.profiler`` (viewable in TensorBoard /
  Perfetto), driven by ``POST /distributed/profile/start`` + ``/stop`` or
  the :func:`trace` context manager;
- host<->device transfer accounting (:class:`TransferStats`): every device
  edge in the ops layer reports bytes through :func:`record_transfer`,
  attributed to the executing workflow node (:func:`node_scope`) — the
  software-measurable proxy for "tensors never leave HBM";
- retrace/compile counters (:class:`RetraceStats`) fed by
  ``jax.monitoring`` events: a steady-state serving process must report
  ZERO new traces on a repeated workflow (``install_jax_monitoring``).
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from comfyui_distributed_tpu.utils.logging import log


class PhaseStats:
    """Aggregated per-phase wall-clock: count/total/max (thread-safe)."""

    def __init__(self) -> None:
        self._stats: Dict[str, Dict[str, float]] = {}
        self._lock = threading.Lock()

    def record(self, phase: str, seconds: float) -> None:
        with self._lock:
            s = self._stats.setdefault(
                phase, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            s["count"] += 1
            s["total_s"] += seconds
            s["max_s"] = max(s["max_s"], seconds)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._stats.items()}

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


# process-wide sink the Timer class reports into
GLOBAL_PHASES = PhaseStats()


@contextmanager
def phase(name: str):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        GLOBAL_PHASES.record(name, time.perf_counter() - t0)


# --- pipeline stage timeline -------------------------------------------------

# Per-job stage wall-clock for the overlapped serving pipeline
# (queue_wait / coalesced_batch / compute / d2h / encode / upload).
# Separate from GLOBAL_PHASES so /distributed/metrics can expose the
# pipeline timeline as its own coherent block: stage totals here overlap
# in wall-clock (that is the point), so summing them against a run's
# wall time yields the device-idle-fraction estimate bench.py reports.
GLOBAL_STAGES = PhaseStats()


@contextmanager
def stage(name: str):
    """Time one pipeline stage into :data:`GLOBAL_STAGES`."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        GLOBAL_STAGES.record(name, time.perf_counter() - t0)


class CounterStats:
    """Named monotonic counters (thread-safe) — scheduler/wire events."""

    def __init__(self) -> None:
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + int(n)

    def get(self, name: str) -> int:
        with self._lock:
            return int(self._counts.get(name, 0))

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


# coalesced_batches / coalesced_prompts / exec_runs / wire_tensor_msgs /
# wire_png_msgs / wire_bytes ... — the scheduler and wire layers bump,
# /distributed/metrics and bench.py --phase pipeline read
GLOBAL_COUNTERS = CounterStats()


def pipeline_snapshot() -> Dict[str, Any]:
    """The serving-pipeline block of /distributed/metrics."""
    return {"stages": GLOBAL_STAGES.snapshot(),
            "counters": GLOBAL_COUNTERS.snapshot()}


# --- device/XLA tracing ------------------------------------------------------

_trace_lock = threading.Lock()
_trace_dir: Optional[str] = None


def start_device_trace(out_dir: Optional[str] = None) -> str:
    """Begin a ``jax.profiler`` trace (TensorBoard/Perfetto format)."""
    global _trace_dir
    import jax
    with _trace_lock:
        if _trace_dir is not None:
            raise RuntimeError(f"trace already running -> {_trace_dir}")
        out_dir = out_dir or os.path.join(
            os.getcwd(), "traces", time.strftime("%Y%m%d-%H%M%S"))
        os.makedirs(out_dir, exist_ok=True)
        jax.profiler.start_trace(out_dir)
        _trace_dir = out_dir
        log(f"device trace started -> {out_dir}")
        return out_dir


def stop_device_trace() -> str:
    global _trace_dir
    import jax
    with _trace_lock:
        if _trace_dir is None:
            raise RuntimeError("no trace running")
        jax.profiler.stop_trace()
        out = _trace_dir
        _trace_dir = None
        log(f"device trace stopped -> {out}")
        return out


def trace_status() -> Dict[str, Any]:
    with _trace_lock:
        return {"running": _trace_dir is not None, "dir": _trace_dir}


@contextmanager
def device_trace(out_dir: Optional[str] = None):
    d = start_device_trace(out_dir)
    try:
        yield d
    finally:
        stop_device_trace()


# --- host<->device transfer accounting ---------------------------------------

class TransferStats:
    """Per-label host<->device transfer byte/call counters (thread-safe).

    Labels are workflow node ids when a :func:`node_scope` is active,
    ``"_unattributed"`` otherwise.  Directions: ``d2h`` (device fetch —
    the expensive edge the tensor plane exists to eliminate) and ``h2d``
    (host put)."""

    def __init__(self) -> None:
        self._stats: Dict[str, Dict[str, float]] = {}
        self._lock = threading.Lock()

    def record(self, direction: str, nbytes: int,
               label: Optional[str] = None) -> None:
        key = label or "_unattributed"
        with self._lock:
            s = self._stats.setdefault(
                key, {"d2h_bytes": 0, "d2h_calls": 0,
                      "h2d_bytes": 0, "h2d_calls": 0})
            s[f"{direction}_bytes"] += int(nbytes)
            s[f"{direction}_calls"] += 1

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._stats.items()}

    def total(self, direction: str) -> int:
        with self._lock:
            return sum(int(v[f"{direction}_bytes"])
                       for v in self._stats.values())

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


# process-wide sink (feeds /distributed/metrics); executors push a per-run
# sink on top so ExecutionResult can report per-node transfers for just
# that run
GLOBAL_TRANSFERS = TransferStats()

_transfer_state = threading.local()


def _sinks() -> List[TransferStats]:
    return getattr(_transfer_state, "sinks", None) or []


@contextmanager
def transfer_sink(sink: TransferStats):
    """Additionally record this thread's transfers into ``sink`` (the
    executor's per-run accounting)."""
    stack = getattr(_transfer_state, "sinks", None)
    if stack is None:
        stack = _transfer_state.sinks = []
    stack.append(sink)
    try:
        yield sink
    finally:
        stack.remove(sink)


@contextmanager
def node_scope(node_id: str):
    """Attribute transfers recorded inside the block to a workflow node."""
    prev = getattr(_transfer_state, "node", None)
    _transfer_state.node = str(node_id)
    try:
        yield
    finally:
        _transfer_state.node = prev


def current_node() -> Optional[str]:
    return getattr(_transfer_state, "node", None)


def capture_transfer_context() -> tuple:
    """Snapshot this thread's transfer attribution (node label + per-run
    sinks) so deferred host work keeps reporting into the run that
    spawned it.  The sinks/node state is thread-local; without this, a
    d2h fetch moved onto the encoder pool would vanish from the
    run-local ``ExecutionResult.transfers`` ledger."""
    return (current_node(), list(_sinks()))


@contextmanager
def transfer_context(captured: tuple):
    """Re-enter a :func:`capture_transfer_context` snapshot on another
    thread (the host-IO pool's worker)."""
    node, sinks = captured
    prev_node = getattr(_transfer_state, "node", None)
    stack = getattr(_transfer_state, "sinks", None)
    if stack is None:
        stack = _transfer_state.sinks = []
    added = [s for s in sinks if s not in stack]
    stack.extend(added)
    _transfer_state.node = node
    try:
        yield
    finally:
        _transfer_state.node = prev_node
        for s in added:
            stack.remove(s)


def record_transfer(direction: str, nbytes: int) -> None:
    """Report one host<->device edge (``direction`` in {"d2h", "h2d"}) from
    the ops layer; attribution and per-run fan-out happen here."""
    label = current_node()
    GLOBAL_TRANSFERS.record(direction, nbytes, label)
    for sink in _sinks():
        sink.record(direction, nbytes, label)


# --- retrace / compile counters ----------------------------------------------

class RetraceStats:
    """Monotonic counters over ``jax.monitoring`` events (thread-safe).

    ``traces`` counts jaxpr traces (every cache-missed jit call),
    ``compiles`` counts backend (XLA) compilations — with the persistent
    compilation cache warm, a retrace can hit the disk cache and skip the
    backend compile, so the two differ."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.traces = 0
        self.compiles = 0

    def bump(self, what: str) -> None:
        with self._lock:
            setattr(self, what, getattr(self, what) + 1)

    def mark(self) -> Dict[str, int]:
        with self._lock:
            return {"traces": self.traces, "compiles": self.compiles}

    def since(self, mark: Dict[str, int]) -> Dict[str, int]:
        with self._lock:
            return {"traces": self.traces - mark["traces"],
                    "compiles": self.compiles - mark["compiles"]}


GLOBAL_RETRACES = RetraceStats()

_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_monitoring_installed = False
_monitoring_lock = threading.Lock()


def install_jax_monitoring() -> None:
    """Register the (process-global, idempotent) ``jax.monitoring``
    listener feeding :data:`GLOBAL_RETRACES`.  Cheap to call per run."""
    global _monitoring_installed
    with _monitoring_lock:
        if _monitoring_installed:
            return
        import jax.monitoring as monitoring

        def on_duration(name: str, duration: float, **kw) -> None:
            if name == _TRACE_EVENT:
                GLOBAL_RETRACES.bump("traces")
            elif name == _COMPILE_EVENT:
                GLOBAL_RETRACES.bump("compiles")

        monitoring.register_event_duration_secs_listener(on_duration)
        _monitoring_installed = True


def counters_snapshot() -> Dict[str, Any]:
    """One payload for /distributed/metrics and bench artifacts."""
    return {"transfers": GLOBAL_TRANSFERS.snapshot(),
            "retraces": GLOBAL_RETRACES.mark()}
