"""Image codecs: device arrays <-> PIL <-> PNG bytes.

Capability parity with reference ``utils/image.py:8-24`` (``tensor_to_pil`` /
``pil_to_tensor``) and the PNG wire marshalling in ``distributed.py:1262-1272``.
The canonical in-framework layout is **NHWC float32 in [0, 1]** (TPU-friendly
channels-last), matching the reference's ``[B, H, W, C]`` convention.

On-mesh tensors never use this path — it exists only for IO edges (workflow
LoadImage/SaveImage) and the multi-host HTTP data plane.
"""

from __future__ import annotations

import io
from typing import List, Union

import numpy as np
from PIL import Image


def to_numpy(x) -> np.ndarray:
    """Accept jax/torch/np arrays; return float32 ndarray."""
    if hasattr(x, "detach"):  # torch
        x = x.detach().cpu().numpy()
    arr = np.asarray(x, dtype=np.float32)
    return arr


def ensure_bhwc(arr: np.ndarray) -> np.ndarray:
    if arr.ndim == 3:
        arr = arr[None]
    if arr.ndim != 4:
        raise ValueError(f"expected [B,H,W,C] or [H,W,C], got shape {arr.shape}")
    return arr


def tensor_to_pil(x, index: int = 0) -> Image.Image:
    """[B,H,W,C] float in [0,1] -> PIL uint8 (reference ``utils/image.py:8-14``)."""
    arr = ensure_bhwc(to_numpy(x))[index]
    arr = np.clip(arr * 255.0 + 0.5, 0, 255).astype(np.uint8)
    if arr.shape[-1] == 1:
        arr = arr[..., 0]
    return Image.fromarray(arr)


def pil_to_tensor(img: Image.Image) -> np.ndarray:
    """PIL -> [1,H,W,C] float32 in [0,1] (reference ``utils/image.py:16-21``)."""
    if img.mode not in ("RGB", "RGBA", "L"):
        img = img.convert("RGB")
    arr = np.asarray(img, dtype=np.float32) / 255.0
    if arr.ndim == 2:
        arr = arr[..., None]
    return arr[None]


def batch_to_pils(x) -> List[Image.Image]:
    arr = ensure_bhwc(to_numpy(x))
    return [tensor_to_pil(arr, i) for i in range(arr.shape[0])]


def encode_png(x: Union[np.ndarray, Image.Image], compress_level: int = 0) -> bytes:
    """Lossless PNG bytes (reference wire format, ``distributed.py:1262-1272``;
    compress_level=0 trades size for CPU, as the reference does)."""
    img = x if isinstance(x, Image.Image) else tensor_to_pil(x)
    buf = io.BytesIO()
    img.save(buf, format="PNG", compress_level=compress_level)
    return buf.getvalue()


def decode_png(data: bytes) -> np.ndarray:
    """PNG bytes -> [1,H,W,C] float32 (reference ``distributed.py:1196-1204``)."""
    img = Image.open(io.BytesIO(data))
    img.load()
    return pil_to_tensor(img)


# --- raw-tensor wire format (application/x-dtpu-tensor) ----------------------
#
# The PNG wire costs a float->uint8 quantize + zlib filter pass per image
# and clamps to 8 bits; between our own processes neither is needed.  The
# fast path ships the npy header+buffer compressed: 4-byte magic, 1 codec
# byte, payload.  The SENDER only emits a codec the receiver advertised
# (GET /distributed/wire_formats lists ``tensor_codecs``;
# utils.net.negotiate_wire_format picks the best shared one) — zstd is
# optional on both ends (the container may not ship the module — gate,
# don't install) and zlib is the always-available floor, so a
# zstd-capable worker never strands a deflate-only master.

_TENSOR_WIRE_MAGIC = b"DTT1"
_CODEC_ZLIB = 1
_CODEC_ZSTD = 2

try:  # optional dependency — never required
    import zstandard as _zstd
except ImportError:  # pragma: no cover - environment-dependent
    _zstd = None


def tensor_codecs() -> List[str]:
    """Codecs THIS process can decode, best-first (wire negotiation)."""
    return (["zstd", "zlib"] if _zstd is not None else ["zlib"])


def encode_tensor(x, codec: str = "zlib") -> bytes:
    """Array -> raw-tensor wire bytes (lossless, dtype-preserving).
    ``codec`` must be one the RECEIVER advertised; default is the
    always-decodable zlib."""
    import zlib
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(to_numpy(x)), allow_pickle=False)
    raw = buf.getvalue()
    if codec == "zstd" and _zstd is not None:
        return (_TENSOR_WIRE_MAGIC + bytes([_CODEC_ZSTD])
                + _zstd.ZstdCompressor(level=3).compress(raw))
    return _TENSOR_WIRE_MAGIC + bytes([_CODEC_ZLIB]) + zlib.compress(raw, 1)


def decode_tensor(data: bytes) -> np.ndarray:
    """Raw-tensor wire bytes -> [B,H,W,C] float32 (the shape contract the
    PNG path honors; callers see the same value either way)."""
    import zlib
    if data[:4] != _TENSOR_WIRE_MAGIC:
        raise ValueError("bad tensor wire magic")
    codec, payload = data[4], data[5:]
    if codec == _CODEC_ZSTD:
        if _zstd is None:
            raise ValueError("zstd tensor payload but zstandard missing")
        raw = _zstd.ZstdDecompressor().decompress(payload)
    elif codec == _CODEC_ZLIB:
        raw = zlib.decompress(payload)
    else:
        raise ValueError(f"unknown tensor wire codec {codec}")
    arr = np.load(io.BytesIO(raw), allow_pickle=False)
    return ensure_bhwc(np.asarray(arr, np.float32))


def resize_image(x, width: int, height: int, method: str = "lanczos") -> np.ndarray:
    """Batched float resize for parity with the reference's LANCZOS usage
    (``distributed_upscale.py:505,583``; ImageScale node).

    Resampling happens per-channel on 32-bit float PIL images ('F' mode), so
    no uint8 quantization or [0,1] clipping is introduced — out-of-range
    values (latents, lanczos overshoot) survive intact."""
    filters = {
        "nearest": Image.NEAREST,
        "nearest-exact": Image.NEAREST,
        "bilinear": Image.BILINEAR,
        "area": Image.BOX,
        "bicubic": Image.BICUBIC,
        "lanczos": Image.LANCZOS,
    }
    f = filters.get(method, Image.LANCZOS)
    arr = ensure_bhwc(to_numpy(x))
    b, _, _, c = arr.shape
    out = np.empty((b, height, width, c), dtype=np.float32)
    for i in range(b):
        for ch in range(c):
            plane = Image.fromarray(arr[i, :, :, ch], mode="F")
            out[i, :, :, ch] = np.asarray(
                plane.resize((width, height), f), dtype=np.float32)
    return out
