"""Cluster configuration.

Capability parity with reference ``utils/config.py:1-50`` plus the endpoints'
mutation semantics (``distributed.py:209-364``): a single JSON file holding
master + worker definitions, settings, and managed-process state.  Extended
with a ``mesh`` section (TPU topology) the reference has no analog for.

Schema::

    {
      "master":  {"host": str|None, "port": int?, "extra_args": str?},
      "workers": [{"id": str, "name": str, "host": str?, "port": int,
                   "enabled": bool, "extra_args": str?}],
      "settings": {"debug": bool, "auto_launch_workers": bool,
                   "stop_workers_on_master_exit": bool},
      "mesh":    {"axes": {"data": int, "tensor": int, "seq": int},
                  "allow_cpu_fallback": bool},
      "managed_processes": {name: {"pid": int, ...}}
    }
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Any, Dict, Optional

from comfyui_distributed_tpu.utils.logging import log, set_debug

_lock = threading.RLock()

CONFIG_ENV = "DISTRIBUTED_TPU_CONFIG"
DEFAULT_CONFIG_NAME = "cluster_config.json"


def default_config_path() -> str:
    env = os.environ.get(CONFIG_ENV)
    if env:
        return env
    return os.path.join(os.getcwd(), DEFAULT_CONFIG_NAME)


def get_default_config() -> Dict[str, Any]:
    """Default schema (reference ``get_default_config``, ``utils/config.py:10-20``)."""
    return {
        "master": {"host": None},
        "workers": [],
        "settings": {
            "debug": False,
            "auto_launch_workers": False,
            "stop_workers_on_master_exit": True,
        },
        "mesh": {
            "axes": {"data": -1, "tensor": 1, "seq": 1},  # -1: all devices
            "allow_cpu_fallback": True,
        },
        "managed_processes": {},
    }


def _merge_defaults(cfg: Any) -> Dict[str, Any]:
    base = get_default_config()
    if not isinstance(cfg, dict):
        return base
    for key, val in base.items():
        if isinstance(val, dict):
            if not isinstance(cfg.get(key), dict):
                cfg[key] = val
            else:
                for k2, v2 in val.items():
                    cfg[key].setdefault(k2, v2)
        elif key not in cfg or cfg[key] is None:
            cfg[key] = val
    return cfg


def load_config(path: Optional[str] = None) -> Dict[str, Any]:
    """Load (reference ``load_config``, ``utils/config.py:22-30``); missing or
    corrupt files yield defaults rather than raising."""
    path = path or default_config_path()
    with _lock:
        try:
            with open(path, "r", encoding="utf-8") as f:
                cfg = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            cfg = get_default_config()
        cfg = _merge_defaults(cfg)
    set_debug(bool(cfg["settings"].get("debug", False)))
    return cfg


def save_config(cfg: Dict[str, Any], path: Optional[str] = None) -> None:
    """Atomic write (reference ``save_config``, ``utils/config.py:32-40``)."""
    path = path or default_config_path()
    with _lock:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".cfg-", suffix=".tmp")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(cfg, f, indent=2)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    set_debug(bool(cfg.get("settings", {}).get("debug", False)))


def mutate_config(mutator, path: Optional[str] = None) -> Dict[str, Any]:
    """Atomic read-modify-write: load, apply ``mutator(cfg)``, save — all
    under the config lock, so concurrent writers (HTTP handlers, the process
    manager's PID persistence, auto-launch timer threads) can't clobber each
    other's edits with stale copies."""
    with _lock:
        cfg = load_config(path)
        mutator(cfg)
        save_config(cfg, path)
        return cfg


def ensure_config_exists(path: Optional[str] = None) -> str:
    """Create the default config if absent (reference ``utils/config.py:42-50``)."""
    path = path or default_config_path()
    if not os.path.exists(path):
        save_config(get_default_config(), path)
        log(f"created default config at {path}")
    return path


# --- worker CRUD (semantics of reference distributed.py:209-364) -----------

def upsert_worker(cfg: Dict[str, Any], worker: Dict[str, Any]) -> Dict[str, Any]:
    """Insert or update a worker by id; a value of ``None`` deletes that field
    (reference ``update_worker_endpoint``, ``distributed.py:209-278``)."""
    wid = str(worker["id"])
    workers = cfg.setdefault("workers", [])
    for existing in workers:
        if str(existing.get("id")) == wid:
            for k, v in worker.items():
                if v is None:
                    existing.pop(k, None)
                else:
                    existing[k] = v
            return existing
    clean = {k: v for k, v in worker.items() if v is not None}
    clean.setdefault("enabled", False)
    workers.append(clean)
    return clean


def delete_worker(cfg: Dict[str, Any], worker_id: str) -> bool:
    """Remove a worker by id (reference ``distributed.py:280-313``)."""
    workers = cfg.setdefault("workers", [])
    before = len(workers)
    cfg["workers"] = [w for w in workers if str(w.get("id")) != str(worker_id)]
    return len(cfg["workers"]) != before


def update_setting(cfg: Dict[str, Any], key: str, value: Any) -> None:
    """Set one settings key (reference ``distributed.py:315-337``)."""
    cfg.setdefault("settings", {})[key] = value
    if key == "debug":
        set_debug(bool(value))


def update_master(cfg: Dict[str, Any], **fields: Any) -> None:
    """Update master host/port/extra_args (reference ``distributed.py:339-364``)."""
    master = cfg.setdefault("master", {})
    for k, v in fields.items():
        if v is None:
            master.pop(k, None)
        else:
            master[k] = v


def enabled_workers(cfg: Dict[str, Any]) -> list:
    return [w for w in cfg.get("workers", []) if w.get("enabled")]
