"""Process-global interrupt flag, pollable from inside compiled programs.

The reference inherits ComfyUI's per-step interrupt: ``common_ksampler``
checks a processing flag between denoise steps (reference
``distributed_upscale.py:516-541`` runs under ComfyUI's executor, whose
``/interrupt`` route flips that flag).  An ``lax.scan`` denoise loop is one
compiled program, so between-node checks (``ops/base.py check_interrupt``)
can't stop a 20-step sample already in flight — instead the scan body polls
this flag through a host callback each step and skips the model call once
set (``models/samplers.py _scan_sampler``), returning the partially-denoised
latent within one step.

One process-global event mirrors ComfyUI's global processing-interrupted
semantics; the server's ``/interrupt`` route sets it, the executor clears it
at run start.
"""

from __future__ import annotations

import os
import threading

import numpy as np

_event = threading.Event()


def interrupt_event() -> threading.Event:
    """The process-wide interrupt event (shared with the server state)."""
    return _event


def request_interrupt() -> None:
    _event.set()


def clear_interrupt() -> None:
    _event.clear()


def is_interrupted() -> bool:
    return _event.is_set()


def _backend_supports_callbacks() -> bool:
    """Whether the active JAX backend can run host callbacks at all.
    The axon PJRT plugin (the tunneled single-chip TPU used for
    benching) raises UNIMPLEMENTED for host send/recv — polling must
    compile out there or every sampled batch dies at runtime."""
    try:
        import jax
        plat = jax.default_backend()
    except Exception:
        return True
    return plat != "axon"


def polling_enabled() -> bool:
    """Whether compiled samplers poll the flag each step.  Default: on
    wherever the backend supports host callbacks.  ``DTPU_INTERRUPT_POLL``
    forces it: ``0`` opts out (e.g. microbenchmarks that don't want the
    per-step host readback), ``1`` forces it on even for backends on the
    no-callback list (e.g. a newer plugin that grew support)."""
    forced = os.environ.get("DTPU_INTERRUPT_POLL")
    if forced is not None:
        return forced != "0"
    return _backend_supports_callbacks()


def poll(_sequencer=None) -> np.bool_:
    """Host-callback body: reads the flag.  The ignored operand exists so
    callers can pass a carry-dependent scalar, giving the callback a data
    dependency on the previous step (otherwise XLA could hoist all the
    polls to the start of the scan)."""
    return np.bool_(_event.is_set())
