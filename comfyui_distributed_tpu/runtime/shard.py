"""Multi-master sharded control plane (ISSUE 14).

Through PR 13 every request funnels through ONE master process:
admission, WAL appends, ledger transitions and blend drains all
serialize on a single box — the single-master scaling wall MapReduce
warns about, and the single-process admission chokepoint The Tail at
Scale says must be spread before hedging helps.  PR 7 built the
primitives precisely to unlock this — epoch-fenced WALs, lease-based
election, worker re-homing, exactly-once check-in — and this module
cashes them in:

- :class:`HashRing` — consistent hashing with virtual nodes over the
  prompt-id space.  Deterministic placement; when a member joins or
  leaves, only ~1/N of the keyspace moves (the property the tests
  assert), so a takeover re-homes one shard's keys, not everyone's.
- :class:`ShardManager` — one per active master (armed by
  ``DTPU_SHARD_ID`` + ``DTPU_SHARD_PEERS``).  Owns this master's ring
  view, gossips it to peers (``POST /distributed/ring/gossip``; the
  merged view is served at ``GET /distributed/ring``), watches every
  peer shard's :class:`~..runtime.durable.MasterLease` under the shared
  ``DTPU_SHARD_WAL_ROOT``, and — when a peer's lease expires and this
  master is the dead shard's ring successor — ABSORBS the shard:
  bumps its epoch (fencing any zombie), replays its WAL, merges its
  recovered ledger jobs + idempotency keys + spilled unit payloads,
  re-enqueues its in-flight prompts under their original ids, removes
  the member from the ring and gossips the new membership.  There is no
  dedicated standby: every master is a peer-takeover target.
- :func:`build_router_app` — the thin STATELESS admission router
  (``cli router``): hashes each ``/prompt`` to its owning shard and
  forwards it there; its only state is a refreshable cached ring.
  Clients may equally hash client-side via ``GET /distributed/ring``.

Mis-routed submissions (a client that posted to the wrong master, or a
router with a stale ring) are forwarded AT MOST ONE HOP by the
receiving master (``server/app.py``), marked with
``SHARD_FORWARD_HEADER`` so disagreement between ring views can never
loop; the admission lands in the OWNING shard's WAL before the client
gets its prompt-id.
"""

from __future__ import annotations

import bisect
import hashlib
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from comfyui_distributed_tpu.utils import clock as clock_mod
from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils import trace as trace_mod
from comfyui_distributed_tpu.utils.logging import debug_log, log


def _hash64(key: str) -> int:
    return int.from_bytes(
        hashlib.sha1(key.encode("utf-8")).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring with virtual nodes.  Immutable after
    construction (membership changes build a new ring), so reads are
    lock-free for the owner-lookup hot path."""

    def __init__(self, members: Dict[str, Any], vnodes: int = None):
        if vnodes is None:
            try:
                vnodes = int(os.environ.get(C.SHARD_VNODES_ENV,
                                            C.SHARD_VNODES_DEFAULT))
            except ValueError:
                vnodes = C.SHARD_VNODES_DEFAULT
        self.vnodes = max(int(vnodes), 1)
        self.members = sorted(str(m) for m in members)
        points: List[tuple] = []
        for m in self.members:
            for v in range(self.vnodes):
                points.append((_hash64(f"{m}#{v}"), m))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._owners = [m for _, m in points]

    def owner(self, key: str) -> Optional[str]:
        """The member owning ``key``: first virtual node clockwise from
        the key's hash (wrapping)."""
        if not self._owners:
            return None
        i = bisect.bisect_right(self._hashes, _hash64(str(key)))
        return self._owners[i % len(self._owners)]

    def successor(self, member: str) -> Optional[str]:
        """Deterministic takeover target for a dead ``member``: the
        owner of the member's own id on the ring WITHOUT it.  Every
        surviving peer computes the same answer from the same live
        view, so exactly one absorbs (the flock'd lease acquire breaks
        any residual race safely)."""
        rest = [m for m in self.members if m != str(member)]
        if not rest:
            return None
        return HashRing({m: None for m in rest}, self.vnodes).owner(
            str(member))


def parse_peers(raw: str) -> Dict[str, str]:
    """``"m0=http://h:p,m1=http://h:p"`` -> ``{id: url}``."""
    out: Dict[str, str] = {}
    for part in (raw or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        sid, _, url = part.partition("=")
        if sid.strip() and url.strip():
            out[sid.strip()] = url.strip().rstrip("/")
    return out


def shard_config() -> Optional[Dict[str, Any]]:
    """The sharding arm switch: None unless ``DTPU_SHARD_ID`` is set.
    Resolved once per ServerState construction (before the durability
    plane attaches, so the per-shard WAL dir can be derived)."""
    sid = os.environ.get(C.SHARD_ID_ENV, "").strip()
    if not sid:
        return None
    members = parse_peers(os.environ.get(C.SHARD_PEERS_ENV, ""))
    members.setdefault(sid, "")
    root = os.environ.get(C.SHARD_WAL_ROOT_ENV, "").strip()
    return {
        "id": sid,
        "members": members,
        "wal_root": os.path.expanduser(root) if root else None,
    }


def _env_float(env: str, default: float) -> float:
    try:
        return float(os.environ.get(env, default))
    except ValueError:
        return default


class ShardManager:
    """One active master's membership in the multi-master ring: ring
    state + gossip + peer-lease watch + dead-shard absorption."""

    def __init__(self, state, shard_id: str, members: Dict[str, str],
                 wal_root: Optional[str] = None,
                 vnodes: Optional[int] = None,
                 gossip_s: Optional[float] = None,
                 start_threads: bool = True,
                 clock: Optional[Any] = None):
        # clock seam (ISSUE 19): peer-gossip liveness ages and takeover
        # timestamps run off this; wall default = pre-seam behavior
        self._clock = clock if clock is not None else clock_mod.WALL
        self.id = str(shard_id)
        self.wal_root = wal_root
        self._state = state
        self.gossip_s = _env_float(C.SHARD_GOSSIP_ENV,
                                   C.SHARD_GOSSIP_DEFAULT) \
            if gossip_s is None else float(gossip_s)
        self.peer_down_s = _env_float(C.SHARD_PEER_DOWN_ENV,
                                      C.SHARD_PEER_DOWN_DEFAULT)
        self.takeover_enabled = os.environ.get(
            C.SHARD_TAKEOVER_ENV, "1").lower() not in ("0", "false",
                                                       "off")
        self._vnodes = vnodes
        self._lock = threading.Lock()
        # ring membership + epoch: mutated by gossip merges (handler
        # thread) and absorb (watcher thread), read by every /prompt —
        # the lockset rule holds every access to the annotations
        self._members: Dict[str, str] = {           # guarded-by: self._lock
            str(k): str(v or "") for k, v in members.items()}
        self._ring = HashRing(self._members, vnodes)  # guarded-by: self._lock
        self._ring_epoch = 1                        # guarded-by: self._lock
        self._peer_seen: Dict[str, float] = {}      # guarded-by: self._lock
        self._peer_queue: Dict[str, int] = {}       # guarded-by: self._lock
        self._absorbed: Dict[str, Dict] = {}        # guarded-by: self._lock
        self._absorbing: set = set()                # guarded-by: self._lock
        # absorbed prompts whose takeover re-enqueue failed (full queue
        # mid-overload): {dead_shard: {pid: wal prompt record}}.  They
        # stay durably open in the dead shard's WAL — whose lease this
        # survivor keeps holding — until the gossip loop's retry lands
        # them (retry_absorbed_reenqueues); without the retry they'd be
        # lost forever, since the dead member leaves every ring and its
        # restart is fenced out by design.
        self._pending_reenqueue: Dict[str, Dict] = {}  # guarded-by: self._lock
        # a peer's higher-epoch ring that EXCLUDES us means we were
        # absorbed while dead/partitioned: this master must stop
        # acting like an owner (no further takeovers) and say so
        self.deposed = False
        self.takeovers = 0
        self.forwards = 0
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        if start_threads:
            self.start()

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if self._threads:
            return
        t = threading.Thread(target=self._gossip_loop, daemon=True,
                             name=f"dtpu-shard-gossip-{self.id}")
        t.start()
        self._threads.append(t)
        if self.wal_root:
            w = threading.Thread(target=self._watch_loop, daemon=True,
                                 name=f"dtpu-shard-watch-{self.id}")
            w.start()
            self._threads.append(w)

    def stop(self) -> None:
        self._stop.set()

    # -- ring reads -----------------------------------------------------------

    def owner_of(self, key: str) -> str:
        """Owning shard for a prompt-id; absorbed shards' keys resolve
        to their absorber because the member left the ring."""
        with self._lock:
            return self._ring.owner(str(key)) or self.id

    def is_mine(self, key: str) -> bool:
        return self.owner_of(key) == self.id

    def member_url(self, shard_id: str) -> Optional[str]:
        with self._lock:
            return self._members.get(str(shard_id)) or None

    def ring_epoch(self) -> int:
        with self._lock:
            return self._ring_epoch

    def n_members(self) -> int:
        with self._lock:
            return max(len(self._members), 1)

    def owned_shards(self) -> List[str]:
        with self._lock:
            return [self.id] + sorted(self._absorbed)

    def local_pid(self, counter: "itertools.count") -> str:
        """Generate a prompt id THIS shard owns (bounded rejection
        sampling over a disambiguating suffix), so a directly-submitted
        prompt with no router hint never needs a forward hop."""
        base = f"p_{int(self._clock.time() * 1000)}_{next(counter)}"
        if self.is_mine(base):
            return base
        for k in range(256):
            pid = f"{base}s{k}"
            if self.is_mine(pid):
                return pid
        return base  # pathological ring: accept locally anyway

    # -- gossip ---------------------------------------------------------------

    def _gossip_payload(self) -> Dict[str, Any]:
        # queue depth read BEFORE taking the ring lock: queue_remaining
        # acquires ServerState._queue_lock (and the CB executor's lock),
        # and calling a foreign subsystem while holding self._lock is
        # the ordering edge the dtpu-lint deadlock-cycle rule hunts —
        # one queue-side call back into the ring would have closed an
        # ABBA cycle between the gossip thread and the admission path
        st = self._state
        queue_remaining = st.queue_remaining() if st is not None else 0
        with self._lock:
            return {
                "from": self.id,
                "ring_epoch": self._ring_epoch,
                "members": dict(self._members),
                "queue_remaining": queue_remaining,
            }

    def merge_gossip(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Apply a peer's gossiped view; returns our own (pull+push —
        one exchange converges both sides).  A strictly higher ring
        epoch replaces our membership; at equal epochs each side keeps
        its own (they started identical and only absorb bumps them)."""
        peer = str(payload.get("from", ""))
        now = self._clock.monotonic()
        changed = None
        with self._lock:
            if peer and peer != self.id:
                self._peer_seen[peer] = now
                try:
                    self._peer_queue[peer] = int(
                        payload.get("queue_remaining", 0) or 0)
                except (TypeError, ValueError):
                    pass
            their_epoch = int(payload.get("ring_epoch", 0) or 0)
            members = payload.get("members")
            if isinstance(members, dict) and members:
                if their_epoch > self._ring_epoch \
                        and str(self.id) in members:
                    # never re-adopt a member WE absorbed: a peer whose
                    # higher-epoch view predates our takeover would
                    # resurrect the dead id — and dead_peer_shards
                    # skips absorbed ids, so nobody would ever remove
                    # it again (its keyspace slice routing to a dead
                    # URL forever).  If we genuinely lost that shard's
                    # lease, renew_absorbed_leases clears _absorbed and
                    # the revived member re-enters on the next round.
                    changed = {str(k): str(v or "")
                               for k, v in members.items()
                               if str(k) not in self._absorbed}
                    self._ring_epoch = their_epoch
                elif their_epoch > self._ring_epoch and not self.deposed:
                    # a higher-epoch ring WITHOUT us: a peer absorbed
                    # our shard while we were dead/partitioned — we are
                    # a zombie owner now (the WAL fence already stops
                    # our appends; this stops our takeovers and labels
                    # the snapshot)
                    self.deposed = True
                    log(f"shard {self.id}: DEPOSED — peer ring epoch "
                        f"{their_epoch} no longer includes this shard")
                elif their_epoch == self._ring_epoch \
                        and set(members) != set(self._members) \
                        and str(self.id) in members:
                    # equal-epoch divergence = two concurrent absorbs
                    # removed different dead members.  The INTERSECTION
                    # is the deterministic merge both sides converge to
                    # (every removal was a real death; nobody re-adds).
                    keep = set(members) & set(self._members)
                    if keep and keep != set(self._members):
                        changed = {k: (self._members.get(k)
                                       or str(members.get(k) or ""))
                                   for k in keep}
            if changed is not None:
                self._members = changed
                self._ring = HashRing(self._members, self._vnodes)
                # members that left the merged ring were absorbed
                # elsewhere; drop their gossip residue
                for gone in [p for p in self._peer_seen
                             if p not in self._members]:
                    self._peer_seen.pop(gone, None)
                    self._peer_queue.pop(gone, None)
        if changed is not None:
            self._rescale_admission()
        return self._gossip_payload()

    def _rescale_admission(self) -> None:
        """Re-apply the per-client rate split after any membership
        change (the N in rate/N just moved)."""
        st = self._state
        if st is None:
            return
        try:
            st.admission.set_rate_scale(1.0 / self.n_members())
        except Exception as e:  # noqa: BLE001 - advisory
            debug_log(f"shard {self.id}: rate rescale failed: {e}")

    def gossip_once(self) -> int:
        """Push our view to every peer, merging each reply.  Plain
        urllib on this daemon thread (never the event loop)."""
        import urllib.request
        payload = self._gossip_payload()
        with self._lock:
            peers = [(sid, url) for sid, url in self._members.items()
                     if sid != self.id and url]
        reached = 0
        for sid, url in peers:
            try:
                req = urllib.request.Request(
                    f"{url}/distributed/ring/gossip",
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=3) as r:
                    reply = json.loads(r.read())
                if isinstance(reply, dict):
                    self.merge_gossip(reply)
                reached += 1
            except Exception as e:  # noqa: BLE001 - gossip best-effort
                debug_log(f"shard {self.id}: gossip to {sid} failed: "
                          f"{e}")
        return reached

    def _gossip_loop(self) -> None:
        while not self._stop.wait(self.gossip_s):
            try:
                self.gossip_once()
            except Exception as e:  # noqa: BLE001 - keep gossiping
                debug_log(f"shard {self.id}: gossip round failed: {e}")

    def renew_absorbed_leases(self) -> None:
        """Keep holding every absorbed shard's lease: a restart of the
        dead master must get LeaseHeldError (failing loudly at startup)
        instead of reclaiming an expired lease and replaying a shard
        whose prompts this survivor already took over."""
        from comfyui_distributed_tpu.runtime import durable as dur
        if not self.wal_root:
            return
        with self._lock:
            held = {sid: rec["epoch"]
                    for sid, rec in self._absorbed.items()}
        for sid, epoch in held.items():
            lease = dur.MasterLease(os.path.join(self.wal_root, sid))
            if not lease.renew(self.id, epoch, dur.master_lease_s()):
                # superseded: another owner acquired it (e.g. the dead
                # master restarted in an expiry gap).  Stop acting as
                # this shard's owner NOW — keeping the _absorbed /
                # _pending_reenqueue records would re-drive prompts the
                # new owner is also replaying (duplicate execution)
                log(f"shard {self.id}: lost absorbed shard {sid}'s "
                    f"lease (epoch {epoch} superseded); dropping "
                    f"ownership")
                with self._lock:
                    self._absorbed.pop(sid, None)
                    self._pending_reenqueue.pop(sid, None)

    def retry_absorbed_reenqueues(self) -> int:
        """Re-drive absorbed prompts whose takeover re-enqueue failed
        (this survivor's queue was full mid-overload — exactly when
        takeovers are most likely).  Until a retry lands, the prompt
        stays durably open in the dead shard's WAL, whose lease this
        master keeps renewing, so nobody else replays it and a restart
        of the dead master still fails loudly; once enqueued it is
        closed there under the absorb epoch exactly like the
        first-pass transfers.  Returns the number landed."""
        from comfyui_distributed_tpu.runtime import durable as dur
        st = self._state
        if st is None or not self.wal_root:
            return 0
        with self._lock:
            pending = {sid: dict(pids) for sid, pids
                       in self._pending_reenqueue.items() if pids}
        total = 0
        for sid, pids in pending.items():
            with self._lock:
                rec = self._absorbed.get(sid)
            if rec is None:
                continue  # shard's lease lost/superseded: not ours
            done: List[str] = []
            landed = 0
            for pid, p in pids.items():
                prompt = p.get("prompt")
                if not isinstance(prompt, dict):
                    done.append(pid)  # unreplayable record: drop it
                    continue
                try:
                    from comfyui_distributed_tpu.workflow. \
                        orchestrate import \
                        register_recovery_redispatchers
                    register_recovery_redispatchers(st, prompt)
                except Exception as e:  # noqa: BLE001 - local refine
                    debug_log(f"shard retry redispatchers for {pid} "
                              f"skipped: {e}")
                try:
                    st.enqueue_prompt(
                        prompt, p.get("client_id", "recovered"),
                        p.get("extra") or {}, pid=pid,
                        _recovered=True, _absorbed=True)
                except Exception as e:  # noqa: BLE001 - still full:
                    # stays pending (and durable) for the next round
                    debug_log(f"shard {self.id}: re-enqueue retry of "
                              f"{pid} still failing: {e}")
                    continue
                done.append(pid)
                landed += 1
            if not done:
                continue
            # close the now-transferred admissions in the dead shard's
            # log (under OUR absorb epoch), mirroring absorb(): a
            # fenced-out restart must never replay them
            try:
                ddir = os.path.join(self.wal_root, sid)
                closer = dur.WriteAheadLog(
                    ddir, epoch=int(rec["epoch"]),
                    lease=dur.MasterLease(ddir))
                for pid in done:
                    closer.append("exec_done", pid=str(pid),
                                  status="absorbed")
                closer.close()
            except Exception as e:  # noqa: BLE001 - the renewed lease
                # still blocks a restart while we hold it
                log(f"shard {self.id}: closing retried transfers in "
                    f"{sid}'s WAL failed: {e}")
            with self._lock:
                cur = self._pending_reenqueue.get(sid)
                if cur is not None:
                    for pid in done:
                        cur.pop(pid, None)
                    if not cur:
                        self._pending_reenqueue.pop(sid, None)
                rec2 = self._absorbed.get(sid)
                if rec2 is not None:
                    rec2["resumed_prompts"] = \
                        int(rec2.get("resumed_prompts", 0)) + landed
            if landed:
                trace_mod.GLOBAL_COUNTERS.bump(
                    "shard_absorbed_prompts", landed)
                log(f"shard {self.id}: re-enqueued {landed} deferred "
                    f"prompt(s) from absorbed shard {sid}")
            total += landed
        return total

    # -- peer-lease watch + takeover ------------------------------------------

    def dead_peer_shards(self) -> List[str]:
        """Peer shards whose master lease EXPIRED (the holder stopped
        renewing — the same signal a PR 7 standby acts on).  A shard
        whose lease file never existed hasn't started; leave it be."""
        from comfyui_distributed_tpu.runtime import durable as dur
        if not self.wal_root:
            return []
        with self._lock:
            peers = [sid for sid in self._members
                     if sid != self.id and sid not in self._absorbed]
        out = []
        for sid in peers:
            lease = dur.MasterLease(os.path.join(self.wal_root, sid))
            rec = lease.read()
            if rec is not None and lease.expired(rec):
                out.append(sid)
        return out

    def watch_once(self) -> List[str]:
        """One takeover scan: absorb every dead peer shard this master
        is the ring successor for.  The successor is computed on the
        ring of LIVE members only — with two simultaneous deaths, the
        plain one-member-removed successor can be the OTHER dead shard
        (and vice versa), deadlocking takeover forever; excluding every
        currently-dead member guarantees a live absorber exists, and
        all survivors still compute the same answer from the same dead
        set (the flock'd lease acquire breaks any residual race).
        Returns the shards absorbed."""
        absorbed = []
        if self.deposed:
            return absorbed  # a zombie owner must not absorb anyone
        dead = self.dead_peer_shards()
        if not dead:
            return absorbed
        with self._lock:
            live_ring = HashRing(
                {m: None for m in self._members
                 if m == self.id or m not in dead},
                self._ring.vnodes)
        for sid in dead:
            succ = live_ring.owner(sid)
            if succ != self.id or not self.takeover_enabled:
                continue
            try:
                if self.absorb(sid):
                    absorbed.append(sid)
            except Exception as e:  # noqa: BLE001 - keep watching
                log(f"shard {self.id}: takeover of {sid} failed: "
                    f"{type(e).__name__}: {e}")
        return absorbed

    def _watch_loop(self) -> None:
        from comfyui_distributed_tpu.runtime import durable as dur
        interval = max(dur.master_lease_s() / C.MASTER_LEASE_FRACTION,
                       0.05)
        # absorbed-lease renewal rides THIS loop, not the gossip loop:
        # its cadence is lease/fraction by construction, and it is
        # never delayed behind gossip HTTP timeouts to dead peers —
        # with lease_s <= gossip_s an absorbed lease could otherwise
        # sit expired between renewals, letting a restarted dead
        # master reclaim it while the survivor still drives its
        # prompts (split ownership)
        while not self._stop.wait(interval):
            self.watch_once()
            try:
                self.renew_absorbed_leases()
            except Exception as e:  # noqa: BLE001
                debug_log(f"shard {self.id}: absorbed-lease renew "
                          f"failed: {e}")
            try:
                self.retry_absorbed_reenqueues()
            except Exception as e:  # noqa: BLE001
                debug_log(f"shard {self.id}: absorbed re-enqueue "
                          f"retry failed: {e}")

    def absorb(self, dead_id: str) -> Optional[Dict[str, Any]]:
        """Peer takeover of a dead shard (the multi-master analog of the
        PR 7 standby election): acquire its lease (epoch bump = the
        fencing event), replay its WAL, merge its recovered ledger
        state + idempotency keys + spilled unit payloads into THIS
        master's planes, re-enqueue its in-flight prompts under their
        ORIGINAL prompt-ids (appended to OUR WAL — the dead log goes
        dormant), re-home its workers, and remove the member from the
        ring (ring-epoch bump, gossiped immediately)."""
        from comfyui_distributed_tpu.runtime import durable as dur
        dead_id = str(dead_id)
        with self._lock:
            if dead_id in self._absorbed or dead_id in self._absorbing:
                return None
            self._absorbing.add(dead_id)
        try:
            ddir = os.path.join(self.wal_root, dead_id)
            lease = dur.MasterLease(ddir)
            try:
                epoch = lease.acquire(self.id, dur.master_lease_s())
            except dur.LeaseHeldError:
                return None  # revived (or a racing peer won): back off
            replayed, info = dur.replay(ddir)
            store = dur.UnitStore(ddir)
            st = self._state
            log(f"shard {self.id}: absorbing dead shard {dead_id} "
                f"(epoch {epoch}, "
                f"{info.get('records_replayed', 0)} records, "
                f"{len(replayed.prompts)} in-flight prompt(s), "
                f"{len(replayed.jobs)} open job(s))")
            if st is not None:
                # idempotency keys BEFORE the ledger jobs: an upload
                # check-in for an absorbed job can only be accepted
                # once the job is reachable, so seeding the dead
                # shard's replayed keys first closes the window where
                # a racing retry could miss its key and double-enqueue
                # (merge_idem runs on this watcher thread; the store's
                # asyncio locks cannot exclude it)
                st.jobs.merge_idem(replayed.idem, scope=dead_id)
                st.ledger.merge_recovered(dict(replayed.jobs), store)
                try:
                    st.health.poll_once()
                except Exception as e:  # noqa: BLE001 - best-effort
                    debug_log(f"shard absorb preflight poll: {e}")
                resumed = 0
                transferred = []
                failed_reenq: Dict[str, Dict] = {}
                for pid, p in replayed.prompts.items():
                    prompt = p.get("prompt")
                    if not isinstance(prompt, dict):
                        continue
                    try:
                        from comfyui_distributed_tpu.workflow. \
                            orchestrate import \
                            register_recovery_redispatchers
                        register_recovery_redispatchers(st, prompt)
                    except Exception as e:  # noqa: BLE001 - local refine
                        debug_log(f"shard absorb redispatchers for "
                                  f"{pid} skipped: {e}")
                    try:
                        st.enqueue_prompt(
                            prompt, p.get("client_id", "recovered"),
                            p.get("extra") or {}, pid=pid,
                            _recovered=True, _absorbed=True)
                    except Exception as e:  # noqa: BLE001 - one full
                        # queue must not abort the takeover half-done:
                        # the prompt stays open in the dead WAL (whose
                        # lease we keep holding) and in _pending_
                        # reenqueue, where the gossip loop re-drives it
                        # until it lands — without that retry it would
                        # be lost forever, since the dead member leaves
                        # every ring and its restart is fenced out
                        log(f"shard {self.id}: absorbed prompt {pid} "
                            f"not re-enqueued ({type(e).__name__}: "
                            f"{e}); left pending in {dead_id}'s WAL "
                            f"for retry")
                        failed_reenq[str(pid)] = p
                        continue
                    transferred.append(pid)
                    resumed += 1
                # ownership transfer completes in the DEAD shard's log:
                # close the transferred admissions there (under OUR
                # acquired epoch) so a restart of the dead master can
                # never replay prompts this survivor already took over
                try:
                    closer = dur.WriteAheadLog(ddir, epoch=epoch,
                                               lease=lease,
                                               tracker=replayed)
                    for pid in transferred:
                        closer.append("exec_done", pid=str(pid),
                                      status="absorbed")
                    closer.close()
                except Exception as e:  # noqa: BLE001 - the renewed
                    # lease still blocks a restart while we hold it
                    log(f"shard {self.id}: closing {dead_id}'s "
                        f"transferred prompts failed: {e}")
            else:
                resumed = 0
                failed_reenq = {}
            with self._lock:
                self._members.pop(dead_id, None)
                self._ring = HashRing(self._members, self._vnodes)
                self._ring_epoch += 1
                ring_epoch = self._ring_epoch
                self._peer_seen.pop(dead_id, None)
                self._peer_queue.pop(dead_id, None)
                self._absorbed[dead_id] = {
                    "epoch": epoch,
                    "ring_epoch": ring_epoch,
                    "resumed_prompts": resumed,
                    "recovered_jobs": len(replayed.jobs),
                    "at": self._clock.time(),
                }
                if failed_reenq:
                    self._pending_reenqueue[dead_id] = failed_reenq
            self.takeovers += 1
            trace_mod.GLOBAL_COUNTERS.bump("shard_takeovers")
            trace_mod.GLOBAL_COUNTERS.bump("shard_absorbed_prompts",
                                           resumed)
            self._rescale_admission()
            self._rehome_workers()
            try:
                self.gossip_once()
            except Exception:  # noqa: BLE001 - next round re-gossips
                pass
            log(f"shard {self.id}: absorbed {dead_id} (resumed "
                f"{resumed} prompt(s), ring epoch {ring_epoch})")
            with self._lock:
                return dict(self._absorbed[dead_id])
        finally:
            with self._lock:
                self._absorbing.discard(dead_id)

    def _rehome_workers(self) -> None:
        """Best-effort PR 7-style rehome fan-out (shared helper).
        Sharded workers already heartbeat EVERY master (one lease per
        shard), so this only matters for single-homed legacy workers
        from the config."""
        from comfyui_distributed_tpu.runtime import durable as dur
        st = self._state
        if st is None or st.port is None:
            return
        url = self.member_url(self.id) \
            or f"http://127.0.0.1:{st.port}"
        dur.rehome_workers(url, st.config_path)

    # -- federation reads -----------------------------------------------------

    def peer_queue_depth(self) -> int:
        """Sum of the peers' last-gossiped queue depths — the merged
        half of the autoscaler's federated signal."""
        now = self._clock.monotonic()
        with self._lock:
            return sum(q for sid, q in self._peer_queue.items()
                       if now - self._peer_seen.get(sid, 0)
                       <= self.peer_down_s)

    def live_peer_masters(self) -> int:
        now = self._clock.monotonic()
        with self._lock:
            return sum(1 for sid in self._members
                       if sid != self.id
                       and now - self._peer_seen.get(sid, -1e9)
                       <= self.peer_down_s)

    def is_autoscale_actuator(self) -> bool:
        """True when this master is the ring-designated fleet-autoscale
        actuator: the owner of a fixed sentinel key on the CURRENT
        merged ring.  Every master folds the same gossiped backlog into
        its autoscale signal, so letting each one spawn/retire would
        react N times to ONE backlog; instead exactly one shard
        actuates for the fleet, and the role moves automatically with
        ring membership (a dead actuator's successor inherits the
        sentinel key along with its shard)."""
        if self.deposed:
            return False
        with self._lock:
            return self._ring.owner(C.AUTOSCALE_ACTUATOR_KEY) == self.id

    def snapshot(self) -> Dict[str, Any]:
        now = self._clock.monotonic()
        with self._lock:
            peers = {
                sid: {
                    "url": url,
                    "last_gossip_age_s": (
                        None if sid not in self._peer_seen else
                        round(now - self._peer_seen[sid], 3)),
                    "queue_remaining": self._peer_queue.get(sid),
                    "down": (sid != self.id
                             and now - self._peer_seen.get(sid, -1e9)
                             > self.peer_down_s),
                }
                for sid, url in self._members.items()}
            return {
                "enabled": True,
                "id": self.id,
                "deposed": self.deposed,
                "ring_epoch": self._ring_epoch,
                "vnodes": self._ring.vnodes,
                "members": peers,
                "owned": [self.id] + sorted(self._absorbed),
                "absorbed": dict(self._absorbed),
                "takeovers": self.takeovers,
                "forwards": self.forwards,
                "pending_reenqueue": {
                    sid: sorted(pids) for sid, pids
                    in self._pending_reenqueue.items() if pids},
                "wal_root": self.wal_root,
            }

    def ring_snapshot(self) -> Dict[str, Any]:
        """The ``GET /distributed/ring`` body: everything a client (or
        the stateless router) needs to hash prompt-ids itself."""
        snap = self.snapshot()
        return {
            "enabled": True,
            "self": self.id,
            "ring_epoch": snap["ring_epoch"],
            "vnodes": snap["vnodes"],
            "members": {sid: m["url"]
                        for sid, m in snap["members"].items()},
            "down": [sid for sid, m in snap["members"].items()
                     if m["down"]],
            "owned": snap["owned"],
        }

    @classmethod
    def attach(cls, state, cfg: Optional[Dict[str, Any]] = None,
               start_threads: bool = True) -> Optional["ShardManager"]:
        """Arm the shard plane on a master when ``DTPU_SHARD_ID`` is
        set (``cfg`` lets ServerState pass the config it already
        resolved for the WAL-dir derivation)."""
        cfg = cfg if cfg is not None else shard_config()
        if cfg is None or state.is_worker:
            return None
        return cls(state, cfg["id"], cfg["members"],
                   wal_root=cfg.get("wal_root"),
                   start_threads=start_threads)


# --- the stateless admission router ------------------------------------------

class RouterState:
    """The router's ONLY state: a refreshable cached ring.  Losing it
    costs one re-pull from a seed master — the router holds no queue,
    no WAL, no leases, and any number of replicas can run."""

    def __init__(self, masters: List[str],
                 refresh_s: Optional[float] = None):
        self.seeds = [u.rstrip("/") for u in masters if u.strip()]
        self.refresh_s = _env_float(C.ROUTER_REFRESH_ENV,
                                    C.ROUTER_REFRESH_DEFAULT) \
            if refresh_s is None else float(refresh_s)
        self._lock = threading.Lock()
        self._members: Dict[str, str] = {}     # guarded-by: self._lock
        self._ring: Optional[HashRing] = None  # guarded-by: self._lock
        self._ring_epoch = 0                   # guarded-by: self._lock
        self._fetched_at = 0.0                 # guarded-by: self._lock
        # replica-unique pid salt: any number of stateless router
        # replicas may mint ids concurrently, and a shared
        # "p_<ms>_r<counter>" namespace would collide across them
        import uuid
        self._salt = uuid.uuid4().hex[:8]
        self._counter = itertools.count()
        self.routed = 0
        self.rerouted = 0

    def adopt(self, ring_body: Dict[str, Any]) -> bool:
        members = ring_body.get("members")
        if not isinstance(members, dict) or not members:
            return False
        epoch = int(ring_body.get("ring_epoch", 1) or 1)
        with self._lock:
            if epoch < self._ring_epoch:
                return False
            self._members = {str(k): str(v or "")
                             for k, v in members.items()}
            self._ring = HashRing(self._members,
                                  ring_body.get("vnodes"))
            self._ring_epoch = epoch
            self._fetched_at = time.monotonic()
        return True

    def targets(self) -> List[str]:
        with self._lock:
            urls = [u for u in self._members.values() if u]
        return urls or list(self.seeds)

    def stale(self) -> bool:
        with self._lock:
            return (self._ring is None
                    or time.monotonic() - self._fetched_at
                    > self.refresh_s)

    def route(self, pid: str) -> Optional[tuple]:
        with self._lock:
            if self._ring is None:
                return None
            owner = self._ring.owner(pid)
            return owner, self._members.get(owner, "")

    def new_pid(self) -> str:
        return (f"p_{int(time.time() * 1000)}_r{self._salt}"
                f"_{next(self._counter)}")

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "router": True,
                "ring_epoch": self._ring_epoch,
                "members": dict(self._members),
                "seeds": list(self.seeds),
                "routed": self.routed,
                "rerouted": self.rerouted,
                "ring_age_s": (None if not self._fetched_at else
                               round(time.monotonic()
                                     - self._fetched_at, 3)),
            }


def build_router_app(masters: List[str],
                     refresh_s: Optional[float] = None):
    """aiohttp application for ``cli router``: prompt-id-hash admission
    spreading plus merged multi-shard read views (``cli fleet`` /
    ``cli top`` / ``cli cluster`` pointed at a router URL render the
    whole fleet)."""
    import aiohttp
    from aiohttp import web

    from comfyui_distributed_tpu.utils.net import (
        cleanup_client_session, get_client_session)

    rs = RouterState(masters, refresh_s=refresh_s)
    app = web.Application(client_max_size=512 * 1024 * 1024)
    app["router"] = rs

    async def refresh(force: bool = False) -> bool:
        if not force and not rs.stale():
            return True
        session = await get_client_session()
        for url in rs.targets():
            try:
                async with session.get(
                        f"{url}/distributed/ring",
                        timeout=aiohttp.ClientTimeout(total=3)) as r:
                    if r.status != 200:
                        continue
                    body = await r.json()
                    if body.get("enabled") and rs.adopt(body):
                        return True
            except Exception as e:  # noqa: BLE001 - try the next seed
                debug_log(f"router: ring pull from {url} failed: {e}")
        return False

    async def post_prompt(request):
        data = await request.json()
        if not await refresh():
            return web.json_response(
                {"error": "router: no reachable master with an "
                          "enabled ring"}, status=503)
        pid = str(data.get("prompt_id") or rs.new_pid())
        body = {**data, "prompt_id": pid}
        session = await get_client_session()
        tried = set()
        for attempt in range(2):
            routed = rs.route(pid)
            if routed is None or not routed[1] \
                    or routed[1] in tried:
                break
            owner, url = routed
            tried.add(url)
            try:
                async with session.post(
                        f"{url}/prompt", json=body,
                        timeout=aiohttp.ClientTimeout(
                            total=120)) as r:
                    out = await r.json()
                    rs.routed += 1
                    if isinstance(out, dict):
                        out.setdefault("shard", owner)
                    resp = web.json_response(out, status=r.status)
                    # relay the shard's backpressure hint: a shed
                    # (429) must keep its HTTP-standard Retry-After
                    # across the routing hop
                    ra = r.headers.get("Retry-After")
                    if ra is not None:
                        resp.headers["Retry-After"] = ra
                    return resp
            except Exception as e:  # noqa: BLE001 - owner died: re-pull
                debug_log(f"router: owner {owner} unreachable ({e}); "
                          "refreshing ring")
                rs.rerouted += 1
                await refresh(force=True)
        return web.json_response(
            {"error": f"router: no reachable owner for {pid!r}"},
            status=503)

    async def _fanout_json(path: str) -> Dict[str, Dict[str, Any]]:
        """GET ``path`` on every ring member; {shard: body} for the
        ones that answered."""
        await refresh()
        session = await get_client_session()
        out: Dict[str, Dict[str, Any]] = {}
        snap = rs.snapshot()

        async def hit(sid, url):
            try:
                async with session.get(
                        f"{url}{path}",
                        timeout=aiohttp.ClientTimeout(total=5)) as r:
                    if r.status == 200:
                        body = await r.json()
                        if isinstance(body, dict):
                            out[sid] = body
            except Exception as e:  # noqa: BLE001 - skip dead members
                debug_log(f"router: {path} from {sid} failed: {e}")

        import asyncio
        await asyncio.gather(*(hit(sid, url) for sid, url
                               in snap["members"].items() if url))
        return out

    async def ring(request):
        await refresh()
        return web.json_response(rs.snapshot())

    async def history(request):
        merged: Dict[str, Any] = {}
        for sid, body in (await _fanout_json("/history")).items():
            merged.update(body)
        return web.json_response(merged)

    async def cluster_metrics(request):
        """Merged federated resources: participants keyed
        ``<shard>/<participant>`` so `cli top` renders one fleet-wide
        table."""
        parts: Dict[str, Any] = {}
        ttl = None
        per = await _fanout_json("/distributed/cluster/metrics")
        for sid, body in per.items():
            ttl = body.get("ttl_s", ttl)
            for wid, p in (body.get("participants") or {}).items():
                parts[f"{sid}/{wid}"] = p
        return web.json_response({"participants": parts,
                                  "ttl_s": ttl,
                                  "shards": sorted(per)})

    async def cluster(request):
        """Merged lease/ledger view: workers and jobs keyed per shard;
        scalar policy fields from the first shard that answered."""
        per = await _fanout_json("/distributed/cluster")
        merged: Dict[str, Any] = {"workers": {}, "transitions": [],
                                  "ledger": {"active_jobs": {},
                                             "completed_jobs": []},
                                  "shards": sorted(per)}
        for sid in sorted(per):
            body = per[sid]
            for k in ("policy", "hedge", "lease_s", "suspect_probes"):
                merged.setdefault(k, body.get(k))
            for wid, w in (body.get("workers") or {}).items():
                merged["workers"][f"{sid}/{wid}"] = w
            led = body.get("ledger") or {}
            for jid, j in (led.get("active_jobs") or {}).items():
                merged["ledger"]["active_jobs"][f"{sid}/{jid}"] = j
            merged["ledger"]["completed_jobs"].extend(
                led.get("completed_jobs") or [])
            merged["transitions"].extend(body.get("transitions") or [])
        return web.json_response(merged)

    async def fleet(request):
        """Merged elastic-fleet view: admission counters summed across
        shards, autoscaler blocks nested per shard."""
        per = await _fanout_json("/distributed/fleet")
        adm: Dict[str, Any] = {"per_class": {}, "queued_by_class": {},
                               "classes": None, "drain_rate_per_s": 0.0}
        auto: Dict[str, Any] = {"enabled": False, "shards": {},
                                "scale_ups": 0, "scale_downs": 0,
                                "flaps": 0}
        workers: Dict[str, Any] = {}
        for sid in sorted(per):
            body = per[sid]
            a = body.get("admission") or {}
            adm["classes"] = adm["classes"] or a.get("classes")
            adm.setdefault("default_class", a.get("default_class"))
            adm.setdefault("weights", a.get("weights"))
            adm.setdefault("shed_thresholds", a.get("shed_thresholds"))
            adm["drain_rate_per_s"] = round(
                adm["drain_rate_per_s"]
                + float(a.get("drain_rate_per_s") or 0), 4)
            for cls, v in (a.get("per_class") or {}).items():
                agg = adm["per_class"].setdefault(
                    cls, {k: 0 for k in v})
                for k, n in v.items():
                    agg[k] = agg.get(k, 0) + int(n or 0)
            for cls, n in (a.get("queued_by_class") or {}).items():
                adm["queued_by_class"][cls] = \
                    adm["queued_by_class"].get(cls, 0) + int(n or 0)
            s = body.get("autoscale") or {}
            auto["shards"][sid] = s
            if s.get("enabled"):
                auto["enabled"] = True
                for k in ("scale_ups", "scale_downs", "flaps"):
                    auto[k] += int(s.get(k, 0) or 0)
            for wid, w in (body.get("workers") or {}).items():
                workers[f"{sid}/{wid}"] = w
        return web.json_response({
            "autoscale": auto, "admission": adm, "workers": workers,
            "shards": sorted(per)})

    async def on_cleanup(app):
        await cleanup_client_session()

    app.on_cleanup.append(on_cleanup)
    app.router.add_post("/prompt", post_prompt)
    app.router.add_get("/distributed/ring", ring)
    app.router.add_get("/history", history)
    app.router.add_get("/distributed/cluster/metrics", cluster_metrics)
    app.router.add_get("/distributed/cluster", cluster)
    app.router.add_get("/distributed/fleet", fleet)
    return app
