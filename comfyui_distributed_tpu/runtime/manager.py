"""Worker process manager.

Capability parity with the reference's ``WorkerProcessManager``
(``distributed.py:603-1021``): spawn worker server processes, daily log
files with session headers, PID persistence in the config file,
revive-or-purge on restart, process-tree kill, cleanup-on-exit hooks and
delayed auto-launch.

On TPU a "worker" is not one-process-per-chip (the mesh handles local chips);
managed workers exist for multi-host deployments and CPU staging — each runs
``python -m comfyui_distributed_tpu.cli worker --port N``.
"""

from __future__ import annotations

import atexit
import datetime
import os
import signal
import sys
import threading
from typing import Any, Dict, List, Optional

from comfyui_distributed_tpu.utils import config as cfg_mod
from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils import process as proc
from comfyui_distributed_tpu.utils.constants import WORKER_STARTUP_DELAY
from comfyui_distributed_tpu.utils.logging import debug_log, log

MASTER_PID_ENV = "DTPU_MASTER_PID"

_compile_cache_dir: Optional[str] = None
_compile_cache_lock = threading.Lock()


def enable_persistent_compile_cache(
        cache_dir: Optional[str] = None,
        min_compile_secs: Optional[float] = None,
        default_dir: Optional[str] = None) -> Optional[str]:
    """Turn on JAX's persistent (on-disk) XLA compilation cache.

    Makes compilation a ONE-TIME cost across process restarts: a warm
    cache turns the cold-start SDXL compile into a trace + deserialize.
    Resolution order for the directory: explicit ``cache_dir`` >
    ``DTPU_COMPILE_CACHE_DIR`` env > ``default_dir`` (a caller's
    preferred location — bench/tests pass the repo-local ``.jax_cache``)
    > the default under ``~/.cache``; the values "0"/"off"/"" in the
    env disable the cache entirely.

    The resolved dir is re-exported to ``os.environ`` so workers spawned
    by :class:`WorkerProcessManager` (which inherit the environment)
    share one cache with the master — every participant compiles each
    program at most once per fleet, not once per process.  Idempotent;
    returns the active dir (None when disabled)."""
    global _compile_cache_dir
    with _compile_cache_lock:
        if cache_dir is None:
            cache_dir = os.environ.get(C.COMPILE_CACHE_ENV)
            if cache_dir is not None \
                    and cache_dir.strip().lower() in ("", "0", "off"):
                debug_log("persistent compile cache disabled via env")
                return None
            cache_dir = cache_dir or default_dir \
                or C.COMPILE_CACHE_DEFAULT_DIR
        cache_dir = os.path.abspath(os.path.expanduser(cache_dir))
        if _compile_cache_dir == cache_dir:
            return _compile_cache_dir
        import jax
        try:
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                float(C.COMPILE_CACHE_MIN_COMPILE_SECS
                      if min_compile_secs is None else min_compile_secs))
            # cache every entry that clears the time bar, regardless of
            # serialized size
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
        except Exception as e:  # noqa: BLE001 - cache is an optimization
            log(f"persistent compile cache unavailable: {e!r}")
            return None
        os.environ[C.COMPILE_CACHE_ENV] = cache_dir
        _compile_cache_dir = cache_dir
        log(f"persistent compile cache at {cache_dir}")
        return cache_dir


class WorkerProcessManager:
    """Singleton-ish manager for locally spawned worker processes."""

    def __init__(self, config_path: Optional[str] = None,
                 log_dir: Optional[str] = None,
                 models_dir: Optional[str] = None):
        self.config_path = config_path
        self.models_dir = models_dir
        self.log_dir = log_dir or os.path.join(os.getcwd(), "logs", "workers")
        self.processes: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self.load_processes()

    # --- launch (reference launch_worker :667, build_launch_command :644) --

    def build_launch_command(self, worker: Dict[str, Any]) -> List[str]:
        cmd = [proc.get_python_executable(), "-m",
               "comfyui_distributed_tpu.cli", "worker",
               "--port", str(worker["port"])]
        if self.config_path:
            cmd.extend(["--config", self.config_path])
        if self.models_dir:
            cmd.extend(["--models-dir", self.models_dir])
        extra = worker.get("extra_args")
        if extra:
            cmd.extend(str(extra).split())
        return cmd

    def _log_file(self, name: str) -> str:
        os.makedirs(self.log_dir, exist_ok=True)
        day = datetime.date.today().strftime("%Y%m%d")
        return os.path.join(self.log_dir, f"{name}_{day}.log")

    def launch_worker(self, worker: Dict[str, Any],
                      stop_on_master_exit: bool = True) -> Dict[str, Any]:
        wid = str(worker["id"])
        with self._lock:
            existing = self.processes.get(wid)
            if existing and (existing.get("pid") is None  # launch in flight
                             or proc.is_process_alive(existing.get("pid", -1))):
                raise RuntimeError(
                    f"worker {wid} already running (pid {existing['pid']})")
            # reserve the slot before releasing the lock so a concurrent
            # launch (auto-launch timer vs HTTP endpoint) can't double-spawn
            self.processes[wid] = {"pid": None, "launching": True}

        try:
            env = dict(os.environ)
            env[MASTER_PID_ENV] = str(os.getpid())
            # cluster identity: the spawned worker heartbeats its lease
            # back to this master (runtime/cluster.maybe_start_heartbeat)
            from comfyui_distributed_tpu.utils import constants as C
            env[C.WORKER_ID_ENV] = wid
            if C.MASTER_URL_ENV not in env:
                try:
                    from comfyui_distributed_tpu.utils import config \
                        as cfg_mod
                    master = cfg_mod.load_config(
                        self.config_path).get("master", {})
                    if master.get("port"):
                        env[C.MASTER_URL_ENV] = (
                            f"http://{master.get('host') or '127.0.0.1'}"
                            f":{master['port']}")
                except Exception:  # noqa: BLE001 - heartbeat is optional
                    pass
            # never inherit the master's pod-cluster identity: a managed
            # HTTP worker is its own single-process jax world, and a
            # duplicate jax.distributed.initialize with the master's
            # process_id would error/block inside the child's CLI boot
            for k in ("DTPU_COORDINATOR", "DTPU_NUM_PROCESSES",
                      "DTPU_PROCESS_ID"):
                env.pop(k, None)
            # serve-path mesh layout (ISSUE 16): the worker inherits
            # DTPU_TP / DTPU_MESH_SHAPE — resolve them HERE so a
            # malformed layout fails THIS launch with a clear error
            # instead of crashing every spawned worker at mesh build,
            # and the launch log records the fleet's layout
            if env.get(C.TP_ENV) or env.get(C.MESH_SHAPE_ENV):
                from comfyui_distributed_tpu.parallel.mesh import \
                    axes_from_env
                tp_axes = axes_from_env()
                if tp_axes is not None:
                    log(f"worker {wid}: serve-path mesh layout "
                        f"{tp_axes} (inherited)")
            # continuous-batching knobs (ISSUE 17, same fail-fast
            # pattern): a malformed DTPU_CB_SLOTS / DTPU_CB_PARK* value
            # dies at THIS launch with the knob named, instead of
            # poisoning the spawned worker's driver thread at its first
            # admission
            if env.get(C.CB_ENV) or env.get(C.CB_PARK_ENV) \
                    or env.get(C.CB_SLOTS_ENV) \
                    or env.get(C.CB_PARK_MAX_ENV) \
                    or env.get(C.CB_PARK_HBM_FRACTION_ENV):
                from comfyui_distributed_tpu.workflow.batch_executor \
                    import validate_cb_env
                validate_cb_env(env)
                if env.get(C.CB_PARK_ENV):
                    log(f"worker {wid}: continuous batching with "
                        f"latent paging "
                        f"({C.CB_PARK_ENV}={env[C.CB_PARK_ENV]}, "
                        f"max parked="
                        f"{env.get(C.CB_PARK_MAX_ENV) or C.CB_PARK_MAX_DEFAULT})")
            cmd = self.build_launch_command(worker)
            if stop_on_master_exit:
                # wrap with the master-death monitor (reference
                # worker_monitor.py)
                cmd = [proc.get_python_executable(), "-m",
                       "comfyui_distributed_tpu.runtime.monitor",
                       "--master-pid", str(os.getpid()), "--"] + cmd

            log_path = self._log_file(worker.get("name", wid))
            logf = open(log_path, "a", encoding="utf-8")
            try:
                logf.write(f"\n=== session "
                           f"{datetime.datetime.now().isoformat()} "
                           f"cmd={' '.join(cmd)} ===\n")
                logf.flush()
                p = proc.popen_detached(cmd, env=env, stdout=logf,
                                        stderr=logf)
            finally:
                # the child inherited the fd; keeping ours open would leak
                # one per launch across restart cycles
                logf.close()
        except BaseException:
            with self._lock:  # roll back the reservation
                self.processes.pop(wid, None)
            raise
        entry = {
            "pid": p.pid,
            "process": p,
            "log_file": log_path,
            "started_at": datetime.datetime.now().isoformat(),
            "config": {k: v for k, v in worker.items() if k != "process"},
            "launching": True,
        }
        with self._lock:
            if wid not in self.processes:
                # stop_worker popped our reservation mid-launch: honor the
                # stop — kill the just-spawned process instead of tracking it
                proc.kill_process_tree(p.pid)
                raise RuntimeError(f"worker {wid} stopped during launch")
            self.processes[wid] = entry
        self.save_processes()
        log(f"launched worker {wid} (pid {p.pid}, port {worker['port']}, "
            f"log {log_path})")
        return {k: v for k, v in entry.items() if k != "process"}

    # --- stop (reference stop_worker :768) ---------------------------------

    def stop_worker(self, worker_id: str) -> bool:
        wid = str(worker_id)
        with self._lock:
            entry = self.processes.pop(wid, None)
        if entry is None:
            return False
        pid = entry.get("pid")
        ok = proc.kill_process_tree(pid) if pid else True
        self.save_processes()
        log(f"stopped worker {wid} (pid {pid})")
        return ok

    def clear_launching(self, worker_id: str) -> None:
        with self._lock:
            if str(worker_id) in self.processes:
                self.processes[str(worker_id)]["launching"] = False

    def get_managed_workers(self) -> Dict[str, Dict[str, Any]]:
        """Liveness-annotated snapshot (reference ``get_managed_workers
        :828``)."""
        out = {}
        with self._lock:
            items = list(self.processes.items())
        for wid, entry in items:
            out[wid] = {
                "pid": entry.get("pid"),
                "alive": proc.is_process_alive(entry.get("pid", -1)),
                "launching": entry.get("launching", False),
                "started_at": entry.get("started_at"),
                "log_file": entry.get("log_file"),
                "config": entry.get("config", {}),
            }
        return out

    def cleanup_all(self) -> None:
        """Stop every managed worker (reference ``cleanup_all :848``)."""
        with self._lock:
            wids = list(self.processes)
        for wid in wids:
            self.stop_worker(wid)

    # --- persistence (reference load/save_processes :861-904) --------------

    def load_processes(self) -> None:
        cfg = cfg_mod.load_config(self.config_path)
        managed = cfg.get("managed_processes", {}) or {}
        revived, purged = 0, 0
        with self._lock:
            for wid, entry in managed.items():
                pid = entry.get("pid")
                if pid and proc.is_process_alive(pid):
                    self.processes[str(wid)] = dict(entry)
                    revived += 1
                else:
                    purged += 1
        if revived or purged:
            log(f"managed workers: revived {revived}, purged {purged} stale")
        if purged:
            self.save_processes()

    def save_processes(self) -> None:
        with self._lock:
            snapshot = {
                wid: {k: v for k, v in entry.items() if k != "process"}
                for wid, entry in self.processes.items()
            }

        def mutate(cfg):
            cfg["managed_processes"] = snapshot

        # atomic RMW: a stale full-config write here would clobber worker
        # edits made concurrently through the HTTP config endpoints
        cfg_mod.mutate_config(mutate, self.config_path)

    # --- log tail (reference get_worker_log_endpoint :525-599) -------------

    def tail_log(self, worker_id: str, max_bytes: int = 65536) -> str:
        with self._lock:
            entry = self.processes.get(str(worker_id))
        path = entry.get("log_file") if entry else None
        if not path or not os.path.exists(path):
            raise FileNotFoundError(f"no log for worker {worker_id}")
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            f.seek(max(0, size - max_bytes))
            return f.read().decode("utf-8", errors="replace")


_manager: Optional[WorkerProcessManager] = None
_manager_lock = threading.Lock()


def get_manager() -> WorkerProcessManager:
    global _manager
    with _manager_lock:
        if _manager is None:
            _manager = WorkerProcessManager()
        return _manager


def auto_launch_workers(manager: WorkerProcessManager,
                        delay: float = WORKER_STARTUP_DELAY) -> threading.Timer:
    """Delayed auto-launch of enabled local workers (reference
    ``delayed_auto_launch``/``auto_launch_workers``,
    ``distributed.py:1024-1092``).  Skips remote workers and ones already
    running; returns the timer so callers/tests can cancel it."""

    def run():
        cfg = cfg_mod.load_config(manager.config_path)
        if not cfg["settings"].get("auto_launch_workers"):
            return
        for w in cfg_mod.enabled_workers(cfg):
            if w.get("host") not in (None, "", "localhost", "127.0.0.1"):
                continue  # remote workers are never auto-launched
            wid = str(w["id"])
            entry = manager.processes.get(wid)
            if entry and proc.is_process_alive(entry.get("pid", -1)):
                continue
            try:
                manager.launch_worker(
                    w, stop_on_master_exit=cfg["settings"].get(
                        "stop_workers_on_master_exit", True))
            except RuntimeError as e:
                debug_log(f"auto-launch {wid}: {e}")

    t = threading.Timer(delay, run)
    t.daemon = True
    t.start()
    return t


def install_exit_hooks(manager: WorkerProcessManager) -> None:
    """atexit + signal handlers stopping managed workers when the master
    exits (reference ``cleanup_on_exit`` + handlers,
    ``distributed.py:1097-1123``)."""

    def cleanup(*_a):
        cfg = cfg_mod.load_config(manager.config_path)
        if cfg["settings"].get("stop_workers_on_master_exit", True):
            manager.cleanup_all()

    atexit.register(cleanup)
    for sig in (signal.SIGINT, signal.SIGTERM, signal.SIGHUP):
        try:
            prev = signal.getsignal(sig)
            if prev == signal.SIG_IGN:
                # previously ignored (e.g. SIGHUP under nohup): installing a
                # dying handler would defeat the ignore — leave it alone
                continue

            def handler(signum, frame, _prev=prev):
                cleanup()
                if callable(_prev):
                    _prev(signum, frame)
                else:  # SIG_DFL: mimic default termination
                    sys.exit(128 + signum)

            signal.signal(sig, handler)
        except (ValueError, OSError):  # non-main thread
            pass
