"""Worker health poller.

The reference's browser polls every worker's ``GET /prompt`` every 2 s to
drive the status dots and clear the 'launching' state
(``/root/reference/web/gpupanel.js:1233-1311``).  Headless equivalent: a
daemon thread on the master polling enabled workers, deriving
online / processing / offline from reachability and queue depth, feeding
``GET /distributed/workers_status``.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from comfyui_distributed_tpu.utils import config as cfg_mod
from comfyui_distributed_tpu.utils.constants import WORKER_CHECK_INTERVAL
from comfyui_distributed_tpu.utils.logging import debug_log


def probe_worker(worker: Dict[str, Any], timeout: float = 2.0) -> Dict[str, Any]:
    """One status probe — reference ``checkWorkerStatus`` semantics
    (``gpupanel.js:1249-1311``): offline on error, processing when
    ``queue_remaining > 0``."""
    host = worker.get("host") or "127.0.0.1"
    url = f"http://{host}:{worker['port']}/prompt"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            data = json.loads(r.read())
        remaining = int(data.get("exec_info", {}).get("queue_remaining", 0))
        return {"status": "processing" if remaining > 0 else "online",
                "queue_remaining": remaining, "last_seen": time.time()}
    except (urllib.error.URLError, OSError, ValueError, TimeoutError):
        return {"status": "offline", "queue_remaining": None,
                "last_seen": None}


class HealthPoller:
    """Daemon polling thread + status snapshot store."""

    def __init__(self, config_path: Optional[str] = None, manager=None,
                 interval: float = WORKER_CHECK_INTERVAL,
                 registry=None):
        self.config_path = config_path
        self.manager = manager
        # cluster control plane (runtime/cluster.py): every probe result
        # feeds the worker registry's lease state machine
        self.registry = registry
        self.interval = interval
        self._status: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dtpu-health")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 - poller must survive
                debug_log(f"health poll error: {e}")

    def poll_once(self) -> Dict[str, Dict[str, Any]]:
        cfg = cfg_mod.load_config(self.config_path)
        workers: List[Dict[str, Any]] = cfg.get("workers", [])
        snapshot: Dict[str, Dict[str, Any]] = {}
        for w in workers:
            wid = str(w.get("id"))
            st = probe_worker(w) if w.get("enabled") else {
                "status": "disabled", "queue_remaining": None,
                "last_seen": None}
            st["enabled"] = bool(w.get("enabled"))
            snapshot[wid] = st
            if self.registry is not None and w.get("enabled"):
                self.registry.observe_probe(
                    wid, st["status"] in ("online", "processing"),
                    info={"host": w.get("host") or "127.0.0.1",
                          "port": w.get("port"), "name": w.get("name"),
                          "queue_remaining": st.get("queue_remaining")})
            # first successful contact clears 'launching' (reference
            # gpupanel.js:1286-1293 -> clear_launching endpoint)
            if st["status"] in ("online", "processing") \
                    and self.manager is not None:
                self.manager.clear_launching(wid)
        with self._lock:
            self._status = snapshot
        return snapshot

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {k: dict(v) for k, v in self._status.items()}
