"""Elastic-fleet autoscaler (ISSUE 9).

The MapReduce lesson (PAPERS.md): a master that owns a work ledger and
lease-based worker liveness can treat the worker pool itself as
elastic — workers join by registration, leave by lease expiry, and the
ledger reassigns whatever a leaver still owed.  This module closes the
loop: a reconciliation thread on the master reads the fleet's
*telemetry* (federated queue depth from the registry + the PR 5
utilization estimate), compares it against thresholds, and spawns or
retires workers.

Convergence over reactivity — every decision passes three gates:

- **sustained window**: a signal must sit beyond its threshold for
  ``DTPU_AUTOSCALE_WINDOW`` *consecutive* samples (one noisy scrape
  never scales anything);
- **hysteresis**: the scale-down bars sit strictly below the scale-up
  bars, so a signal oscillating between them does nothing;
- **cooldown**: after any action the loop holds ``DTPU_AUTOSCALE_
  COOLDOWN_S`` before the next one, giving the previous action time to
  move the signal.

Scale-up spawns through an injectable ``spawner`` (default: the
process manager launches a local worker on a free port and registers
it in the config so dispatch sees it).  Scale-down is *drain by lease
non-renewal*: mark the victim RETIRING in the registry (the dispatcher
stops handing it new work), wait for its queue to empty, then stop the
process — its lease simply never renews again, the registry ages it to
DEAD, and any unit it still owed is reassigned by the ledger exactly
once (the PR 7 WAL makes that safe even across a master crash
mid-retirement).

Every decision lands in a bounded ring (``GET /distributed/fleet``,
``cli fleet``) and bumps ``autoscale_*`` counters on both metrics
surfaces; a direction reversal inside ``AUTOSCALE_FLAP_S`` of the
previous action is counted as a **flap** — the oscillation failure the
overload bench asserts is zero.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from comfyui_distributed_tpu.utils import clock as clock_mod
from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils import trace as trace_mod
from comfyui_distributed_tpu.utils.logging import debug_log, log


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return int(default)


def autoscale_armed() -> bool:
    return os.environ.get(C.AUTOSCALE_ENV, "0").lower() \
        in ("1", "true", "on")


class FleetAutoscaler:
    """Telemetry-driven reconciliation loop.

    ``queue_depth_fn`` returns the MASTER's queued+running prompt count;
    the worker half of the federated depth comes from the registry's
    heartbeat-carried ``queue_remaining`` info.  ``util_fn`` returns the
    fleet utilization estimate in [0, 1] (or None when telemetry is
    off).  ``spawner()`` must start one worker and return its id (or
    None on failure); ``retirer(worker_id)`` must stop the named
    worker's process once the drain decided it is idle.  Both are
    injectable so tests and the loopback bench scale real in-process
    workers without subprocesses."""

    def __init__(self,
                 registry,
                 queue_depth_fn: Callable[[], int],
                 util_fn: Optional[Callable[[], Optional[float]]] = None,
                 spawner: Optional[Callable[[], Optional[str]]] = None,
                 retirer: Optional[Callable[[str], bool]] = None,
                 worker_queue_fn: Optional[Callable[[str], Optional[int]]]
                 = None,
                 min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 up_queue: Optional[float] = None,
                 down_queue: Optional[float] = None,
                 up_util: Optional[float] = None,
                 down_util: Optional[float] = None,
                 window: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 drain_s: Optional[float] = None,
                 flap_window_s: Optional[float] = None,
                 shard: Optional[Any] = None,
                 parked_backlog_fn: Optional[Callable[[], int]] = None,
                 slo_burn_fn: Optional[Callable[[], Optional[float]]]
                 = None,
                 clock: Optional[Any] = None):
        # clock seam (ISSUE 19): cooldowns, drain deadlines and decision
        # timestamps run off this; the wall default is the old behavior
        self._clock = clock if clock is not None else clock_mod.WALL
        self.registry = registry
        self.queue_depth_fn = queue_depth_fn
        self.util_fn = util_fn
        self.spawner = spawner
        self.retirer = retirer
        self.worker_queue_fn = worker_queue_fn
        # latent paging (ISSUE 17): parked continuous-batching rows are
        # ADMITTED work the fleet has not finished — invisible to the
        # queue-depth probe (they left the queue at admission) but real
        # backlog, so they fold into the scale-up signal
        self.parked_backlog_fn = parked_backlog_fn
        # SLO burn-rate fold-in (ISSUE 18, DTPU_AUTOSCALE_SLO=1): the
        # paid class burning its fast-window budget is scale-up pressure
        # even when the queue looks shallow — latency violations don't
        # queue, they finish late
        self.slo_burn_fn = slo_burn_fn
        # multi-master federation (ISSUE 14): the ShardManager (or None)
        # — its gossiped peer queue depths fold into the signal, so each
        # shard's reconciliation sees the MERGED fleet pressure instead
        # of only its own slice
        self.shard = shard
        self.min_workers = _env_int(C.AUTOSCALE_MIN_ENV,
                                    C.AUTOSCALE_MIN_DEFAULT) \
            if min_workers is None else int(min_workers)
        self.max_workers = _env_int(C.AUTOSCALE_MAX_ENV,
                                    C.AUTOSCALE_MAX_DEFAULT) \
            if max_workers is None else int(max_workers)
        self.up_queue = _env_float(C.AUTOSCALE_UP_QUEUE_ENV,
                                   C.AUTOSCALE_UP_QUEUE_DEFAULT) \
            if up_queue is None else float(up_queue)
        self.down_queue = _env_float(C.AUTOSCALE_DOWN_QUEUE_ENV,
                                     C.AUTOSCALE_DOWN_QUEUE_DEFAULT) \
            if down_queue is None else float(down_queue)
        self.up_util = _env_float(C.AUTOSCALE_UP_UTIL_ENV,
                                  C.AUTOSCALE_UP_UTIL_DEFAULT) \
            if up_util is None else float(up_util)
        self.down_util = _env_float(C.AUTOSCALE_DOWN_UTIL_ENV,
                                    C.AUTOSCALE_DOWN_UTIL_DEFAULT) \
            if down_util is None else float(down_util)
        self.window = max(_env_int(C.AUTOSCALE_WINDOW_ENV,
                                   C.AUTOSCALE_WINDOW_DEFAULT)
                          if window is None else int(window), 1)
        self.cooldown_s = _env_float(C.AUTOSCALE_COOLDOWN_ENV,
                                     C.AUTOSCALE_COOLDOWN_DEFAULT) \
            if cooldown_s is None else float(cooldown_s)
        self.interval_s = max(
            _env_float(C.AUTOSCALE_INTERVAL_ENV,
                       C.AUTOSCALE_INTERVAL_DEFAULT)
            if interval_s is None else float(interval_s), 0.02)
        self.drain_s = _env_float(C.AUTOSCALE_DRAIN_ENV,
                                  C.AUTOSCALE_DRAIN_DEFAULT) \
            if drain_s is None else float(drain_s)
        # a reversal is only a FLAP when it lands before the previous
        # action could have moved the signal — i.e. within ~2 cooldowns;
        # scaled to the configured loop tempo, capped by the constant so
        # production cooldowns don't make every reversal a flap
        self.flap_window_s = min(2.0 * self.cooldown_s,
                                 C.AUTOSCALE_FLAP_S) \
            if flap_window_s is None else float(flap_window_s)
        # Decision state is shared between the reconciliation thread and
        # the HTTP handlers' snapshot()/fleet route (the PR 9
        # forced-retirement bug lived exactly in this interplay), so
        # every field below is lock-guarded — and the lockset rule
        # enforces it from the annotations.
        # sustained-window counters (consecutive samples beyond bar)
        self._over_streak = 0                     # guarded-by: self._lock
        self._under_streak = 0                    # guarded-by: self._lock
        self._last_action: Optional[str] = None   # guarded-by: self._lock
        self._last_action_t: Optional[float] = None  # guarded-by: self._lock
        self._spawned: List[str] = []             # guarded-by: self._lock
        self._retiring: Dict[str, float] = {}     # guarded-by: self._lock
        self.decisions: deque = deque(
            maxlen=C.AUTOSCALE_DECISIONS_KEPT)    # guarded-by: self._lock
        self.flaps = 0                            # guarded-by: self._lock
        self.scale_ups = 0                        # guarded-by: self._lock
        self.scale_downs = 0                      # guarded-by: self._lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signal ---------------------------------------------------------------

    def fleet_signal(self) -> Dict[str, Any]:
        """One federated sample: master queue depth + every live
        worker's heartbeat-reported ``queue_remaining``, normalized per
        participant, plus the utilization estimate."""
        from comfyui_distributed_tpu.runtime import cluster as cl
        master_q = 0
        try:
            master_q = int(self.queue_depth_fn() or 0)
        except Exception as e:  # noqa: BLE001 - signal must not kill loop
            debug_log(f"autoscale: queue probe failed: {e}")
        worker_q = 0
        live = 0
        snap = self.registry.snapshot()["workers"] \
            if self.registry is not None else {}
        for wid, w in snap.items():
            if w["state"] in (cl.HEALTHY, cl.SUSPECT, cl.RETIRING):
                live += 1
                q = self._worker_queue(wid, registry_hint=w)
                worker_q += int(q or 0)
        util = None
        if self.util_fn is not None:
            try:
                util = self.util_fn()
            except Exception as e:  # noqa: BLE001
                debug_log(f"autoscale: util probe failed: {e}")
        # multi-master federation: peer masters' gossiped queue depths
        # (each already includes THAT shard's worker backlog view only
        # for its own queue — workers are shared, so their heartbeat
        # backlog is counted once, here) merge into one fleet signal
        peer_q = 0
        peer_masters = 0
        if self.shard is not None:
            try:
                peer_q = int(self.shard.peer_queue_depth())
                peer_masters = int(self.shard.live_peer_masters())
            except Exception as e:  # noqa: BLE001 - signal survives
                debug_log(f"autoscale: shard signal failed: {e}")
        # parked backlog (ISSUE 17): rows paged out of their CB slot
        # wait on RESIDENCY, not on a queue — scale-up pressure all the
        # same (an extra participant is exactly what would let them run)
        parked = 0
        if self.parked_backlog_fn is not None:
            try:
                parked = int(self.parked_backlog_fn() or 0)
            except Exception as e:  # noqa: BLE001 - signal survives
                debug_log(f"autoscale: parked probe failed: {e}")
        slo_burn = None
        if self.slo_burn_fn is not None:
            try:
                slo_burn = self.slo_burn_fn()
            except Exception as e:  # noqa: BLE001 - signal survives
                debug_log(f"autoscale: slo probe failed: {e}")
        participants = 1 + live + peer_masters   # masters serve too
        depth = master_q + worker_q + peer_q + parked
        out = {
            "queue_depth": depth,
            "queue_per_participant": depth / participants,
            "utilization": util,
            "live_workers": live,
            "participants": participants,
        }
        if parked:
            out["parked_backlog"] = parked
        if slo_burn is not None:
            out["slo_burn"] = round(float(slo_burn), 4)
        if self.shard is not None:
            out["peer_masters"] = peer_masters
            out["peer_queue_depth"] = peer_q
        return out

    # -- decision -------------------------------------------------------------

    def _record(self, action: str, reason: str, now: float,
                signal: Dict[str, Any],
                worker_id: Optional[str] = None) -> None:
        entry = {"t": self._clock.time(), "action": action,
                 "reason": reason,
                 "worker_id": worker_id,
                 "queue_per_participant": round(
                     signal.get("queue_per_participant", 0.0), 3),
                 "utilization": signal.get("utilization"),
                 "live_workers": signal.get("live_workers")}
        # decide-and-mutate under ONE lock hold (a snapshot() landing
        # between the flap check and the last-action update used to be
        # able to read torn decision state); logging/counters happen
        # after release — they have their own locks
        flap_delta: Optional[float] = None
        with self._lock:
            self.decisions.append(entry)
            if action in ("up", "down"):
                prev, prev_t = self._last_action, self._last_action_t
                if prev is not None and prev != action \
                        and prev_t is not None \
                        and now - prev_t < self.flap_window_s:
                    self.flaps += 1
                    flap_delta = now - prev_t
                self._last_action, self._last_action_t = action, now
        if action in ("up", "down"):
            if flap_delta is not None:
                trace_mod.GLOBAL_COUNTERS.bump("autoscale_flaps")
                log(f"autoscale: FLAP — {action} within "
                    f"{flap_delta:.1f}s of the previous action "
                    f"(hysteresis/window too tight for this workload)")
            trace_mod.GLOBAL_COUNTERS.bump(f"autoscale_{action}")
            log(f"autoscale: scale {action} ({reason})"
                + (f" worker={worker_id}" if worker_id else ""))

    def _in_cooldown(self, now: float) -> bool:
        with self._lock:
            return (self._last_action_t is not None
                    and now - self._last_action_t < self.cooldown_s)

    def sample_once(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One reconciliation step (thread-free — tests drive this
        directly with a fake clock).  Returns the sample + the action
        taken ("up"/"down"/"retire_done"/None)."""
        now = self._clock.monotonic() if now is None else now
        signal = self.fleet_signal()
        # finish in-flight retirements first (their drain is async)
        action = self._reap_retiring(now)
        qpp = signal["queue_per_participant"]
        util = signal["utilization"]
        slo_burn = signal.get("slo_burn")
        slo_hot = slo_burn is not None and slo_burn > 1.0
        over = qpp > self.up_queue or (util is not None
                                       and util > self.up_util) \
            or slo_hot
        under = qpp < self.down_queue and (util is None
                                           or util < self.down_util)
        # streaks + readiness decided under the lock (the HTTP
        # snapshot() and a test-driven sample_once may interleave with
        # the loop thread); the spawner/retirer — subprocess + registry
        # I/O — runs OUTSIDE it
        with self._lock:
            self._over_streak = self._over_streak + 1 if over else 0
            self._under_streak = self._under_streak + 1 if under else 0
            over_ready = over and self._over_streak >= self.window
            under_ready = under and self._under_streak >= self.window
        if self._in_cooldown(now):
            return {**signal, "action": action, "cooldown": True}
        # federated actuation (ISSUE 14): every sharded master folds
        # the same gossiped backlog into its signal, so N independent
        # actuators would spawn/retire N times for ONE backlog (and
        # amplify the very flap the hysteresis damps).  The ring
        # designates exactly one actuator; the others keep sampling —
        # and reaping their own in-flight retirements, above — but
        # defer new scale actions to the designated shard.
        if self.shard is not None:
            try:
                actuator = bool(self.shard.is_autoscale_actuator())
            except Exception:  # noqa: BLE001 - fail open: act alone
                actuator = True
            if not actuator:
                return {**signal, "action": action, "cooldown": False,
                        "actuator": False}
        live = signal["live_workers"]
        if over_ready and live < self.max_workers \
                and self.spawner is not None:
            wid = None
            try:
                wid = self.spawner()
            except Exception as e:  # noqa: BLE001 - spawn must not kill loop
                log(f"autoscale: spawn failed: {type(e).__name__}: {e}")
            if wid:
                with self._lock:
                    self._spawned.append(str(wid))
                    self.scale_ups += 1
                    self._over_streak = 0
                if qpp > self.up_queue:
                    reason = (f"queue/participant {qpp:.2f} > "
                              f"{self.up_queue:g}")
                elif util is not None and util > self.up_util:
                    reason = (f"utilization {util:.2f} > "
                              f"{self.up_util:g}")
                else:
                    reason = (f"paid SLO burn rate {slo_burn:.2f} > 1 "
                              f"(fast window)")
                self._record("up", reason, now, signal, wid)
                action = "up"
        elif under_ready and live > self.min_workers \
                and self.retirer is not None:
            wid = self._pick_retirement_victim()
            if wid is not None:
                if self.registry is not None:
                    self.registry.set_retiring(wid, True)
                with self._lock:
                    self.scale_downs += 1
                    self._retiring[wid] = now + self.drain_s
                    self._under_streak = 0
                self._record(
                    "down",
                    f"queue/participant {qpp:.2f} < "
                    f"{self.down_queue:g} (drain via lease non-renewal)",
                    now, signal, wid)
                action = "down"
        return {**signal, "action": action, "cooldown": False}

    def _pick_retirement_victim(self) -> Optional[str]:
        """LIFO over the workers this loop spawned (the fixed config
        fleet is never autoscaled away), skipping ones already
        retiring."""
        with self._lock:
            for wid in reversed(self._spawned):
                if wid not in self._retiring:
                    return wid
        return None

    def _worker_queue(self, wid: str,
                      registry_hint: Optional[Dict[str, Any]] = None
                      ) -> Optional[int]:
        """A worker's queued-prompt count: the injected probe when it
        knows this worker (tests/bench reach the in-process state
        directly), else the registry's heartbeat/health-carried
        value."""
        if self.worker_queue_fn is not None:
            try:
                q = self.worker_queue_fn(wid)
                if q is not None:
                    return q
            except Exception:  # noqa: BLE001 - unknown, not zero
                pass
        w = registry_hint
        if w is None and self.registry is not None:
            w = self.registry.snapshot()["workers"].get(wid)
        return None if w is None else w.get("queue_remaining")

    def _reap_retiring(self, now: float) -> Optional[str]:
        """Retirement completion: once a retiring worker's queue reads
        empty (or its drain deadline passed — the ledger will reassign
        whatever it still owed), stop its process and let the lease
        age out.  An UNKNOWN queue waits for the deadline: retiring is
        reversible until the process stops, so err toward patience."""
        with self._lock:
            pending = list(self._retiring.items())
        finished = None
        for wid, deadline in pending:
            q = self._worker_queue(wid)
            if not (q == 0 or now >= deadline):
                continue
            forced = q not in (0, None)
            try:
                if self.retirer is not None:
                    self.retirer(wid)
            except Exception as e:  # noqa: BLE001
                log(f"autoscale: retire of {wid} failed: {e}")
            with self._lock:
                self._retiring.pop(wid, None)
                if wid in self._spawned:
                    self._spawned.remove(wid)
            if self.registry is not None and not forced:
                # drained clean: nothing in flight references this
                # worker, so drop the tombstone.  A FORCED stop must
                # keep the record — the drain loops detect lost owners
                # via registry.state()==DEAD after the lease ages out,
                # and forgetting the id now would read UNKNOWN forever,
                # skipping the immediate ledger reassignment (and the
                # DTPU_FAULT_POLICY=fail escalation) for whatever the
                # worker still owed.
                self.registry.forget(wid)
            trace_mod.GLOBAL_COUNTERS.bump("autoscale_retired")
            debug_log(f"autoscale: worker {wid} retired"
                      + (" (drain deadline; lease will age to DEAD and "
                         "the ledger reassigns the remainder)"
                         if forced else " (drained clean)"))
            finished = "retire_done"
        return finished

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.sample_once()
                except Exception as e:  # noqa: BLE001 - loop survives
                    log(f"autoscale: reconcile error: "
                        f"{type(e).__name__}: {e}")

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="dtpu-autoscale")
        self._thread.start()
        log(f"autoscale: armed (interval {self.interval_s:g}s, window "
            f"{self.window} samples, up>{self.up_queue:g} q/p or "
            f">{self.up_util:g} util, down<{self.down_queue:g} q/p, "
            f"cooldown {self.cooldown_s:g}s, workers "
            f"[{self.min_workers}, {self.max_workers}])")

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2 * self.interval_s + 1.0)
        self._thread = None

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": True,
                "running": self._thread is not None
                and self._thread.is_alive(),
                "interval_s": self.interval_s,
                "window": self.window,
                "cooldown_s": self.cooldown_s,
                "thresholds": {
                    "up_queue_per_participant": self.up_queue,
                    "down_queue_per_participant": self.down_queue,
                    "up_utilization": self.up_util,
                    "down_utilization": self.down_util,
                },
                "bounds": {"min_workers": self.min_workers,
                           "max_workers": self.max_workers},
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "flaps": self.flaps,
                "spawned": list(self._spawned),
                "retiring": sorted(self._retiring),
                "decisions": list(self.decisions),
            }


def default_spawner(state) -> Callable[[], Optional[str]]:
    """The production spawner: add an ``auto_N`` worker on a free port
    to the config and launch it through the process manager (it
    inherits DTPU_MASTER_URL/DTPU_WORKER_ID, so it heartbeats its lease
    back here and dispatch picks it up on the next fan-out)."""
    from comfyui_distributed_tpu.utils import config as cfg_mod
    from comfyui_distributed_tpu.utils.net import find_free_port
    counter = {"n": 0}

    def spawn() -> Optional[str]:
        counter["n"] += 1
        wid = f"auto_{int(time.time())}_{counter['n']}"
        worker = {"id": wid, "name": wid, "host": "127.0.0.1",
                  "port": find_free_port(), "enabled": True}
        cfg_mod.mutate_config(
            lambda cfg: cfg.setdefault("workers", []).append(worker),
            state.config_path)
        state.manager.launch_worker(worker)
        return wid

    return spawn


def default_retirer(state) -> Callable[[str], bool]:
    """The production retirer: stop the managed process and drop the
    worker from the config (the registry ages the lease out on its
    own)."""
    from comfyui_distributed_tpu.utils import config as cfg_mod

    def retire(worker_id: str) -> bool:
        ok = state.manager.stop_worker(worker_id)
        try:
            cfg_mod.mutate_config(
                lambda cfg: cfg_mod.delete_worker(cfg, str(worker_id)),
                state.config_path)
        except Exception as e:  # noqa: BLE001 - config cleanup best-effort
            debug_log(f"autoscale: config cleanup of {worker_id}: {e}")
        return ok

    return retire


def install(state) -> Optional[FleetAutoscaler]:
    """Arm the autoscaler for a master ``state`` when DTPU_AUTOSCALE=1:
    federated queue signal from the ServerState + registry, utilization
    from the resource monitor, spawn/retire through the process
    manager.  Returns None when unarmed (the default)."""
    if not autoscale_armed():
        return None
    from comfyui_distributed_tpu.utils import resource as resource_mod

    def util() -> Optional[float]:
        snap = resource_mod.fleet_sample()
        u = snap.get("utilization")
        return float(u) if isinstance(u, (int, float)) else None

    cb = getattr(state, "cb", None)
    # SLO fold-in (ISSUE 18): opt-in via DTPU_AUTOSCALE_SLO=1 and only
    # meaningful when a spec is configured — the paid class's fast-window
    # burn rate becomes a third scale-up trigger next to queue depth and
    # utilization (burn > 1.0 means the objective fails at this rate)
    from comfyui_distributed_tpu.utils import slo as slo_mod
    slo_engine = getattr(state, "slo", None)
    slo_burn_fn = None
    if slo_mod.autoscale_slo_armed() and slo_engine is not None \
            and slo_engine.enabled:
        def slo_burn() -> Optional[float]:
            return slo_engine.burn_rate(C.TENANT_DEFAULT_CLASS, "fast")

        slo_burn_fn = slo_burn
    scaler = FleetAutoscaler(
        registry=state.cluster,
        queue_depth_fn=state.queue_remaining,
        util_fn=util,
        spawner=default_spawner(state),
        retirer=default_retirer(state),
        shard=getattr(state, "shard", None),
        parked_backlog_fn=cb.parked_count if cb is not None else None,
        slo_burn_fn=slo_burn_fn,
    )
    scaler.start()
    return scaler
