"""Durable job state + master failover (ISSUE 7).

PR 4 made jobs survive *worker* death; master death still lost the
queue, the WorkLedger and every in-flight job.  This module is the
MapReduce answer (Dean & Ghemawat, OSDI 2004 — master-state
checkpointing + re-execution of only unfinished units), adapted to the
deterministic per-tile/per-slice seeds that make replay bit-identical:

- :class:`WriteAheadLog` — every queue admission, ledger ownership
  transition, unit check-in and idempotency-key stamp is appended as a
  compact checksummed record to segment files under ``DTPU_WAL_DIR``
  (``DTPU_WAL_SYNC`` picks the fsync policy).  Segment rotation writes a
  snapshot of the materialized state and truncates the old segments, so
  replay time is bounded by one segment, not job history.
- :class:`ReplayState` — the single materializer: the WAL applies every
  append to it live, snapshots serialize it, and recovery replays
  snapshot+log through the very same ``apply`` — one code path, no
  snapshot-vs-replay drift.
- :class:`UnitStore` — completed units' payloads (refined tile windows,
  collected seed-slice images) spill next to the log, so a recovered
  job re-refines ONLY its unfinished units; a done unit whose payload
  file is missing is downgraded to pending (recomputed, bit-identical)
  rather than trusted.
- :class:`MasterLease` — file-based master lease with monotonically
  increasing epochs (the fencing token).  A standby (``DTPU_STANDBY=1``)
  observes it and takes over on expiry by replaying the shared WAL;
  appends from the deposed epoch raise :class:`FencedError` so a zombie
  master cannot corrupt the log.  Each epoch writes its OWN segment
  files — two processes never interleave inside one file.
- :class:`DurableMaster` — the facade ``ServerState`` owns: acquire (or
  watch) the lease, replay, preload ledger/idempotency state, resume
  in-flight prompts, heartbeat the lease, re-home workers on takeover.

Crash-consistency ordering (the invariants tests/test_durable.py's
crash-point matrix asserts):

- a record is fsync'd before its effect is acknowledged (idempotency
  keys before the 200, enqueue before the prompt_id reaches the client);
- unit payloads are spilled (atomic tmp+rename) BEFORE the check-in
  record is appended — a crash between leaves an orphan payload that
  replay ignores, never a done-without-payload unit;
- replay is idempotent: re-applying any prefix or duplicated record
  converges to the same state (no lost, no duplicate units).
"""

from __future__ import annotations

import base64
import io
import json
import os
import re
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils import trace as trace_mod
from comfyui_distributed_tpu.utils.logging import debug_log, log

_SEGMENT_RE = re.compile(r"^wal-(\d{6})-(\d{6})\.log$")
_SNAPSHOT_RE = re.compile(r"^snapshot-(\d{6})-(\d{6})\.json$")


class WalError(RuntimeError):
    """Base class for durability failures."""


class FencedError(WalError):
    """A newer epoch holds the master lease; this writer is a zombie."""


class WalCrashedError(WalError):
    """Test/bench hook: the simulated crash point was reached — this
    WAL refuses all further appends, as a dead process would."""


class LeaseHeldError(WalError):
    """The master lease is live and owned by someone else."""


def wal_dir() -> Optional[str]:
    d = os.environ.get(C.WAL_DIR_ENV, "").strip()
    return os.path.expanduser(d) if d else None


def _sync_policy() -> Any:
    raw = os.environ.get(C.WAL_SYNC_ENV, C.WAL_SYNC_DEFAULT).strip().lower()
    if raw in ("always", ""):
        return "always"
    if raw in ("off", "0", "false", "no"):
        return "off"
    try:
        return max(float(raw), 0.0)
    except ValueError:
        log(f"bad {C.WAL_SYNC_ENV}={raw!r}; using always")
        return "always"


def _segment_bytes() -> int:
    try:
        return max(int(os.environ.get(C.WAL_SEGMENT_BYTES_ENV,
                                      C.WAL_SEGMENT_BYTES_DEFAULT)), 4096)
    except ValueError:
        return C.WAL_SEGMENT_BYTES_DEFAULT


def master_lease_s() -> float:
    try:
        return max(float(os.environ.get(C.MASTER_LEASE_ENV,
                                        C.MASTER_LEASE_DEFAULT)), 0.2)
    except ValueError:
        return C.MASTER_LEASE_DEFAULT


def encode_record(rec: Dict[str, Any]) -> bytes:
    body = json.dumps(rec, separators=(",", ":"), sort_keys=True)
    payload = body.encode("utf-8")
    return b"%08x %s\n" % (zlib.crc32(payload), payload)


def decode_line(line: bytes) -> Optional[Dict[str, Any]]:
    """One record, or None when the line is torn/corrupt."""
    if not line.endswith(b"\n") or b" " not in line:
        return None
    crc_hex, _, payload = line.rstrip(b"\n").partition(b" ")
    try:
        if int(crc_hex, 16) != zlib.crc32(payload):
            return None
        rec = json.loads(payload)
    except (ValueError, TypeError):
        return None
    return rec if isinstance(rec, dict) else None


def read_segment(path: str) -> Tuple[List[Dict[str, Any]], Optional[int]]:
    """All valid records + the byte offset of the first bad line (None
    when the whole segment is clean).  Replay stops at the first bad
    line — everything after a torn write is untrusted."""
    records: List[Dict[str, Any]] = []
    offset = 0
    with open(path, "rb") as f:
        for line in f:
            rec = decode_line(line)
            if rec is None:
                return records, offset
            records.append(rec)
            offset += len(line)
    return records, None


def _list_by(dirpath: str, pattern: re.Pattern) -> List[Tuple[int, int, str]]:
    out = []
    try:
        names = os.listdir(dirpath)
    except OSError:
        return out
    for name in names:
        m = pattern.match(name)
        if m:
            out.append((int(m.group(1)), int(m.group(2)),
                        os.path.join(dirpath, name)))
    return sorted(out)


def list_segments(dirpath: str) -> List[Tuple[int, int, str]]:
    """[(epoch, seq, path)] sorted — the replay order."""
    return _list_by(dirpath, _SEGMENT_RE)


def list_snapshots(dirpath: str) -> List[Tuple[int, int, str]]:
    return _list_by(dirpath, _SNAPSHOT_RE)


def _fsync_dir(dirpath: str) -> None:
    try:
        fd = os.open(dirpath, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


def _atomic_write(path: str, data: bytes) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


# --- the materialized master state -------------------------------------------

class ReplayState:
    """What the WAL materializes: pending prompts, active ledger jobs
    (per-unit owner/done), per-job idempotency keys.  Both the live
    tracker and crash recovery go through :meth:`apply` — snapshots are
    just this object serialized."""

    def __init__(self) -> None:
        # pid -> {prompt, client_id, extra}
        self.prompts: Dict[str, Dict[str, Any]] = {}
        # job -> {kind, units: {unit(str): {owner, done, by, spilled}}}
        self.jobs: Dict[str, Dict[str, Any]] = {}
        # scope ("image"|"tile") -> job -> [keys]
        self.idem: Dict[str, Dict[str, List[str]]] = {"image": {},
                                                      "tile": {}}
        self.counts: Dict[str, int] = {}
        self.applied = 0

    def apply(self, rec: Dict[str, Any]) -> None:
        t = rec.get("t")
        self.applied += 1
        self.counts[t] = self.counts.get(t, 0) + 1
        if t == "enqueue":
            self.prompts[str(rec["pid"])] = {
                "prompt": rec.get("prompt"),
                "client_id": rec.get("client_id", "recovered"),
                "extra": rec.get("extra") or {},
            }
        elif t == "exec_done":
            self.prompts.pop(str(rec["pid"]), None)
        elif t == "job_create":
            jid = str(rec["job"])
            job = self.jobs.get(jid)
            owners = {str(u): str(o)
                      for u, o in (rec.get("owners") or {}).items()}
            if job is None:
                self.jobs[jid] = {
                    "kind": rec.get("kind", "tile"),
                    "units": {u: {"owner": o, "done": False,
                                  "by": None, "spilled": False}
                              for u, o in owners.items()}}
            else:
                # re-create of a live job (a recovered run re-registers
                # it): refresh pending owners, NEVER forget done units
                units = job["units"]
                for u, o in owners.items():
                    cur = units.get(u)
                    if cur is None:
                        units[u] = {"owner": o, "done": False,
                                    "by": None, "spilled": False}
                    elif not cur["done"]:
                        cur["owner"] = o
        elif t == "unit_checkin":
            job = self.jobs.get(str(rec["job"]))
            if job is not None:
                u = job["units"].setdefault(
                    str(rec["unit"]), {"owner": str(rec.get("by", "")),
                                       "done": False, "by": None,
                                       "spilled": False})
                u["done"] = True
                u["by"] = str(rec.get("by", ""))
                u["spilled"] = bool(rec.get("spilled"))
        elif t == "unit_reassign":
            job = self.jobs.get(str(rec["job"]))
            if job is not None:
                for u in rec.get("units", []):
                    cur = job["units"].get(str(u))
                    if cur is not None and not cur["done"]:
                        cur["owner"] = str(rec["to"])
        elif t == "unit_hedge":
            # audit-only: hedges are speculation, not ownership — a
            # recovered job re-decides hedging from live latencies
            pass
        elif t == "job_finish":
            self.jobs.pop(str(rec["job"]), None)
            for scope in self.idem.values():
                scope.pop(str(rec["job"]), None)
        elif t == "idem":
            scope = self.idem.setdefault(str(rec.get("scope", "image")), {})
            keys = scope.setdefault(str(rec["job"]), [])
            k = str(rec["key"])
            if k not in keys:
                keys.append(k)

    # -- snapshot codec -------------------------------------------------------

    def to_json(self) -> Dict[str, Any]:
        return {"prompts": self.prompts, "jobs": self.jobs,
                "idem": self.idem, "counts": self.counts,
                "applied": self.applied}

    @classmethod
    def from_json(cls, data: Dict[str, Any]) -> "ReplayState":
        st = cls()
        st.prompts = dict(data.get("prompts") or {})
        st.jobs = dict(data.get("jobs") or {})
        idem = data.get("idem") or {}
        st.idem = {"image": dict(idem.get("image") or {}),
                   "tile": dict(idem.get("tile") or {})}
        st.counts = dict(data.get("counts") or {})
        st.applied = int(data.get("applied") or 0)
        return st


def replay(dirpath: str) -> Tuple[ReplayState, Dict[str, Any]]:
    """Newest valid snapshot + the segments at/after its watermark ->
    the materialized state, plus an info dict for logs/`cli wal`."""
    state = ReplayState()
    watermark = (-1, -1)
    snap_used = None
    for epoch, seq, path in reversed(list_snapshots(dirpath)):
        try:
            with open(path, "r", encoding="utf-8") as f:
                state = ReplayState.from_json(json.load(f))
            watermark, snap_used = (epoch, seq), path
            break
        except (OSError, ValueError) as e:
            log(f"wal: snapshot {os.path.basename(path)} unreadable "
                f"({e}); falling back to the previous one")
    segments = [s for s in list_segments(dirpath)
                if (s[0], s[1]) >= watermark]
    torn = []
    records = 0
    for epoch, seq, path in segments:
        recs, bad = read_segment(path)
        for rec in recs:
            state.apply(rec)
        records += len(recs)
        if bad is not None:
            torn.append({"segment": os.path.basename(path),
                         "offset": bad})
    return state, {"snapshot": snap_used,
                   "segments_replayed": len(segments),
                   "records_replayed": records,
                   "torn": torn}


# --- completed-unit payload spill --------------------------------------------

def _unit_token(unit: Any) -> str:
    return base64.urlsafe_b64encode(
        str(unit).encode("utf-8")).decode("ascii").rstrip("=")


def _unit_from_token(token: str) -> str:
    pad = "=" * (-len(token) % 4)
    return base64.urlsafe_b64decode(token + pad).decode("utf-8")


class UnitStore:
    """Completed-unit payloads on disk: ``units/<job>/<unit>.npz`` with
    the tensors plus a JSON meta field.  Writes are atomic
    (tmp+rename+fsync) and happen BEFORE the unit's check-in record is
    appended — a crash in between leaves an orphan file replay ignores."""

    def __init__(self, root: str) -> None:
        self.root = os.path.join(root, "units")

    def _job_dir(self, job: str) -> str:
        return os.path.join(self.root, _unit_token(job))

    def path(self, job: str, unit: Any) -> str:
        return os.path.join(self._job_dir(str(job)),
                            f"{_unit_token(unit)}.npz")

    def put(self, job: str, unit: Any, tensors: List[Any],
            meta: Dict[str, Any]) -> None:
        import numpy as np
        d = self._job_dir(str(job))
        os.makedirs(d, exist_ok=True)
        buf = io.BytesIO()
        arrays = {f"t{i}": np.asarray(t) for i, t in enumerate(tensors)}
        np.savez_compressed(buf, meta=np.frombuffer(
            json.dumps({**meta, "n": len(tensors)}).encode(), np.uint8),
            **arrays)
        _atomic_write(self.path(str(job), unit), buf.getvalue())

    def has(self, job: str, unit: Any) -> bool:
        return os.path.exists(self.path(str(job), unit))

    def get(self, job: str, unit: Any
            ) -> Optional[Tuple[List[Any], Dict[str, Any]]]:
        import numpy as np
        p = self.path(str(job), unit)
        try:
            with np.load(p) as z:
                meta = json.loads(bytes(z["meta"]).decode())
                tensors = [z[f"t{i}"] for i in range(int(meta.pop("n", 0)))]
            return tensors, meta
        except (OSError, ValueError, KeyError) as e:
            debug_log(f"unit store: {p} unreadable ({e}); unit will be "
                      f"recomputed")
            return None

    def drop_job(self, job: str) -> None:
        import shutil
        shutil.rmtree(self._job_dir(str(job)), ignore_errors=True)

    def jobs(self) -> List[str]:
        try:
            return [_unit_from_token(n) for n in os.listdir(self.root)]
        except OSError:
            return []

    def prune(self, keep_jobs) -> int:
        """Recovery-time GC: drop unit dirs whose job is not in the
        replayed state (stranded by a crash between the job_finish
        append and drop_job) and tmp files a crash left mid-spill —
        without this the durability dir grows with every crash."""
        keep = {str(j) for j in keep_jobs}
        dropped = 0
        for job in self.jobs():
            if job not in keep:
                self.drop_job(job)
                dropped += 1
        try:
            for dirpath, _dirs, files in os.walk(self.root):
                for name in files:
                    if ".tmp." in name:
                        try:
                            os.remove(os.path.join(dirpath, name))
                        except OSError:
                            pass
        except OSError:
            pass
        return dropped


# --- master lease (the election + fencing medium) ----------------------------

class MasterLease:
    """File-based master lease in the WAL dir, mutated under an flock'd
    lock file so acquire/renew races resolve on one host or one shared
    filesystem.  The epoch only ever increases — it is the fencing token
    every WAL append carries and checks."""

    def __init__(self, dirpath: str):
        self.dir = dirpath
        self.path = os.path.join(dirpath, "master.lease")
        self._lock_path = os.path.join(dirpath, "master.lock")

    def _with_lock(self, fn: Callable[[], Any]) -> Any:
        os.makedirs(self.dir, exist_ok=True)
        f = open(self._lock_path, "a+")
        try:
            try:
                import fcntl
                fcntl.flock(f.fileno(), fcntl.LOCK_EX)
            except (ImportError, OSError):
                pass  # non-POSIX: best-effort (atomic rename still holds)
            return fn()
        finally:
            f.close()

    def read(self) -> Optional[Dict[str, Any]]:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                data = json.load(f)
            return data if isinstance(data, dict) else None
        except (OSError, ValueError):
            return None

    def current_epoch(self) -> int:
        cur = self.read()
        return int(cur.get("epoch", 0)) if cur else 0

    @staticmethod
    def expired(rec: Optional[Dict[str, Any]]) -> bool:
        return rec is None or time.time() > float(rec.get("expires_at", 0))

    def acquire(self, owner: str, lease_s: float,
                force: bool = False) -> int:
        """Take the lease; bumps the epoch.  Refused while a DIFFERENT
        owner's lease is live (a same-owner reacquire is the
        crash-restart path: the previous holder was us, and we are
        provably not running it anymore)."""
        def go():
            cur = self.read()
            if cur and not force and str(cur.get("owner")) != str(owner) \
                    and not self.expired(cur):
                raise LeaseHeldError(
                    f"master lease held by {cur.get('owner')!r} for "
                    f"another {float(cur.get('expires_at', 0)) - time.time():.1f}s")
            epoch = (int(cur.get("epoch", 0)) if cur else 0) + 1
            now = time.time()
            _atomic_write(self.path, json.dumps({
                "owner": str(owner), "epoch": epoch,
                "lease_s": float(lease_s),
                "acquired_at": now,
                "expires_at": now + float(lease_s)}).encode())
            return epoch
        return self._with_lock(go)

    def renew(self, owner: str, epoch: int, lease_s: float) -> bool:
        """Extend the lease; False when it was lost (epoch superseded)."""
        def go():
            cur = self.read()
            if not cur or int(cur.get("epoch", 0)) != int(epoch) \
                    or str(cur.get("owner")) != str(owner):
                return False
            now = time.time()
            _atomic_write(self.path, json.dumps({
                **cur, "expires_at": now + float(lease_s),
                "renewed_at": now}).encode())
            return True
        return self._with_lock(go)

    def snapshot(self) -> Dict[str, Any]:
        cur = self.read()
        if cur is None:
            return {"held": False, "epoch": 0}
        return {"held": not self.expired(cur),
                "owner": cur.get("owner"),
                "epoch": int(cur.get("epoch", 0)),
                "expires_in_s": round(
                    float(cur.get("expires_at", 0)) - time.time(), 3)}


# --- the log itself ----------------------------------------------------------

class WriteAheadLog:
    """Append-only checksummed record log with per-epoch segment files,
    snapshot-on-rotation truncation, a configurable fsync policy, lease
    fencing, and a crash-injection hook for the recovery test matrix."""

    def __init__(self, dirpath: str, epoch: int = 1,
                 lease: Optional[MasterLease] = None,
                 tracker: Optional[ReplayState] = None,
                 sync: Optional[Any] = None,
                 segment_bytes: Optional[int] = None):
        self.dir = dirpath
        self.epoch = int(epoch)
        self.lease = lease
        self.tracker = tracker if tracker is not None else ReplayState()
        self.sync_policy = _sync_policy() if sync is None else sync
        self.segment_bytes = _segment_bytes() if segment_bytes is None \
            else int(segment_bytes)
        self._lock = threading.Lock()
        # append/rotate/fsync state: one writer at a time, and stats()
        # scrapes from the HTTP handlers — everything below holds the
        # lock (the lockset rule enforces it)
        self._f: Optional[Any] = None       # guarded-by: self._lock
        self._seq = max([s for e, s, _ in list_segments(dirpath)],
                        default=0) + 1      # guarded-by: self._lock
        self._size = 0                      # guarded-by: self._lock
        self._unsynced = 0                  # guarded-by: self._lock
        self._last_sync = time.monotonic()  # guarded-by: self._lock
        self._last_fence_check = 0.0        # guarded-by: self._lock
        self.fenced = False
        self.crashed = False                # guarded-by: self._lock
        self.records_appended = 0           # guarded-by: self._lock
        self.fsyncs = 0                     # guarded-by: self._lock
        # test/bench crash hook: {"type": rtype-or-None, "point":
        # pre_append|torn|post_sync, "after": n matching appends}
        self._crash: Optional[Dict[str, Any]] = None  # guarded-by: self._lock
        os.makedirs(dirpath, exist_ok=True)
        self._open_segment()

    # -- segment plumbing -----------------------------------------------------

    # dtpu-lint: holds[self._lock]  (only _open_segment calls it)
    def _segment_path(self) -> str:
        return os.path.join(self.dir,
                            f"wal-{self.epoch:06d}-{self._seq:06d}.log")

    # dtpu-lint: holds[self._lock]  (__init__ calls it pre-publication)
    def _open_segment(self) -> None:
        if self._f is not None:
            self._f.close()
        self._f = open(self._segment_path(), "ab")
        self._size = self._f.tell()

    def _rotate_locked(self) -> None:
        """Close the full segment, snapshot the materialized state, and
        delete everything the snapshot covers — bounded replay."""
        self._fsync_locked()
        self._seq += 1
        self._open_segment()
        snap_path = os.path.join(
            self.dir, f"snapshot-{self.epoch:06d}-{self._seq:06d}.json")
        try:
            _atomic_write(snap_path,
                          json.dumps(self.tracker.to_json()).encode())
        except OSError as e:
            log(f"wal: snapshot failed ({e}); keeping full log")
            return
        watermark = (self.epoch, self._seq)
        for e, s, path in list_segments(self.dir):
            if (e, s) < watermark:
                try:
                    os.remove(path)
                except OSError:
                    pass
        for e, s, path in list_snapshots(self.dir):
            if (e, s) < watermark:
                try:
                    os.remove(path)
                except OSError:
                    pass
        debug_log(f"wal: rotated to seq {self._seq}, snapshot + "
                  f"truncation done")

    def _fsync_locked(self) -> None:
        if self._f is None:
            return
        self._f.flush()
        os.fsync(self._f.fileno())
        self.fsyncs += 1
        self._unsynced = 0
        self._last_sync = time.monotonic()

    # -- fencing / crash hooks ------------------------------------------------

    def _check_fence_locked(self) -> None:
        if self.fenced:
            raise FencedError(f"epoch {self.epoch} was deposed")
        if self.lease is None:
            return
        now = time.monotonic()
        if now - self._last_fence_check < C.WAL_FENCE_CHECK_S:
            return
        self._last_fence_check = now
        cur = self.lease.current_epoch()
        if cur > self.epoch:
            self.fenced = True
            trace_mod.GLOBAL_COUNTERS.bump("wal_fenced")
            raise FencedError(
                f"epoch {self.epoch} fenced: lease now at epoch {cur}")

    def inject_crash(self, point: str, rtype: Optional[str] = None,
                     after: int = 0) -> None:
        """Arm the test hook: crash at ``point`` ("pre_append" — nothing
        written; "torn" — half a record written, no fsync; "post_sync" —
        record durable, ack never delivered) on the ``after``-th append
        matching ``rtype`` (None = any)."""
        with self._lock:
            self._crash = {"point": point, "type": rtype,
                           "after": int(after)}

    def simulate_crash(self) -> None:
        """Make this WAL behave like its process died: every further
        append (and sync) raises.  Nothing else is written."""
        with self._lock:
            self.crashed = True

    # -- the append path ------------------------------------------------------

    def append(self, rtype: str, **fields: Any) -> Dict[str, Any]:
        rec = {"t": rtype, "e": self.epoch,
               "ts": round(time.time(), 3), **fields}
        with self._lock:
            if self.crashed:
                raise WalCrashedError("wal is crashed")
            self._check_fence_locked()
            hook = self._crash
            if hook is not None and (hook["type"] is None
                                     or hook["type"] == rtype):
                if hook["after"] > 0:
                    hook["after"] -= 1
                    hook = None
            else:
                hook = None
            if hook is not None and hook["point"] == "pre_append":
                self.crashed = True
                raise WalCrashedError(f"injected pre_append crash at "
                                      f"{rtype}")
            data = encode_record(rec)
            if hook is not None and hook["point"] == "torn":
                self._f.write(data[:max(len(data) // 2, 1)])
                self._f.flush()
                self.crashed = True
                raise WalCrashedError(f"injected torn write at {rtype}")
            self._f.write(data)
            self._size += len(data)
            self.records_appended += 1
            self._unsynced += 1
            pol = self.sync_policy
            if pol == "always":
                self._fsync_locked()
            elif pol != "off" \
                    and time.monotonic() - self._last_sync >= float(pol):
                self._fsync_locked()
            else:
                self._f.flush()
            if hook is not None and hook["point"] == "post_sync":
                self._fsync_locked()
                self.crashed = True
                raise WalCrashedError(f"injected post_sync crash at "
                                      f"{rtype} (record durable, ack "
                                      f"lost)")
            self.tracker.apply(rec)
            trace_mod.GLOBAL_COUNTERS.bump("wal_records")
            if self._size >= self.segment_bytes:
                self._rotate_locked()
        return rec

    def sync(self) -> None:
        with self._lock:
            if not self.crashed:
                self._fsync_locked()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    if not self.crashed:
                        self._fsync_locked()
                finally:
                    self._f.close()
                    self._f = None

    def stats(self) -> Dict[str, Any]:
        segs = list_segments(self.dir)
        with self._lock:
            return {
                "dir": self.dir,
                "epoch": self.epoch,
                "fenced": self.fenced,
                "segments": len(segs),
                "segment_seq": self._seq,
                "bytes": sum(os.path.getsize(p) for _, _, p in segs
                             if os.path.exists(p)),
                "records_appended": self.records_appended,
                "records_materialized": self.tracker.applied,
                "unsynced_records": self._unsynced,
                "last_sync_age_s": round(
                    time.monotonic() - self._last_sync, 3),
                "fsyncs": self.fsyncs,
                "sync_policy": str(self.sync_policy),
                "pending_prompts": len(self.tracker.prompts),
                "active_jobs": len(self.tracker.jobs),
            }


# --- offline verification (cli wal) ------------------------------------------

def verify(dirpath: str) -> Dict[str, Any]:
    """Walk the log: per-segment record counts and checksum status,
    snapshot inventory, per-job record counts, the replayed summary.
    A bad line at the very tail of the NEWEST segment is a torn write
    (expected after a crash); anywhere else it is corruption."""
    segs = list_segments(dirpath)
    seg_reports = []
    per_job: Dict[str, int] = {}
    per_type: Dict[str, int] = {}
    corrupt = False
    for epoch, seq, path in segs:
        recs, bad = read_segment(path)
        size = os.path.getsize(path)
        for rec in recs:
            per_type[rec.get("t", "?")] = per_type.get(rec.get("t", "?"),
                                                       0) + 1
            if "job" in rec:
                jid = str(rec["job"])
                per_job[jid] = per_job.get(jid, 0) + 1
        tail_bad = bad is not None
        is_torn_tail = False
        if tail_bad:
            # a torn write is a partial FINAL record: nothing
            # line-shaped follows the bad offset.  A valid-looking line
            # after it means mid-file corruption, which replay would
            # silently truncate — flag it.
            with open(path, "rb") as f:
                f.seek(bad)
                rest = f.read()
            is_torn_tail = b"\n" not in rest
        if tail_bad and not is_torn_tail:
            corrupt = True
        seg_reports.append({
            "segment": os.path.basename(path), "epoch": epoch,
            "seq": seq, "bytes": size, "records": len(recs),
            "checksum": ("ok" if not tail_bad else
                         "torn-tail" if is_torn_tail else
                         f"CORRUPT@{bad}"),
        })
    state, info = replay(dirpath)
    return {
        "dir": dirpath,
        "ok": not corrupt,
        "segments": seg_reports,
        "snapshots": [os.path.basename(p)
                      for _, _, p in list_snapshots(dirpath)],
        "lease": MasterLease(dirpath).snapshot(),
        "records_by_type": per_type,
        "records_by_job": per_job,
        "replay": {**info,
                   "pending_prompts": sorted(state.prompts),
                   "active_jobs": {
                       jid: {"kind": j["kind"],
                             "done": sum(1 for u in j["units"].values()
                                         if u["done"]),
                             "total": len(j["units"])}
                       for jid, j in state.jobs.items()},
                   "idem_keys": {s: sum(len(v) for v in m.values())
                                 for s, m in state.idem.items()}},
    }


def rehome_workers(master_url: str, config_path: Optional[str]) -> None:
    """Tell every enabled config worker to heartbeat ``master_url`` now
    (best-effort; a worker that misses it re-registers when its next
    redispatch graph names that master_url).  Shared by the standby
    takeover (DurableMaster) and the multi-master shard absorb
    (runtime/shard.py) so the rehome protocol cannot diverge."""
    import urllib.request

    from comfyui_distributed_tpu.utils import config as cfg_mod
    try:
        cfg = cfg_mod.load_config(config_path)
    except Exception:  # noqa: BLE001 - config optional
        return
    for w in cfg_mod.enabled_workers(cfg):
        target = (f"http://{w.get('host') or '127.0.0.1'}:"
                  f"{w['port']}/distributed/rehome")
        try:
            req = urllib.request.Request(
                target,
                data=json.dumps({"master_url": master_url,
                                 "worker_id": str(w["id"])}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=3) as r:
                r.read()
            debug_log(f"durable: re-homed worker {w['id']} to "
                      f"{master_url}")
        except Exception as e:  # noqa: BLE001 - best-effort
            debug_log(f"durable: rehome of {w.get('id')} failed: {e}")


# --- the ServerState facade --------------------------------------------------

class DurableMaster:
    """Owns the lease, the WAL and the recovered state for one master
    process.  ``attach`` is the single entry point: returns None when
    durability is off (no ``DTPU_WAL_DIR``) or for worker processes."""

    def __init__(self, dirpath: str, owner: str, standby: bool = False):
        self.dir = dirpath
        self.owner = owner
        self.standby = standby
        self.lease = MasterLease(dirpath)
        self.lease_s = master_lease_s()
        self.unit_store = UnitStore(dirpath)
        self.wal: Optional[WriteAheadLog] = None
        self.epoch = 0
        self.recovered: Optional[ReplayState] = None
        self.recovery_info: Dict[str, Any] = {}
        self._pending_prompts: List[Tuple[str, Dict[str, Any]]] = []
        self._resumed = False
        self._stop = threading.Event()
        self._heartbeat_thread: Optional[threading.Thread] = None
        self._watcher_thread: Optional[threading.Thread] = None
        self._state = None  # the ServerState, set by attach
        self.takeovers = 0

    # -- construction ---------------------------------------------------------

    @classmethod
    def attach(cls, state, dirpath: Optional[str] = None,
               owner: Optional[str] = None) -> Optional["DurableMaster"]:
        """``dirpath``/``owner`` overrides are the multi-master shard
        path (ISSUE 14): each shard keeps its OWN WAL dir under the
        shared root, and its lease-owner identity is the shard id (so a
        crash-restart of the same shard reclaims its lease, while a
        peer's absorb acquire is a fresh-owner epoch bump)."""
        d = dirpath or wal_dir()
        if not d or state.is_worker:
            return None
        standby = os.environ.get(C.STANDBY_ENV, "").lower() \
            in ("1", "true", "on", "yes")
        # same-owner re-acquire is the crash-restart fast path, so a
        # standby must NOT share the primary's default identity — it
        # would be able to steal a live lease
        owner = owner or os.environ.get(C.WAL_OWNER_ENV, "").strip() \
            or (f"standby_{os.getpid()}" if standby else "master")
        dm = cls(d, owner=owner, standby=standby)
        dm._state = state
        os.makedirs(d, exist_ok=True)
        if standby:
            dm._start_watcher()
            log(f"durable: standby {owner!r} watching master lease in "
                f"{d} (takes over on expiry)")
        else:
            dm._activate()
        return dm

    def _activate(self) -> None:
        """Acquire the lease, replay the log, preload the live state."""
        self.epoch = self.lease.acquire(self.owner, self.lease_s)
        self.recovered, self.recovery_info = replay(self.dir)
        self.unit_store.prune(self.recovered.jobs)
        self.wal = WriteAheadLog(self.dir, epoch=self.epoch,
                                 lease=self.lease,
                                 tracker=self.recovered)
        st = self._state
        st.ledger.attach_wal(self.wal, self.unit_store,
                             {jid: job for jid, job
                              in self.recovered.jobs.items()})
        st.jobs.attach_wal(self.wal, self.recovered.idem)
        self._pending_prompts = [
            (pid, dict(p)) for pid, p in self.recovered.prompts.items()]
        self._resumed = False
        self._start_heartbeat()
        n_jobs = len(self.recovered.jobs)
        n_done = sum(sum(1 for u in j["units"].values() if u["done"])
                     for j in self.recovered.jobs.values())
        log(f"durable: epoch {self.epoch} holds the lease; replayed "
            f"{self.recovery_info.get('records_replayed', 0)} records "
            f"({len(self._pending_prompts)} in-flight prompt(s), "
            f"{n_jobs} open job(s), {n_done} unit(s) already done"
            + (f", torn tail in {len(self.recovery_info['torn'])} "
               f"segment(s)" if self.recovery_info.get("torn") else "")
            + ")")
        trace_mod.GLOBAL_COUNTERS.bump("wal_recovered_prompts",
                                       len(self._pending_prompts))
        trace_mod.GLOBAL_COUNTERS.bump("wal_recovered_done_units", n_done)

    # -- in-flight prompt resumption ------------------------------------------

    def resume(self) -> int:
        """Re-enqueue the prompts the crash interrupted (original
        prompt_ids, so clients polling /history re-find them) and
        register recovery redispatchers so their unfinished units can
        re-fan-out to live workers.  Called once the server loop is up
        (on_startup) — idempotent."""
        if self._resumed or not self._pending_prompts:
            self._resumed = True
            return 0
        self._resumed = True
        st = self._state
        try:
            # feed the registry before the recovered drains consult it:
            # redispatch targets must be probed-HEALTHY, not UNKNOWN
            st.health.poll_once()
        except Exception as e:  # noqa: BLE001 - probe is best-effort
            debug_log(f"durable: recovery preflight poll failed: {e}")
        n = 0
        for pid, p in self._pending_prompts:
            prompt = p.get("prompt")
            if not isinstance(prompt, dict):
                continue
            try:
                from comfyui_distributed_tpu.workflow.orchestrate import (
                    register_recovery_redispatchers)
                register_recovery_redispatchers(st, prompt)
            except Exception as e:  # noqa: BLE001 - master-local refine
                # still recovers every unit without redispatchers
                debug_log(f"durable: recovery redispatchers for {pid} "
                          f"skipped: {e}")
            st.enqueue_prompt(prompt, p.get("client_id", "recovered"),
                              p.get("extra") or {}, pid=pid,
                              _recovered=True)
            n += 1
        self._pending_prompts = []
        if n:
            log(f"durable: resumed {n} in-flight prompt(s) from the WAL")
            trace_mod.GLOBAL_COUNTERS.bump("wal_resumed_prompts", n)
        return n

    # -- prompt/queue records -------------------------------------------------

    def log_enqueue(self, pid: str, prompt: Dict[str, Any],
                    client_id: str, extra: Optional[Dict[str, Any]]) -> None:
        if self.wal is None:
            return
        safe_extra = None
        if extra:
            try:
                safe_extra = json.loads(json.dumps(extra))
            except (TypeError, ValueError):
                safe_extra = None
        self.wal.append("enqueue", pid=str(pid), prompt=prompt,
                        client_id=str(client_id), extra=safe_extra)

    def log_exec_done(self, pid: str, status: str) -> None:
        if self.wal is not None:
            try:
                self.wal.append("exec_done", pid=str(pid),
                                status=str(status))
            except WalError as e:
                debug_log(f"durable: exec_done for {pid} not logged "
                          f"({e})")

    # -- lease heartbeat / standby watcher ------------------------------------

    def _start_heartbeat(self) -> None:
        if self._heartbeat_thread is not None:
            return
        interval = max(self.lease_s / C.MASTER_LEASE_FRACTION, 0.05)

        def run():
            while not self._stop.wait(interval):
                try:
                    if not self.lease.renew(self.owner, self.epoch,
                                            self.lease_s):
                        log(f"durable: lost the master lease (epoch "
                            f"{self.epoch} superseded); fencing the WAL")
                        if self.wal is not None:
                            self.wal.fenced = True
                        return
                except OSError as e:
                    debug_log(f"durable: lease renew failed: {e}")

        self._heartbeat_thread = threading.Thread(
            target=run, daemon=True, name="dtpu-master-lease")
        self._heartbeat_thread.start()

    def _start_watcher(self) -> None:
        if self._watcher_thread is not None:
            return
        interval = max(self.lease_s / C.MASTER_LEASE_FRACTION, 0.05)

        def run():
            while not self._stop.wait(interval):
                try:
                    if self.lease.expired(self.lease.read()):
                        log("durable: master lease expired — standby "
                            "taking over")
                        self.takeover()
                        return
                except LeaseHeldError:
                    continue  # someone else re-acquired first; keep watching
                except Exception as e:  # noqa: BLE001 - keep watching
                    log(f"durable: standby takeover attempt failed: "
                        f"{type(e).__name__}: {e}")

        self._watcher_thread = threading.Thread(
            target=run, daemon=True, name="dtpu-standby-watch")
        self._watcher_thread.start()

    def takeover(self, force: bool = False) -> Dict[str, Any]:
        """Standby -> master: acquire the lease (bumping the epoch — the
        fencing event), replay the shared WAL, resume the in-flight
        prompts, and re-home workers to this server."""
        if self.wal is not None and not self.wal.fenced:
            return {"ok": True, "epoch": self.epoch,
                    "note": "already active"}
        if force:
            self.epoch = self.lease.acquire(self.owner, self.lease_s,
                                            force=True)
            self._activate_post_acquire()
        else:
            self._activate()  # raises LeaseHeldError while the lease lives
        self.takeovers += 1
        trace_mod.GLOBAL_COUNTERS.bump("master_takeovers")
        resumed = self.resume()
        self._rehome_workers()
        return {"ok": True, "epoch": self.epoch,
                "resumed_prompts": resumed,
                "recovered_jobs": len(self.recovered.jobs)
                if self.recovered else 0}

    def _activate_post_acquire(self) -> None:
        """The force-acquire variant of _activate (epoch already taken)."""
        self.recovered, self.recovery_info = replay(self.dir)
        self.unit_store.prune(self.recovered.jobs)
        self.wal = WriteAheadLog(self.dir, epoch=self.epoch,
                                 lease=self.lease,
                                 tracker=self.recovered)
        st = self._state
        st.ledger.attach_wal(self.wal, self.unit_store,
                             dict(self.recovered.jobs))
        st.jobs.attach_wal(self.wal, self.recovered.idem)
        self._pending_prompts = [
            (pid, dict(p)) for pid, p in self.recovered.prompts.items()]
        self._resumed = False
        self._start_heartbeat()

    def _rehome_workers(self) -> None:
        """Tell every enabled config worker to heartbeat HERE now
        (best-effort; a worker that misses it re-registers when its next
        redispatch graph names this master_url)."""
        url = self.master_url()
        if url is not None:
            rehome_workers(url, self._state.config_path)

    def master_url(self) -> Optional[str]:
        st = self._state
        if st is None or st.port is None:
            return None
        from comfyui_distributed_tpu.utils import config as cfg_mod
        host = "127.0.0.1"
        try:
            host = cfg_mod.load_config(st.config_path).get(
                "master", {}).get("host") or "127.0.0.1"
        except Exception:  # noqa: BLE001 - config optional
            pass
        return f"http://{host}:{st.port}"

    # -- lifecycle / introspection --------------------------------------------

    def simulate_crash(self) -> None:
        """Bench/test hook: behave like this master's process died —
        stop renewing the lease, refuse every further WAL append.  The
        in-memory ServerState is left to rot exactly as a SIGKILL'd
        process's memory would."""
        self._stop.set()
        if self.wal is not None:
            self.wal.simulate_crash()

    def close(self) -> None:
        self._stop.set()
        if self.wal is not None:
            self.wal.close()

    def stats(self) -> Dict[str, Any]:
        return {
            "enabled": True,
            "role": ("standby" if self.standby and self.wal is None
                     else "active"),
            "owner": self.owner,
            "epoch": self.epoch,
            "takeovers": self.takeovers,
            "lease": self.lease.snapshot(),
            "recovery": {
                "records_replayed":
                    self.recovery_info.get("records_replayed", 0),
                "resumed": self._resumed,
            },
            "wal": self.wal.stats() if self.wal is not None else None,
        }
