"""Fault-tolerant cluster control plane (ISSUE 4).

The reference (and this reproduction through PR 3) distributes work
*statically*: tile ranges and seed slices are computed from
``(worker_index, worker_count)`` at dispatch time, and the collectors'
only failure response is a timeout that returns a partial result — a
dead worker permanently loses its units.  This module makes jobs
*complete* through worker failure instead of merely surviving it,
following MapReduce's re-execution-on-failure + backup-task model
(Dean & Ghemawat, OSDI 2004) and the hedged-request technique from
"The Tail at Scale" (Dean & Barroso, CACM 2013):

- :class:`ClusterRegistry` — worker registry with leases.  Workers are
  seeded from config or register over HTTP and renew via heartbeat; the
  ``runtime/health.py`` poller and the data-plane POSTs both feed it.
  State machine ``healthy -> suspect -> dead`` with configurable lease
  and probe thresholds (``DTPU_LEASE_S``, ``DTPU_SUSPECT_PROBES``).
- :class:`WorkLedger` — per-job work ledger: which participant owns
  which tile indices / seed slices, exactly-once check-in (retried
  POSTs and hedge losers dedupe at the blend), reassignment, a moving
  per-unit latency estimate that drives hedging, and per-job redispatch
  callbacks the orchestrator registers so lost units can be re-issued
  to healthy HTTP workers.

Every transition (suspect, dead, reassign, hedge win/loss) bumps a
``GLOBAL_COUNTERS`` event (surfaced in ``/distributed/metrics`` and the
Prometheus exposition) and the collectors emit matching spans into the
PR 3 trace tree.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from comfyui_distributed_tpu.utils import clock as clock_mod
from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils import trace as trace_mod
from comfyui_distributed_tpu.utils.logging import debug_log, log

HEALTHY = "healthy"
SUSPECT = "suspect"
DEAD = "dead"
UNKNOWN = "unknown"       # registered but never contacted
# autoscaler drain (ISSUE 9): lease still renewing (in-flight units keep
# checking in) but the dispatcher must not hand it NEW work; the
# retirement completes when the lease is simply not renewed again
RETIRING = "retiring"


class ClusterFaultError(RuntimeError):
    """DTPU_FAULT_POLICY=fail: a participant died mid-job."""


# --- policy / hedge knobs (read per call: cheap, and tests monkeypatch
# the environment) ------------------------------------------------------------

def fault_policy() -> str:
    p = os.environ.get(C.FAULT_POLICY_ENV,
                       C.FAULT_POLICY_DEFAULT).strip().lower()
    if p not in C.FAULT_POLICIES:
        log(f"unknown {C.FAULT_POLICY_ENV}={p!r}; using "
            f"{C.FAULT_POLICY_DEFAULT!r}")
        return C.FAULT_POLICY_DEFAULT
    return p


def hedge_armed() -> bool:
    return os.environ.get(C.HEDGE_ENV, "1").lower() \
        not in ("0", "false", "off")


def hedge_pct() -> float:
    try:
        return float(os.environ.get(C.HEDGE_PCT_ENV, C.HEDGE_PCT_DEFAULT))
    except ValueError:
        return C.HEDGE_PCT_DEFAULT


def hedge_factor() -> float:
    try:
        return float(os.environ.get(C.HEDGE_FACTOR_ENV,
                                    C.HEDGE_FACTOR_DEFAULT))
    except ValueError:
        return C.HEDGE_FACTOR_DEFAULT


def hedge_min_wait() -> float:
    try:
        return float(os.environ.get(C.HEDGE_MIN_WAIT_ENV,
                                    C.HEDGE_MIN_WAIT_DEFAULT))
    except ValueError:
        return C.HEDGE_MIN_WAIT_DEFAULT


def slo_hedge_fraction() -> float:
    try:
        return float(os.environ.get(C.SLO_HEDGE_FRACTION_ENV,
                                    C.SLO_HEDGE_FRACTION_DEFAULT))
    except ValueError:
        return C.SLO_HEDGE_FRACTION_DEFAULT


def fault_injection(raw: Optional[str] = None) -> Dict[str, Any]:
    """Parse the test/bench fault-injection spec (env or explicit)."""
    raw = raw if raw is not None else os.environ.get(C.FAULT_INJECT_ENV, "")
    if not raw:
        return {}
    try:
        spec = json.loads(raw)
        return spec if isinstance(spec, dict) else {}
    except ValueError:
        log(f"bad {C.FAULT_INJECT_ENV}={raw!r}; ignoring")
        return {}


# --- worker registry with leases --------------------------------------------

class ClusterRegistry:
    """Lease-based worker liveness, fed by heartbeats, health probes and
    data-plane contact.  State is *computed at read time* from the lease
    and probe counters, so a stalled poller can never hold a dead worker
    healthy; transitions are detected on read/write and recorded (ring
    buffer + counters) when the computed state changes."""

    def __init__(self, lease_s: Optional[float] = None,
                 suspect_probes: Optional[int] = None,
                 clock: Optional[Any] = None):
        # clock seam (ISSUE 19): lease expiry and transition timestamps
        # run off this; the wall default preserves the pre-seam behavior
        self._clock = clock if clock is not None else clock_mod.WALL
        if lease_s is None:
            try:
                lease_s = float(os.environ.get(C.LEASE_ENV,
                                               C.LEASE_DEFAULT))
            except ValueError:
                lease_s = C.LEASE_DEFAULT
        if suspect_probes is None:
            try:
                suspect_probes = int(os.environ.get(
                    C.SUSPECT_PROBES_ENV, C.SUSPECT_PROBES_DEFAULT))
            except ValueError:
                suspect_probes = C.SUSPECT_PROBES_DEFAULT
        self.lease_s = max(float(lease_s), 0.05)
        self.suspect_probes = max(int(suspect_probes), 1)
        self._lock = threading.Lock()
        # fed concurrently by the health poller, heartbeat handlers,
        # data-plane touches and the autoscaler's retire/forget path —
        # the lockset rule holds every access to the annotation
        self._workers: Dict[str, Dict[str, Any]] = {}  # guarded-by: self._lock
        self._transitions: deque = deque(
            maxlen=C.CLUSTER_TRANSITIONS_KEPT)         # guarded-by: self._lock

    # -- writes ---------------------------------------------------------------

    def register(self, worker_id: str, info: Optional[Dict[str, Any]] = None,
                 alive: bool = True) -> Dict[str, Any]:
        """Upsert a worker.  ``alive=True`` (an explicit registration or
        heartbeat) counts as contact and starts/renews the lease;
        ``alive=False`` (config seeding) leaves it UNKNOWN until the
        first probe so a configured-but-never-started worker is never
        reported healthy."""
        wid = str(worker_id)
        now = self._clock.monotonic()
        with self._lock:
            rec = self._workers.get(wid)
            if rec is None:
                rec = self._workers[wid] = {
                    "info": dict(info or {}), "registered_at": now,
                    "last_seen": None, "failed_probes": 0,
                    "state": UNKNOWN,
                }
            elif info:
                rec["info"].update(info)
            if alive:
                rec["last_seen"] = now
                rec["failed_probes"] = 0
            self._refresh_locked(wid, rec, now)
            return {"worker_id": wid, "state": rec["state"],
                    "lease_s": self.lease_s}

    def heartbeat(self, worker_id: str,
                  info: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Lease renewal; unknown workers are auto-registered (the
        reference's workers are config-seeded, but an elastic worker
        that only knows the master URL must be able to join)."""
        return self.register(worker_id, info=info, alive=True)

    def observe_probe(self, worker_id: str, ok: bool,
                      info: Optional[Dict[str, Any]] = None) -> None:
        """Health-poller feed: a successful probe renews the lease, a
        failed one advances the suspect counter."""
        wid = str(worker_id)
        now = self._clock.monotonic()
        with self._lock:
            rec = self._workers.get(wid)
            if rec is None:
                rec = self._workers[wid] = {
                    "info": dict(info or {}), "registered_at": now,
                    "last_seen": None, "failed_probes": 0,
                    "state": UNKNOWN,
                }
            elif info:
                rec["info"].update(info)
            if ok:
                rec["last_seen"] = now
                rec["failed_probes"] = 0
            else:
                rec["failed_probes"] += 1
            self._refresh_locked(wid, rec, now)

    def touch(self, worker_id: str) -> None:
        """Data-plane contact (a tile/image POST arrived) proves
        liveness without a probe.  Only KNOWN ids renew — the image
        path's positional ``worker_N`` labels must not pollute the
        registry with phantom entries."""
        wid = str(worker_id)
        now = self._clock.monotonic()
        with self._lock:
            rec = self._workers.get(wid)
            if rec is None:
                return
            rec["last_seen"] = now
            rec["failed_probes"] = 0
            self._refresh_locked(wid, rec, now)

    def update_resources(self, worker_id: str,
                         snapshot: Dict[str, Any]) -> None:
        """Retain a worker's latest resource snapshot (ISSUE 5): fed by
        heartbeats (which now carry one) and by the federation
        endpoint's pull-through.  Only known ids retain — same phantom
        guard as :meth:`touch`."""
        wid = str(worker_id)
        if not isinstance(snapshot, dict):
            return
        with self._lock:
            rec = self._workers.get(wid)
            if rec is None:
                return
            rec["resources"] = dict(snapshot)
            rec["resources_at"] = self._clock.monotonic()

    def update_skew(self, worker_id: str, offset_s: float) -> None:
        """Feed one clock-offset sample (ISSUE 20): ``master wall clock
        at receive − worker wall clock at send`` for a heartbeat or
        registration round trip.  Each sample is the true offset plus a
        non-negative uplink delay, so the retained estimate is the
        MINIMUM over a sliding window (NTP's insight: the least-delayed
        sample is the most truthful).  Only known ids retain — same
        phantom guard as :meth:`touch`."""
        wid = str(worker_id)
        try:
            offset = float(offset_s)
        except (TypeError, ValueError):
            return
        with self._lock:
            rec = self._workers.get(wid)
            if rec is None:
                return
            samples = rec.get("skew_samples")
            if samples is None:
                samples = rec["skew_samples"] = deque(
                    maxlen=C.SKEW_SAMPLES_KEPT)
            samples.append(offset)
            rec["skew_s"] = min(samples)
            rec["skew_at"] = self._clock.monotonic()

    def skew(self, worker_id: str) -> float:
        """Current offset estimate to ADD to a worker's wall-clock
        timestamps to land them on this master's clock; 0.0 when no
        estimate exists."""
        with self._lock:
            rec = self._workers.get(str(worker_id))
            if rec is None:
                return 0.0
            return float(rec.get("skew_s") or 0.0)

    def skew_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-worker skew estimates with sample counts and age — the
        /distributed/analysis + prom gauge feed."""
        now = self._clock.monotonic()
        with self._lock:
            out = {}
            for wid, rec in self._workers.items():
                if rec.get("skew_s") is None:
                    continue
                at = rec.get("skew_at")
                out[wid] = {
                    "offset_s": round(float(rec["skew_s"]), 6),
                    "samples": len(rec.get("skew_samples") or ()),
                    "age_s": (None if at is None
                              else round(now - at, 3)),
                }
            return out

    def reset_skew(self) -> int:
        """Drop every skew estimate (POST /distributed/metrics/reset);
        returns how many workers had one."""
        with self._lock:
            n = 0
            for rec in self._workers.values():
                if rec.pop("skew_s", None) is not None:
                    n += 1
                rec.pop("skew_samples", None)
                rec.pop("skew_at", None)
            return n

    def resource_snapshots(self) -> Dict[str, Dict[str, Any]]:
        """Latest retained resource snapshot per worker with its age
        and the worker's address/state — the federation merge input."""
        now = self._clock.monotonic()
        with self._lock:
            out = {}
            for wid, rec in self._workers.items():
                st = self._refresh_locked(wid, rec, now)
                at = rec.get("resources_at")
                out[wid] = {
                    "state": st,
                    "host": rec["info"].get("host"),
                    "port": rec["info"].get("port"),
                    "resources": (dict(rec["resources"])
                                  if rec.get("resources") else None),
                    "age_s": (None if at is None
                              else round(now - at, 3)),
                }
            return out

    def seed_from_config(self, workers: List[Dict[str, Any]]) -> None:
        """Pre-register config workers (enabled only) without marking
        them alive."""
        for w in workers or []:
            if not w.get("enabled"):
                continue
            self.register(str(w.get("id")),
                          info={"host": w.get("host") or "127.0.0.1",
                                "port": w.get("port"),
                                "name": w.get("name")},
                          alive=False)

    # -- reads ----------------------------------------------------------------

    def set_retiring(self, worker_id: str, retiring: bool = True) -> bool:
        """Autoscaler drain flag: a retiring worker keeps its lease (its
        in-flight units still check in) but reads as RETIRING so the
        dispatcher stops handing it new work.  Returns False for
        unknown ids."""
        wid = str(worker_id)
        now = self._clock.monotonic()
        with self._lock:
            rec = self._workers.get(wid)
            if rec is None:
                return False
            rec["retiring"] = bool(retiring)
            self._refresh_locked(wid, rec, now)
            return True

    def forget(self, worker_id: str) -> bool:
        """Drop a worker from the registry entirely (a retired worker's
        process is gone; keeping the record would hold a DEAD tombstone
        in every snapshot forever)."""
        with self._lock:
            return self._workers.pop(str(worker_id), None) is not None

    def _compute_locked(self, rec: Dict[str, Any], now: float) -> str:
        if rec["last_seen"] is None:
            # never contacted: config-seeded entries stay UNKNOWN (the
            # dispatcher probes them normally) instead of racing to DEAD
            return UNKNOWN
        if now - rec["last_seen"] > self.lease_s:
            return DEAD
        if rec.get("retiring"):
            # draining: alive (lease fresh) but not dispatchable
            return RETIRING
        if rec["failed_probes"] >= self.suspect_probes:
            return SUSPECT
        return HEALTHY

    def _refresh_locked(self, wid: str, rec: Dict[str, Any],
                        now: float) -> str:
        new = self._compute_locked(rec, now)
        old = rec["state"]
        if new != old:
            rec["state"] = new
            self._transitions.append(
                {"worker_id": wid, "from": old, "to": new,
                 "t": self._clock.time()})
            trace_mod.GLOBAL_COUNTERS.bump(f"cluster_{new}_transitions")
            (log if new in (SUSPECT, DEAD) else debug_log)(
                f"cluster: worker {wid} {old} -> {new}")
        return new

    def state(self, worker_id: str) -> str:
        """Effective state now; UNKNOWN for unregistered ids."""
        wid = str(worker_id)
        now = self._clock.monotonic()
        with self._lock:
            rec = self._workers.get(wid)
            if rec is None:
                return UNKNOWN
            return self._refresh_locked(wid, rec, now)

    def healthy_ids(self) -> List[str]:
        now = self._clock.monotonic()
        with self._lock:
            return [wid for wid, rec in self._workers.items()
                    if self._refresh_locked(wid, rec, now) == HEALTHY]

    def snapshot(self) -> Dict[str, Any]:
        now = self._clock.monotonic()
        with self._lock:
            workers = {}
            for wid, rec in self._workers.items():
                st = self._refresh_locked(wid, rec, now)
                age = (None if rec["last_seen"] is None
                       else round(now - rec["last_seen"], 3))
                workers[wid] = {
                    "state": st,
                    "retiring": bool(rec.get("retiring")),
                    "last_seen_age_s": age,
                    "failed_probes": rec["failed_probes"],
                    "lease_remaining_s": (
                        None if rec["last_seen"] is None else
                        round(self.lease_s - (now - rec["last_seen"]), 3)),
                    **{k: v for k, v in rec["info"].items()
                       if k in ("host", "port", "name",
                                "queue_remaining")},
                }
            return {"lease_s": self.lease_s,
                    "suspect_probes": self.suspect_probes,
                    "workers": workers,
                    "transitions": list(self._transitions)}


# --- per-job work ledger -----------------------------------------------------

class WorkLedger:
    """Which participant owns which work units, with exactly-once
    check-in.  A *unit* is a tile index (tiled upscale) or a seed-slice
    id (image collector); the *owner* is a participant id ("master" or
    a worker's config id).  Completions check in through the ledger so
    retried POSTs and hedge losers are deduped at the blend; pending
    units can be reassigned (locally) or redispatched (to a healthy
    HTTP worker via the orchestrator's registered callback)."""

    def __init__(self, clock: Optional[Any] = None) -> None:
        # clock seam (ISSUE 19): job ages, the latency EMA and the
        # hedge-overdue bars run off this; wall default = old behavior
        self._clock = clock if clock is not None else clock_mod.WALL
        self._lock = threading.Lock()
        self._jobs: Dict[str, Dict[str, Any]] = {}      # guarded-by: self._lock
        self._redispatch: Dict[str, Callable] = {}      # guarded-by: self._lock
        self._completed: deque = deque(
            maxlen=C.LEDGER_COMPLETED_KEPT)             # guarded-by: self._lock
        # deadline-aware hedging (ISSUE 9): per-job SLO deadlines on the
        # monotonic clock, stamped by the orchestrator BEFORE create_job
        # (the request knows its budget; the op only knows its units).
        # Bounded FIFO like the redispatcher map — a request whose job
        # never materializes must not leak its deadline forever.
        self._deadlines: Dict[str, float] = {}          # guarded-by: self._lock
        # durability plane (ISSUE 7): when a WAL is attached, every
        # ownership transition appends a record, winning check-ins spill
        # their payload first, and create_job merges the crash-recovered
        # unit states so a resumed job re-refines ONLY unfinished units
        self._wal = None
        self._unit_store = None
        self._recovered_jobs: Dict[str, Dict[str, Any]] = {}  # guarded-by: self._lock
        # multi-master takeover (ISSUE 14): an ABSORBED shard's
        # recovered jobs carry their own UnitStore (the dead shard's
        # spill dir) — preload/blend reads come from THERE, while new
        # check-ins spill into this master's own store/WAL
        self._recovered_stores: Dict[str, Any] = {}  # guarded-by: self._lock

    def attach_wal(self, wal, unit_store,
                   recovered_jobs: Optional[Dict[str, Any]] = None) -> None:
        """Wire the durability plane in (runtime/durable.py).
        ``recovered_jobs`` is the replayed WAL state keyed by job id —
        consumed (and cleared per job) by :meth:`create_job`."""
        # under the lock: a standby takeover attaches on its watcher
        # thread while collector drains may be reading recovered state
        with self._lock:
            self._wal = wal
            self._unit_store = unit_store
            if recovered_jobs is not None:
                self._recovered_jobs = dict(recovered_jobs)

    def merge_recovered(self, recovered_jobs: Dict[str, Any],
                        unit_store: Any = None) -> None:
        """ADD a peer shard's replayed jobs (multi-master absorb) —
        unlike :meth:`attach_wal` this never replaces the existing
        recovered set, and each merged job remembers the DEAD shard's
        unit store so its preloaded payloads blend from the right
        disk."""
        with self._lock:
            for jid, job in (recovered_jobs or {}).items():
                self._recovered_jobs[str(jid)] = job
                if unit_store is not None:
                    self._recovered_stores[str(jid)] = unit_store

    def _wal_append(self, rtype: str, **fields) -> None:
        """Append an ownership-transition record; fencing errors
        PROPAGATE (a deposed master must stop mutating job state), any
        other failure degrades to in-memory-only."""
        if self._wal is None:
            return
        from comfyui_distributed_tpu.runtime import durable as dur
        try:
            self._wal.append(rtype, **fields)
        except (dur.FencedError, dur.WalCrashedError):
            raise
        except Exception as e:  # noqa: BLE001 - durability is best-effort
            debug_log(f"ledger: wal append {rtype} failed: {e}")

    # -- lifecycle ------------------------------------------------------------

    def create_job(self, job_id: str, owners: Dict[Any, str],
                   kind: str = "tile") -> None:
        jid = str(job_id)
        now = self._clock.monotonic()
        preloaded = []
        with self._lock:
            # consume the recovered state under the lock (it used to be
            # popped outside — racing a concurrent takeover's attach_wal
            # could drop or double-apply a recovered job)
            recovered = self._recovered_jobs.pop(jid, None)
            # an absorbed job reads its preloaded payloads from the
            # DEAD shard's store; everything else uses our own
            job_store = self._recovered_stores.pop(jid, None) \
                or self._unit_store
            rec_units = (recovered or {}).get("units", {})
            units = {}
            for u, o in owners.items():
                ru = rec_units.get(str(u))
                if ru is not None and ru.get("done") \
                        and job_store is not None \
                        and ru.get("spilled") \
                        and job_store.has(jid, u):
                    # completed before the crash AND its payload
                    # survived: never re-refined, blended from the spill
                    units[u] = {"owner": str(ru.get("by") or o),
                                "state": "done", "attempts": 1,
                                "hedged": False, "hedge_owner": None,
                                "done_by": str(ru.get("by") or o)}
                    preloaded.append(u)
                else:
                    # pending (or done-but-payload-lost: recomputed —
                    # deterministic seeds make the redo bit-identical);
                    # a recovered reassignment keeps its LAST owner
                    owner = str(ru["owner"]) if ru is not None \
                        and not ru.get("done") and ru.get("owner") \
                        else str(o)
                    units[u] = {"owner": owner, "state": "pending",
                                "attempts": 1, "hedged": False,
                                "hedge_owner": None, "done_by": None}
            self._jobs[jid] = {
                "kind": kind,
                "created_at": now,
                "units": units,
                # per-owner last-activity clock feeding the moving
                # per-unit latency estimate (EMA of check-in intervals)
                "owner_last": {},
                "latency_ema": None,
                "reassigned": 0,
                "hedged": 0,
                "recovered": recovered is not None,
                "recovered_handled": False,
                "preloaded": list(preloaded),
                # where THIS job's preloaded payloads live (differs
                # from self._unit_store only for absorbed jobs)
                "store": job_store,
            }
        if preloaded:
            log(f"ledger: job {jid} recovered with {len(preloaded)}/"
                f"{len(owners)} unit(s) already durable — only the "
                f"remainder will be re-refined")
            trace_mod.GLOBAL_COUNTERS.bump("wal_preloaded_units",
                                           len(preloaded))
        self._wal_append("job_create", job=jid, kind=kind,
                         owners={str(u): str(o)
                                 for u, o in owners.items()})

    def has_job(self, job_id: str) -> bool:
        with self._lock:
            return str(job_id) in self._jobs

    def finish_job(self, job_id: str) -> Optional[Dict[str, Any]]:
        """Seal a job: drop live state, keep a bounded summary (served
        by GET /distributed/cluster and consumed by the fault bench)."""
        jid = str(job_id)
        with self._lock:
            job = self._jobs.pop(jid, None)
            self._redispatch.pop(jid, None)
            self._deadlines.pop(jid, None)
            if job is None:
                return None
            units = job["units"]
            done = sum(1 for u in units.values() if u["state"] == "done")
            summary = {
                "job_id": jid, "kind": job["kind"],
                "total_units": len(units), "done_units": done,
                "pending_units": sorted(
                    str(u) for u, rec in units.items()
                    if rec["state"] != "done"),
                "reassigned_units": job["reassigned"],
                "hedged_units": job["hedged"],
                "recovered": bool(job.get("recovered")),
                "preloaded_units": len(job.get("preloaded") or ()),
                "duration_s": round(self._clock.monotonic() - job["created_at"],
                                    4),
                "finished_at": self._clock.time(),
            }
            self._completed.append(summary)
        self._wal_append("job_finish", job=jid)
        if self._unit_store is not None:
            # the finish record is durable: the spilled payloads (and
            # this job's idempotency keys, dropped by the tracker) are
            # no longer needed for recovery
            self._unit_store.drop_job(jid)
        store = job.get("store")
        if store is not None and store is not self._unit_store:
            # absorbed job: its preloads lived in the dead shard's dir
            store.drop_job(jid)
        return summary

    # -- check-in (exactly-once) ----------------------------------------------

    def check_in(self, job_id: str, unit: Any, worker_id: str,
                 payload: Optional[tuple] = None) -> bool:
        """Record a unit completion.  Returns True exactly once per
        unit — the first completion wins; retried POSTs and hedge
        losers get False and are dropped at the blend.  Jobs the ledger
        never saw (worker side, SPMD mode) always return True so the
        ledger is opt-in.

        ``payload`` (``(tensors, meta)``, durability plane) is spilled
        to the unit store BEFORE the check-in record is appended, so a
        recovered master blends this unit from disk instead of
        re-refining it; a crash between spill and append leaves an
        orphan payload that replay ignores."""
        now = self._clock.monotonic()
        status = self._check_in_locked(job_id, unit, worker_id, now)
        if status == "dup":
            return False
        if status == "untracked":
            return True
        if self._wal is not None:
            spilled = False
            if payload is not None and self._unit_store is not None:
                tensors, meta = payload
                try:
                    self._unit_store.put(str(job_id), unit, tensors,
                                         meta)
                    spilled = True
                except OSError as e:
                    debug_log(f"ledger: unit spill {job_id}/{unit} "
                              f"failed ({e}); unit will be recomputed "
                              f"on recovery")
            self._wal_append("unit_checkin", job=str(job_id),
                             unit=str(unit), by=str(worker_id),
                             spilled=spilled)
        return True

    def _check_in_locked(self, job_id: str, unit: Any, worker_id: str,
                         now: float) -> str:
        with self._lock:
            job = self._jobs.get(str(job_id))
            if job is None:
                return "untracked"
            rec = job["units"].get(unit)
            if rec is None:
                # unit the ledger didn't plan (shouldn't happen; accept
                # rather than drop real work)
                debug_log(f"ledger: unplanned unit {unit!r} for "
                          f"{job_id}")
                return "untracked"
            if rec["state"] == "done":
                trace_mod.GLOBAL_COUNTERS.bump(
                    "cluster_duplicate_checkins")
                return "dup"
            rec["state"] = "done"
            rec["done_by"] = str(worker_id)
            if rec["hedge_owner"]:
                # attribution only when the hedge runner has its own
                # identity (master-local tile hedges); a redispatch
                # hedge impersonates the lost owner and stays uncounted
                won = str(worker_id) == rec["hedge_owner"]
                trace_mod.GLOBAL_COUNTERS.bump(
                    "cluster_hedge_wins" if won else "cluster_hedge_losses")
            # moving per-unit latency estimate: EMA over each owner's
            # inter-check-in interval (first interval anchors at job
            # creation)
            last = job["owner_last"].get(str(worker_id),
                                         job["created_at"])
            sample = max(now - last, 1e-6)
            ema = job["latency_ema"]
            job["latency_ema"] = sample if ema is None \
                else 0.7 * ema + 0.3 * sample
            job["owner_last"][str(worker_id)] = now
            return "won"

    # -- queries --------------------------------------------------------------

    def pending(self, job_id: str, owner: Optional[str] = None
                ) -> List[Any]:
        with self._lock:
            job = self._jobs.get(str(job_id))
            if job is None:
                return []
            return sorted(
                (u for u, rec in job["units"].items()
                 if rec["state"] != "done"
                 and (owner is None or rec["owner"] == str(owner))),
                key=str)

    def owners_of_pending(self, job_id: str,
                          skip_hedged: bool = False) -> Dict[Any, str]:
        """Pending units and their owners; ``skip_hedged=True`` drops
        units a hedge is already racing (recovery for those would be
        triple work — the hedge or the post-drain fallback covers
        them)."""
        with self._lock:
            job = self._jobs.get(str(job_id))
            if job is None:
                return {}
            return {u: rec["owner"] for u, rec in job["units"].items()
                    if rec["state"] != "done"
                    and not (skip_hedged and rec["hedged"])}

    def progress(self, job_id: str) -> tuple:
        with self._lock:
            job = self._jobs.get(str(job_id))
            if job is None:
                return (0, 0)
            units = job["units"]
            return (sum(1 for u in units.values()
                        if u["state"] == "done"), len(units))

    def latency_estimate(self, job_id: str) -> Optional[float]:
        with self._lock:
            job = self._jobs.get(str(job_id))
            return None if job is None else job["latency_ema"]

    def attempts(self, job_id: str, unit: Any) -> int:
        with self._lock:
            job = self._jobs.get(str(job_id))
            if job is None:
                return 0
            rec = job["units"].get(unit)
            return 0 if rec is None else rec["attempts"]

    # -- recovery -------------------------------------------------------------

    def reassign(self, job_id: str, units: List[Any],
                 new_owner: str) -> List[Any]:
        """Move still-pending units to ``new_owner``; returns the units
        actually moved (a unit that completed in the meantime stays
        put)."""
        moved = []
        with self._lock:
            job = self._jobs.get(str(job_id))
            if job is None:
                return moved
            for u in units:
                rec = job["units"].get(u)
                if rec is None or rec["state"] == "done":
                    continue
                rec["owner"] = str(new_owner)
                rec["attempts"] += 1
                moved.append(u)
            job["reassigned"] += len(moved)
        if moved:
            trace_mod.GLOBAL_COUNTERS.bump("cluster_reassigned_units",
                                           len(moved))
            self._wal_append("unit_reassign", job=str(job_id),
                             units=[str(u) for u in moved],
                             to=str(new_owner))
        return moved

    def mark_hedged(self, job_id: str, units: List[Any],
                    hedge_owner: Optional[str] = None) -> List[Any]:
        """Record a speculative re-issue; the original owner keeps the
        unit (first completion wins either way).  ``hedge_owner`` names
        the hedge runner for win/loss attribution; None records the
        hedge without attribution (redispatch hedges impersonate the
        lost identity on the wire)."""
        hedged = []
        with self._lock:
            job = self._jobs.get(str(job_id))
            if job is None:
                return hedged
            for u in units:
                rec = job["units"].get(u)
                if rec is None or rec["state"] == "done" \
                        or rec["hedged"]:
                    continue
                rec["hedged"] = True
                rec["hedge_owner"] = (None if hedge_owner is None
                                      else str(hedge_owner))
                rec["attempts"] += 1
                hedged.append(u)
            job["hedged"] += len(hedged)
        if hedged:
            trace_mod.GLOBAL_COUNTERS.bump("cluster_hedges", len(hedged))
            self._wal_append("unit_hedge", job=str(job_id),
                             units=[str(u) for u in hedged],
                             by=(None if hedge_owner is None
                                 else str(hedge_owner)))
        return hedged

    def is_hedged(self, job_id: str, unit: Any) -> bool:
        with self._lock:
            job = self._jobs.get(str(job_id))
            if job is None:
                return False
            rec = job["units"].get(unit)
            return bool(rec and rec["hedged"])

    def unmark_hedged(self, job_id: str, units: List[Any]) -> None:
        """Roll back a hedge that never launched (no target, dispatch
        failed) so the unit stays eligible for dead-owner reassignment
        and future hedges."""
        with self._lock:
            job = self._jobs.get(str(job_id))
            if job is None:
                return
            n = 0
            for u in units:
                rec = job["units"].get(u)
                if rec is not None and rec["hedged"] \
                        and rec["state"] != "done":
                    rec["hedged"] = False
                    rec["hedge_owner"] = None
                    rec["attempts"] = max(rec["attempts"] - 1, 1)
                    n += 1
            job["hedged"] -= n

    def set_deadline(self, job_id: str,
                     deadline_monotonic: float) -> None:
        """Stamp a job's SLO deadline (monotonic clock).  May be called
        before :meth:`create_job` — the orchestrator stamps at dispatch
        time, the op creates the job when it runs.  Re-keys
        :meth:`overdue_units` on the remaining budget."""
        with self._lock:
            self._deadlines[str(job_id)] = float(deadline_monotonic)
            while len(self._deadlines) > 512:
                self._deadlines.pop(next(iter(self._deadlines)))

    def deadline(self, job_id: str) -> Optional[float]:
        with self._lock:
            return self._deadlines.get(str(job_id))

    def overdue_units(self, job_id: str,
                      factor: Optional[float] = None,
                      min_progress_pct: Optional[float] = None,
                      min_wait_s: Optional[float] = None
                      ) -> Dict[Any, str]:
        """Hedge candidates: pending, not already hedged, whose owner
        has been silent longer than ``max(factor x the moving latency
        estimate, min_wait_s)`` — but only once the job is at least
        ``min_progress_pct`` % complete (the Tail-at-Scale guard: hedge
        the last stragglers, not the whole job; the wait floor keeps
        the happy path hedge-free when units land in sub-second
        bursts).

        Deadline-aware re-keying (ISSUE 9): a job stamped with an SLO
        deadline (:meth:`set_deadline`) hedges on its REMAINING BUDGET
        once that is tighter than the global policy — the overdue bar
        drops to ``max(DTPU_SLO_HEDGE_FRACTION x budget left,
        SLO_MIN_WAIT_S)`` and the min-progress gate is waived, so a job
        about to blow its deadline hedges its first straggler instead
        of politely waiting for 50% completion."""
        factor = hedge_factor() if factor is None else factor
        min_pct = hedge_pct() if min_progress_pct is None \
            else min_progress_pct
        min_wait = hedge_min_wait() if min_wait_s is None else min_wait_s
        now = self._clock.monotonic()
        with self._lock:
            job = self._jobs.get(str(job_id))
            if job is None or job["latency_ema"] is None:
                return {}
            units = job["units"]
            if not units:
                return {}
            threshold = max(factor * job["latency_ema"], min_wait)
            slo_pressed = False
            dl = self._deadlines.get(str(job_id))
            if dl is not None:
                budget = max(dl - now, 0.0)
                slo_threshold = max(budget * slo_hedge_fraction(),
                                    C.SLO_MIN_WAIT_S)
                if slo_threshold < threshold:
                    threshold = slo_threshold
                    slo_pressed = True
            done = sum(1 for u in units.values() if u["state"] == "done")
            if not slo_pressed and 100.0 * done / len(units) < min_pct:
                return {}
            out = {}
            for u, rec in units.items():
                if rec["state"] == "done" or rec["hedged"]:
                    continue
                last = job["owner_last"].get(rec["owner"],
                                             job["created_at"])
                if now - last > threshold:
                    out[u] = rec["owner"]
        if out and slo_pressed:
            trace_mod.GLOBAL_COUNTERS.bump("cluster_slo_overdue",
                                           len(out))
        return out

    # -- crash recovery (durability plane) ------------------------------------

    def load_payloads(self, job_id: str) -> Dict[Any, tuple]:
        """Spilled ``(tensors, meta)`` payloads for this job's preloaded
        (recovered-done) units — the blend inputs that replace a
        re-refine.  A unit whose file went unreadable since create_job
        is downgraded back to pending here, so the drain recomputes it
        instead of blending a hole."""
        jid = str(job_id)
        with self._lock:
            job = self._jobs.get(jid)
            preloaded = list(job.get("preloaded") or ()) if job else []
            store = (job.get("store") if job else None) \
                or self._unit_store
        if not preloaded or store is None:
            return {}
        out: Dict[Any, tuple] = {}
        lost = []
        for u in preloaded:
            payload = store.get(jid, u)
            if payload is None:
                lost.append(u)
            else:
                out[u] = payload
        if lost:
            with self._lock:
                job = self._jobs.get(jid)
                if job is not None:
                    for u in lost:
                        rec = job["units"].get(u)
                        if rec is not None:
                            rec["state"] = "pending"
                            rec["done_by"] = None
                    job["preloaded"] = [u for u in job["preloaded"]
                                        if u not in lost]
            log(f"ledger: {len(lost)} recovered unit payload(s) of "
                f"{jid} unreadable; recomputing them")
        return out

    def take_recovered_lost(self, job_id: str) -> Dict[str, List[Any]]:
        """Once per recovered job: the pending units whose owner is a
        participant from the DEAD epoch (any non-master owner — their
        dispatches died with the old master), grouped by owner.  The
        drains treat these exactly like lease-expired owners:
        redispatch with explicit unit lists, else master-local refine."""
        with self._lock:
            job = self._jobs.get(str(job_id))
            if job is None or not job.get("recovered") \
                    or job.get("recovered_handled"):
                return {}
            job["recovered_handled"] = True
            out: Dict[str, List[Any]] = {}
            for u, rec in job["units"].items():
                if rec["state"] != "done" and rec["owner"] != "master":
                    out.setdefault(rec["owner"], []).append(u)
            return out

    # -- redispatch (orchestrator-registered) ---------------------------------

    def set_redispatcher(self, job_id: str, fn: Callable) -> None:
        """``fn`` is ``async (units, lost_owner) -> bool`` — re-issue
        the units to a healthy HTTP worker.  Registered by
        ``workflow/orchestrate.py`` before dispatch; the collectors call
        :meth:`redispatch` when an owner dies.  Bounded FIFO: entries
        are popped by finish_job, but a run that crashes before its
        collector executes would otherwise leak its graph-capturing
        closure forever."""
        with self._lock:
            self._redispatch[str(job_id)] = fn
            while len(self._redispatch) > 512:
                self._redispatch.pop(next(iter(self._redispatch)))

    def has_redispatcher(self, job_id: str) -> bool:
        with self._lock:
            return str(job_id) in self._redispatch

    async def redispatch(self, job_id: str, units: List[Any],
                         lost_owner: str) -> bool:
        with self._lock:
            fn = self._redispatch.get(str(job_id))
        if fn is None:
            return False
        try:
            ok = bool(await fn(units, lost_owner))
        except Exception as e:  # noqa: BLE001 - recovery must not crash
            log(f"ledger: redispatch for {job_id} failed: "
                f"{type(e).__name__}: {e}")
            return False
        if ok:
            trace_mod.GLOBAL_COUNTERS.bump("cluster_redispatches")
        return ok

    # -- introspection --------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            active = {}
            for jid, job in self._jobs.items():
                units = job["units"]
                done = sum(1 for u in units.values()
                           if u["state"] == "done")
                dl = self._deadlines.get(jid)
                active[jid] = {
                    "kind": job["kind"],
                    "total_units": len(units),
                    "done_units": done,
                    "slo_deadline_remaining_s": (
                        None if dl is None
                        else round(dl - self._clock.monotonic(), 3)),
                    "reassigned_units": job["reassigned"],
                    "hedged_units": job["hedged"],
                    "latency_estimate_s": (
                        None if job["latency_ema"] is None
                        else round(job["latency_ema"], 4)),
                    "age_s": round(self._clock.monotonic() - job["created_at"],
                                   3),
                }
            return {"active_jobs": active,
                    "completed_jobs": list(self._completed)}


# --- worker-side heartbeat ---------------------------------------------------

class HeartbeatSender:
    """Daemon thread a worker server runs to renew its lease at the
    master (``POST /distributed/heartbeat``) every ``lease/3``.  Gated
    on DTPU_MASTER_URL + DTPU_WORKER_ID (the process manager exports
    both for spawned workers); external/elastic workers set them by
    hand.  Best-effort: a down master just means the next beat retries."""

    def __init__(self, master_url: str, worker_id: str,
                 interval: Optional[float] = None,
                 port: Optional[int] = None):
        self.master_url = master_url.rstrip("/")
        self.worker_id = str(worker_id)
        self.port = port
        if interval is None:
            try:
                lease = float(os.environ.get(C.LEASE_ENV, C.LEASE_DEFAULT))
            except ValueError:
                lease = C.LEASE_DEFAULT
            interval = max(lease / C.HEARTBEAT_FRACTION, 0.05)
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.beats_sent = 0

    def beat_once(self, timeout: float = 3.0) -> bool:
        import urllib.request

        from comfyui_distributed_tpu.utils import chaos as chaos_mod
        cm = chaos_mod.get_chaos()
        if cm.active and cm.heartbeat_frozen(self.worker_id):
            # chaos harness: a frozen heartbeat ages the lease out while
            # the process is alive — the suspect/dead/rehome edge
            debug_log(f"chaos: heartbeat for {self.worker_id} frozen")
            return False
        payload = {"worker_id": self.worker_id}
        if self.port:
            payload["port"] = self.port
        # heartbeats double as the fleet-telemetry transport (ISSUE 5):
        # each beat carries this worker's current resource snapshot so
        # the master's federated metrics stay fresh without a scrape
        # fan-out.  Best-effort — a failed probe must not skip a beat —
        # and honoring DTPU_RESOURCE=0: with the monitor disabled a
        # fresh probe could initialize the JAX backend (seconds on a
        # real TPU) on the heartbeat thread and blow the lease.
        try:
            from comfyui_distributed_tpu.utils import resource as res_mod
            if res_mod.resource_enabled():
                payload["resources"] = res_mod.fleet_sample()
        except Exception as e:  # noqa: BLE001 - liveness > telemetry
            debug_log(f"heartbeat resource snapshot failed: {e}")
        # the beat carries this worker's wall clock (ISSUE 20): the
        # master turns (its receive time − sent_at) into a per-worker
        # clock-offset estimate so shipped worker spans become
        # timestamp-comparable with master spans.  Stamped LAST — the
        # resource probe above must not inflate the delay baked into
        # the sample (the master min-filters, but why waste a sample)
        payload["sent_at"] = time.time()
        req = urllib.request.Request(
            f"{self.master_url}/distributed/heartbeat",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                r.read()
            self.beats_sent += 1
            return True
        except Exception as e:  # noqa: BLE001 - best-effort renewal
            debug_log(f"heartbeat to {self.master_url} failed: {e}")
            return False

    def rehome(self, master_url: str, attempts: int = 3) -> bool:
        """Retarget this sender at a new master and register there NOW.

        The takeover fix (ISSUE 9): the first rehomed beat can race the
        dying master's sockets (connection refused / reset while the
        host is mid-failover), and a single best-effort beat would
        leave this worker unregistered at the new master for a full
        heartbeat interval — during which its lease reads as expired
        and its in-flight units get needlessly reassigned.  A short
        immediate retry burst closes that window: the worker is
        re-registered on the first beat that lands."""
        self.master_url = master_url.rstrip("/")
        for i in range(max(attempts, 1)):
            if self.beat_once():
                return True
            time.sleep(min(0.2 * (2 ** i), 1.0))
        return False

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dtpu-heartbeat")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.beat_once()


class MultiHeartbeatSender:
    """Multi-master worker heartbeats (ISSUE 14): one
    :class:`HeartbeatSender` — one LEASE — per master shard, so each
    master detects and recovers this worker's death independently.
    Quacks like a single sender for the rehome route."""

    def __init__(self, master_urls: List[str], worker_id: str,
                 port: Optional[int] = None):
        self.worker_id = str(worker_id)
        self.port = port
        self._lock = threading.Lock()
        self._senders: Dict[str, HeartbeatSender] = {  # guarded-by: self._lock
            u.rstrip("/"): HeartbeatSender(u, worker_id, port=port)
            for u in dict.fromkeys(
                x.strip() for x in master_urls if x.strip())}

    @property
    def master_urls(self) -> List[str]:
        with self._lock:
            return sorted(self._senders)

    def start(self) -> None:
        with self._lock:
            senders = list(self._senders.values())
        for hb in senders:
            hb.start()

    def stop(self) -> None:
        with self._lock:
            senders = list(self._senders.values())
        for hb in senders:
            hb.stop()

    def beat_once(self) -> int:
        with self._lock:
            senders = list(self._senders.values())
        return sum(1 for hb in senders if hb.beat_once())

    def rehome(self, master_url: str, attempts: int = 3) -> bool:
        """A (new) master announced itself: ensure a lease heartbeat
        toward it exists and register there NOW.  Existing masters keep
        their senders — multi-homing is the contract."""
        url = master_url.rstrip("/")
        with self._lock:
            hb = self._senders.get(url)
            if hb is None:
                hb = self._senders[url] = HeartbeatSender(
                    url, self.worker_id, port=self.port)
                fresh = True
            else:
                fresh = False
        if fresh:
            hb.start()
        ok = False
        for i in range(max(attempts, 1)):
            if hb.beat_once():
                ok = True
                break
            time.sleep(min(0.2 * (2 ** i), 1.0))
        return ok


def maybe_start_heartbeat(port: Optional[int] = None):
    """Start the worker->master heartbeat(s) when the environment names
    a master (spawned workers inherit DTPU_MASTER_URL/DTPU_WORKER_ID
    from the process manager).  ``DTPU_MASTER_URLS`` (comma list) is
    the multi-master form: one sender — one lease — per master shard."""
    multi = os.environ.get(C.MASTER_URLS_ENV, "")
    master = os.environ.get(C.MASTER_URL_ENV)
    wid = os.environ.get(C.WORKER_ID_ENV)
    if not wid or not (multi or master):
        return None
    if multi:
        urls = [u for u in multi.split(",") if u.strip()]
        hb = MultiHeartbeatSender(urls, wid, port=port)
        hb.start()
        log(f"heartbeat: renewing {len(hb.master_urls)} master-shard "
            f"lease(s) for {wid!r} ({', '.join(hb.master_urls)})")
        return hb
    hb = HeartbeatSender(master, wid, port=port)
    hb.start()
    log(f"heartbeat: renewing lease for {wid!r} at {master} every "
        f"{hb.interval:.1f}s")
    return hb
