"""Runtime services: job store, worker process manager, monitors."""

from comfyui_distributed_tpu.runtime.jobs import JobStore  # noqa: F401
from comfyui_distributed_tpu.runtime.manager import (  # noqa: F401
    WorkerProcessManager,
)
