"""Runtime services: job store, worker process manager, monitors, and
the fault-tolerant cluster control plane (registry + work ledger)."""

from comfyui_distributed_tpu.runtime.cluster import (  # noqa: F401
    ClusterRegistry,
    WorkLedger,
)
from comfyui_distributed_tpu.runtime.jobs import JobStore  # noqa: F401
from comfyui_distributed_tpu.runtime.manager import (  # noqa: F401
    WorkerProcessManager,
)
