"""Native checkpoint save/restore (orbax) — the subsystem the reference lacks.

SURVEY.md §5: "Checkpoint / resume: ABSENT" in the reference — the only
persisted state is config + PIDs; model weights live solely in torch
checkpoint files that every machine must carry.  Here:

- pipelines (UNet + CLIPs + VAE param trees) save/restore through orbax in
  a sharding-aware, mmap-friendly native format — restoring is much faster
  than re-converting a torch single-file checkpoint, and on a mesh the
  restore can place shards directly;
- the registry transparently loads a directory checkpoint when the
  configured "checkpoint name" points at one (``models_dir/<name>/``),
  falling back to torch-file conversion and then virtual init;
- train-state checkpointing for the training step (params + opt state +
  step) so long fine-tunes survive preemption.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple


from comfyui_distributed_tpu.utils.logging import log

METADATA_FILE = "dtpu_checkpoint.json"


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def is_native_checkpoint(path: str) -> bool:
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, METADATA_FILE))


def save_pipeline_checkpoint(path: str, family_name: str, unet: Any,
                             clips: List[Any], vae: Any) -> None:
    """Write a native pipeline checkpoint: one orbax tree per component +
    a metadata manifest."""
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    ckptr = _checkpointer()
    tree = {"unet": unet, "vae": vae,
            **{f"clip_{i}": c for i, c in enumerate(clips)}}
    ckptr.save(os.path.join(path, "params"), tree, force=True)
    ckptr.wait_until_finished()
    with open(os.path.join(path, METADATA_FILE), "w", encoding="utf-8") as f:
        json.dump({"format": "dtpu-pipeline", "version": 1,
                   "family": family_name, "num_clips": len(clips)}, f)
    log(f"saved native checkpoint ({family_name}) -> {path}")


def load_pipeline_checkpoint(path: str) -> Tuple[str, Any, List[Any], Any]:
    """Restore (family_name, unet, clips, vae) from a native checkpoint."""
    path = os.path.abspath(path)
    with open(os.path.join(path, METADATA_FILE), "r", encoding="utf-8") as f:
        meta = json.load(f)
    ckptr = _checkpointer()
    tree = ckptr.restore(os.path.join(path, "params"))
    clips = [tree[f"clip_{i}"] for i in range(int(meta["num_clips"]))]
    log(f"restored native checkpoint ({meta['family']}) <- {path}")
    return meta["family"], tree["unet"], clips, tree["vae"]


# --- train-state checkpointing ----------------------------------------------

def save_train_state(path: str, params: Any, opt_state: Any,
                     step: int, extra: Optional[Dict[str, Any]] = None) -> None:
    """Persist a training run (params + optimizer state + step counter) so a
    preempted fine-tune resumes exactly."""
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    ckptr = _checkpointer()
    ckptr.save(os.path.join(path, f"step_{step:08d}"),
               {"params": params, "opt_state": opt_state}, force=True)
    ckptr.wait_until_finished()
    with open(os.path.join(path, METADATA_FILE), "w", encoding="utf-8") as f:
        json.dump({"format": "dtpu-train", "version": 1, "step": int(step),
                   **(extra or {})}, f)


def latest_train_step(path: str) -> Optional[int]:
    try:
        with open(os.path.join(path, METADATA_FILE), "r",
                  encoding="utf-8") as f:
            return int(json.load(f)["step"])
    except (FileNotFoundError, KeyError, ValueError):
        return None


def load_train_state(path: str, step: Optional[int] = None
                     ) -> Tuple[Any, Any, int]:
    path = os.path.abspath(path)
    step = step if step is not None else latest_train_step(path)
    if step is None:
        raise FileNotFoundError(f"no train checkpoint under {path}")
    ckptr = _checkpointer()
    tree = ckptr.restore(os.path.join(path, f"step_{step:08d}"))
    return tree["params"], tree["opt_state"], int(step)
