"""Cross-request compute reuse plane (ISSUE 13).

Production diffusion traffic is massively redundant — retry storms,
seed-variant fans, and re-upscales of mostly-unchanged images re-pay
text-encode, VAE-encode, and even whole-graph compute that is
byte-identical to work this process just did.  The vLLM lesson
(PAPERS.md) is that memory/cache policy around an *unchanged kernel*
dominates serving throughput; this module is that policy, in three
content-addressed tiers plus a preview/cancellation channel:

- **Exact-hit result cache** (:attr:`ReusePlane.result`): key = the
  PR 2 structural signature + the FULL widget values (seed included) —
  a byte-identical re-submission replays the stored per-prompt images
  from host memory instead of re-running the graph.  The server stamps
  the replayed job's history/metrics/span as ``cache_hit``.
- **Sub-graph memoization** (:attr:`ReusePlane.subgraph`): text-encoder
  embeddings and VAE-encoded conditioning latents cached ON DEVICE
  across requests, keyed by a content hash of their input sub-graph
  (:func:`subgraph_keys`) — a retry/variant storm pays encode once;
  the continuous-batching bucket build's prefix run consumes the same
  cache, so new slots skip straight to denoise.
- **Changed-tile skipping** (:attr:`ReusePlane.tiles`): per-tile
  content hashes in the tiled-upscale path — a re-run of a
  mostly-unchanged image refines only the dirty tiles; the WorkLedger's
  pending set shrinks to the dirty units and the blend reuses stored
  refined windows bit-identically.

Every tier is an LRU bounded by its own byte budget (``DTPU_CACHE_*``
envs; the PR 5 resource telemetry samples the total into a
``cache_bytes`` ring so residency is observable next to RSS/HBM), and
``DTPU_CACHE=0`` is a true kill switch: the hot paths check
:func:`reuse_enabled` before any key is computed or any cache touched —
the PR 5 ``DTPU_RESOURCE=0`` pattern.

The **preview/cancellation channel** (:class:`PreviewBus`): step-wise
progressive previews streamed over SSE from the denoise loop (the
continuous-batching driver publishes a cheap latent->RGB projection at
step boundaries, only while a subscriber is attached), where a
disconnected client is the cancellation signal — the job is marked
abandoned, its CB slot exits at the next step boundary, queued copies
are purged, and the ledger/WAL record the abandonment.

Host-side hashing (``np.asarray`` et al.) lives HERE, outside the
dtpu-lint spine-host-fetch scope, so the ops layer calls helpers
instead of growing new host-fetch sites.
"""

from __future__ import annotations

import hashlib
import json
import os
import queue as queue_mod
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils import trace as trace_mod
from comfyui_distributed_tpu.utils.logging import debug_log


class AbandonedError(RuntimeError):
    """The job's last preview client disconnected (client-gone
    cancellation): the prompt is finalized as ``abandoned`` instead of
    executed to completion."""


# --- kill switches -----------------------------------------------------------

def reuse_enabled() -> bool:
    """``DTPU_CACHE=0`` disables every cache tier entirely: callers
    check this BEFORE computing keys or touching a cache, so the off
    state costs one env read on the hot path (the PR 5
    ``DTPU_RESOURCE=0`` pattern)."""
    return os.environ.get(C.CACHE_ENV, "1").lower() \
        not in ("0", "false", "off")


def previews_enabled() -> bool:
    return os.environ.get(C.PREVIEW_ENV, "1").lower() \
        not in ("0", "false", "off")


def _env_int(env: str, default: int) -> int:
    try:
        return int(os.environ.get(env, default))
    except (TypeError, ValueError):
        return int(default)


# --- content keys ------------------------------------------------------------

def _sha(blob: str) -> str:
    return hashlib.sha256(blob.encode()).hexdigest()


def hash_array(arr: Any) -> str:
    """Content hash of an array-like (host fetch happens here, outside
    the spine-lint scope; callers pass device arrays only for small
    conditioning tensors)."""
    a = np.ascontiguousarray(np.asarray(arr))
    return hashlib.sha1(
        a.tobytes() + str((a.shape, a.dtype.str)).encode()).hexdigest()


def result_key(prompt: Dict[str, Any],
               input_dir: Optional[str] = None,
               models_dir: Optional[str] = None,
               scope: Optional[str] = None) -> Optional[str]:
    """Exact-hit cache key: the canonical FULL node/widget structure
    (seed included — this is the PR 2 structural signature WITHOUT the
    seed mask) over the deterministic-safe node set, plus out-of-graph
    state salts (LoadImage file stat, the serving dirs).  A near-miss
    (ONE widget changed) produces a different key by construction;
    None = not cacheable (graphs with distributed nodes, hidden
    orchestration state, or any node type outside the safe set run
    normally, every time)."""
    nodes: Dict[str, Any] = {}
    salts: List[str] = [f"dirs:{input_dir or ''}:{models_dir or ''}"]
    if scope:
        # shard-owner-epoch scope (ISSUE 14 satellite): with N active
        # masters sharing this process-global plane, shard A must never
        # serve shard B's stored outputs, and entries a DEPOSED epoch
        # stored must go cold after a takeover (the new owner cannot
        # vouch the dead master finished storing them) — both fall out
        # of folding "<shard>:e<wal-epoch>" into the key.  Unset (the
        # single-master default) keys are unchanged bit-for-bit.
        salts.append(f"scope:{scope}")
    has_sampler = False
    for nid, node in prompt.items():
        if not isinstance(node, dict) or "class_type" not in node:
            continue  # metadata keys ride along untouched
        ct = node.get("class_type")
        if ct not in C.RESULT_CACHE_SAFE_NODE_TYPES:
            return None
        if node.get("hidden"):
            return None
        has_sampler |= ct in ("KSampler", "KSamplerAdvanced")
        if ct == "LoadImage":
            # the file's content can change between requests: fold the
            # stat identity in so a re-upload under the same name
            # misses instead of replaying stale outputs
            name = str(node.get("inputs", {}).get("image", ""))
            path = os.path.join(input_dir or "input", name)
            try:
                st = os.stat(path)
                salts.append(
                    f"{nid}:file:{name}:{st.st_mtime_ns}:{st.st_size}")
            except OSError:
                salts.append(f"{nid}:file:{name}:absent")
        nodes[str(nid)] = {"class_type": ct,
                           "inputs": node.get("inputs", {})}
    if not nodes or not has_sampler:
        return None
    try:
        blob = json.dumps(nodes, sort_keys=True, default=str)
    except (TypeError, ValueError):
        return None
    return _sha(blob + "|" + "|".join(sorted(salts)))


_LOADER_TYPES = ("CheckpointLoaderSimple", "LoraLoader",
                 "LoraLoaderModelOnly")


def _node_salt(node: Any, input_dir: Optional[str],
               models_dir: Optional[str]) -> Optional[str]:
    """Extra key material for nodes whose output depends on state
    outside the graph.  None = the node type disqualifies its subtree
    from content addressing."""
    if node.class_type == "LoadImage":
        # the file's content can change between requests: fold the stat
        # identity in so a re-upload under the same name misses
        name = str(node.inputs.get("image", ""))
        path = os.path.join(input_dir or "input", name)
        try:
            st = os.stat(path)
            return f"file:{name}:{st.st_mtime_ns}:{st.st_size}"
        except OSError:
            return f"file:{name}:absent"
    if node.class_type in _LOADER_TYPES:
        # two ServerStates with different model dirs in one process must
        # not alias each other's checkpoints
        return f"mdir:{models_dir or ''}"
    return ""


def subgraph_keys(graph: Any, hidden: Dict[str, Dict[str, Any]],
                  input_dir: Optional[str] = None,
                  models_dir: Optional[str] = None) -> Dict[str, str]:
    """Per-node content hash of each node's input SUB-GRAPH: node type +
    widget values + the content keys of every upstream producer, in
    topo order.  Only nodes whose whole subtree is in
    ``REUSE_KEY_NODE_TYPES`` (pure functions of their widgets/inputs)
    get a key; anything downstream of a non-addressable node is
    excluded, so a cache hit can never alias differing inputs.  Nodes
    carrying per-run hidden overrides (coalesced seeds, recovery state)
    are excluded too."""
    keys: Dict[str, str] = {}
    for nid in graph.topo_order():
        node = graph.nodes[nid]
        if node.class_type not in C.REUSE_KEY_NODE_TYPES:
            continue
        if node.hidden or hidden.get(nid):
            continue
        salt = _node_salt(node, input_dir, models_dir)
        if salt is None:
            continue
        parts: List[str] = [node.class_type, salt]
        ok = True
        for name in sorted(node.inputs):
            if name == "__widgets__":
                continue
            value = node.inputs[name]
            if isinstance(value, (list, tuple)) and len(value) == 2 \
                    and not isinstance(value[0], (list, dict)) \
                    and isinstance(value[1], int) \
                    and str(value[0]) in graph.nodes:
                up = keys.get(str(value[0]))
                if up is None:
                    ok = False
                    break
                parts.append(f"{name}<-{up}:{value[1]}")
            else:
                try:
                    parts.append(f"{name}={json.dumps(value, sort_keys=True, default=str)}")
                except (TypeError, ValueError):
                    ok = False
                    break
        if ok:
            keys[nid] = _sha("|".join(parts))
    return keys


# --- the bounded LRU ---------------------------------------------------------

class ByteLRU:
    """Thread-safe LRU keyed by content hash, bounded by a byte budget
    and an entry cap.  Values are opaque (host numpy for the result and
    tile tiers, device arrays for the sub-graph tier — jax buffers free
    when the entry drops).  Every decision lands in per-tier counters
    AND the process-global event counters (both metrics surfaces)."""

    def __init__(self, name: str, max_bytes: int, max_entries: int):
        self.name = str(name)
        self.max_bytes = max(int(max_bytes), 0)
        self.max_entries = max(int(max_entries), 1)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, Tuple[Any, int]]" = \
            OrderedDict()                      # guarded-by: self._lock
        self._bytes = 0                        # guarded-by: self._lock
        self.hits = 0                          # guarded-by: self._lock
        self.misses = 0                        # guarded-by: self._lock
        self.stores = 0                        # guarded-by: self._lock
        self.evictions = 0                     # guarded-by: self._lock

    def get(self, key: str) -> Optional[Any]:
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                trace_mod.GLOBAL_COUNTERS.bump(
                    f"cache_{self.name}_misses")
                return None
            self._entries.move_to_end(key)
            self.hits += 1
        trace_mod.GLOBAL_COUNTERS.bump(f"cache_{self.name}_hits")
        return ent[0]

    def put(self, key: str, value: Any, nbytes: int) -> bool:
        """Insert (no-op when the single value exceeds the whole
        budget — caching it would just evict everything else)."""
        nbytes = max(int(nbytes), 0)
        if self.max_bytes and nbytes > self.max_bytes:
            return False
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            self.stores += 1
            while len(self._entries) > self.max_entries or \
                    (self.max_bytes and self._bytes > self.max_bytes
                     and len(self._entries) > 1):
                _, (_, nb) = self._entries.popitem(last=False)
                self._bytes -= nb
                self.evictions += 1
                trace_mod.GLOBAL_COUNTERS.bump(
                    f"cache_{self.name}_evictions")
        return True

    def clear(self) -> int:
        """Drop everything; returns the freed bytes."""
        with self._lock:
            freed = self._bytes
            self._entries.clear()
            self._bytes = 0
        return freed

    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "max_bytes": self.max_bytes,
                    "max_entries": self.max_entries,
                    "hits": self.hits, "misses": self.misses,
                    "stores": self.stores, "evictions": self.evictions}


# --- the plane ---------------------------------------------------------------

class ReusePlane:
    """The three cache tiers plus the invalidation generation.  Budgets
    resolve from env at construction so tests pin them per instance."""

    def __init__(self,
                 result_bytes: Optional[int] = None,
                 device_bytes: Optional[int] = None,
                 tile_bytes: Optional[int] = None,
                 max_entries: Optional[int] = None):
        entries = max_entries if max_entries is not None else \
            _env_int(C.CACHE_ENTRIES_ENV, C.CACHE_ENTRIES_DEFAULT)
        self.result = ByteLRU(
            "result",
            result_bytes if result_bytes is not None
            else _env_int(C.CACHE_BYTES_ENV, C.CACHE_BYTES_DEFAULT),
            entries)
        self.subgraph = ByteLRU(
            "embed",
            device_bytes if device_bytes is not None
            else _env_int(C.CACHE_DEVICE_BYTES_ENV,
                          C.CACHE_DEVICE_BYTES_DEFAULT),
            entries)
        self.tiles = ByteLRU(
            "tile",
            tile_bytes if tile_bytes is not None
            else _env_int(C.CACHE_TILE_BYTES_ENV,
                          C.CACHE_TILE_BYTES_DEFAULT),
            entries)
        # bumped on clear: folded into model-identity salts so a
        # post-clear reload can never alias a stale entry
        self._generation = 0
        # stable per-pipeline identity tokens: a WeakKeyDictionary keyed
        # by the LIVE pipe object — unlike id(), a token is never
        # recycled when a pipeline is evicted/freed and CPython reuses
        # its address (a recycled id could replay another model's
        # refined tiles)
        import itertools
        import weakref
        self._salt_lock = threading.Lock()
        self._model_ids: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()     # guarded-by: self._salt_lock
        self._model_next = itertools.count()  # guarded-by: self._salt_lock

    def bytes_total(self) -> int:
        return self.result.bytes + self.subgraph.bytes + self.tiles.bytes

    def clear(self) -> int:
        """Invalidate every tier (the /distributed/clear_memory hook);
        returns the freed bytes."""
        freed = self.result.clear() + self.subgraph.clear() \
            + self.tiles.clear()
        self._generation += 1
        return freed

    def model_salt(self, pipe: Any) -> Optional[str]:
        """Process-local identity of a loaded pipeline for tile keys: a
        monotonic token held in a weak-keyed registry (dies with the
        object, never recycled) plus the clear generation.  None when
        the object can't carry a stable identity (unhashable /
        non-weakrefable) — the caller skips the tier rather than risk
        aliasing."""
        try:
            with self._salt_lock:
                tok = self._model_ids.get(pipe)
                if tok is None:
                    tok = next(self._model_next)
                    self._model_ids[pipe] = tok
        except TypeError:
            return None
        return f"m{tok}g{self._generation}"

    def snapshot(self) -> Dict[str, Any]:
        return {
            "enabled": reuse_enabled(),
            "bytes_total": self.bytes_total(),
            "generation": self._generation,
            "result": self.result.snapshot(),
            "embed": self.subgraph.snapshot(),
            "tile": self.tiles.snapshot(),
        }


_PLANE: Optional[ReusePlane] = None
_plane_lock = threading.Lock()


def get_reuse() -> ReusePlane:
    """The process-global plane (ONE per process, like the resource
    monitor: caches are process facts, not per-ServerState)."""
    global _PLANE
    with _plane_lock:
        if _PLANE is None:
            _PLANE = ReusePlane()
        return _PLANE


def reset_reuse() -> ReusePlane:
    """Tests: rebuild the plane so env-pinned budgets take effect."""
    global _PLANE
    with _plane_lock:
        _PLANE = ReusePlane()
        return _PLANE


def cache_bytes_total() -> int:
    """Total cached bytes across tiers — the ResourceMonitor's
    ``cache_bytes`` series provider (0 when nothing was ever cached, so
    sampling never constructs a plane just to measure it)."""
    plane = _PLANE
    return plane.bytes_total() if plane is not None else 0


# --- preview / client-gone cancellation channel ------------------------------

# latent->RGB projection (the standard cheap preview trick: a fixed
# linear map from the 4 SD latent channels to RGB, normalized into
# [0,1]) — good enough to watch composition emerge, no VAE decode
_LATENT_RGB = np.asarray([[0.298, 0.207, 0.208],
                          [0.187, 0.286, 0.173],
                          [-0.158, 0.189, 0.264],
                          [-0.184, -0.271, -0.473]], np.float32)


def latent_preview_png(latent: Any) -> bytes:
    """One latent sample -> small PNG bytes (host fetch happens here)."""
    from comfyui_distributed_tpu.utils.image import encode_png
    lat = np.asarray(latent, np.float32)
    if lat.ndim == 4:
        lat = lat[0]
    ch = lat.shape[-1]
    if ch >= 4:
        rgb = lat[..., :4] @ _LATENT_RGB
    else:
        rgb = np.repeat(lat[..., :1], 3, axis=-1)
    rgb = np.clip(rgb / 6.0 + 0.5, 0.0, 1.0)
    return encode_png(rgb[None], compress_level=3)


class PreviewBus:
    """Per-prompt SSE fan-out + the abandonment registry.

    The denoise driver asks :meth:`wants` at each step boundary (one
    dict lookup while nobody is subscribed) and :meth:`publish_latent`
    only for watched prompts; SSE handlers :meth:`subscribe` a bounded
    queue each.  A handler whose client disconnects calls
    :meth:`abandon` — the flag is consumed by the queue purge and the
    CB driver's slot scan, which finalize the job as ``abandoned``."""

    def __init__(self, max_clients: Optional[int] = None):
        # None = resolve from env PER CALL (the module-global bus is
        # built at import, and the cap must respond to the env like the
        # sibling DTPU_PREVIEW/_EVERY knobs do); tests pin an explicit
        # value
        self._max_clients = max_clients
        self._lock = threading.Lock()
        self._subs: Dict[str, List[queue_mod.Queue]] = {}  # guarded-by: self._lock
        self._abandoned: set = set()                       # guarded-by: self._lock

    @property
    def max_clients(self) -> int:
        return self._max_clients if self._max_clients is not None else \
            _env_int(C.PREVIEW_MAX_CLIENTS_ENV,
                     C.PREVIEW_MAX_CLIENTS_DEFAULT)

    # -- subscription ---------------------------------------------------------

    def subscribe(self, pid: str) -> Optional[queue_mod.Queue]:
        """A bounded per-client event queue, or None at the client cap
        (the SSE route then 429s)."""
        q: queue_mod.Queue = queue_mod.Queue(maxsize=16)
        with self._lock:
            if sum(len(v) for v in self._subs.values()) \
                    >= self.max_clients:
                return None
            self._subs.setdefault(str(pid), []).append(q)
        trace_mod.GLOBAL_COUNTERS.bump("preview_clients")
        return q

    def unsubscribe(self, pid: str, q: queue_mod.Queue) -> int:
        """Detach; returns how many subscribers REMAIN for the prompt
        (0 = this was the last client — the caller decides whether that
        means abandonment)."""
        with self._lock:
            subs = self._subs.get(str(pid), [])
            if q in subs:
                subs.remove(q)
            n = len(subs)
            if not subs:
                self._subs.pop(str(pid), None)
        return n

    def wants(self, pid: str) -> bool:
        with self._lock:
            return str(pid) in self._subs

    def client_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._subs.values())

    # -- publishing -----------------------------------------------------------

    def _fan_out(self, pid: str, event: Dict[str, Any]) -> None:
        with self._lock:
            subs = list(self._subs.get(str(pid), ()))
        for q in subs:
            try:
                q.put_nowait(event)
            except queue_mod.Full:
                # a slow client drops frames, never backpressures the
                # denoise loop
                trace_mod.GLOBAL_COUNTERS.bump("preview_drops")

    def publish_latent(self, pid: str, step: int, total: int,
                       latent: Any) -> None:
        """Encode + fan out one step's preview (called only when
        :meth:`wants` said someone is watching)."""
        import base64
        try:
            png = latent_preview_png(latent)
        except Exception as e:  # noqa: BLE001 - preview must never kill a step
            debug_log(f"preview encode failed for {pid}: {e}")
            return
        trace_mod.GLOBAL_COUNTERS.bump("preview_events")
        self._fan_out(str(pid), {
            "type": "preview", "prompt_id": str(pid),
            "step": int(step), "total_steps": int(total),
            "png_b64": base64.b64encode(png).decode()})

    def finish(self, pid: str, status: str) -> None:
        """Terminal event: push to remaining clients, clear the
        abandonment flag (the job is settled either way)."""
        self._fan_out(str(pid), {"type": "done", "prompt_id": str(pid),
                                 "status": str(status)})
        with self._lock:
            self._abandoned.discard(str(pid))

    # -- client-gone cancellation ---------------------------------------------

    def abandon(self, pid: str) -> None:
        with self._lock:
            if str(pid) in self._abandoned:
                return
            self._abandoned.add(str(pid))
        trace_mod.GLOBAL_COUNTERS.bump("jobs_abandoned")

    def clear_abandoned(self, pid: str) -> None:
        """Consume a stale flag for a job that settled in the race
        between the disconnect handler's liveness check and its
        abandon() — finish() already ran, so nothing else would."""
        with self._lock:
            self._abandoned.discard(str(pid))

    def is_abandoned(self, pid: str) -> bool:
        with self._lock:
            return str(pid) in self._abandoned

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": previews_enabled(),
                "clients": sum(len(v) for v in self._subs.values()),
                "watched_prompts": len(self._subs),
                "abandoned_pending": len(self._abandoned),
                "max_clients": self.max_clients,
            }


PREVIEWS = PreviewBus()


def preview_every() -> int:
    """Publish a preview every N steps (DTPU_PREVIEW_EVERY, min 1)."""
    return max(_env_int(C.PREVIEW_EVERY_ENV, C.PREVIEW_EVERY_DEFAULT), 1)


# --- tile-tier helpers -------------------------------------------------------

def conditioning_fingerprint(positive: Any, negative: Any) -> str:
    """Content identity of a (positive, negative) conditioning pair for
    tile keys — the refined tile depends on the prompt embeddings, not
    just the widget params.  Small arrays; the fetch happens here."""
    parts = []
    for cond in (positive, negative):
        parts.append(hash_array(cond.context))
        pooled = getattr(cond, "pooled", None)
        parts.append(hash_array(pooled) if pooled is not None else "-")
        sc = getattr(cond, "size_cond", None)
        parts.append(str(tuple(sc)) if sc is not None else "-")
    return _sha("|".join(parts))


def tile_keys(model_salt: str, cond_fp: str, params: Dict[str, Any],
              tiles: np.ndarray,
              tile_indices: List[int]) -> List[str]:
    """Per-tile content keys: model identity + conditioning fingerprint
    + refine params + the tile INDEX (its seed is ``seed + idx``) + the
    extracted window's bytes.  A 10%-changed source re-keys only the
    windows whose pixels moved."""
    base = _sha(model_salt + "|" + cond_fp + "|"
                + json.dumps(params, sort_keys=True, default=str))
    out = []
    arr = np.ascontiguousarray(np.asarray(tiles, np.float32))
    for k, idx in enumerate(tile_indices):
        h = hashlib.sha1(arr[k].tobytes())
        h.update(f"|{base}|{int(idx)}".encode())
        out.append(h.hexdigest())
    return out


def tile_nbytes(window: np.ndarray) -> int:
    return int(np.asarray(window).nbytes)


# --- result-tier helpers -----------------------------------------------------

def nbytes_of(x: Any) -> int:
    """Byte size WITHOUT forcing a host fetch (device arrays carry
    .nbytes; everything else goes through numpy)."""
    nb = getattr(x, "nbytes", None)
    return int(nb) if nb is not None else int(np.asarray(x).nbytes)


def images_nbytes(images: List[Any]) -> int:
    return int(sum(nbytes_of(im) for im in images))


def store_result(key: str, images: List[Any],
                 duration_s: float) -> bool:
    """Finalize-path store: per-prompt images + replay metadata."""
    plane = get_reuse()
    entry = {"images": [np.asarray(im) for im in images],
             "duration_s": float(duration_s),
             "stored_at": time.time()}
    return plane.result.put(key, entry, images_nbytes(images))


def conditioning_nbytes(cond: Any) -> int:
    n = nbytes_of(cond.context)
    pooled = getattr(cond, "pooled", None)
    if pooled is not None:
        n += nbytes_of(pooled)
    return n
