"""Per-job result queues (the HTTP data plane's buffer).

Capability parity with the reference's queue stores: image jobs
(``distributed.py:1125-1133``) and tile jobs
(``distributed_upscale.py:27-34``) — per-job ``asyncio.Queue``s created
*before* dispatch (the prepare-before-dispatch protocol that closes the
result/startup race, ``distributed.py:366-381``).  The reference attaches
these to ComfyUI's PromptServer to survive module reloads; here the store is
owned by the server app directly.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Callable, Dict, List, Optional, Set

from comfyui_distributed_tpu.utils import trace as trace_mod


class JobStore:
    """Image-job and tile-job queues, asyncio-locked.

    Idempotency (ISSUE 4 satellite): ``job_complete``/``tile_complete``
    POSTs retried by ``post_form_with_retry`` can be delivered twice (a
    timed-out-but-received POST is resent).  Senders stamp each upload
    with an idempotency key ``worker_id:unit_idx:attempt`` — stable
    across HTTP retries of the same logical send, distinct across
    dispatch attempts (reassign/hedge) — and ``put_result``/``put_tile``
    dedupe on it: a replay is acknowledged (200, so the sender stops
    retrying) but never enqueued twice."""

    def __init__(self) -> None:
        self._jobs: Dict[str, asyncio.Queue] = {}
        self._tile_jobs: Dict[str, asyncio.Queue] = {}
        self._seen: Dict[str, Set[str]] = {}
        self._tile_seen: Dict[str, Set[str]] = {}
        self._lock = asyncio.Lock()
        self._tile_lock = asyncio.Lock()
        # durability plane (ISSUE 7): with a WAL attached, accepted keys
        # are appended (and fsync'd, under DTPU_WAL_SYNC=always) BEFORE
        # the 200 ack — so an acked-but-dropped upload replayed AFTER a
        # master restart is still recognized and deduped, instead of
        # double-inserting into the rebuilt queue (the PR 4 note: keys
        # used to die with the queue)
        self._wal = None
        # shard-owner scope (ISSUE 14 satellite): with N active masters
        # in one process (tests/benches) — or after a peer takeover
        # merges an absorbed shard's replayed keys in — two shards'
        # jobs could collide on (job_id, key).  Keys are namespaced by
        # the owning shard so a takeover can never mistake another
        # master's acked unit for its own (nor vice versa); "" (the
        # single-master default) keeps the legacy keyspace bit-for-bit.
        self._scope = ""
        # job -> owning-shard scope for ABSORBED jobs: a retried upload
        # for a job the takeover inherited must dedupe against the DEAD
        # shard's replayed keys, and its future check-ins stay in that
        # job's namespace
        self._job_scope: Dict[str, str] = {}

    def set_scope(self, scope: Optional[str]) -> None:
        self._scope = str(scope or "")

    def _scoped(self, job_id: str, idem_key: str) -> str:
        s = self._job_scope.get(str(job_id), self._scope)
        return f"{s}|{idem_key}" if s else str(idem_key)

    def attach_wal(self, wal, recovered_idem: Optional[Dict[str, Any]]
                   = None) -> None:
        """Wire the write-ahead log in and reseed the replayed keys
        (``{"image": {job: [keys]}, "tile": {...}}``) — under THIS
        store's scope: they came from our own shard's WAL."""
        self._wal = wal
        self.merge_idem(recovered_idem, scope=self._scope)

    def merge_idem(self, recovered_idem: Optional[Dict[str, Any]],
                   scope: Optional[str] = None) -> None:
        """Seed replayed idempotency keys under ``scope`` (a peer
        takeover passes the ABSORBED shard's id, so the dead master's
        acked units stay exactly-once without aliasing ours)."""
        if not recovered_idem:
            return
        scope = self._scope if scope is None else str(scope)

        def seed(seen, block):
            for job, keys in (block or {}).items():
                if scope != self._scope:
                    self._job_scope[str(job)] = scope
                pfx = f"{scope}|" if scope else ""
                seen.setdefault(str(job), set()).update(
                    f"{pfx}{k}" for k in keys)

        seed(self._seen, recovered_idem.get("image"))
        seed(self._tile_seen, recovered_idem.get("tile"))

    def _dedupe(self, seen: Dict[str, Set[str]], job_id: str,
                idem_key: Optional[str]) -> tuple:
        """``(duplicate, fresh_key)`` — pure bookkeeping under the
        caller's lock; the WAL append for a fresh key happens OUTSIDE
        the lock (and off the event loop) via :meth:`_log_idem`.  The
        returned fresh key is UNSCOPED (what the WAL records — the
        shard dir IS the scope on disk)."""
        if not idem_key:
            return False, None
        keys = seen.setdefault(job_id, set())
        scoped = self._scoped(job_id, idem_key)
        if scoped in keys:
            trace_mod.GLOBAL_COUNTERS.bump("idem_dropped")
            return True, None
        keys.add(scoped)
        return False, idem_key

    def _log_idem(self, scope: str, job_id: str, idem_key: str) -> None:
        """Durably record an accepted key (fsync per DTPU_WAL_SYNC)
        BEFORE the upload is acked; fencing errors propagate so a
        deposed master's data plane stops acking."""
        from comfyui_distributed_tpu.runtime import durable as dur
        try:
            self._wal.append("idem", scope=scope, job=str(job_id),
                             key=str(idem_key))
        except (dur.FencedError, dur.WalCrashedError):
            raise
        except Exception as e:  # noqa: BLE001 - best-effort
            from comfyui_distributed_tpu.utils.logging import debug_log
            debug_log(f"jobstore: idem wal append failed: {e}")

    async def _log_idem_off_loop(self, scope: str, job_id: str,
                                 fresh_key: Optional[str]) -> None:
        if fresh_key is None or self._wal is None:
            return
        await asyncio.get_running_loop().run_in_executor(
            None, lambda: self._log_idem(scope, job_id, fresh_key))

    # --- image jobs (reference distributed.py:1125-1218) -------------------

    async def prepare_job(self, multi_job_id: str) -> None:
        async with self._lock:
            if multi_job_id not in self._jobs:
                self._jobs[multi_job_id] = asyncio.Queue()

    async def get_queue(self, multi_job_id: str) -> asyncio.Queue:
        async with self._lock:
            if multi_job_id not in self._jobs:
                self._jobs[multi_job_id] = asyncio.Queue()
            return self._jobs[multi_job_id]

    async def has_job(self, multi_job_id: str) -> bool:
        async with self._lock:
            return multi_job_id in self._jobs

    async def put_result(self, multi_job_id: str, item: Dict[str, Any],
                         require_existing: bool = True,
                         idem_key: Optional[str] = None) -> bool:
        """Queue a worker result; ``require_existing`` mirrors the 404
        behavior for unknown jobs (``distributed.py:1190-1194``);
        ``idem_key`` replays are acknowledged but dropped."""
        async with self._lock:
            q = self._jobs.get(multi_job_id)
            if q is None:
                if require_existing:
                    return False
                q = self._jobs[multi_job_id] = asyncio.Queue()
            dup, fresh_key = self._dedupe(self._seen, multi_job_id,
                                          idem_key)
        if dup:
            return True
        await self._log_idem_off_loop("image", multi_job_id, fresh_key)
        await q.put(item)
        return True

    async def remove_job(self, multi_job_id: str) -> None:
        async with self._lock:
            self._jobs.pop(multi_job_id, None)
            self._seen.pop(multi_job_id, None)
            if multi_job_id not in self._tile_seen:
                self._job_scope.pop(str(multi_job_id), None)

    # --- tile jobs (reference distributed_upscale.py:27-34, 711-760) -------

    async def prepare_tile_job(self, multi_job_id: str) -> None:
        """Pre-create a tile queue at dispatch time (the reference does this
        at prompt-validation via IS_CHANGED, ``distributed_upscale.py:
        85-105``) — workers can finish their tiles before the master's
        executor even reaches the upscale node."""
        async with self._tile_lock:
            if multi_job_id not in self._tile_jobs:
                self._tile_jobs[multi_job_id] = asyncio.Queue()

    async def get_tile_queue(self, multi_job_id: str) -> asyncio.Queue:
        async with self._tile_lock:
            if multi_job_id not in self._tile_jobs:
                self._tile_jobs[multi_job_id] = asyncio.Queue()
            return self._tile_jobs[multi_job_id]

    async def has_tile_job(self, multi_job_id: str) -> bool:
        async with self._tile_lock:
            return multi_job_id in self._tile_jobs

    async def put_tile(self, multi_job_id: str, item: Dict[str, Any],
                       require_existing: bool = True,
                       idem_key: Optional[str] = None) -> bool:
        """Queue a worker tile.  ``require_existing`` keeps late posts (after
        the master timed out and removed the queue) from resurrecting an
        orphan queue that would hold decoded tensors forever — the caller
        returns 404 and the worker's retry loop backs off, mirroring the
        image path (reference 404-retry, ``distributed_upscale.py:640-654``)."""
        async with self._tile_lock:
            q = self._tile_jobs.get(multi_job_id)
            if q is None:
                if require_existing:
                    return False
                q = self._tile_jobs[multi_job_id] = asyncio.Queue()
            dup, fresh_key = self._dedupe(self._tile_seen, multi_job_id,
                                          idem_key)
        if dup:
            return True
        await self._log_idem_off_loop("tile", multi_job_id, fresh_key)
        await q.put(item)
        return True

    async def remove_tile_queue(self, multi_job_id: str) -> None:
        async with self._tile_lock:
            self._tile_jobs.pop(multi_job_id, None)
            self._tile_seen.pop(multi_job_id, None)
            if multi_job_id not in self._seen:
                self._job_scope.pop(str(multi_job_id), None)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "image_jobs": sorted(self._jobs),
            "tile_jobs": sorted(self._tile_jobs),
        }


class ParkedStore:
    """Host-side registry of PARKED continuous-batching rows (ISSUE 17).

    A parked record is a started job whose device slot was handed to a
    higher-class prompt: the latent row, per-row PRNG key, sigma index and
    admit timestamp pulled to host — the *whole* slot truth, so a later
    RESUME is bit-identical.  The store is the "beyond-HBM" working set:
    ``DTPU_CB_SLOTS`` stays the physical cap while admission capacity
    becomes ``slots + room()``.

    Records are opaque to this store except for the fields the residency
    scheduler orders by: ``.pid`` (double-park guard / client-gone lookup),
    ``.sig`` (bucket signature — resume must land in a same-shape bucket),
    ``.rank`` (tenant-class rank: resume highest class first) and
    ``.t_park`` (FIFO within a class).  Mutating slot-state fields is the
    park/resume API's job alone (dtpu-lint ``cb-slot-state-discipline``).

    Own ``threading.Lock`` (NOT the driver's implicit single-thread
    ownership): the driver thread parks/resumes, but the HTTP metrics
    thread reads ``count()`` and the autoscaler samples the backlog.
    """

    def __init__(self, capacity: int) -> None:
        self._capacity = max(0, int(capacity))
        self._rows: List[Any] = []            # guarded-by: self._lock
        self._pids: Set[str] = set()          # guarded-by: self._lock
        self._lock = threading.Lock()

    # --- write side (driver thread) ----------------------------------------

    def park(self, records: List[Any]) -> None:
        """Register freshly-parked rows.  Raises ``ValueError`` on a
        double-park (a pid already resident — slot state would fork) or
        when the batch would exceed ``DTPU_CB_PARK_MAX`` (callers must
        check :meth:`room` first; the raise is the invariant's backstop,
        not a control-flow path)."""
        with self._lock:
            if len(self._rows) + len(records) > self._capacity:
                raise ValueError(
                    f"parked-store overflow: {len(self._rows)} resident + "
                    f"{len(records)} new > capacity {self._capacity}")
            for rec in records:
                pid = str(rec.pid)
                if pid in self._pids:
                    raise ValueError(f"double-park of prompt {pid}")
            for rec in records:
                self._pids.add(str(rec.pid))
                self._rows.append(rec)

    def pop_for(self, sig: Any, k: int) -> List[Any]:
        """Up to ``k`` records with bucket signature ``sig``, best-first:
        highest tenant-class rank, then earliest park time (FIFO) — the
        starved row a class has waited longest on resumes first."""
        if k <= 0:
            return []
        with self._lock:
            cands = [r for r in self._rows if r.sig == sig]
            cands.sort(key=lambda r: (-int(r.rank), float(r.t_park)))
            picked = cands[:k]
            for rec in picked:
                self._rows.remove(rec)
                self._pids.discard(str(rec.pid))
            return picked

    def pop_abandoned(self, is_abandoned: Callable[[str], bool]) -> List[Any]:
        """Remove and return records whose owning client is gone (the
        PR 13 client-gone signal): a parked row for a disconnected client
        is freed, never resumed."""
        with self._lock:
            gone = [r for r in self._rows if is_abandoned(str(r.pid))]
            for rec in gone:
                self._rows.remove(rec)
                self._pids.discard(str(rec.pid))
            return gone

    def drain_all(self) -> List[Any]:
        """Remove and return everything (abort/shutdown path)."""
        with self._lock:
            rows, self._rows = self._rows, []
            self._pids.clear()
            return rows

    # --- read side (any thread) --------------------------------------------

    def has(self, pid: str) -> bool:
        with self._lock:
            return str(pid) in self._pids

    def count(self) -> int:
        with self._lock:
            return len(self._rows)

    def room(self) -> int:
        with self._lock:
            return max(0, self._capacity - len(self._rows))

    def sigs(self) -> List[Any]:
        """Distinct signatures of resident rows, resume-priority order."""
        with self._lock:
            ordered = sorted(self._rows,
                             key=lambda r: (-int(r.rank), float(r.t_park)))
            out: List[Any] = []
            for r in ordered:
                if r.sig not in out:
                    out.append(r.sig)
            return out
