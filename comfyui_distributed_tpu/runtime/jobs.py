"""Per-job result queues (the HTTP data plane's buffer).

Capability parity with the reference's queue stores: image jobs
(``distributed.py:1125-1133``) and tile jobs
(``distributed_upscale.py:27-34``) — per-job ``asyncio.Queue``s created
*before* dispatch (the prepare-before-dispatch protocol that closes the
result/startup race, ``distributed.py:366-381``).  The reference attaches
these to ComfyUI's PromptServer to survive module reloads; here the store is
owned by the server app directly.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional, Set

from comfyui_distributed_tpu.utils import trace as trace_mod


class JobStore:
    """Image-job and tile-job queues, asyncio-locked.

    Idempotency (ISSUE 4 satellite): ``job_complete``/``tile_complete``
    POSTs retried by ``post_form_with_retry`` can be delivered twice (a
    timed-out-but-received POST is resent).  Senders stamp each upload
    with an idempotency key ``worker_id:unit_idx:attempt`` — stable
    across HTTP retries of the same logical send, distinct across
    dispatch attempts (reassign/hedge) — and ``put_result``/``put_tile``
    dedupe on it: a replay is acknowledged (200, so the sender stops
    retrying) but never enqueued twice."""

    def __init__(self) -> None:
        self._jobs: Dict[str, asyncio.Queue] = {}
        self._tile_jobs: Dict[str, asyncio.Queue] = {}
        self._seen: Dict[str, Set[str]] = {}
        self._tile_seen: Dict[str, Set[str]] = {}
        self._lock = asyncio.Lock()
        self._tile_lock = asyncio.Lock()
        # durability plane (ISSUE 7): with a WAL attached, accepted keys
        # are appended (and fsync'd, under DTPU_WAL_SYNC=always) BEFORE
        # the 200 ack — so an acked-but-dropped upload replayed AFTER a
        # master restart is still recognized and deduped, instead of
        # double-inserting into the rebuilt queue (the PR 4 note: keys
        # used to die with the queue)
        self._wal = None

    def attach_wal(self, wal, recovered_idem: Optional[Dict[str, Any]]
                   = None) -> None:
        """Wire the write-ahead log in and reseed the replayed keys
        (``{"image": {job: [keys]}, "tile": {...}}``)."""
        self._wal = wal
        if recovered_idem:
            for job, keys in (recovered_idem.get("image") or {}).items():
                self._seen.setdefault(str(job), set()).update(
                    str(k) for k in keys)
            for job, keys in (recovered_idem.get("tile") or {}).items():
                self._tile_seen.setdefault(str(job), set()).update(
                    str(k) for k in keys)

    def _dedupe(self, seen: Dict[str, Set[str]], job_id: str,
                idem_key: Optional[str]) -> tuple:
        """``(duplicate, fresh_key)`` — pure bookkeeping under the
        caller's lock; the WAL append for a fresh key happens OUTSIDE
        the lock (and off the event loop) via :meth:`_log_idem`."""
        if not idem_key:
            return False, None
        keys = seen.setdefault(job_id, set())
        if idem_key in keys:
            trace_mod.GLOBAL_COUNTERS.bump("idem_dropped")
            return True, None
        keys.add(idem_key)
        return False, idem_key

    def _log_idem(self, scope: str, job_id: str, idem_key: str) -> None:
        """Durably record an accepted key (fsync per DTPU_WAL_SYNC)
        BEFORE the upload is acked; fencing errors propagate so a
        deposed master's data plane stops acking."""
        from comfyui_distributed_tpu.runtime import durable as dur
        try:
            self._wal.append("idem", scope=scope, job=str(job_id),
                             key=str(idem_key))
        except (dur.FencedError, dur.WalCrashedError):
            raise
        except Exception as e:  # noqa: BLE001 - best-effort
            from comfyui_distributed_tpu.utils.logging import debug_log
            debug_log(f"jobstore: idem wal append failed: {e}")

    async def _log_idem_off_loop(self, scope: str, job_id: str,
                                 fresh_key: Optional[str]) -> None:
        if fresh_key is None or self._wal is None:
            return
        await asyncio.get_running_loop().run_in_executor(
            None, lambda: self._log_idem(scope, job_id, fresh_key))

    # --- image jobs (reference distributed.py:1125-1218) -------------------

    async def prepare_job(self, multi_job_id: str) -> None:
        async with self._lock:
            if multi_job_id not in self._jobs:
                self._jobs[multi_job_id] = asyncio.Queue()

    async def get_queue(self, multi_job_id: str) -> asyncio.Queue:
        async with self._lock:
            if multi_job_id not in self._jobs:
                self._jobs[multi_job_id] = asyncio.Queue()
            return self._jobs[multi_job_id]

    async def has_job(self, multi_job_id: str) -> bool:
        async with self._lock:
            return multi_job_id in self._jobs

    async def put_result(self, multi_job_id: str, item: Dict[str, Any],
                         require_existing: bool = True,
                         idem_key: Optional[str] = None) -> bool:
        """Queue a worker result; ``require_existing`` mirrors the 404
        behavior for unknown jobs (``distributed.py:1190-1194``);
        ``idem_key`` replays are acknowledged but dropped."""
        async with self._lock:
            q = self._jobs.get(multi_job_id)
            if q is None:
                if require_existing:
                    return False
                q = self._jobs[multi_job_id] = asyncio.Queue()
            dup, fresh_key = self._dedupe(self._seen, multi_job_id,
                                          idem_key)
        if dup:
            return True
        await self._log_idem_off_loop("image", multi_job_id, fresh_key)
        await q.put(item)
        return True

    async def remove_job(self, multi_job_id: str) -> None:
        async with self._lock:
            self._jobs.pop(multi_job_id, None)
            self._seen.pop(multi_job_id, None)

    # --- tile jobs (reference distributed_upscale.py:27-34, 711-760) -------

    async def prepare_tile_job(self, multi_job_id: str) -> None:
        """Pre-create a tile queue at dispatch time (the reference does this
        at prompt-validation via IS_CHANGED, ``distributed_upscale.py:
        85-105``) — workers can finish their tiles before the master's
        executor even reaches the upscale node."""
        async with self._tile_lock:
            if multi_job_id not in self._tile_jobs:
                self._tile_jobs[multi_job_id] = asyncio.Queue()

    async def get_tile_queue(self, multi_job_id: str) -> asyncio.Queue:
        async with self._tile_lock:
            if multi_job_id not in self._tile_jobs:
                self._tile_jobs[multi_job_id] = asyncio.Queue()
            return self._tile_jobs[multi_job_id]

    async def has_tile_job(self, multi_job_id: str) -> bool:
        async with self._tile_lock:
            return multi_job_id in self._tile_jobs

    async def put_tile(self, multi_job_id: str, item: Dict[str, Any],
                       require_existing: bool = True,
                       idem_key: Optional[str] = None) -> bool:
        """Queue a worker tile.  ``require_existing`` keeps late posts (after
        the master timed out and removed the queue) from resurrecting an
        orphan queue that would hold decoded tensors forever — the caller
        returns 404 and the worker's retry loop backs off, mirroring the
        image path (reference 404-retry, ``distributed_upscale.py:640-654``)."""
        async with self._tile_lock:
            q = self._tile_jobs.get(multi_job_id)
            if q is None:
                if require_existing:
                    return False
                q = self._tile_jobs[multi_job_id] = asyncio.Queue()
            dup, fresh_key = self._dedupe(self._tile_seen, multi_job_id,
                                          idem_key)
        if dup:
            return True
        await self._log_idem_off_loop("tile", multi_job_id, fresh_key)
        await q.put(item)
        return True

    async def remove_tile_queue(self, multi_job_id: str) -> None:
        async with self._tile_lock:
            self._tile_jobs.pop(multi_job_id, None)
            self._tile_seen.pop(multi_job_id, None)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "image_jobs": sorted(self._jobs),
            "tile_jobs": sorted(self._tile_jobs),
        }
