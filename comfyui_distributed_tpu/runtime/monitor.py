"""Master-death monitor: wrapper executable for managed workers.

Capability parity with reference ``worker_monitor.py:1-129``: spawns the real
worker command, polls the master PID every 2 s, and kills the worker (tree)
when the master dies; forwards termination signals for clean teardown.

Usage: ``python -m comfyui_distributed_tpu.runtime.monitor
--master-pid <pid> -- <worker command...>``
"""

from __future__ import annotations

import argparse
import signal
import subprocess
import sys
import time

from comfyui_distributed_tpu.utils.constants import WORKER_CHECK_INTERVAL
from comfyui_distributed_tpu.utils.process import (
    is_process_alive,
    kill_process_tree,
    terminate_process,
)


def monitor_and_run(master_pid: int, cmd: list) -> int:
    child = subprocess.Popen(cmd)

    def cleanup(signum=None, _frame=None):
        kill_process_tree(child.pid)
        sys.exit(0 if signum is None else 128 + signum)

    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, cleanup)

    while True:
        code = child.poll()
        if code is not None:
            return code  # worker exited on its own: propagate
        if not is_process_alive(master_pid):
            print(f"[monitor] master {master_pid} died; stopping worker "
                  f"{child.pid}", file=sys.stderr)
            terminate_process(child)
            kill_process_tree(child.pid)
            return 0
        time.sleep(WORKER_CHECK_INTERVAL)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--master-pid", type=int, required=True)
    ap.add_argument("cmd", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no worker command given")
    return monitor_and_run(args.master_pid, cmd)


if __name__ == "__main__":
    sys.exit(main())
