"""aiohttp application: the reference's full route surface plus TPU-native
status/metrics.

Route inventory (capability parity with reference ``distributed.py:49-599,
1135-1218`` and ``distributed_upscale.py:711-760``; SURVEY.md §2 #5-#8,
#13, #15, #22-#24):

  control plane
    GET  /distributed/config                 full config
    POST /distributed/config/update_worker   upsert (None deletes field)
    POST /distributed/config/delete_worker
    POST /distributed/config/update_setting
    POST /distributed/config/update_master
    GET  /distributed/network_info           host IPs + recommended master IP
    POST /distributed/clear_memory           drop model/jit caches, gc
    POST /distributed/launch_worker          process manager
    POST /distributed/stop_worker
    GET  /distributed/managed_workers
    GET  /distributed/worker_log             backwards log tail
    POST /distributed/worker/clear_launching
    GET  /distributed/queue_status           does a tile job queue exist
    POST /distributed/prepare_job            create queue before dispatch
    POST /distributed/load_image             base64 input staging
    GET  /distributed/status                 mesh topology + runtime (new)
    GET  /distributed/metrics                counters/timings (new)
    GET  /distributed/metrics.prom           Prometheus text exposition (new)
    POST /distributed/metrics/reset          clear aggregate sinks (new)
    GET  /distributed/traces                 flight-recorder index (new)
    GET  /distributed/trace/<prompt_id>      one job's span tree (new)
    GET  /distributed/slo                    SLO burn-rate snapshot (new)
    GET  /distributed/cluster                lease states + work ledger (new)
    POST /distributed/register               elastic worker registration (new)
    POST /distributed/heartbeat              worker lease renewal (new)

  data plane
    POST /distributed/job_complete           multipart PNG -> image queue
    POST /distributed/tile_complete          multipart PNG -> tile queue

  ComfyUI-compatible worker surface (what the reference's workers expose)
    GET  /prompt        {"exec_info": {"queue_remaining": N}}
    POST /prompt        queue a workflow for execution
    POST /interrupt     stop the running job
    POST /upload/image  receive staged input images
"""

from __future__ import annotations

import asyncio
import base64
import collections
import itertools
import json
import math
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional

from aiohttp import web

from comfyui_distributed_tpu.ops.base import OpContext
from comfyui_distributed_tpu.runtime import autoscale as autoscale_mod
from comfyui_distributed_tpu.runtime import cluster as cluster_mod
from comfyui_distributed_tpu.runtime import reuse as reuse_mod
from comfyui_distributed_tpu.runtime import shard as shard_mod
from comfyui_distributed_tpu.runtime.jobs import JobStore
from comfyui_distributed_tpu.utils import chaos as chaos_mod
from comfyui_distributed_tpu.runtime.manager import (
    WorkerProcessManager,
    auto_launch_workers,
)
from comfyui_distributed_tpu.utils import config as cfg_mod
from comfyui_distributed_tpu.utils import constants as C
from comfyui_distributed_tpu.utils import net as net_mod
from comfyui_distributed_tpu.utils import resource as resource_mod
from comfyui_distributed_tpu.utils import slo as slo_mod
from comfyui_distributed_tpu.utils import trace as trace_mod
from comfyui_distributed_tpu.utils import trace_analysis as analysis_mod
from comfyui_distributed_tpu.utils import trace_export as trace_export_mod
from comfyui_distributed_tpu.utils.constants import LOG_TAIL_BYTES
from comfyui_distributed_tpu.utils.image import decode_png, decode_tensor
from comfyui_distributed_tpu.utils.logging import debug_log, log
from comfyui_distributed_tpu.workflow import scheduler as sched_mod
from comfyui_distributed_tpu.workflow.executor import WorkflowExecutor


class QueueFullError(RuntimeError):
    """enqueue_prompt hit the DTPU_MAX_QUEUE backpressure cap."""


class ShedError(QueueFullError):
    """Admission shed the prompt (class-aware overload shedding or a
    per-client token bucket); carries the rejection detail so the 429
    can tell the client WHY and HOW LONG to back off."""

    def __init__(self, rejection: Dict[str, Any]):
        self.rejection = dict(rejection)
        super().__init__(
            f"shed ({rejection.get('reason')}) for tenant class "
            f"{rejection.get('tenant')!r}")


class DrainingError(RuntimeError):
    """enqueue_prompt refused: the server is shutting down."""


def _env_flag(name: str, default: str = "1") -> bool:
    return os.environ.get(name, default).lower() not in ("0", "false", "off")


class ServerState:
    """Everything the handlers share: config path, job store, process
    manager, the execution queue and its pipelined worker thread.

    The execution pipeline (ISSUE 2): the exec thread pops a *group* of
    signature-identical prompts (workflow/scheduler.py) and runs them as
    one batched dispatch; OUTPUT-node host edges (d2h/PNG/disk) defer
    onto a bounded host-IO pool so job N's encode overlaps job N+1's
    denoise loop; a finalizer thread joins the deferred work and writes
    history/metrics.  ``overlap``/``coalesce`` default from
    DTPU_OVERLAP/DTPU_COALESCE ("0" restores the strictly serial seed
    behavior — same thread does everything)."""

    def __init__(self, config_path: Optional[str] = None,
                 is_worker: bool = False,
                 input_dir: Optional[str] = None,
                 output_dir: Optional[str] = None,
                 models_dir: Optional[str] = None,
                 start_exec_thread: bool = True,
                 overlap: Optional[bool] = None,
                 coalesce: Optional[bool] = None,
                 cb: Optional[bool] = None):
        self.config_path = config_path
        self.is_worker = is_worker
        self.port: Optional[int] = None  # set by serve()
        self.input_dir = input_dir or os.path.join(os.getcwd(), "input")
        self.output_dir = output_dir or os.path.join(os.getcwd(), "output")
        self.models_dir = models_dir
        self.jobs = JobStore()
        self.manager = WorkerProcessManager(config_path=config_path,
                                            models_dir=models_dir)
        # cluster control plane (ISSUE 4): worker registry with leases +
        # per-job work ledger.  Seeded from config; the health poller,
        # heartbeats and data-plane POSTs all renew leases; the
        # collectors consult both through OpContext.
        self.cluster = cluster_mod.ClusterRegistry()
        self.ledger = cluster_mod.WorkLedger()
        if not is_worker:
            try:
                self.cluster.seed_from_config(
                    cfg_mod.load_config(config_path).get("workers", []))
            except Exception as e:  # noqa: BLE001 - config is optional
                debug_log(f"cluster seed skipped: {e}")
        self.fault_inject = cluster_mod.fault_injection()
        # worker->master lease renewal (set by serve(); the rehome
        # endpoint retargets it when a standby master takes over)
        self.heartbeat: Optional[Any] = None
        from comfyui_distributed_tpu.runtime.health import HealthPoller
        self.health = HealthPoller(config_path=config_path,
                                   manager=self.manager,
                                   registry=self.cluster)
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        # the process-global flag: compiled samplers poll it per step
        # (runtime/interrupt.py), so /interrupt stops a sample in flight
        from comfyui_distributed_tpu.runtime.interrupt import interrupt_event
        self.interrupt_event = interrupt_event()
        self.metrics: Dict[str, Any] = {
            "prompts_executed": 0, "prompts_failed": 0,
            "images_received": 0, "tiles_received": 0,
            # cross-request reuse (ISSUE 13): exact-hit replays and
            # client-gone abandonments are neither executed nor failed
            "prompts_replayed": 0, "prompts_abandoned": 0,
            "last_execution_s": None,
        }
        self.max_queue = int(os.environ.get(C.MAX_QUEUE_ENV,
                                            C.MAX_QUEUE_DEFAULT))
        # SLO-aware multi-tenant admission (ISSUE 9): priority classes
        # with weighted fair dequeue, class-aware shedding and optional
        # per-client token buckets.  Untagged traffic rides the highest
        # class, so single-tenant deployments keep the plain
        # DTPU_MAX_QUEUE backpressure semantics unchanged.
        self.admission = sched_mod.AdmissionController()
        # SLO burn-rate engine (ISSUE 18): per-tenant-class objectives
        # from DTPU_SLO_SPEC over fast/slow rolling windows, fed by
        # _finalize_group; disarmed (record() is a no-op) without a spec
        self.slo = slo_mod.SLOEngine.from_env()
        # completion timestamps ring feeding the 429 Retry-After hint
        # (drain rate = prompts finalized per second, recent window)
        self._completions: collections.deque = collections.deque(
            maxlen=128)
        # elastic-fleet autoscaler: armed by serve() on a master when
        # DTPU_AUTOSCALE=1 (runtime/autoscale.install)
        self.autoscaler: Optional[Any] = None
        # resource telemetry plane (ISSUE 5): process-global sampler
        # feeding bounded ring timeseries; queue depth reads from THIS
        # state (the most recent ServerState in a multi-state process).
        # DTPU_RESOURCE=0 disables; None then.
        self.resources = resource_mod.install_monitor(
            queue_depth_fn=self.queue_remaining)
        self.overlap_enabled = _env_flag(C.OVERLAP_ENV) \
            if overlap is None else bool(overlap)
        self.coalesce_enabled = _env_flag(C.COALESCE_ENV) \
            if coalesce is None else bool(coalesce)
        self.coalesce_max = max(1, int(os.environ.get(
            C.COALESCE_MAX_ENV, C.COALESCE_MAX_DEFAULT)))
        # iteration-level continuous batching (ISSUE 12): DTPU_CB=1
        # replaces the pop-a-group exec loop with the step-granular
        # batch executor (workflow/batch_executor.py) — eligible prompts
        # join a RUNNING padded batch at step boundaries; everything
        # else rides its fallback thread through _execute_group.  Off
        # by default: the legacy dispatch model is untouched without
        # the flag.
        self.cb_enabled = _env_flag(C.CB_ENV, "0") \
            if cb is None else bool(cb)
        self.cb: Optional[Any] = None
        self.host_pool = net_mod.HostIOPool() if self.overlap_enabled \
            else None
        self._queue: List[Dict[str, Any]] = []
        # every admitted-but-not-finalized prompt id (queued, in a CB
        # slot, mid-decode, or in a fallback group): the preview
        # route's authoritative liveness check — the queue/CB-slot
        # views individually have handoff windows where a live prompt
        # is in neither
        self._inflight: set = set()        # guarded-by: self._queue_lock
        self._queue_lock = threading.Lock()
        self._queue_event = threading.Event()
        # bench/test hook: the exec loop waits here before popping, so a
        # caller can clear it, stage a burst that must coalesce into ONE
        # group, and set it again — no race against the pop
        self._exec_gate = threading.Event()
        self._exec_gate.set()
        self._running = False
        self._draining = False
        self._history: Dict[str, Any] = {}
        self._id_counter = itertools.count()
        # finalizer plumbing (overlap mode): (group, result, error, wall)
        # tuples, joined off the exec thread so compute never waits on
        # encode/disk.  FIFO -> history lands in execution order.
        self._finalize_q: "queue.Queue" = queue.Queue()
        self._finalize_pending = 0
        # multi-master shard plane (ISSUE 14): resolve the shard config
        # BEFORE the durability plane attaches — each shard keeps its
        # own WAL/epoch stream under DTPU_SHARD_WAL_ROOT/<id>, its
        # lease-owner identity is the shard id (crash-restart reclaims;
        # a PEER's absorb acquire is the fresh-owner epoch bump), and
        # the JobStore's idempotency keys are namespaced by shard so a
        # takeover can never alias another master's acked units
        self._shard_cfg = None if is_worker else shard_mod.shard_config()
        shard_wal_dir = None
        shard_owner = None
        if self._shard_cfg is not None:
            self.jobs.set_scope(self._shard_cfg["id"])
            shard_owner = self._shard_cfg["id"]
            if self._shard_cfg.get("wal_root"):
                shard_wal_dir = os.path.join(
                    self._shard_cfg["wal_root"], self._shard_cfg["id"])
        # durability plane (ISSUE 7): with DTPU_WAL_DIR set, a master
        # acquires (or, under DTPU_STANDBY=1, watches) the file lease,
        # replays the write-ahead job log, and preloads the recovered
        # ledger/idempotency state BEFORE the exec thread can pop
        # anything.  The interrupted prompts themselves are re-enqueued
        # by resume_recovered() once the server loop is up.
        from comfyui_distributed_tpu.runtime import durable as durable_mod
        try:
            self.durable = durable_mod.DurableMaster.attach(
                self, dirpath=shard_wal_dir, owner=shard_owner)
        except durable_mod.WalError as e:
            # a held lease (second active master) must fail LOUDLY, not
            # boot a split-brain — but a standby construction never hits
            # this (it only watches)
            raise RuntimeError(f"durable master startup refused: {e}")
        # the ShardManager itself (ring + gossip + peer-lease watch)
        # attaches after the durability plane so a takeover can merge
        # an absorbed shard's recovered state into live planes; the
        # per-client admission rate splits by the member count (one
        # client's traffic spreads over the shards by prompt-id hash)
        self.shard = shard_mod.ShardManager.attach(
            self, cfg=self._shard_cfg, start_threads=start_exec_thread)
        if self.shard is not None:
            self.admission.set_rate_scale(1.0 / self.shard.n_members())
        self._exec_started = bool(start_exec_thread)
        if start_exec_thread:
            if self.cb_enabled:
                from comfyui_distributed_tpu.workflow import \
                    batch_executor as cb_mod
                self.cb = cb_mod.ContinuousBatchExecutor(self)
                self.cb.start()
            else:
                t = threading.Thread(target=self._exec_loop, daemon=True,
                                     name="dtpu-exec")
                t.start()
            if self.overlap_enabled:
                f = threading.Thread(target=self._finalize_loop,
                                     daemon=True, name="dtpu-finalize")
                f.start()

    def _drop_tile_queues(self, prompt: Dict[str, Any]) -> None:
        """Remove master-mode tile queues for a finished prompt.  They're
        pre-created at /prompt time (before the exec thread runs), so a
        prompt that fails before its upscale node would otherwise leave an
        orphan queue accepting tiles forever — the leak put_tile's
        require_existing guard exists to prevent.  The upscale node's own
        drain also removes the queue; this is the failure-path backstop."""
        if self.loop is None:
            return
        for node in prompt.values():
            if not isinstance(node, dict) \
                    or node.get("class_type") != "UltimateSDUpscaleDistributed":
                continue
            h = {**node.get("inputs", {}), **node.get("hidden", {})}
            mj = h.get("multi_job_id")
            if mj and not h.get("is_worker"):
                try:
                    asyncio.run_coroutine_threadsafe(
                        self.jobs.remove_tile_queue(str(mj)),
                        self.loop).result(timeout=5)
                except Exception as e:  # noqa: BLE001 - cleanup best-effort
                    debug_log(f"tile queue cleanup {mj}: {e}")

    # --- execution queue (ComfyUI /prompt semantics) -----------------------

    def queue_remaining(self) -> int:
        with self._queue_lock:
            n = len(self._queue) + (1 if self._running else 0)
        if self.cb is not None:
            # continuous batching: in-flight slots + decoding tails are
            # queued-or-executing work exactly like the legacy in-flight
            # group (backpressure, autoscale signal, Retry-After hints)
            n += self.cb.active_prompts()
        return n

    def queued_by_class(self) -> Dict[str, int]:
        """Queued (not yet running) prompts per tenant class — the
        admission block's live gauge on both metrics surfaces."""
        out = {cls: 0 for cls in self.admission.classes}
        with self._queue_lock:
            for item in self._queue:
                cls = item.get("tenant") or self.admission.default_class
                out[cls] = out.get(cls, 0) + 1
        return out

    def enqueue_prompt(self, prompt: Dict[str, Any], client_id: str,
                       extra_data: Optional[Dict[str, Any]] = None,
                       trace_parent: Optional[tuple] = None,
                       trace_span: Any = None,
                       pid: Optional[str] = None,
                       tenant: Optional[str] = None,
                       span_attrs: Optional[Dict[str, Any]] = None,
                       _recovered: bool = False,
                       _preadmitted: bool = False,
                       _absorbed: bool = False) -> str:
        """Queue one prompt.  Every job gets a request-scoped trace: a
        ``job`` root span that lives from enqueue to finalize and lands
        in the flight recorder under the prompt id.  ``trace_parent`` is
        an inbound (trace_id, parent_span_id) extracted from a peer's
        traceparent header (this process becomes a child of the caller's
        trace — the dispatched-worker case); ``trace_span`` hands in an
        already-open span to adopt as the job span (the master's fan-out
        root, so its dispatch/collect children and the local execution
        share one tree)."""
        # `pid` override = crash recovery re-enqueueing an interrupted
        # prompt under its ORIGINAL id (clients polling /history find it
        # on the stand-in master), or a router/client-supplied hash hint.
        # A sharded master GENERATES ids its own shard owns, so a direct
        # (hint-less) submission never needs the forward hop.
        if pid is None:
            pid = self.shard.local_pid(self._id_counter) \
                if self.shard is not None \
                else f"p_{int(time.time() * 1000)}_{next(self._id_counter)}"
        # an extra_data-carried priority survives paths that don't pass
        # tenant explicitly (crash-recovery re-enqueues replay extra_data
        # from the WAL; direct embedded callers)
        tenant = self.admission.classify(
            tenant or (extra_data or {}).get("priority"))
        sp = trace_span
        if sp is None:
            tid, par = trace_parent if trace_parent else (None, None)
            sp = trace_mod.start_span(
                "job", trace_id=tid, parent_id=par,
                attrs={"prompt_id": pid, "client_id": str(client_id),
                       "tenant": tenant,
                       "role": "worker" if self.is_worker else "master"})
        else:
            sp.attrs.setdefault("prompt_id", pid)
            sp.attrs.setdefault("tenant", tenant)
        if sp is not None:
            if self.shard is not None:
                sp.attrs["shard"] = self.shard.id
                sp.attrs["ring_epoch"] = self.shard.ring_epoch()
            for k, v in (span_attrs or {}).items():
                sp.attrs[k] = v
        # signature hashed OUTSIDE the lock (it walks the whole graph):
        # _pop_group then only compares strings under the lock.  The
        # continuous-batching flag rides along the same way: a cheap
        # whole-graph screen now, so the step executor's pop decisions
        # are string/int compares under the lock.
        sig = sched_mod.coalesce_signature(prompt) \
            if (self.coalesce_enabled or self.cb_enabled) else None
        cb_ok = False
        if self.cb_enabled and sig is not None:
            from comfyui_distributed_tpu.workflow import \
                batch_executor as cb_mod
            cb_ok = cb_mod.quick_eligible(prompt)
        # exact-hit result cache (ISSUE 13 tier a): a byte-identical
        # re-submission (same signature AND same full widget values,
        # seed included) replays the stored outputs without ever
        # touching the queue — history/metrics/span stamped cache_hit.
        # DTPU_CACHE=0 skips the key computation entirely; recovery
        # re-enqueues always re-execute (their first run may not have
        # finished storing).
        rkey = None
        if not self.is_worker and not _recovered \
                and reuse_mod.reuse_enabled():
            rkey = reuse_mod.result_key(prompt, input_dir=self.input_dir,
                                        models_dir=self.models_dir,
                                        scope=self.shard_cache_scope())
            if rkey is not None:
                entry = reuse_mod.get_reuse().result.get(rkey)
                if entry is not None:
                    self._replay_cached(pid, sp, entry)
                    return pid
        # rejection decided under the lock, but the span seal/commit
        # (FlightRecorder lock) and the raise happen OUTSIDE it: the
        # queue lock is the hottest lock in the process and must never
        # be held across a foreign subsystem's lock — the dtpu-lint
        # deadlock-cycle rule tracks exactly these ordering edges
        reject: Optional[tuple] = None
        with self._queue_lock:
            if self._draining:
                reject = (DrainingError("server is draining; not "
                                        "accepting prompts"),
                          "rejected: draining")
            elif not _recovered and not _preadmitted:
                # class-aware admission (token bucket + shed
                # thresholds); recovery re-enqueues and pre-admitted
                # fan-out shares skip it — their admission already
                # happened (and was WAL'd).  The admission lock is a
                # leaf: AdmissionController never calls back out.
                rejection = self.admission.admit(
                    tenant, str(client_id), len(self._queue),
                    self.max_queue)
                if rejection is not None:
                    reject = (ShedError(rejection),
                              f"rejected: shed "
                              f"({rejection['reason']}, {tenant})")
            if reject is None \
                    and len(self._queue) >= self.max_queue:
                reject = (QueueFullError(
                    f"prompt queue full ({self.max_queue})"),
                    "rejected: queue full")
            if reject is None:
                self._queue.append({"id": pid, "prompt": prompt,
                                    "client_id": client_id,
                                    "extra_data": extra_data or {},
                                    "sig": sig,
                                    "cb": cb_ok,
                                    "rkey": rkey,
                                    "tenant": tenant,
                                    "span": sp,
                                    "t_enq": time.perf_counter()})
                self._inflight.add(pid)
        if reject is not None:
            self._abandon_span(sp, pid, reject[1])
            raise reject[0]
        # write-ahead: the admission record is durable BEFORE the
        # prompt_id reaches the client (a crash after the append but
        # before the response re-runs the prompt — at-least-once at the
        # prompt level, exactly-once per unit through the ledger).
        # Recovery re-enqueues suppress the append (their record — the
        # original admission — is already in the log) EXCEPT absorbed
        # shards' prompts: their record lives in the DEAD shard's now-
        # dormant log, so ownership transfers by re-logging them here.
        if self.durable is not None and (not _recovered or _absorbed):
            self.durable.log_enqueue(pid, prompt, client_id, extra_data)
        self._queue_event.set()
        return pid

    def shard_cache_scope(self) -> Optional[str]:
        """The shard-owner-epoch token salting the exact-hit result
        cache (ISSUE 14 satellite): shard id + this shard's current WAL
        epoch, so cross-shard entries never alias and a deposed epoch's
        entries go cold after a takeover.  None (key unchanged) when
        sharding is off."""
        if self.shard is None:
            return None
        epoch = self.durable.epoch if self.durable is not None else 0
        return f"{self.shard.id}:e{epoch}"

    def _replay_cached(self, pid: str, sp,
                       entry: Dict[str, Any]) -> None:
        """Exact-hit replay: settle the prompt NOW from the stored
        outputs.  The history entry and the committed job span look
        like a normal success, distinguished by ``cache_hit`` — a
        client polling /history cannot tell the difference except by
        latency.  Counted ONLY as ``prompts_replayed``: nothing
        executed (prompts_executed stays honest), nothing was admitted
        (the per-class completed counter would break
        admitted >= completed), and no queue slot freed (the
        drain-rate ring feeds the Retry-After estimate)."""
        done_t = time.time()
        self.metrics["prompts_replayed"] += 1
        trace_mod.GLOBAL_COUNTERS.bump("cache_result_replays")
        trace_mod.GLOBAL_STAGES.record("cache_replay", 0.0)
        self._history[pid] = {
            "status": "success",
            "images": len(entry.get("images", ())),
            "duration_s": 0.0,
            "cache_hit": True,
            "finished_at": done_t,
        }
        if sp is not None:
            sp.attrs["cache_hit"] = True
            sp.attrs["cache_tier"] = "result"
            sp.end()
            trace_mod.GLOBAL_TRACES.commit(
                pid, sp.trace_id, status="ok", root_span_id=sp.span_id,
                duration_s=round(done_t - sp.start_s, 6))

    def _purge_abandoned(self) -> int:
        """Client-gone cancellation for prompts still IN the queue: the
        exec/CB driver calls this before popping, so an abandoned job
        never starts executing.  Each purged prompt finalizes as
        ``abandoned`` through the normal finalize path (history, WAL
        record, sealed span)."""
        bus = reuse_mod.PREVIEWS
        with self._queue_lock:
            if not self._queue:
                return 0
            doomed = [it for it in self._queue
                      if bus.is_abandoned(it["id"])]
            if not doomed:
                return 0
            gone = {id(it) for it in doomed}
            self._queue = [it for it in self._queue
                           if id(it) not in gone]
        err = reuse_mod.AbandonedError(
            "client disconnected before execution")
        for item in doomed:
            self._finalize_hand([item], None, err, time.perf_counter())
        return len(doomed)

    @staticmethod
    def _abandon_span(sp, pid: str, reason: str) -> None:
        """End + commit a job span for a prompt that never executes
        (backpressure/drain rejections and purges still leave a
        postmortem trace)."""
        if sp is None:
            return
        sp.set_status("error", reason)
        sp.end()
        trace_mod.GLOBAL_TRACES.commit(
            pid, sp.trace_id, status="error", root_span_id=sp.span_id,
            duration_s=round(time.time() - sp.start_s, 6))

    def _pop_group(self) -> Optional[List[Dict[str, Any]]]:
        """Pop the next dispatch group under weighted fair scheduling
        (workflow/scheduler.pop_fair_group): the scheduled class's
        head prompt plus that class's next signature-identical prompts
        (capped at DTPU_MAX_COALESCE).  Per-class FIFO order is
        preserved by construction — no prompt ever executes before one
        of ITS OWN class queued ahead of it — and with a single class
        queued (the default: untagged traffic) this is exactly the
        legacy head-of-queue contiguous-run pop."""
        with self._queue_lock:
            if not self._queue:
                self._queue_event.clear()
                return None
            group = sched_mod.pop_fair_group(
                self._queue, self.admission,
                coalesce_max=self.coalesce_max
                if self.coalesce_enabled else 1)
            self._running = True
        now = time.perf_counter()
        now_wall = time.time()
        for item in group:
            wait = now - item.get("t_enq", now)
            trace_mod.GLOBAL_STAGES.record("queue_wait", wait)
            if item.get("span") is not None:
                trace_mod.event_span("queue_wait", now_wall - wait,
                                     now_wall, parent=item["span"])
        return group

    def _exec_loop(self) -> None:
        while True:
            self._queue_event.wait()
            self._exec_gate.wait()
            self._purge_abandoned()
            group = self._pop_group()
            if group is None:
                continue
            self._execute_group(group)

    def _execute_group(self, group: List[Dict[str, Any]]) -> None:
        """Run one popped dispatch group end to end (the legacy
        whole-graph model): coalesced build, executor run, finalize
        hand-off.  Shared by the classic exec loop and the continuous-
        batching executor's fallback thread — non-step-batchable
        prompts keep every PR 2/9 behavior bit for bit."""
        from comfyui_distributed_tpu.parallel.mesh import get_runtime
        self.interrupt_event.clear()
        t0 = time.perf_counter()
        res, err = None, None
        try:
            ctx = OpContext(
                runtime=get_runtime(),
                models_dir=self.models_dir,
                input_dir=self.input_dir,
                output_dir=self.output_dir,
                is_worker=self.is_worker,
                job_store=self.jobs,
                server_loop=self.loop,
                interrupt_event=self.interrupt_event,
                host_pool=self.host_pool,
                cluster=self.cluster,
                ledger=self.ledger,
                fault_inject=self.fault_inject,
            )
            first = group[0]
            trace_mod.GLOBAL_COUNTERS.bump("exec_runs")
            # the run executes under the HEAD prompt's job span
            # (coalesced followers' traces stay thin — job +
            # queue_wait — and name their leader); per-node and
            # stage spans created inside attach to this trace
            with trace_mod.use_span(first.get("span")), \
                    trace_mod.span("execute",
                                   coalesced=len(group)):
                if len(group) > 1:
                    graph, hidden = sched_mod.build_coalesced(
                        [it["prompt"] for it in group])
                    ctx.coalesce = len(group)
                    trace_mod.GLOBAL_COUNTERS.bump("coalesced_batches")
                    trace_mod.GLOBAL_COUNTERS.bump("coalesced_prompts",
                                                   len(group))
                    debug_log(f"coalesced {len(group)} prompts into "
                              f"one dispatch ({first['id']}..)")
                    for item in group[1:]:
                        if item.get("span") is not None:
                            item["span"].attrs["coalesced_into"] = \
                                first["id"]
                    with trace_mod.stage("coalesced_batch"):
                        res = WorkflowExecutor(ctx).execute(
                            graph, hidden=hidden,
                            extra_pnginfo=first.get(
                                "extra_data", {}).get("extra_pnginfo"))
                else:
                    res = WorkflowExecutor(ctx).execute(
                        first["prompt"],
                        extra_pnginfo=first.get("extra_data", {}).get(
                            "extra_pnginfo"))
            trace_mod.GLOBAL_STAGES.record("compute", res.total_s)
        except Exception as e:  # noqa: BLE001 - survive bad prompts
            err = e
        finally:
            with self._queue_lock:
                self._running = False
                self._finalize_pending += 1
        if self.overlap_enabled:
            # hand host-side joining to the finalizer so the next
            # group's compute starts NOW — this is the overlap
            self._finalize_q.put((group, res, err, t0))
        else:
            self._finalize_group(group, res, err, t0)

    def _finalize_hand(self, group, res, err, t0) -> None:
        """Finalize entry point for the continuous-batching executor
        (tail decodes, slot aborts): books the pending finalize and
        routes through the same overlap/inline split as
        _execute_group."""
        with self._queue_lock:
            self._finalize_pending += 1
        if self.overlap_enabled:
            self._finalize_q.put((group, res, err, t0))
        else:
            self._finalize_group(group, res, err, t0)

    def _finalize_loop(self) -> None:
        while True:
            group, res, err, t0 = self._finalize_q.get()
            self._finalize_group(group, res, err, t0)

    def _finalize_group(self, group, res, err, t0) -> None:
        """Join deferred host edges, split per-prompt results, write
        history/metrics, drop orphan tile queues, seal the group's job
        traces into the flight recorder (+ the slow-job log line)."""
        if res is not None and err is None:
            try:
                # the join runs under the head job's span so the
                # host-edge wait is visible in the trace tree
                with trace_mod.use_span(group[0].get("span")), \
                        trace_mod.span("finalize"):
                    res.wait_host()
            except Exception as e:  # noqa: BLE001 - host edge failed
                err = e
        k = len(group)
        done_t = time.time()
        abandoned = isinstance(err, reuse_mod.AbandonedError)
        if err is None:
            per_prompt = sched_mod.split_images(res.images, k)
            # metrics BEFORE history: clients poll history for
            # completion, then read metrics — the other order would give
            # them a window where the prompt is "done" but uncounted
            self.metrics["prompts_executed"] += k
            self.metrics["last_execution_s"] = res.total_s
            reuse_on = reuse_mod.reuse_enabled()
            for item, imgs in zip(group, per_prompt):
                entry = {"status": "success", "images": len(imgs),
                         "duration_s": res.total_s,
                         "finished_at": done_t}
                if k > 1:
                    entry["coalesced"] = k
                self._history[item["id"]] = entry
                # exact-hit result tier: store the per-prompt outputs
                # so a byte-identical re-submission replays instead of
                # recomputing (LRU-bounded by DTPU_CACHE_BYTES)
                if reuse_on and item.get("rkey") and imgs:
                    reuse_mod.store_result(item["rkey"], imgs,
                                           res.total_s)
        elif abandoned:
            # client-gone cancellation: settled, not failed — the WAL
            # completion record below closes the admission record so a
            # crash-recovery never resurrects an abandoned job
            log(f"prompt group {group[0]['id']} (x{k}) abandoned: {err}")
            self.metrics["prompts_abandoned"] += k
            for item in group:
                entry = {"status": "abandoned", "error": str(err),
                         "finished_at": done_t}
                if k > 1:
                    entry["coalesced"] = k
                self._history[item["id"]] = entry
        else:
            log(f"prompt group {group[0]['id']} (x{k}) failed: "
                f"{type(err).__name__}: {err}")
            self.metrics["prompts_failed"] += k
            for item in group:
                entry = {"status": "error", "error": str(err),
                         "finished_at": done_t}
                if k > 1:
                    entry["coalesced"] = k
                self._history[item["id"]] = entry
        # seal each prompt's trace: end the job span, commit to the
        # flight recorder under the prompt id, and emit the always-on
        # slow-job line when the end-to-end span exceeds DTPU_SLOW_JOB_S
        status = "ok" if err is None \
            else ("abandoned" if abandoned else "error")
        if self.durable is not None:
            # the completion record closes the admission record: a
            # crash BEFORE this point re-runs the prompt on recovery
            # (deterministic seeds make the redo bit-identical), after
            # it the prompt is settled history
            for item in group:
                self.durable.log_exec_done(item["id"], status)
        for item in group:
            self._drop_tile_queues(item["prompt"])
        slow_thr = 0.0
        try:
            slow_thr = float(os.environ.get(C.SLOW_JOB_ENV, "0") or 0)
        except ValueError:
            pass
        # peak device memory + RSS ride the slow-job line and error
        # traces (satellite: an OOM-adjacent slow job is diagnosed from
        # the log line alone).  Executor-attributed numbers when the run
        # survived; a fresh process probe when it died before reporting.
        # Resolved lazily: with tracing off (no spans) nothing below
        # reads it, and the probe shouldn't tax every finalize.
        _job_res_cache: List[Dict[str, Any]] = []

        def _job_res() -> Dict[str, Any]:
            if _job_res_cache:
                return _job_res_cache[0]
            jr = res.resources if (res is not None
                                   and getattr(res, "resources", None)) \
                else None
            if jr is None:
                mem = resource_mod.device_memory_snapshot()
                jr = {"device_peak_bytes": mem["peak_bytes_in_use"],
                      "host_rss_bytes": resource_mod.host_rss_bytes(),
                      "source": mem["source"]}
            _job_res_cache.append(jr)
            return jr

        def _mem_note() -> str:
            jr = _job_res()
            return (f"mem device_peak="
                    f"{jr['device_peak_bytes'] / 1e6:.1f}MB "
                    f"rss={jr['host_rss_bytes'] / 1e6:.1f}MB "
                    f"({jr['source']})")
        # SLO burn-rate feed (ISSUE 18): EVERY finalized prompt lands in
        # its class's fast/slow windows — span-less ones too (tracing
        # off must not blind the engine).  Abandoned counts as bad: the
        # client saw no completion.
        ok = err is None
        if ok and res is not None:
            fallback_dur = float(res.total_s)
        else:
            fallback_dur = max(time.perf_counter() - t0, 0.0)
        for item in group:
            sp = item.get("span")
            dur_slo = round(done_t - sp.start_s, 6) if sp is not None \
                else fallback_dur
            tenant = str(item.get("tenant")
                         or self.admission.default_class)
            self.slo.record(tenant, dur_slo, ok)
            if sp is not None:
                # trace <-> SLO cross-links: the class on the root span,
                # and an slo_breach event when the job blew its class's
                # latency objective (the spec-driven cousin of the
                # DTPU_SLOW_JOB_S log line)
                sp.attrs.setdefault("tenant", tenant)
                thr = self.slo.latency_threshold(tenant)
                if thr is not None and dur_slo > thr:
                    trace_mod.event_span(
                        "slo_breach", done_t, done_t, parent=sp,
                        attrs={"tenant": tenant, "threshold_s": thr})
        for item in group:
            sp = item.get("span")
            if sp is None:
                continue
            if err is not None:
                sp.set_status(status, str(err))
                # the job never set its execute-span mem attrs (the
                # exception aborted the executor) — stamp the root so
                # the error trace still answers "how much memory"
                sp.attrs.setdefault(
                    "device_peak_mb",
                    round(_job_res()["device_peak_bytes"] / 1e6, 2))
                sp.attrs.setdefault(
                    "rss_mb",
                    round(_job_res()["host_rss_bytes"] / 1e6, 2))
            dur = round(done_t - sp.start_s, 6)
            sp.end()
            # end-to-end latency histogram WITH an exemplar: the bucket
            # this job landed in now points at its trace, so a slow
            # .prom bucket resolves to a flight-recorder/capture entry
            trace_mod.GLOBAL_STAGES.record("job_e2e", dur,
                                           trace_id=sp.trace_id)
            trace_mod.GLOBAL_TRACES.commit(
                item["id"], sp.trace_id, status=status,
                root_span_id=sp.span_id, duration_s=dur)
            if slow_thr > 0 and dur > slow_thr:
                stages = trace_mod.GLOBAL_TRACES.breakdown(sp.trace_id)
                stages.pop("job", None)
                top = sorted(stages.items(), key=lambda kv: -kv[1])[:8]
                log(f"SLOW job {item['id']} ({status}): {dur:.2f}s > "
                    f"{slow_thr:g}s threshold; trace {sp.trace_id}; "
                    f"{_mem_note()}; stages "
                    + ", ".join(f"{n}={s:.2f}s" for n, s in top))
        # drain-rate ring + per-class completion counters: each
        # finalized prompt frees a queue slot, which is what the 429
        # Retry-After hint estimates from
        self._completions.append((time.monotonic(), k))
        if err is None:
            for item in group:
                self.admission.on_complete(
                    item.get("tenant") or self.admission.default_class)
        # preview channel: terminal SSE event for any attached client,
        # and the abandonment flag (if set) is consumed — the job is
        # settled either way
        for item in group:
            reuse_mod.PREVIEWS.finish(item["id"], status)
        with self._queue_lock:
            self._finalize_pending -= 1
            for item in group:
                self._inflight.discard(item["id"])
        debug_log(f"group {group[0]['id']} (x{k}) done in "
                  f"{time.perf_counter() - t0:.2f}s")

    # --- backpressure hints --------------------------------------------------

    def drain_rate(self, window_s: float = 30.0) -> float:
        """Prompts finalized per second over the recent window (0.0
        until anything completed) — the denominator of the Retry-After
        hint."""
        now = time.monotonic()
        n = sum(k for t, k in self._completions if now - t <= window_s)
        if n <= 0:
            return 0.0
        oldest = min(t for t, _ in self._completions
                     if now - t <= window_s)
        return n / max(now - oldest, 0.5)

    def retry_after_hint(self, floor_s: float = 1.0) -> int:
        """Whole seconds a shed client should wait before retrying,
        derived from the current backlog and the measured drain rate:
        roughly "when will a quarter of the queue have drained".
        Conservative bounds [1, 30] — the point is de-synchronizing the
        retry storm, not a precise reservation."""
        depth = self.queue_remaining()
        rate = self.drain_rate()
        if rate <= 0:
            hint = 5.0          # nothing measured yet: a polite default
        else:
            hint = max(depth, 1) / (4.0 * rate)
        return int(min(max(math.ceil(max(hint, floor_s)), 1), 30))

    # --- crash recovery (durability plane) ----------------------------------

    def resume_recovered(self) -> int:
        """Re-enqueue the prompts a crash interrupted (replayed from the
        WAL at construction).  Called from on_startup — by then the
        server loop exists, so the resumed upscale jobs' tile queues and
        collector drains work; idempotent."""
        if self.durable is None:
            return 0
        return self.durable.resume()

    # --- graceful drain -----------------------------------------------------

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: stop accepting prompts, let the queue, the
        in-flight group and the host-IO pool finish (bounded by
        DTPU_DRAIN_TIMEOUT_S), then cancel what remains.  Returns True
        when everything drained inside the bound."""
        if timeout is None:
            timeout = float(os.environ.get(C.DRAIN_TIMEOUT_ENV,
                                           C.DRAIN_TIMEOUT_DEFAULT))
        if self.autoscaler is not None:
            # a reconciliation firing mid-shutdown would spawn workers
            # into a dying fleet
            self.autoscaler.stop()
        if self.shard is not None:
            # stop gossip + the peer-lease watcher: a dying master must
            # not absorb a peer's shard on its way out
            self.shard.stop()
        with self._queue_lock:
            self._draining = True
        deadline = time.monotonic() + max(timeout, 0.0)
        while True:
            with self._queue_lock:
                # without an exec thread nothing will ever pop the queue
                # — only in-flight/host work is drainable
                idle = (not self._running and self._finalize_pending == 0
                        and (not self._queue or not self._exec_started))
            if idle and self.cb is not None:
                # continuous batching: in-flight slots / decoding tails /
                # fallback groups are in-flight work like the legacy
                # running group
                idle = self.cb.idle()
            if idle and (self.host_pool is None
                         or self.host_pool.pending == 0):
                if self.cb is not None:
                    # drained for shutdown: stop the step driver so a
                    # dead ServerState's threads don't keep polling the
                    # process-global interrupt/queue state (loopback
                    # tests and benches run many states per process)
                    self.cb.stop()
                return True
            if time.monotonic() >= deadline:
                break
            time.sleep(0.05)
        # bound exceeded: purge the not-yet-started queue FIRST (or the
        # exec loop would keep popping groups — and clearing the
        # interrupt flag — right through the shutdown), then cancel the
        # in-flight work instead of dying mid-job silently (the compiled
        # samplers poll the flag per step)
        with self._queue_lock:
            purged, self._queue = self._queue, []
            for item in purged:
                self._inflight.discard(item["id"])
        done_t = time.time()
        for item in purged:
            self._abandon_span(item.get("span"), item["id"],
                               "cancelled: server drain timeout")
            self._history[item["id"]] = {
                "status": "error",
                "error": "cancelled: server drain timeout",
                "finished_at": done_t}
        self.metrics["prompts_failed"] += len(purged)
        log(f"drain timeout after {timeout:.1f}s; cancelled "
            f"{len(purged)} queued prompt(s), interrupting in-flight work")
        self.interrupt_event.set()
        if self.cb is not None:
            # give the driver a beat to consume the interrupt (aborting
            # its slots), then stop its threads — a timed-out drain must
            # not leak a live driver polling process-global state any
            # more than a clean one does
            stop_by = time.monotonic() + 2.0
            while time.monotonic() < stop_by and not self.cb.idle():
                time.sleep(0.02)
            self.cb.stop()
        if self.host_pool is not None:
            self.host_pool.shutdown(wait=False)
        return False


def build_app(state: Optional[ServerState] = None) -> web.Application:
    state = state or ServerState()
    # chaos harness (ISSUE 9): with DTPU_CHAOS armed the middleware may
    # 503/delay a fraction of inbound data-plane requests; unarmed it is
    # one env-change check per request
    app = web.Application(client_max_size=512 * 1024 * 1024,
                          middlewares=[chaos_mod.middleware()])
    app["state"] = state

    async def on_startup(app):
        state.loop = asyncio.get_running_loop()
        # recovery resume off the event loop: it health-polls the
        # workers and may enqueue several prompts.  Needs state.port
        # (the recovery redispatch graphs embed this master's URL) —
        # serve() sets it before run_app; embedded/test servers with a
        # late-bound port call resume_recovered() themselves.
        if state.durable is not None and state.port is not None:
            await state.loop.run_in_executor(None, state.resume_recovered)

    async def on_cleanup(app):
        # graceful drain: refuse new prompts, let the in-flight group and
        # the encoder pool finish (bounded), THEN drop the HTTP client —
        # the exec thread used to be a daemon that died mid-job here
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, state.drain)
        if state.durable is not None:
            # the close fsyncs the WAL tail — off the loop like every
            # other durability edge
            await loop.run_in_executor(None, state.durable.close)
        await net_mod.cleanup_client_session()

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    r = app.router

    def ok(payload: Any = None, **kw):
        body = {"status": "ok"}
        if payload is not None:
            body.update(payload)
        body.update(kw)
        return web.json_response(body)

    # --- config CRUD (reference distributed.py:49-364) ---------------------

    async def _mutate(mutator):
        """Config RMW off the event loop: the config lock is shared with the
        exec thread and auto-launch timer, and file IO under it must not
        stall the data plane (same reason PNG decode is offloaded)."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, lambda: cfg_mod.mutate_config(mutator, state.config_path))

    async def get_config(request):
        loop = asyncio.get_running_loop()
        cfg = await loop.run_in_executor(
            None, lambda: cfg_mod.load_config(state.config_path))
        return web.json_response(cfg)

    async def update_worker(request):
        data = await request.json()
        if "id" not in data:
            return web.json_response({"error": "missing worker id"},
                                     status=400)
        result = {}
        await _mutate(lambda cfg: result.update(
            cfg_mod.upsert_worker(cfg, data)))
        return ok({"worker": result})

    async def delete_worker(request):
        data = await request.json()
        found = []
        await _mutate(lambda cfg: found.append(
            cfg_mod.delete_worker(cfg, str(data.get("id")))))
        if not found[0]:
            return web.json_response({"error": "worker not found"},
                                     status=404)
        return ok()

    async def update_setting(request):
        data = await request.json()
        if "key" not in data:
            return web.json_response({"error": "missing key"}, status=400)
        await _mutate(lambda cfg: cfg_mod.update_setting(
            cfg, data["key"], data.get("value")))
        return ok()

    async def update_master(request):
        data = await request.json()
        # only keys present in the request are touched — an explicit null
        # deletes a field, an absent key leaves it alone (partial update)
        fields = {k: data[k] for k in ("host", "port", "extra_args")
                  if k in data}
        await _mutate(lambda cfg: cfg_mod.update_master(cfg, **fields))
        return ok()

    # --- info / lifecycle ---------------------------------------------------

    async def network_info(request):
        return web.json_response(net_mod.network_info())

    async def status(request):
        from comfyui_distributed_tpu.parallel.mesh import get_runtime
        # first call may initialize the JAX backend (seconds on real TPU) —
        # keep it off the event loop so the data plane stays responsive
        loop = asyncio.get_running_loop()
        st = await loop.run_in_executor(None,
                                        lambda: get_runtime().status())
        st["jobs"] = state.jobs.snapshot()
        st["queue_remaining"] = state.queue_remaining()
        st["is_worker"] = state.is_worker
        return web.json_response(st)

    async def metrics(request):
        from comfyui_distributed_tpu.utils.trace import (
            GLOBAL_NODES, GLOBAL_PHASES, GLOBAL_TRACES,
            counters_snapshot, pipeline_snapshot, tracing_enabled)
        # wal stats list segment files and may contend with an
        # append's fsync/rotation under the WAL lock — off the loop
        dur_stats = {"enabled": False}
        if state.durable is not None:
            dur_stats = await asyncio.get_running_loop() \
                .run_in_executor(None, state.durable.stats)
        # the exporter's first stats() call may construct it (a dir
        # scan) — keep that filesystem touch off the event loop
        export_stats = await asyncio.get_running_loop() \
            .run_in_executor(None, trace_export_mod.stats)
        return web.json_response({**state.metrics,
                                  "phases": GLOBAL_PHASES.snapshot(),
                                  # per-node-type op latency histograms
                                  # (count/mean/p50/p95/p99)
                                  "nodes": GLOBAL_NODES.snapshot(),
                                  # request-tracing health (+ the
                                  # durable capture plane: exporter
                                  # counters, eviction visibility)
                                  "tracing": {
                                      "enabled": tracing_enabled(),
                                      "ring_size": GLOBAL_TRACES.size(),
                                      "ring_max":
                                          GLOBAL_TRACES.max_traces,
                                      "dropped_spans":
                                          GLOBAL_TRACES.dropped_spans,
                                      "evictions": GLOBAL_TRACES
                                          .eviction_count(),
                                      "export": export_stats,
                                  },
                                  # SLO burn-rate engine: per-tenant
                                  # objectives, fast/slow window stats,
                                  # burn rates + budget remaining
                                  "slo": state.slo.evaluate(),
                                  # per-job stage timeline (queue_wait /
                                  # coalesced_batch / compute / d2h /
                                  # encode / upload) + scheduler and wire
                                  # counters: the overlapped-pipeline
                                  # health signals
                                  "pipeline": {
                                      **pipeline_snapshot(),
                                      "overlap": state.overlap_enabled,
                                      "coalesce": state.coalesce_enabled,
                                      "max_queue": state.max_queue,
                                  },
                                  # iteration-level continuous batching:
                                  # slot occupancy, per-bucket admit/
                                  # retire/step/retrace counters, pad set
                                  "batching": (
                                      state.cb.snapshot()
                                      if state.cb is not None
                                      else {"enabled": False}),
                                  # cluster control plane: lease states,
                                  # ledger activity, recovery counters
                                  "cluster": {
                                      **state.cluster.snapshot(),
                                      "ledger": state.ledger.snapshot(),
                                      "policy":
                                          cluster_mod.fault_policy(),
                                      "hedge_armed":
                                          cluster_mod.hedge_armed(),
                                  },
                                  # durability plane: WAL size/sync-lag
                                  # gauges, lease holder + epoch
                                  "durability": dur_stats,
                                  # multi-master shard plane: ring
                                  # membership/epoch, owned shards,
                                  # absorbed takeovers, forward count
                                  "shard": (state.shard.snapshot()
                                            if state.shard is not None
                                            else {"enabled": False}),
                                  # multi-tenant admission: per-class
                                  # admitted/shed/completed counters,
                                  # weights, shed bars, drain rate
                                  "admission": {
                                      **state.admission.snapshot(),
                                      "queued_by_class":
                                          state.queued_by_class(),
                                      "drain_rate_per_s": round(
                                          state.drain_rate(), 4),
                                  },
                                  # elastic fleet: autoscaler decisions
                                  # ring + flap/scale counters
                                  "autoscale": (
                                      state.autoscaler.snapshot()
                                      if state.autoscaler is not None
                                      else {"enabled":
                                            autoscale_mod
                                            .autoscale_armed()}),
                                  # chaos harness: armed spec + injected
                                  # fault counters (all zero unarmed)
                                  "chaos": chaos_mod.get_chaos()
                                  .snapshot(),
                                  # critical-path analytics plane: live
                                  # anomaly counters vs the armed
                                  # baseline profile + per-worker clock
                                  # skew estimates (ISSUE 20)
                                  "analysis": {
                                      **analysis_mod.LIVE.snapshot(),
                                      "skew": state.cluster
                                          .skew_snapshot(),
                                  },
                                  # cross-request compute reuse: per-tier
                                  # hit/miss/eviction counters + byte
                                  # residency, and the preview channel's
                                  # client/abandonment gauges
                                  "reuse": {
                                      **reuse_mod.get_reuse().snapshot(),
                                      "previews":
                                          reuse_mod.PREVIEWS.snapshot(),
                                  },
                                  # resource telemetry: current gauges +
                                  # bounded ring-series stats (device
                                  # memory, RSS, utilization, queue)
                                  "resources": (
                                      state.resources.snapshot()
                                      if state.resources is not None
                                      else {"enabled": False}),
                                  # host<->device transfer bytes per node
                                  # + jit trace/XLA compile counts: the
                                  # tensor-plane health signals (steady
                                  # serving => retraces stop growing)
                                  **counters_snapshot()})

    _build_info_cache: List[Any] = []

    def _build_info_family():
        """``dtpu_build_info`` gauge: constant 1 with package/jax/backend
        labels so every scrape is attributable to a build (satellite:
        which code produced these numbers).  The labels are
        process-lifetime constants, so they're resolved once and cached
        — reading them must never re-hit disk metadata or initialize a
        backend on the scrape path."""
        if _build_info_cache:
            return _build_info_cache[0]
        import comfyui_distributed_tpu
        labels = {"version": comfyui_distributed_tpu.__version__}
        try:
            import importlib.metadata
            labels["version"] = importlib.metadata.version(
                "comfyui-distributed-tpu")
        except Exception:  # noqa: BLE001 - not installed as a dist
            pass
        resolved = True
        try:
            import jax
            labels["jax"] = jax.__version__
            labels["platform"] = jax.default_backend()
        except Exception:  # noqa: BLE001 - jax mid-init / unavailable
            labels.setdefault("jax", "unknown")
            labels.setdefault("platform", "unknown")
            resolved = False
        fam = ("dtpu_build_info", "gauge",
               "Build identity (constant 1; labels carry the info).",
               [(labels, 1)])
        if resolved:  # an "unknown" backend is transient — don't pin it
            _build_info_cache.append(fam)
        return fam

    async def metrics_prom(request):
        """Prometheus text exposition (``/distributed/metrics.prom``):
        the trace module's stage/phase/node histograms and counters plus
        this server's prompt/image counters, queue gauge, build-info
        gauge and current resource gauges — one scrapable endpoint per
        participant."""
        loop = asyncio.get_running_loop()
        # the first probe may initialize the JAX backend (seconds on a
        # real TPU with DTPU_RESOURCE=0, where no monitor thread already
        # did it) — keep that off the event loop so heartbeats and
        # prompts never stall behind a scrape
        build_info = await loop.run_in_executor(None, _build_info_family)
        self_sample = await loop.run_in_executor(None, _self_sample)
        extra = [
            build_info,
            ("dtpu_prompts_executed_total", "counter",
             "Prompts executed to success.",
             [({}, state.metrics["prompts_executed"])]),
            ("dtpu_prompts_failed_total", "counter",
             "Prompts that finished in error.",
             [({}, state.metrics["prompts_failed"])]),
            ("dtpu_images_received_total", "counter",
             "Worker images received on /distributed/job_complete.",
             [({}, state.metrics["images_received"])]),
            ("dtpu_tiles_received_total", "counter",
             "Worker tiles received on /distributed/tile_complete.",
             [({}, state.metrics["tiles_received"])]),
            ("dtpu_queue_remaining", "gauge",
             "Prompts queued or executing.",
             [({}, state.queue_remaining())]),
            ("dtpu_queue_capacity", "gauge",
             "DTPU_MAX_QUEUE backpressure cap.",
             [({}, state.max_queue)]),
        ]
        cl_workers = state.cluster.snapshot()["workers"].values()
        extra.append(
            ("dtpu_cluster_workers", "gauge",
             "Registered workers by lease state.",
             [({"state": st},
               sum(1 for w in cl_workers if w["state"] == st))
              for st in (cluster_mod.HEALTHY, cluster_mod.SUSPECT,
                         cluster_mod.DEAD, cluster_mod.UNKNOWN,
                         cluster_mod.RETIRING)]))
        # multi-tenant admission: per-class queue gauge + decision
        # counters (tenant label), so overload dashboards can draw the
        # shed-first ordering directly
        queued = state.queued_by_class()
        adm = state.admission.snapshot()["per_class"]
        extra.extend([
            ("dtpu_tenant_queued", "gauge",
             "Queued prompts by tenant class.",
             [({"tenant": cls}, n) for cls, n in sorted(queued.items())]),
            ("dtpu_tenant_admitted_total", "counter",
             "Prompts admitted by tenant class.",
             [({"tenant": cls}, v["admitted"])
              for cls, v in sorted(adm.items())]),
            ("dtpu_tenant_shed_total", "counter",
             "Prompts shed (429) by tenant class and reason.",
             [({"tenant": cls, "reason": reason},
               v[f"shed_{reason}"])
              for cls, v in sorted(adm.items())
              for reason in ("rate", "overload")]),
            ("dtpu_tenant_completed_total", "counter",
             "Prompts completed by tenant class.",
             [({"tenant": cls}, v["completed"])
              for cls, v in sorted(adm.items())]),
            ("dtpu_queue_drain_rate", "gauge",
             "Prompts finalized per second (recent window).",
             [({}, round(state.drain_rate(), 4))]),
        ])
        if state.cb is not None:
            # continuous batching: slot occupancy + admit/retire/step
            # counters and the per-bucket steady-state retrace counter
            # (the zero-retrace invariant, scrapeable per shape bucket)
            bsnap = state.cb.snapshot()
            extra.extend([
                ("dtpu_batch_slots", "gauge",
                 "Continuous-batching slots by state (all shape "
                 "buckets).",
                 [({"state": "active"}, bsnap["slots_active"]),
                  ({"state": "free"}, bsnap["slots_free"])]),
                ("dtpu_cb_admits_total", "counter",
                 "Prompts admitted into a running batch at a step "
                 "boundary.",
                 [({}, bsnap["admits"])]),
                ("dtpu_cb_retires_total", "counter",
                 "Slots retired (prompt finished its steps and moved "
                 "to decode).",
                 [({}, bsnap["retires"])]),
                ("dtpu_cb_steps_total", "counter",
                 "Batched denoise steps executed.",
                 [({}, bsnap["steps"])]),
                ("dtpu_cb_fallback_total", "counter",
                 "Prompts dispatched through the legacy fallback "
                 "executor.",
                 [({}, bsnap["fallbacks"])]),
                ("dtpu_cb_bucket_retraces_total", "counter",
                 "Retraces observed during bucket steps (want 0 in "
                 "steady state).",
                 [({"bucket": b["sig"]}, b["retraces"])
                  for b in bsnap["buckets"]]),
                # latent paging + SLO preemption (ISSUE 17)
                ("dtpu_cb_parked", "gauge",
                 "Continuous-batching rows parked to host (started "
                 "jobs waiting on slot residency).",
                 [({}, bsnap["parked"])]),
                ("dtpu_cb_parks_total", "counter",
                 "Slots parked to host at a step boundary.",
                 [({}, bsnap["parks"])]),
                ("dtpu_cb_resumes_total", "counter",
                 "Parked rows resumed into a slot.",
                 [({}, bsnap["resumes"])]),
                ("dtpu_cb_preemptions_total", "counter",
                 "Parks forced by a higher-class admit (SLO "
                 "preemption; subset of parks).",
                 [({}, bsnap["preemptions"])]),
            ])
        # cross-request reuse + preview channel (ISSUE 13): per-tier
        # cache counters and byte gauges, tile-skip and abandonment
        # counters — the acceptance's dtpu_cache_*/dtpu_preview_*
        # families on the scrapeable surface
        rs = reuse_mod.get_reuse().snapshot()
        pv = reuse_mod.PREVIEWS.snapshot()
        tiers = ("result", "embed", "tile")
        extra.extend([
            ("dtpu_cache_hits_total", "counter",
             "Reuse-cache hits by tier.",
             [({"tier": t}, rs[t]["hits"]) for t in tiers]),
            ("dtpu_cache_misses_total", "counter",
             "Reuse-cache misses by tier.",
             [({"tier": t}, rs[t]["misses"]) for t in tiers]),
            ("dtpu_cache_evictions_total", "counter",
             "Reuse-cache LRU evictions by tier.",
             [({"tier": t}, rs[t]["evictions"]) for t in tiers]),
            ("dtpu_cache_bytes", "gauge",
             "Bytes resident in the reuse cache by tier.",
             [({"tier": t}, rs[t]["bytes"]) for t in tiers]),
            ("dtpu_cache_replays_total", "counter",
             "Prompts settled by exact-hit replay.",
             [({}, state.metrics["prompts_replayed"])]),
            ("dtpu_cache_tiles_skipped_total", "counter",
             "Upscale tiles skipped via per-tile content hashes.",
             [({}, trace_mod.GLOBAL_COUNTERS.get("tiles_skipped"))]),
            ("dtpu_preview_clients", "gauge",
             "Attached SSE preview clients.",
             [({}, pv["clients"])]),
            ("dtpu_preview_events_total", "counter",
             "Progressive preview frames published.",
             [({}, trace_mod.GLOBAL_COUNTERS.get("preview_events"))]),
            ("dtpu_jobs_abandoned_total", "counter",
             "Jobs abandoned by client disconnect (queue purges + "
             "freed CB slots).",
             [({}, state.metrics["prompts_abandoned"])]),
        ])
        if state.shard is not None:
            # multi-master shard plane (ISSUE 14): ownership + ring
            # epoch gauges on the scrapeable surface, so a dashboard
            # can draw who owns which shard through a takeover
            ssnap = state.shard.snapshot()
            extra.extend([
                ("dtpu_shard_owner", "gauge",
                 "Shards owned by this master (1 per owned shard; an "
                 "absorbed peer's shard appears after takeover).",
                 [({"shard": s}, 1) for s in ssnap["owned"]]),
                ("dtpu_ring_epoch", "gauge",
                 "Consistent-hash ring membership epoch.",
                 [({}, ssnap["ring_epoch"])]),
                ("dtpu_shard_members", "gauge",
                 "Members in this master's ring view.",
                 [({}, len(ssnap["members"]))]),
                ("dtpu_shard_forwards_total", "counter",
                 "Mis-routed /prompt submissions forwarded to their "
                 "owning shard.",
                 [({}, ssnap["forwards"])]),
                ("dtpu_shard_takeovers_total", "counter",
                 "Dead peer shards absorbed by this master.",
                 [({}, ssnap["takeovers"])]),
            ])
        if state.autoscaler is not None:
            asnap = state.autoscaler.snapshot()
            extra.extend([
                ("dtpu_autoscale_scale_ups_total", "counter",
                 "Autoscaler scale-up actions.",
                 [({}, asnap["scale_ups"])]),
                ("dtpu_autoscale_scale_downs_total", "counter",
                 "Autoscaler scale-down actions.",
                 [({}, asnap["scale_downs"])]),
                ("dtpu_autoscale_flaps_total", "counter",
                 "Direction reversals inside the flap window "
                 "(should stay 0).",
                 [({}, asnap["flaps"])]),
                ("dtpu_autoscale_retiring", "gauge",
                 "Workers currently draining toward retirement.",
                 [({}, len(asnap["retiring"]))]),
            ])
        if state.durable is not None:
            # WAL size/lag + lease gauges (satellite: the durability
            # plane is scrapeable next to everything else).  stats()
            # lists segment files — keep it off the event loop.
            ds = await loop.run_in_executor(None, state.durable.stats)
            wal = ds.get("wal") or {}
            lease = ds.get("lease") or {}
            extra.extend([
                ("dtpu_wal_records_total", "counter",
                 "Records appended to the write-ahead job log.",
                 [({}, wal.get("records_appended", 0))]),
                ("dtpu_wal_bytes", "gauge",
                 "Live WAL segment bytes on disk.",
                 [({}, wal.get("bytes", 0))]),
                ("dtpu_wal_segments", "gauge",
                 "Live WAL segment files.",
                 [({}, wal.get("segments", 0))]),
                ("dtpu_wal_unsynced_records", "gauge",
                 "Appended records not yet fsync'd (sync lag).",
                 [({}, wal.get("unsynced_records", 0))]),
                ("dtpu_wal_last_sync_age_seconds", "gauge",
                 "Seconds since the last WAL fsync.",
                 [({}, wal.get("last_sync_age_s", 0) or 0)]),
                ("dtpu_master_epoch", "gauge",
                 "This process's master-lease epoch (fencing token); "
                 "0 = standby.",
                 [({}, ds.get("epoch", 0))]),
                ("dtpu_master_lease_remaining_seconds", "gauge",
                 "Seconds until the observed master lease expires.",
                 [({}, max(lease.get("expires_in_s", 0) or 0, 0))]),
                ("dtpu_master_takeovers_total", "counter",
                 "Lease takeovers performed by this process.",
                 [({}, ds.get("takeovers", 0))]),
            ])
        # continuous capture plane (ISSUE 18): exporter counters when
        # armed (first stats() may construct the exporter — a dir scan,
        # so off the loop), plus the SLO burn-rate gauges
        exp_stats = await loop.run_in_executor(None,
                                               trace_export_mod.stats)
        if exp_stats.get("enabled"):
            extra.extend([
                ("dtpu_trace_export_traces_total", "counter",
                 "Committed traces appended to capture segments.",
                 [({}, exp_stats["exported"])]),
                ("dtpu_trace_export_dropped_total", "counter",
                 "Capture records dropped (disk errors or "
                 "unserializable payloads).",
                 [({}, exp_stats["dropped"])]),
                ("dtpu_trace_export_bytes_total", "counter",
                 "Bytes appended to capture segments.",
                 [({}, exp_stats["bytes_written"])]),
                ("dtpu_trace_export_rotations_total", "counter",
                 "Capture segment rotations.",
                 [({}, exp_stats["rotations"])]),
                ("dtpu_trace_export_retired_total", "counter",
                 "Oldest capture segments deleted by the retention "
                 "cap.",
                 [({}, exp_stats["retired_segments"])]),
            ])
        extra.extend(state.slo.prom_families())
        # critical-path analytics plane: anomaly counter (always
        # present so dashboards can alert on rate>0 the moment a
        # baseline is armed) + per-worker clock-skew gauges
        extra.append(
            ("dtpu_analysis_anomalies_total", "counter",
             "Per-trace category blame exceeding the armed baseline "
             "profile's tolerance.",
             [({}, analysis_mod.anomalies_total())]))
        skews = state.cluster.skew_snapshot()
        if skews:
            extra.append(
                ("dtpu_clock_skew_seconds", "gauge",
                 "Estimated worker-clock offset vs this master "
                 "(min-filtered heartbeat one-way samples).",
                 [({"worker_id": w}, s["offset_s"])
                  for w, s in sorted(skews.items())]))
        # current resource gauges (unlabelled = this process); the
        # worker_id-labelled fleet view lives on /cluster/metrics.prom
        extra.extend(resource_mod.resource_prom_families(
            {"": self_sample}))
        text = trace_mod.prometheus_text(extra=extra)
        return web.Response(text=text,
                            content_type="text/plain",
                            charset="utf-8")

    async def metrics_reset(request):
        """Guarded aggregate-metrics reset (benches and multi-phase test
        runs stop inheriting cross-run telemetry).  DTPU_METRICS_RESET=0
        disables the route (403).  Body {"include_traces": true} also
        clears the flight recorder; per-prompt history and the monotonic
        retrace counters are never touched."""
        if os.environ.get(C.METRICS_RESET_ENV, "1").lower() \
                in ("0", "false", "off"):
            return web.json_response(
                {"error": "metrics reset disabled "
                          f"({C.METRICS_RESET_ENV}=0)"}, status=403)
        data = await request.json() if request.can_read_body else {}
        cleared = trace_mod.reset_aggregate_metrics()
        # keep the reset surface TOTAL (ISSUE 18): the new planes clear
        # with everything else — SLO windows, exemplar samples (inside
        # the histograms reset_aggregate_metrics just recreated) and the
        # exporter counters (its first touch may scan the capture dir,
        # so off the loop); capture FILES are durable by design and stay
        state.slo.reset()
        cleared["slo_windows"] = True
        await asyncio.get_running_loop().run_in_executor(
            None, trace_export_mod.reset_counters)
        cleared["export_counters"] = True
        # analytics plane: live profiles + anomaly counters + the
        # per-worker clock-skew estimates (they re-converge from the
        # next heartbeats) — ISSUE 20 satellite
        analysis_mod.reset_live()
        cleared["analysis"] = True
        cleared["skew_estimates"] = state.cluster.reset_skew()
        if data.get("include_traces"):
            trace_mod.GLOBAL_TRACES.reset()
            cleared["traces"] = True
        log("aggregate metrics reset "
            f"(by {request.remote or 'unknown'})")
        return ok({"cleared": cleared})

    async def slo_view(request):
        """SLO burn-rate engine snapshot: per-tenant objectives, window
        stats, burn rates and remaining budget (`cli slo` reads this)."""
        return web.json_response(state.slo.evaluate())

    async def analysis_view(request):
        """Critical-path analytics over the live flight-recorder ring
        (`cli analyze` reads this): blame profiles grouped by tenant /
        structural signature / worker, the per-worker straggler
        scorecard next to the WorkLedger's hedging latency EMAs, the
        live anomaly plane and clock-skew estimates (ISSUE 20)."""
        records = trace_mod.GLOBAL_TRACES.records()
        # pure-CPU span crunching over up to the whole ring — off the
        # event loop so a deep ring can't stall heartbeats
        report = await asyncio.get_running_loop().run_in_executor(
            None, analysis_mod.analyze_records, records)
        ledger = state.ledger.snapshot()
        hedging = {jid: j.get("latency_estimate_s")
                   for jid, j in ledger.get("active_jobs", {}).items()}
        return web.json_response({
            **report,
            "hedging_latency_ema_s": hedging,
            "live": analysis_mod.LIVE.snapshot(),
            "skew": state.cluster.skew_snapshot(),
        })

    async def get_trace(request):
        """Flight recorder: one completed job's full span tree."""
        pid = request.match_info["prompt_id"]
        rec = trace_mod.GLOBAL_TRACES.get(pid)
        if rec is None:
            return web.json_response(
                {"error": f"no recorded trace for {pid!r} (completed "
                          "jobs only; ring keeps the most recent "
                          f"{trace_mod.GLOBAL_TRACES.max_traces})"},
                status=404)
        rec["tree"] = trace_mod.build_span_tree(rec["spans"])
        return web.json_response(rec)

    async def list_traces(request):
        """Flight recorder index, newest first."""
        return web.json_response({
            "traces": trace_mod.GLOBAL_TRACES.index(),
            "ring_max": trace_mod.GLOBAL_TRACES.max_traces,
            "tracing_enabled": trace_mod.tracing_enabled()})

    async def warmup(request):
        """AOT warmup (registry.DiffusionPipeline.warmup): compile +
        execute the serving-shaped programs for a checkpoint so the next
        matching /prompt pays dispatch cost only.  Body: {"ckpt_name",
        "width", "height", "batch", "steps", "cfg", "sampler_name",
        "scheduler", "denoise"} — all optional but ckpt_name."""
        from comfyui_distributed_tpu.models import registry
        data = await request.json() if request.can_read_body else {}
        ckpt = data.get("ckpt_name", "model.safetensors")
        kwargs = {k: data[k] for k in
                  ("height", "width", "batch", "steps", "cfg",
                   "sampler_name", "scheduler", "denoise") if k in data}
        loop = asyncio.get_running_loop()

        def run():
            pipe = registry.load_pipeline(ckpt,
                                          models_dir=state.models_dir)
            return pipe.warmup(**kwargs)

        # compile happens off the event loop; the control plane stays up
        timings = await loop.run_in_executor(None, run)
        return ok({"ckpt_name": ckpt, "timings": timings})

    # --- profiling (the subsystem the reference lacks, SURVEY.md §5) -------

    async def profile_start(request):
        # off the loop: start_device_trace mkdirs the output dir and
        # spins up the device profiler (backend touch) — the dtpu-lint
        # async-blocking-transitive finding this route shipped with
        from comfyui_distributed_tpu.utils import trace as trace_mod
        data = await request.json() if request.can_read_body else {}
        try:
            out = await asyncio.get_running_loop().run_in_executor(
                None, lambda: trace_mod.start_device_trace(
                    data.get("dir")))
        except RuntimeError as e:
            return web.json_response({"error": str(e)}, status=409)
        return ok({"dir": out})

    async def profile_stop(request):
        # off the loop for the same reason: stop flushes the collected
        # device trace to disk before returning
        from comfyui_distributed_tpu.utils import trace as trace_mod
        try:
            out = await asyncio.get_running_loop().run_in_executor(
                None, trace_mod.stop_device_trace)
        except RuntimeError as e:
            return web.json_response({"error": str(e)}, status=409)
        return ok({"dir": out})

    async def profile_status(request):
        from comfyui_distributed_tpu.utils import trace as trace_mod
        return web.json_response(trace_mod.trace_status())

    async def clear_memory(request):
        # the whole probe/clear/GC pass runs off the event loop: the
        # device probes can initialize a backend, jax.clear_caches walks
        # every live executable and three full GC passes over a loaded
        # model take seconds — a scrape or heartbeat must not queue
        # behind any of it (dtpu-lint: async-blocking)
        def clear():
            import gc

            import jax

            from comfyui_distributed_tpu.models import registry
            # before/after memory_stats() snapshots: the response
            # reports what the clear ACTUALLY freed, not just that it
            # ran (satellite: on a fleet, "clear didn't free anything"
            # is the signal that a worker is holding leaked buffers)
            before = resource_mod.device_memory_snapshot()
            rss_before = resource_mod.host_rss_bytes()
            registry.clear_pipeline_cache()
            # invalidate the cross-request reuse plane (ISSUE 13): a
            # reloaded checkpoint must never replay a stale entry, and
            # the freed residency belongs in this route's before/after
            # snapshot like every other cache it drops
            cache_freed = reuse_mod.get_reuse().clear()
            jax.clear_caches()
            for _ in range(3):
                gc.collect()
            after = resource_mod.device_memory_snapshot()
            rss_after = resource_mod.host_rss_bytes()
            return before, rss_before, after, rss_after, cache_freed

        before, rss_before, after, rss_after, cache_freed = await asyncio \
            .get_running_loop().run_in_executor(None, clear)
        freed = max(before["bytes_in_use"] - after["bytes_in_use"], 0)
        log(f"cleared model/jit caches (freed {freed / 1e6:.1f} MB "
            f"device, {cache_freed / 1e6:.1f} MB reuse cache, "
            f"source={after['source']})")
        return ok({
            "freed_bytes": freed,
            "cache_freed_bytes": cache_freed,
            "device_bytes_before": before["bytes_in_use"],
            "device_bytes_after": after["bytes_in_use"],
            "host_rss_before": rss_before,
            "host_rss_after": rss_after,
            "source": after["source"],
        })

    async def launch_worker(request):
        data = await request.json()
        # config read + subprocess spawn off the loop (dtpu-lint:
        # async-blocking): launch_worker waits on the child and rewrites
        # managed-process state under the manager's file lock
        loop = asyncio.get_running_loop()
        cfg = await loop.run_in_executor(
            None, lambda: cfg_mod.load_config(state.config_path))
        worker = next((w for w in cfg["workers"]
                       if str(w.get("id")) == str(data.get("id"))), None)
        if worker is None:
            return web.json_response({"error": "worker not found"},
                                     status=404)
        try:
            entry = await loop.run_in_executor(
                None, lambda: state.manager.launch_worker(
                    worker, stop_on_master_exit=cfg["settings"].get(
                        "stop_workers_on_master_exit", True)))
        except RuntimeError as e:
            return web.json_response({"error": str(e)}, status=409)
        return ok({"worker": entry})

    async def stop_worker(request):
        data = await request.json()
        # terminate + bounded wait (up to PROCESS_TERMINATION_TIMEOUT)
        # off the loop (dtpu-lint: async-blocking)
        stopped = await asyncio.get_running_loop().run_in_executor(
            None, lambda: state.manager.stop_worker(str(data.get("id"))))
        if not stopped:
            return web.json_response({"error": "not managed"}, status=404)
        return ok()

    async def managed_workers(request):
        # off the loop: liveness of each managed pid is probed via
        # `kill -0` through subprocess on some platforms — the dtpu-lint
        # async-blocking-transitive finding this route shipped with
        managed = await asyncio.get_running_loop().run_in_executor(
            None, state.manager.get_managed_workers)
        return web.json_response(managed)

    async def cluster_info(request):
        """Cluster control plane snapshot: lease-based worker states,
        the work ledger's active/completed jobs, and the effective
        fault/hedge policy knobs."""
        return web.json_response({
            **state.cluster.snapshot(),
            "ledger": state.ledger.snapshot(),
            "policy": cluster_mod.fault_policy(),
            "hedge": {"armed": cluster_mod.hedge_armed(),
                      "min_progress_pct": cluster_mod.hedge_pct(),
                      "factor": cluster_mod.hedge_factor()},
        })

    async def cluster_register(request):
        """Elastic worker registration: a worker that only knows the
        master URL joins the registry (and the lease state machine)
        without appearing in the config file."""
        data = await request.json()
        wid = data.get("worker_id") or data.get("id")
        if not wid:
            return web.json_response({"error": "missing worker_id"},
                                     status=400)
        info = {k: data[k] for k in ("host", "port", "name") if k in data}
        info.setdefault("host", request.remote)
        out = state.cluster.register(str(wid), info=info)
        _feed_skew(str(wid), data)
        return ok({**out, "master_time": time.time()})

    def _feed_skew(wid: str, data: Dict[str, Any]) -> None:
        """Clock-skew sample off a heartbeat/register body (ISSUE 20):
        the payload's ``sent_at`` (the worker's wall clock at send) vs
        this process's wall clock now.  The registry min-filters — the
        sample with the least uplink delay wins."""
        sent = data.get("sent_at")
        if sent is None:
            return
        try:
            state.cluster.update_skew(wid, time.time() - float(sent))
        except (TypeError, ValueError):
            pass

    async def cluster_heartbeat(request):
        """Lease renewal (runtime/cluster.HeartbeatSender posts here
        every lease/3); unknown workers are auto-registered."""
        data = await request.json()
        wid = data.get("worker_id") or data.get("id")
        if not wid:
            return web.json_response({"error": "missing worker_id"},
                                     status=400)
        info = {k: data[k] for k in ("host", "port", "name") if k in data}
        info.setdefault("host", request.remote)
        out = state.cluster.heartbeat(str(wid), info=info)
        # heartbeats carry a resource snapshot (ISSUE 5): retain the
        # latest per worker for the federated metrics endpoints
        if isinstance(data.get("resources"), dict):
            state.cluster.update_resources(str(wid), data["resources"])
        _feed_skew(str(wid), data)
        # the reply carries this master's wall clock so a future
        # worker-side refinement can bound the estimate with the RTT
        return ok({**out, "master_time": time.time()})

    async def fleet_info(request):
        """Elastic-fleet plane (ISSUE 9): autoscaler state + decision
        ring, the live federated signal it scales on, per-class
        admission counters and the chaos-harness spec — the one
        endpoint `cli fleet` renders."""
        scaler = state.autoscaler
        snap = {"enabled": False,
                "armed_env": autoscale_mod.autoscale_armed()}
        signal = None
        if scaler is not None:
            loop = asyncio.get_running_loop()
            snap = scaler.snapshot()
            # the signal probes the registry + resource monitor — keep
            # it off the event loop like every other probe
            signal = await loop.run_in_executor(None,
                                                scaler.fleet_signal)
        return web.json_response({
            "autoscale": {**snap, "signal": signal},
            "admission": {
                **state.admission.snapshot(),
                "queued_by_class": state.queued_by_class(),
                "drain_rate_per_s": round(state.drain_rate(), 4),
                "max_queue": state.max_queue,
            },
            "workers": state.cluster.snapshot()["workers"],
            "chaos": chaos_mod.get_chaos().snapshot(),
        })

    async def durability_info(request):
        """Durability plane snapshot: lease holder/epoch, WAL size and
        sync lag, recovery counters — None-shaped when DTPU_WAL_DIR is
        unset."""
        if state.durable is None:
            return web.json_response({"enabled": False})
        stats = await asyncio.get_running_loop().run_in_executor(
            None, state.durable.stats)
        return web.json_response(stats)

    async def takeover(request):
        """Promote this server to master: acquire the lease (allowed
        when it is expired, or ``{"force": true}``), replay the shared
        WAL, resume the interrupted prompts, re-home workers.  The
        standby's own lease watcher calls the same path automatically on
        expiry; this endpoint is the operator's manual trigger."""
        from comfyui_distributed_tpu.runtime import durable as durable_mod
        if state.durable is None:
            return web.json_response(
                {"error": f"durability off (set {C.WAL_DIR_ENV})"},
                status=409)
        data = await request.json() if request.can_read_body else {}
        loop = asyncio.get_running_loop()
        try:
            out = await loop.run_in_executor(
                None, lambda: state.durable.takeover(
                    force=bool(data.get("force"))))
        except durable_mod.LeaseHeldError as e:
            return web.json_response({"error": str(e)}, status=409)
        return ok(out)

    async def rehome(request):
        """Worker side of master failover: a new master announces
        itself; this worker retargets its lease heartbeat (and registers
        there immediately so the new registry sees it without waiting
        for a probe)."""
        data = await request.json()
        url = str(data.get("master_url", "")).rstrip("/")
        if not url:
            return web.json_response({"error": "missing master_url"},
                                     status=400)
        wid = str(data.get("worker_id", "")
                  or os.environ.get(C.WORKER_ID_ENV, ""))
        os.environ[C.MASTER_URL_ENV] = url
        if wid:
            os.environ.setdefault(C.WORKER_ID_ENV, wid)
        hb = state.heartbeat
        if hb is None and wid:
            hb = state.heartbeat = cluster_mod.HeartbeatSender(
                url, wid, port=state.port)
            hb.start()
        beat = False
        if hb is not None:
            # re-register at the new master NOW, with a short retry
            # burst (HeartbeatSender.rehome): the first beat can race
            # the dying master's teardown, and a single best-effort
            # beat would leave this worker unregistered — reading as
            # lease-expired — for a full heartbeat interval, so the new
            # master needlessly reassigns its in-flight units
            loop = asyncio.get_running_loop()
            beat = await loop.run_in_executor(None,
                                              lambda: hb.rehome(url))
        log(f"re-homed to master {url}"
            + ("" if beat else " (first heartbeat pending)"))
        return ok({"master_url": url, "heartbeat": hb is not None,
                   "registered": beat})

    def _self_sample() -> Dict[str, Any]:
        """This process's resource sample for the metrics surfaces: the
        monitor's latest (it carries the utilization estimate, which
        needs two samples) with the queue depth refreshed from THIS
        state — a multi-state process's global monitor may be bound to
        another state's queue."""
        snap = resource_mod.fleet_sample()
        return {**snap, "queue_depth": state.queue_remaining()}

    async def resource_info(request):
        """This participant's current resource sample + monitor state —
        the unit the federation merges, and the pull-through target when
        a worker's heartbeat snapshot goes stale."""
        snap = await asyncio.get_running_loop().run_in_executor(
            None, _self_sample)
        return web.json_response({
            "resources": snap,
            "monitor": (state.resources.snapshot()
                        if state.resources is not None
                        else {"enabled": False}),
        })

    # wid -> monotonic time of the last FAILED federation pull (the
    # negative cache bounding per-scrape pull latency)
    _res_pull_failed_at: Dict[str, float] = {}

    async def _fleet_resources() -> Dict[str, Any]:
        """Merged master+workers resource view (ISSUE 5 federation).

        Each registered worker contributes its latest heartbeat
        snapshot; snapshots older than DTPU_RES_FED_TTL_S (a missed
        heartbeat) are re-pulled live from the worker's
        ``GET /distributed/resource`` and cached back into the registry,
        so scrapes between heartbeats stay fresh without a per-scrape
        fan-out.  Dead workers keep their last snapshot, aged and marked
        stale, rather than vanishing mid-incident.  A failed pull is
        negative-cached for the same TTL so an unreachable (but not yet
        DEAD) worker costs one timeout per TTL window, not one per
        scrape."""
        import aiohttp

        from comfyui_distributed_tpu.utils.net import get_client_session
        try:
            ttl = float(os.environ.get(C.RES_FED_TTL_ENV,
                                       C.RES_FED_TTL_DEFAULT))
        except ValueError:
            ttl = C.RES_FED_TTL_DEFAULT
        now = time.monotonic()
        reg = state.cluster.resource_snapshots()
        to_pull = [
            (wid, v) for wid, v in reg.items()
            if v.get("host") and v.get("port")
            and v["state"] != cluster_mod.DEAD
            and (v["age_s"] is None or v["age_s"] > ttl)
            and now - _res_pull_failed_at.get(wid, -1e9) > ttl]
        if to_pull:
            session = await get_client_session()

            async def pull(wid, v):
                url = (f"http://{v['host']}:{v['port']}"
                       "/distributed/resource")
                try:
                    async with session.get(
                            url, timeout=aiohttp.ClientTimeout(
                                total=2)) as r:
                        if r.status == 200:
                            body = await r.json()
                            if isinstance(body.get("resources"), dict):
                                state.cluster.update_resources(
                                    wid, body["resources"])
                                _res_pull_failed_at.pop(wid, None)
                                return
                except Exception as e:  # noqa: BLE001 - best-effort pull
                    debug_log(f"resource pull from {wid} failed: {e}")
                _res_pull_failed_at[wid] = time.monotonic()

            await asyncio.gather(*(pull(wid, v) for wid, v in to_pull))
            reg = state.cluster.resource_snapshots()
        self_id = "master" if not state.is_worker \
            else os.environ.get(C.WORKER_ID_ENV, "self")
        self_snap = await asyncio.get_running_loop().run_in_executor(
            None, _self_sample)
        participants: Dict[str, Any] = {
            self_id: {
                "state": "self",
                "resources": self_snap,
                "age_s": 0.0,
                "stale": False,
            }}
        for wid, v in reg.items():
            if wid == self_id:
                # a registered worker colliding with this process's own
                # id (someone named a worker "master") still shows up,
                # disambiguated, instead of silently vanishing
                wid = f"{wid}@registry"
            participants[wid] = {
                "state": v["state"],
                "host": v.get("host"), "port": v.get("port"),
                "resources": v["resources"],
                "age_s": v["age_s"],
                "stale": v["age_s"] is None or v["age_s"] > ttl,
            }
        return {"participants": participants, "ttl_s": ttl}

    async def cluster_metrics(request):
        """Federated fleet resources as JSON (feeds ``cli top``)."""
        return web.json_response(await _fleet_resources())

    async def cluster_metrics_prom(request):
        """Federated fleet resources as Prometheus text: one gauge
        series per participant, distinguished by ``worker_id`` — the
        single scrape point for fleet memory/utilization dashboards."""
        fleet = await _fleet_resources()
        parts = fleet["participants"]
        fams = resource_mod.resource_prom_families(
            {wid: p.get("resources") for wid, p in parts.items()},
            ages={wid: p.get("age_s") for wid, p in parts.items()})
        fams.append(
            ("dtpu_res_participants", "gauge",
             "Participants in the federated resource view.",
             [({}, len(parts))]))
        return web.Response(text=trace_mod.render_prom_families(fams),
                            content_type="text/plain", charset="utf-8")

    async def workers_status(request):
        """Live worker health (the reference panel's 2s status dots,
        ``gpupanel.js:1233-1311``), served from the poller's snapshot."""
        return web.json_response(state.health.snapshot())

    async def _fanout_to_workers(path: str,
                                 bodies: Optional[Dict[str, Any]] = None
                                 ) -> Dict[str, Any]:
        """POST ``path`` on every enabled worker (reference toolbar fan-out,
        ``gpupanel.js:204-306``).  ``bodies`` (optional dict) collects each
        worker's parsed JSON response for callers that aggregate."""
        import aiohttp

        from comfyui_distributed_tpu.utils.net import get_client_session
        from comfyui_distributed_tpu.workflow.dispatcher import worker_url
        loop = asyncio.get_running_loop()
        cfg = await loop.run_in_executor(
            None, lambda: cfg_mod.load_config(state.config_path))
        session = await get_client_session()
        results: Dict[str, Any] = {}

        async def hit(w):
            try:
                async with session.post(
                        worker_url(w) + path,
                        timeout=aiohttp.ClientTimeout(total=10)) as r:
                    results[str(w["id"])] = r.status
                    if bodies is not None and r.status == 200:
                        try:
                            bodies[str(w["id"])] = await r.json()
                        except Exception:  # noqa: BLE001 - non-JSON body
                            pass
            except Exception as e:  # noqa: BLE001 - report per-worker
                results[str(w["id"])] = str(e)

        await asyncio.gather(*(hit(w) for w in cfg_mod.enabled_workers(cfg)))
        return results

    async def cluster_clear_memory(request):
        """Clear caches here AND on every enabled worker (reference
        ``_handleClearMemory``, ``gpupanel.js:259-306``), aggregating
        the bytes each participant actually freed."""
        bodies: Dict[str, Any] = {}
        results = await _fanout_to_workers("/distributed/clear_memory",
                                           bodies=bodies)
        resp = await clear_memory(request)
        local = json.loads(resp.body.decode())
        freed_by = {"master": int(local.get("freed_bytes", 0))}
        for wid, body in bodies.items():
            if isinstance(body, dict) and "freed_bytes" in body:
                freed_by[wid] = int(body["freed_bytes"])
        return ok({"workers": results,
                   "freed_bytes": freed_by,
                   "freed_bytes_total": sum(freed_by.values())})

    async def cluster_interrupt(request):
        """Interrupt here AND on every enabled worker (reference
        ``_handleInterruptWorkers``, ``gpupanel.js:204-257``)."""
        results = await _fanout_to_workers("/interrupt")
        state.interrupt_event.set()
        return ok({"workers": results})

    async def worker_log(request):
        wid = request.query.get("id", "")
        try:
            # log-file seek+read off the loop (dtpu-lint: async-blocking)
            max_bytes = int(request.query.get("bytes", LOG_TAIL_BYTES))
            text = await asyncio.get_running_loop().run_in_executor(
                None, lambda: state.manager.tail_log(
                    wid, max_bytes=max_bytes))
        except FileNotFoundError as e:
            return web.json_response({"error": str(e)}, status=404)
        return web.json_response({"log": text})

    async def clear_launching(request):
        data = await request.json()
        state.manager.clear_launching(str(data.get("id")))
        return ok()

    # --- job data plane -----------------------------------------------------

    async def prepare_job(request):
        t_recv = time.time()
        data = await request.json()
        mj = data.get("multi_job_id")
        if not mj:
            return web.json_response({"error": "missing multi_job_id"},
                                     status=400)
        if data.get("kind") == "tile":
            await state.jobs.prepare_tile_job(mj)
        else:
            await state.jobs.prepare_job(mj)
        tp = trace_mod.parse_traceparent(
            request.headers.get(C.TRACEPARENT_HEADER))
        if tp is not None:
            trace_mod.event_span("prepare_job", t_recv, time.time(),
                                 trace_id=tp[0], parent_id=tp[1],
                                 attrs={"job": str(mj)})
        debug_log(f"prepared {data.get('kind', 'image')} job {mj}")
        return ok()

    async def queue_status(request):
        mj = request.query.get("multi_job_id", "")
        exists = await state.jobs.has_tile_job(mj) or \
            await state.jobs.has_job(mj)
        return web.json_response({"exists": exists,
                                  "queue_remaining":
                                      state.queue_remaining(),
                                  "max_queue": state.max_queue})

    async def wire_formats(request):
        """Wire negotiation (utils.net.negotiate_wire_format): workers
        probe this once per master; a master listing the raw-tensor type
        gets npy uploads instead of PNG — less encode CPU and fewer wire
        bytes on the worker->master hop.  ``tensor_codecs`` names what
        THIS build can decode so a zstd-capable worker never sends zstd
        at a deflate-only master."""
        from comfyui_distributed_tpu.utils.image import tensor_codecs
        return web.json_response({
            "formats": [C.TENSOR_WIRE_CONTENT_TYPE, "image/png"],
            "tensor_codecs": tensor_codecs()})

    def _decode_upload(field) -> Any:
        """Multipart image/tile field -> tensor, honoring the negotiated
        content type (raw tensor or PNG) with wire accounting.  The
        chaos harness may corrupt the payload HERE: the decode then
        raises, the sender's retry re-delivers clean, and the
        idempotency keys keep the redelivery exactly-once."""
        data = field.file.read()
        cm = chaos_mod.get_chaos()
        if cm.active:
            data = cm.corrupt(data, what="tile/image upload")
        if (field.content_type or "") == C.TENSOR_WIRE_CONTENT_TYPE:
            trace_mod.GLOBAL_COUNTERS.bump("wire_tensor_msgs")
            trace_mod.GLOBAL_COUNTERS.bump("wire_tensor_bytes", len(data))
            return decode_tensor(data)
        trace_mod.GLOBAL_COUNTERS.bump("wire_png_msgs")
        trace_mod.GLOBAL_COUNTERS.bump("wire_png_bytes", len(data))
        return decode_png(data)

    def _ingest_remote_trace(request, form, name: str,
                             t_recv: float, attrs: Dict[str, Any]) -> None:
        """Stitch an inbound data-plane POST into the job's distributed
        trace: merge the peer's shipped spans (final upload only) and
        record the server-side receive as a child of the sender's span
        named in its traceparent header."""
        # clock-skew correction (ISSUE 20): shipped spans carry the
        # WORKER's wall clock; shift them onto this master's clock by
        # the registry's heartbeat-derived offset estimate before they
        # land in the ring, so cross-process dispatch edges stop going
        # negative and critical-path network blame isn't fiction
        offset = 0.0
        wid = str(attrs.get("worker") or "")
        if wid and analysis_mod.skew_correction_enabled():
            offset = state.cluster.skew(wid)
        spans_field = form.get("spans")
        if spans_field:
            try:
                shipped = json.loads(spans_field)
                if offset and isinstance(shipped, list):
                    for s in shipped:
                        if not isinstance(s, dict):
                            continue
                        for k in ("start_s", "end_s"):
                            if isinstance(s.get(k), (int, float)):
                                s[k] = s[k] + offset
                trace_mod.GLOBAL_TRACES.ingest(shipped)
            except (ValueError, TypeError) as e:
                debug_log(f"bad spans field on {name}: {e}")
        tp = trace_mod.parse_traceparent(
            request.headers.get(C.TRACEPARENT_HEADER))
        if tp is not None:
            if offset:
                attrs = {**attrs, "skew_ms": round(offset * 1e3, 3)}
            trace_mod.event_span(name, t_recv, time.time(),
                                 trace_id=tp[0], parent_id=tp[1],
                                 attrs=attrs)

    async def job_complete(request):
        t_recv = time.time()
        form = await request.post()
        mj = form.get("multi_job_id", "")
        img_field = form.get("image")
        if not mj or img_field is None:
            return web.json_response({"error": "missing fields"}, status=400)
        # decode off the event loop: concurrent uploads must not stall
        # the control plane (a stalled /prompt fails preflight's 300ms probe)
        loop = asyncio.get_running_loop()
        tensor = await loop.run_in_executor(
            None, lambda: _decode_upload(img_field))
        item = {
            "worker_id": form.get("worker_id", ""),
            "is_last": str(form.get("is_last", "false")).lower() == "true",
            "tensor": tensor,
        }
        # only pass the index through when the sender set one: the collector
        # dedups retransmits by (worker, index), and defaulting indexless
        # uploads to 0 would collapse them into a single image
        if form.get("image_index") is not None:
            item["image_index"] = int(form["image_index"])
        if not await state.jobs.put_result(
                mj, item, idem_key=form.get("idem_key")):
            # unknown job -> 404 so the worker's retry loop backs off
            return web.json_response({"error": f"unknown job {mj}"},
                                     status=404)
        # a data-plane POST proves the sender is alive — renew its lease
        state.cluster.touch(str(form.get("worker_id", "")))
        state.metrics["images_received"] += 1
        _ingest_remote_trace(request, form, "receive_image", t_recv,
                             {"job": str(mj),
                              "worker": str(form.get("worker_id", ""))})
        return ok()

    async def tile_complete(request):
        t_recv = time.time()
        form = await request.post()
        mj = form.get("multi_job_id", "")
        tile_field = form.get("tile")
        if not mj or tile_field is None:
            return web.json_response({"error": "missing fields"}, status=400)
        item = {
            "worker_id": form.get("worker_id", ""),
            "tile_idx": int(form.get("tile_idx", 0)),
            "x": int(form.get("x", 0)),
            "y": int(form.get("y", 0)),
            "extracted_width": int(form.get("extracted_width", 0)),
            "extracted_height": int(form.get("extracted_height", 0)),
            "padding": int(form.get("padding", 0)),
            "is_last": str(form.get("is_last", "false")).lower() == "true",
            "tensor": await asyncio.get_running_loop().run_in_executor(
                None, lambda: _decode_upload(tile_field)),
        }
        if not await state.jobs.put_tile(
                mj, item, idem_key=form.get("idem_key")):
            # unknown/expired tile job -> 404; the worker's retry loop backs
            # off instead of resurrecting an orphan queue
            return web.json_response({"error": f"unknown tile job {mj}"},
                                     status=404)
        state.cluster.touch(str(form.get("worker_id", "")))
        state.metrics["tiles_received"] += 1
        _ingest_remote_trace(request, form, "receive_tile", t_recv,
                             {"job": str(mj),
                              "worker": str(form.get("worker_id", "")),
                              "tile_idx": int(form.get("tile_idx", 0))})
        return ok()

    async def load_image(request):
        """Input-image staging for remote workers (reference
        ``distributed.py:1135-1173``): name -> base64 PNG."""
        data = await request.json()
        name = str(data.get("image_name", ""))
        safe = os.path.normpath(name).lstrip(os.sep)
        if safe.startswith(".."):
            return web.json_response({"error": "bad path"}, status=400)
        path = os.path.join(state.input_dir, safe)
        if not os.path.exists(path):
            return web.json_response({"error": f"not found: {name}"},
                                     status=404)
        def read_b64():
            with open(path, "rb") as f:
                return base64.b64encode(f.read()).decode()
        b64 = await asyncio.get_running_loop().run_in_executor(None, read_b64)
        return web.json_response({"image_data": b64, "name": name})

    # --- ComfyUI-compatible worker surface ---------------------------------

    async def get_prompt(request):
        return web.json_response(
            {"exec_info": {"queue_remaining": state.queue_remaining()}})

    def _is_dispatched_share(prompt: Dict[str, Any]) -> bool:
        """Orchestrated-share predicate (one copy: workflow/orchestrate
        .is_dispatched_share).  Shares bypass this server's own
        admission — re-shedding would silently amputate an admitted
        job's worker shares; the hard queue-full cap still applies."""
        from comfyui_distributed_tpu.workflow.orchestrate import \
            is_dispatched_share
        return is_dispatched_share(prompt)

    async def _forward_prompt(url: str, owner: str,
                              data: Dict[str, Any],
                              traceparent: Optional[str] = None):
        """Single-hop mis-route forward: relay the original /prompt
        body to the owning shard, marked with SHARD_FORWARD_HEADER so
        the receiver never forwards again.  None on failure (the
        caller then accepts locally rather than bouncing the client)."""
        import aiohttp

        from comfyui_distributed_tpu.utils.net import get_client_session
        session = await get_client_session()
        headers = {C.SHARD_FORWARD_HEADER: state.shard.id}
        if traceparent:
            headers[C.TRACEPARENT_HEADER] = traceparent
        try:
            async with session.post(
                    f"{url}/prompt", json=data, headers=headers,
                    timeout=aiohttp.ClientTimeout(total=120)) as r:
                body = await r.json()
        except Exception as e:  # noqa: BLE001 - fall back to local
            debug_log(f"shard: forward to {owner} failed: {e}")
            return None
        state.shard.forwards += 1
        trace_mod.GLOBAL_COUNTERS.bump("shard_forwarded")
        if isinstance(body, dict):
            body.setdefault("shard", owner)
            body["forwarded_from"] = state.shard.id
        resp = web.json_response(body, status=r.status)
        # relay the owner's backpressure hint: a shed (429) loses its
        # HTTP-standard Retry-After if only the JSON body survives the
        # hop, and standards-honoring clients would retry immediately
        ra = r.headers.get("Retry-After")
        if ra is not None:
            resp.headers["Retry-After"] = ra
        return resp

    async def ring_info(request):
        """Consistent-hash ring state (ISSUE 14): membership, epoch,
        vnodes — everything a stateless router or a client-side hasher
        needs to place prompt-ids."""
        if state.shard is None:
            return web.json_response({"enabled": False})
        return web.json_response(state.shard.ring_snapshot())

    async def ring_gossip(request):
        """Peer gossip exchange: merge the sender's ring view, answer
        with ours (pure in-memory merge — event-loop safe)."""
        if state.shard is None:
            return web.json_response({"error": "sharding off "
                                      f"(set {C.SHARD_ID_ENV})"},
                                     status=409)
        data = await request.json()
        return web.json_response(state.shard.merge_gossip(data))

    async def post_prompt(request):
        data = await request.json()
        prompt = data.get("prompt")
        if not isinstance(prompt, dict) or not prompt:
            return web.json_response({"error": "missing prompt"}, status=400)
        # multi-master routing (ISSUE 14): a router/client-supplied
        # prompt_id hint is the hash key.  Mis-routed submissions are
        # forwarded AT MOST ONE HOP to the owning shard (the forward
        # header makes a ring disagreement terminate here instead of
        # looping) — the admission then lands in the OWNER's WAL before
        # the client gets its prompt-id.  Hint-less direct submissions
        # get a self-owned generated id (enqueue_prompt), so they never
        # forward.
        pid_hint = str(data.get("prompt_id") or "") or None
        fwd_from = request.headers.get(C.SHARD_FORWARD_HEADER)
        span_attrs = {"forwarded_from": fwd_from} if fwd_from else None
        if state.shard is not None and not state.is_worker \
                and pid_hint and not fwd_from \
                and not state.shard.is_mine(pid_hint):
            owner = state.shard.owner_of(pid_hint)
            url = state.shard.member_url(owner)
            if url:
                fwd = await _forward_prompt(
                    url, owner, data,
                    traceparent=request.headers.get(
                        C.TRACEPARENT_HEADER))
                if fwd is not None:
                    return fwd
            # owner unreachable (or url unknown): accept locally — the
            # availability choice; the ring heals via absorb/gossip and
            # the span records where the job actually landed
            trace_mod.GLOBAL_COUNTERS.bump("shard_forward_fallbacks")
        # master-mode tile jobs: pre-create their queues at prompt-queue
        # time, before the exec thread gets anywhere near the upscale node
        # (reference pre-inits at validation time, distributed_upscale.py:
        # 85-105) — otherwise a fast worker's tiles 404 through its retries
        for node in prompt.values():
            if not isinstance(node, dict) \
                    or node.get("class_type") != "UltimateSDUpscaleDistributed":
                continue
            h = {**node.get("inputs", {}), **node.get("hidden", {})}
            mj = h.get("multi_job_id")
            if mj and not h.get("is_worker"):
                await state.jobs.prepare_tile_job(str(mj))
        client_id = data.get("client_id", "unknown")
        # ComfyUI contract: extra_data.extra_pnginfo.workflow rides every
        # dispatch so saved PNGs embed the source workflow (reference
        # gpupanel.js:1344-1358)
        extra_data = data.get("extra_data") or {}
        # multi-tenant admission (ISSUE 9): {"priority": "paid"|"free"|
        # "batch"} classifies the request (untagged -> highest class);
        # {"slo_s": N} stamps its distributed jobs with a deadline that
        # re-keys the hedge machinery on the remaining budget
        tenant = state.admission.classify(
            data.get("priority") or extra_data.get("priority"))
        if data.get("priority") or extra_data.get("priority"):
            # tagged requests keep their class through extra_data (it
            # is WAL'd with the admission record, so a crash-recovery
            # re-enqueue resumes at the SAME priority)
            extra_data = {**extra_data, "priority": tenant}
        slo_s = data.get("slo_s") or extra_data.get("slo_s")
        try:
            slo_s = float(slo_s) if slo_s is not None else None
        except (TypeError, ValueError):
            slo_s = None
        if slo_s is not None and slo_s > 0:
            extra_data = {**extra_data, "slo_s": slo_s}

        def _shed_response(rejection):
            retry_after = max(int(rejection.get("retry_after_s", 1)),
                              state.retry_after_hint())
            return web.json_response(
                {"error": f"shed ({rejection['reason']}): tenant class "
                          f"{rejection['tenant']!r}",
                 "tenant": rejection["tenant"],
                 "reason": rejection["reason"],
                 "retry_after_s": retry_after,
                 "queue_remaining": state.queue_remaining(),
                 "max_queue": state.max_queue},
                status=429, headers={"Retry-After": str(retry_after)})
        # inbound trace context: a dispatching master's traceparent makes
        # this process's execution a child of ITS trace (the worker half
        # of the distributed tree); absent/malformed headers mean a fresh
        # local root — propagation can never fail a request
        trace_parent = trace_mod.parse_traceparent(
            request.headers.get(C.TRACEPARENT_HEADER))
        pid_kw = {"pid": pid_hint} if pid_hint else {}
        try:
            cfg = await _orchestration_config(prompt)
            if cfg is not None:
                # admission BEFORE the fan-out: a request that will be
                # shed must never reach the workers (they would start
                # seed slices for a master share that was 429'd); the
                # master-share enqueue below is then pre-admitted
                with state._queue_lock:
                    depth = len(state._queue)
                rejection = state.admission.admit(
                    tenant, str(client_id), depth, state.max_queue)
                if rejection is not None:
                    return _shed_response(rejection)
                # headless interceptor (reference setupInterceptor,
                # gpupanel.js:819-834): fan out to enabled HTTP workers,
                # enqueue the master's prepared share locally.  ONE root
                # span covers the whole fan-out: preflight/dispatch spans
                # (orchestrate), the local execution and the collector
                # drain all parent under it, and the worker ships its
                # spans back on the final data-plane POST — the flight
                # recorder then holds the full cross-process tree.
                from comfyui_distributed_tpu.workflow.orchestrate import (
                    run_distributed)
                tid, par = trace_parent if trace_parent else (None, None)
                root = trace_mod.start_span(
                    "job", trace_id=tid, parent_id=par,
                    attrs={"client_id": str(client_id), "role": "master",
                           "tenant": tenant, "fanout": True})

                async def enqueue_graph(g):
                    # off the loop: with durability on, admission
                    # appends+fsyncs a WAL record before returning
                    api = g.to_api_format()
                    return await asyncio.get_running_loop() \
                        .run_in_executor(None, lambda: state.enqueue_prompt(
                            api, client_id, extra_data, trace_span=root,
                            tenant=tenant, span_attrs=span_attrs,
                            _preadmitted=True, **pid_kw))

                host = cfg.get("master", {}).get("host") or "127.0.0.1"
                master_url = f"http://{host}:{state.port or 8288}"
                try:
                    with trace_mod.use_span(root):
                        out = await run_distributed(
                            prompt, master_url,
                            workers=cfg_mod.enabled_workers(cfg),
                            master_dispatch=enqueue_graph,
                            job_store=state.jobs,
                            client_id=client_id, extra_data=extra_data,
                            cluster=state.cluster, ledger=state.ledger)
                except Exception:
                    # the fan-out died before the exec thread adopted the
                    # root (finalize would have sealed it) — seal here so
                    # the failure still leaves a postmortem trace
                    if root is not None and root.end_s is None \
                            and not root.attrs.get("prompt_id"):
                        state._abandon_span(
                            root, f"failed_{root.trace_id[:12]}",
                            "fan-out failed before enqueue")
                    raise
                return web.json_response({
                    "prompt_id": out["result"],
                    "number": state.queue_remaining(),
                    "workers": out["workers"],
                    "failed_workers": out.get("failed", []),
                })
            # off the loop: the durable admission record fsyncs before
            # the prompt_id is acked to the client.  Already-orchestrated
            # shares (a peer master dispatched them) skip local admission
            # — their job was admitted where it entered the fleet.
            pre = _is_dispatched_share(prompt)
            pid = await asyncio.get_running_loop().run_in_executor(
                None, lambda: state.enqueue_prompt(
                    prompt, client_id, extra_data,
                    trace_parent=trace_parent, tenant=tenant,
                    span_attrs=span_attrs, _preadmitted=pre,
                    **pid_kw))
        except ShedError as e:
            return _shed_response(e.rejection)
        except QueueFullError as e:
            # backpressure (DTPU_MAX_QUEUE): tell the client how deep the
            # queue is — and when to come back (Retry-After from the
            # measured drain rate, so shed clients back off instead of
            # hammering in lockstep)
            retry_after = state.retry_after_hint()
            return web.json_response(
                {"error": str(e),
                 "queue_remaining": state.queue_remaining(),
                 "retry_after_s": retry_after,
                 "max_queue": state.max_queue}, status=429,
                headers={"Retry-After": str(retry_after)})
        except DrainingError as e:
            return web.json_response({"error": str(e)}, status=503)
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response({"prompt_id": pid,
                                  "number": state.queue_remaining()})

    async def _orchestration_config(prompt: Dict[str, Any]):
        """Return the loaded config when this prompt should fan out, else
        None.  Conditions: we're a master, the graph has distributed nodes,
        they are not already prepared (no hidden multi_job_id — i.e. not a
        graph some other orchestrator dispatched to us), and HTTP workers
        are enabled (reference routing condition, ``gpupanel.js:826-833``).
        The config is loaded ONCE, off the event loop, and reused for the
        master URL and worker list."""
        if state.is_worker:
            return None
        found = False
        for node in prompt.values():
            if not isinstance(node, dict):
                continue
            if node.get("class_type") in ("DistributedCollector",
                                          "UltimateSDUpscaleDistributed"):
                h = {**node.get("inputs", {}), **node.get("hidden", {})}
                if h.get("multi_job_id"):
                    return None  # already orchestrated elsewhere
                found = True
        if not found:
            return None
        loop = asyncio.get_running_loop()
        cfg = await loop.run_in_executor(
            None, lambda: cfg_mod.load_config(state.config_path))
        return cfg if cfg_mod.enabled_workers(cfg) else None

    async def panel(request):
        """Visual cluster panel (status dots, worker lifecycle, metrics,
        log tail) — one static dependency-free page over the JSON routes;
        the capability analog of the reference's sidebar
        (``gpupanel.js:327-801, 1519-2085``)."""
        return web.FileResponse(
            os.path.join(os.path.dirname(__file__), "panel.html"))

    async def interrupt(request):
        state.interrupt_event.set()
        log("interrupt requested")
        return ok()

    async def upload_image(request):
        form = await request.post()
        img = form.get("image")
        if img is None:
            return web.json_response({"error": "missing image"}, status=400)
        name = os.path.basename(img.filename or "upload.png")

        def write():
            # mkdir + disk write off the loop (dtpu-lint: async-blocking):
            # a slow disk must not stall concurrent data-plane POSTs
            os.makedirs(state.input_dir, exist_ok=True)
            with open(os.path.join(state.input_dir, name), "wb") as f:
                f.write(img.file.read())

        await asyncio.get_running_loop().run_in_executor(None, write)
        return web.json_response({"name": name, "subfolder": "",
                                  "type": "input"})

    async def history(request):
        return web.json_response(state._history)

    def _prompt_live(pid: str) -> bool:
        """Whether the prompt is admitted and not yet finalized (the
        authoritative _inflight set — the queue/CB-slot views have
        handoff windows).  An unknown id must never arm a dangling
        abandonment flag or pin a preview-client slot."""
        with state._queue_lock:
            return pid in state._inflight

    async def preview_stream(request):
        """Server-sent events: step-wise progressive previews for one
        prompt (``event: preview`` frames with a base64 PNG of the
        denoising latent, then one ``event: done``).  The stream is
        ALSO the cancellation channel: when the last subscriber
        disconnects before the job finishes, the job is abandoned — a
        queued prompt is purged, a CB slot exits at the next step
        boundary, and the WAL records the abandonment."""
        if not reuse_mod.previews_enabled():
            return web.json_response(
                {"error": f"previews disabled ({C.PREVIEW_ENV}=0)"},
                status=403)
        pid = request.match_info["prompt_id"]
        if pid not in state._history and not _prompt_live(pid):
            # unknown id: refuse BEFORE subscribing — an endless-ping
            # stream per garbage id would otherwise pin slots under the
            # DTPU_PREVIEW_MAX_CLIENTS cap indefinitely
            return web.json_response(
                {"error": f"unknown prompt {pid!r} (not queued, not "
                          "executing, not in history)"}, status=404)
        bus = reuse_mod.PREVIEWS
        q = bus.subscribe(pid)
        if q is None:
            return web.json_response(
                {"error": "too many preview clients"}, status=429)
        resp = web.StreamResponse(
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache",
                     "X-Accel-Buffering": "no"})
        disconnected = False
        try:
            await resp.prepare(request)
            last_beat = time.monotonic()
            while True:
                ev = None
                try:
                    ev = q.get_nowait()
                except queue.Empty:
                    pass
                if ev is None:
                    hist = state._history.get(pid)
                    if hist is not None:
                        ev = {"type": "done", "prompt_id": pid,
                              "status": hist.get("status", "done")}
                    else:
                        now = time.monotonic()
                        if now - last_beat >= 1.0:
                            # heartbeat comment: disconnect detection
                            # between preview frames (a write to a
                            # closed transport raises)
                            await resp.write(b": ping\n\n")
                            last_beat = now
                        await asyncio.sleep(0.05)
                        continue
                await resp.write(
                    f"event: {ev['type']}\n"
                    f"data: {json.dumps(ev)}\n\n".encode())
                if ev.get("type") == "done":
                    break
            await resp.write_eof()
        except asyncio.CancelledError:
            # aiohttp cancels the handler when the client disconnects
            disconnected = True
            raise
        except (ConnectionResetError, ConnectionError):
            disconnected = True
        finally:
            remaining = bus.unsubscribe(pid, q)
            if disconnected and remaining == 0 \
                    and pid not in state._history and _prompt_live(pid):
                # client gone = cancellation signal: flag the job; the
                # CB driver's boundary scan / queue purge finalizes it
                bus.abandon(pid)
                state._queue_event.set()
                if pid in state._history:
                    # finalize raced the disconnect: the job settled
                    # between our liveness check and the flag — consume
                    # the stale flag (finish() already ran; nothing
                    # else ever would, and the set must not leak)
                    bus.clear_abandoned(pid)
        return resp

    r.add_get("/distributed/config", get_config)
    r.add_post("/distributed/config/update_worker", update_worker)
    r.add_post("/distributed/config/delete_worker", delete_worker)
    r.add_post("/distributed/config/update_setting", update_setting)
    r.add_post("/distributed/config/update_master", update_master)
    r.add_get("/distributed/network_info", network_info)
    r.add_get("/distributed/status", status)
    r.add_get("/distributed/metrics", metrics)
    r.add_get("/distributed/metrics.prom", metrics_prom)
    r.add_post("/distributed/metrics/reset", metrics_reset)
    r.add_get("/distributed/traces", list_traces)
    r.add_get("/distributed/trace/{prompt_id}", get_trace)
    r.add_get("/distributed/slo", slo_view)
    r.add_get("/distributed/analysis", analysis_view)
    r.add_post("/distributed/warmup", warmup)
    r.add_get("/distributed/ring", ring_info)
    r.add_post("/distributed/ring/gossip", ring_gossip)
    r.add_get("/distributed/cluster", cluster_info)
    r.add_get("/distributed/resource", resource_info)
    r.add_get("/distributed/cluster/metrics", cluster_metrics)
    r.add_get("/distributed/cluster/metrics.prom", cluster_metrics_prom)
    r.add_post("/distributed/register", cluster_register)
    r.add_post("/distributed/heartbeat", cluster_heartbeat)
    r.add_get("/distributed/fleet", fleet_info)
    r.add_get("/distributed/durability", durability_info)
    r.add_post("/distributed/takeover", takeover)
    r.add_post("/distributed/rehome", rehome)
    r.add_get("/distributed/workers_status", workers_status)
    r.add_post("/distributed/cluster/clear_memory", cluster_clear_memory)
    r.add_post("/distributed/cluster/interrupt", cluster_interrupt)
    r.add_post("/distributed/profile/start", profile_start)
    r.add_post("/distributed/profile/stop", profile_stop)
    r.add_get("/distributed/profile/status", profile_status)
    r.add_post("/distributed/clear_memory", clear_memory)
    r.add_post("/distributed/launch_worker", launch_worker)
    r.add_post("/distributed/stop_worker", stop_worker)
    r.add_get("/distributed/managed_workers", managed_workers)
    r.add_get("/distributed/worker_log", worker_log)
    r.add_post("/distributed/worker/clear_launching", clear_launching)
    r.add_post("/distributed/prepare_job", prepare_job)
    r.add_get("/distributed/queue_status", queue_status)
    r.add_get("/distributed/wire_formats", wire_formats)
    r.add_post("/distributed/job_complete", job_complete)
    r.add_post("/distributed/tile_complete", tile_complete)
    r.add_post("/distributed/load_image", load_image)
    r.add_get("/distributed/preview/{prompt_id}", preview_stream)
    r.add_get("/prompt", get_prompt)
    r.add_post("/prompt", post_prompt)
    r.add_post("/interrupt", interrupt)
    r.add_get("/panel", panel)
    r.add_post("/upload/image", upload_image)
    r.add_get("/history", history)
    return app


def serve(host: str = "0.0.0.0", port: int = 8288,
          state: Optional[ServerState] = None,
          auto_launch: bool = True) -> None:
    """Blocking server entry point."""
    state = state or ServerState()
    state.port = port
    # compilation is a one-time cost: persistent XLA cache across restarts
    # (spawned workers inherit the resolved dir and share it), plus an
    # optional startup warmup — DTPU_WARMUP='{"ckpt_name": ..., "width":
    # ..., ...}' AOT-compiles the serving shape before the first request
    from comfyui_distributed_tpu.runtime.manager import \
        enable_persistent_compile_cache
    enable_persistent_compile_cache()
    # NOTE: the warmup thread compiles while the server is already
    # accepting requests; jax.monitoring events are process-wide, so a
    # prompt executed DURING warmup may report the warmup's traces in its
    # ExecutionResult.retraces — read the zero-retrace steady-state
    # signal only after warmup completes (its completion is logged).
    warmup_spec = os.environ.get("DTPU_WARMUP")
    if warmup_spec and not state.is_worker:
        def startup_warmup():
            try:
                spec = json.loads(warmup_spec)
                from comfyui_distributed_tpu.models import registry
                ckpt = spec.pop("ckpt_name", "model.safetensors")
                registry.load_pipeline(
                    ckpt, models_dir=state.models_dir).warmup(**spec)
            except Exception as e:  # noqa: BLE001 - warmup is best-effort
                log(f"startup warmup failed: {type(e).__name__}: {e}")

        threading.Thread(target=startup_warmup, daemon=True,
                         name="dtpu-warmup").start()
    app = build_app(state)
    if not state.is_worker:
        # master-IP autodetect: save the recommended private-range IP as
        # master.host when unset (reference detectMasterIP/saveMasterIp,
        # gpupanel.js:2114-2190) so dispatched remote workers can reach us.
        # Skipped when binding loopback-only — the LAN IP would then be
        # unreachable and 127.0.0.1 (the master_url fallback) is correct.
        if host not in ("127.0.0.1", "localhost"):
            def autodetect(cfg):
                if not cfg.get("master", {}).get("host"):
                    cfg.setdefault("master", {})["host"] = \
                        net_mod.get_recommended_ip()
            cfg_mod.mutate_config(autodetect, state.config_path)
        state.health.start()
        # elastic fleet (ISSUE 9): DTPU_AUTOSCALE=1 arms the
        # reconciliation loop — spawn on sustained queue/utilization
        # pressure, retire by drain + lease non-renewal
        state.autoscaler = autoscale_mod.install(state)
    if auto_launch and not state.is_worker:
        auto_launch_workers(state.manager)
    if state.is_worker:
        # renew this worker's lease at the master (spawned workers
        # inherit DTPU_MASTER_URL/DTPU_WORKER_ID from the process
        # manager; elastic workers export them by hand)
        state.heartbeat = cluster_mod.maybe_start_heartbeat(port=port)
    role = "worker" if state.is_worker else "master"
    log(f"{role} server listening on {host}:{port}")
    web.run_app(app, host=host, port=port, print=None)
