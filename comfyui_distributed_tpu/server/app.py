"""aiohttp application: the reference's full route surface plus TPU-native
status/metrics.

Route inventory (capability parity with reference ``distributed.py:49-599,
1135-1218`` and ``distributed_upscale.py:711-760``; SURVEY.md §2 #5-#8,
#13, #15, #22-#24):

  control plane
    GET  /distributed/config                 full config
    POST /distributed/config/update_worker   upsert (None deletes field)
    POST /distributed/config/delete_worker
    POST /distributed/config/update_setting
    POST /distributed/config/update_master
    GET  /distributed/network_info           host IPs + recommended master IP
    POST /distributed/clear_memory           drop model/jit caches, gc
    POST /distributed/launch_worker          process manager
    POST /distributed/stop_worker
    GET  /distributed/managed_workers
    GET  /distributed/worker_log             backwards log tail
    POST /distributed/worker/clear_launching
    GET  /distributed/queue_status           does a tile job queue exist
    POST /distributed/prepare_job            create queue before dispatch
    POST /distributed/load_image             base64 input staging
    GET  /distributed/status                 mesh topology + runtime (new)
    GET  /distributed/metrics                counters/timings (new)

  data plane
    POST /distributed/job_complete           multipart PNG -> image queue
    POST /distributed/tile_complete          multipart PNG -> tile queue

  ComfyUI-compatible worker surface (what the reference's workers expose)
    GET  /prompt        {"exec_info": {"queue_remaining": N}}
    POST /prompt        queue a workflow for execution
    POST /interrupt     stop the running job
    POST /upload/image  receive staged input images
"""

from __future__ import annotations

import asyncio
import base64
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from aiohttp import web

from comfyui_distributed_tpu.ops.base import OpContext
from comfyui_distributed_tpu.runtime.jobs import JobStore
from comfyui_distributed_tpu.runtime.manager import (
    WorkerProcessManager,
    auto_launch_workers,
)
from comfyui_distributed_tpu.utils import config as cfg_mod
from comfyui_distributed_tpu.utils import net as net_mod
from comfyui_distributed_tpu.utils.constants import LOG_TAIL_BYTES
from comfyui_distributed_tpu.utils.image import decode_png
from comfyui_distributed_tpu.utils.logging import debug_log, log
from comfyui_distributed_tpu.workflow.executor import WorkflowExecutor


class ServerState:
    """Everything the handlers share: config path, job store, process
    manager, the execution queue and its worker thread."""

    def __init__(self, config_path: Optional[str] = None,
                 is_worker: bool = False,
                 input_dir: Optional[str] = None,
                 output_dir: Optional[str] = None,
                 models_dir: Optional[str] = None,
                 start_exec_thread: bool = True):
        self.config_path = config_path
        self.is_worker = is_worker
        self.port: Optional[int] = None  # set by serve()
        self.input_dir = input_dir or os.path.join(os.getcwd(), "input")
        self.output_dir = output_dir or os.path.join(os.getcwd(), "output")
        self.models_dir = models_dir
        self.jobs = JobStore()
        self.manager = WorkerProcessManager(config_path=config_path,
                                            models_dir=models_dir)
        from comfyui_distributed_tpu.runtime.health import HealthPoller
        self.health = HealthPoller(config_path=config_path,
                                   manager=self.manager)
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        # the process-global flag: compiled samplers poll it per step
        # (runtime/interrupt.py), so /interrupt stops a sample in flight
        from comfyui_distributed_tpu.runtime.interrupt import interrupt_event
        self.interrupt_event = interrupt_event()
        self.metrics: Dict[str, Any] = {
            "prompts_executed": 0, "prompts_failed": 0,
            "images_received": 0, "tiles_received": 0,
            "last_execution_s": None,
        }
        self._queue: List[Dict[str, Any]] = []
        self._queue_lock = threading.Lock()
        self._queue_event = threading.Event()
        self._running = False
        self._history: Dict[str, Any] = {}
        self._id_counter = itertools.count()
        if start_exec_thread:
            t = threading.Thread(target=self._exec_loop, daemon=True,
                                 name="dtpu-exec")
            t.start()

    def _drop_tile_queues(self, prompt: Dict[str, Any]) -> None:
        """Remove master-mode tile queues for a finished prompt.  They're
        pre-created at /prompt time (before the exec thread runs), so a
        prompt that fails before its upscale node would otherwise leave an
        orphan queue accepting tiles forever — the leak put_tile's
        require_existing guard exists to prevent.  The upscale node's own
        drain also removes the queue; this is the failure-path backstop."""
        if self.loop is None:
            return
        for node in prompt.values():
            if not isinstance(node, dict) \
                    or node.get("class_type") != "UltimateSDUpscaleDistributed":
                continue
            h = {**node.get("inputs", {}), **node.get("hidden", {})}
            mj = h.get("multi_job_id")
            if mj and not h.get("is_worker"):
                try:
                    asyncio.run_coroutine_threadsafe(
                        self.jobs.remove_tile_queue(str(mj)),
                        self.loop).result(timeout=5)
                except Exception as e:  # noqa: BLE001 - cleanup best-effort
                    debug_log(f"tile queue cleanup {mj}: {e}")

    # --- execution queue (ComfyUI /prompt semantics) -----------------------

    def queue_remaining(self) -> int:
        with self._queue_lock:
            return len(self._queue) + (1 if self._running else 0)

    def enqueue_prompt(self, prompt: Dict[str, Any], client_id: str,
                       extra_data: Optional[Dict[str, Any]] = None) -> str:
        pid = f"p_{int(time.time() * 1000)}_{next(self._id_counter)}"
        with self._queue_lock:
            self._queue.append({"id": pid, "prompt": prompt,
                                "client_id": client_id,
                                "extra_data": extra_data or {}})
        self._queue_event.set()
        return pid

    def _exec_loop(self) -> None:
        from comfyui_distributed_tpu.parallel.mesh import get_runtime
        while True:
            self._queue_event.wait()
            with self._queue_lock:
                if not self._queue:
                    self._queue_event.clear()
                    continue
                item = self._queue.pop(0)
                self._running = True
            self.interrupt_event.clear()
            t0 = time.perf_counter()
            try:
                ctx = OpContext(
                    runtime=get_runtime(),
                    models_dir=self.models_dir,
                    input_dir=self.input_dir,
                    output_dir=self.output_dir,
                    is_worker=self.is_worker,
                    job_store=self.jobs,
                    server_loop=self.loop,
                    interrupt_event=self.interrupt_event,
                )
                res = WorkflowExecutor(ctx).execute(
                    item["prompt"],
                    extra_pnginfo=item.get("extra_data", {}).get(
                        "extra_pnginfo"))
                self._history[item["id"]] = {
                    "status": "success",
                    "images": len(res.images),
                    "duration_s": res.total_s,
                }
                self.metrics["prompts_executed"] += 1
                self.metrics["last_execution_s"] = res.total_s
            except Exception as e:  # noqa: BLE001 - survive bad prompts
                log(f"prompt {item['id']} failed: {type(e).__name__}: {e}")
                self._history[item["id"]] = {"status": "error",
                                             "error": str(e)}
                self.metrics["prompts_failed"] += 1
            finally:
                self._drop_tile_queues(item["prompt"])
                with self._queue_lock:
                    self._running = False
                debug_log(f"prompt {item['id']} done in "
                          f"{time.perf_counter() - t0:.2f}s")


def build_app(state: Optional[ServerState] = None) -> web.Application:
    state = state or ServerState()
    app = web.Application(client_max_size=512 * 1024 * 1024)
    app["state"] = state

    async def on_startup(app):
        state.loop = asyncio.get_running_loop()

    async def on_cleanup(app):
        await net_mod.cleanup_client_session()

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    r = app.router

    def ok(payload: Any = None, **kw):
        body = {"status": "ok"}
        if payload is not None:
            body.update(payload)
        body.update(kw)
        return web.json_response(body)

    # --- config CRUD (reference distributed.py:49-364) ---------------------

    async def _mutate(mutator):
        """Config RMW off the event loop: the config lock is shared with the
        exec thread and auto-launch timer, and file IO under it must not
        stall the data plane (same reason PNG decode is offloaded)."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, lambda: cfg_mod.mutate_config(mutator, state.config_path))

    async def get_config(request):
        loop = asyncio.get_running_loop()
        cfg = await loop.run_in_executor(
            None, lambda: cfg_mod.load_config(state.config_path))
        return web.json_response(cfg)

    async def update_worker(request):
        data = await request.json()
        if "id" not in data:
            return web.json_response({"error": "missing worker id"},
                                     status=400)
        result = {}
        await _mutate(lambda cfg: result.update(
            cfg_mod.upsert_worker(cfg, data)))
        return ok({"worker": result})

    async def delete_worker(request):
        data = await request.json()
        found = []
        await _mutate(lambda cfg: found.append(
            cfg_mod.delete_worker(cfg, str(data.get("id")))))
        if not found[0]:
            return web.json_response({"error": "worker not found"},
                                     status=404)
        return ok()

    async def update_setting(request):
        data = await request.json()
        if "key" not in data:
            return web.json_response({"error": "missing key"}, status=400)
        await _mutate(lambda cfg: cfg_mod.update_setting(
            cfg, data["key"], data.get("value")))
        return ok()

    async def update_master(request):
        data = await request.json()
        # only keys present in the request are touched — an explicit null
        # deletes a field, an absent key leaves it alone (partial update)
        fields = {k: data[k] for k in ("host", "port", "extra_args")
                  if k in data}
        await _mutate(lambda cfg: cfg_mod.update_master(cfg, **fields))
        return ok()

    # --- info / lifecycle ---------------------------------------------------

    async def network_info(request):
        return web.json_response(net_mod.network_info())

    async def status(request):
        from comfyui_distributed_tpu.parallel.mesh import get_runtime
        # first call may initialize the JAX backend (seconds on real TPU) —
        # keep it off the event loop so the data plane stays responsive
        loop = asyncio.get_running_loop()
        st = await loop.run_in_executor(None,
                                        lambda: get_runtime().status())
        st["jobs"] = state.jobs.snapshot()
        st["queue_remaining"] = state.queue_remaining()
        st["is_worker"] = state.is_worker
        return web.json_response(st)

    async def metrics(request):
        from comfyui_distributed_tpu.utils.trace import (
            GLOBAL_PHASES, counters_snapshot)
        return web.json_response({**state.metrics,
                                  "phases": GLOBAL_PHASES.snapshot(),
                                  # host<->device transfer bytes per node
                                  # + jit trace/XLA compile counts: the
                                  # tensor-plane health signals (steady
                                  # serving => retraces stop growing)
                                  **counters_snapshot()})

    async def warmup(request):
        """AOT warmup (registry.DiffusionPipeline.warmup): compile +
        execute the serving-shaped programs for a checkpoint so the next
        matching /prompt pays dispatch cost only.  Body: {"ckpt_name",
        "width", "height", "batch", "steps", "cfg", "sampler_name",
        "scheduler", "denoise"} — all optional but ckpt_name."""
        from comfyui_distributed_tpu.models import registry
        data = await request.json() if request.can_read_body else {}
        ckpt = data.get("ckpt_name", "model.safetensors")
        kwargs = {k: data[k] for k in
                  ("height", "width", "batch", "steps", "cfg",
                   "sampler_name", "scheduler", "denoise") if k in data}
        loop = asyncio.get_running_loop()

        def run():
            pipe = registry.load_pipeline(ckpt,
                                          models_dir=state.models_dir)
            return pipe.warmup(**kwargs)

        # compile happens off the event loop; the control plane stays up
        timings = await loop.run_in_executor(None, run)
        return ok({"ckpt_name": ckpt, "timings": timings})

    # --- profiling (the subsystem the reference lacks, SURVEY.md §5) -------

    async def profile_start(request):
        from comfyui_distributed_tpu.utils import trace as trace_mod
        data = await request.json() if request.can_read_body else {}
        try:
            out = trace_mod.start_device_trace(data.get("dir"))
        except RuntimeError as e:
            return web.json_response({"error": str(e)}, status=409)
        return ok({"dir": out})

    async def profile_stop(request):
        from comfyui_distributed_tpu.utils import trace as trace_mod
        try:
            out = trace_mod.stop_device_trace()
        except RuntimeError as e:
            return web.json_response({"error": str(e)}, status=409)
        return ok({"dir": out})

    async def profile_status(request):
        from comfyui_distributed_tpu.utils import trace as trace_mod
        return web.json_response(trace_mod.trace_status())

    async def clear_memory(request):
        import gc

        import jax

        from comfyui_distributed_tpu.models import registry
        registry.clear_pipeline_cache()
        jax.clear_caches()
        for _ in range(3):
            gc.collect()
        log("cleared model/jit caches")
        return ok()

    async def launch_worker(request):
        data = await request.json()
        cfg = cfg_mod.load_config(state.config_path)
        worker = next((w for w in cfg["workers"]
                       if str(w.get("id")) == str(data.get("id"))), None)
        if worker is None:
            return web.json_response({"error": "worker not found"},
                                     status=404)
        try:
            entry = state.manager.launch_worker(
                worker, stop_on_master_exit=cfg["settings"].get(
                    "stop_workers_on_master_exit", True))
        except RuntimeError as e:
            return web.json_response({"error": str(e)}, status=409)
        return ok({"worker": entry})

    async def stop_worker(request):
        data = await request.json()
        if not state.manager.stop_worker(str(data.get("id"))):
            return web.json_response({"error": "not managed"}, status=404)
        return ok()

    async def managed_workers(request):
        return web.json_response(state.manager.get_managed_workers())

    async def workers_status(request):
        """Live worker health (the reference panel's 2s status dots,
        ``gpupanel.js:1233-1311``), served from the poller's snapshot."""
        return web.json_response(state.health.snapshot())

    async def _fanout_to_workers(path: str) -> Dict[str, Any]:
        """POST ``path`` on every enabled worker (reference toolbar fan-out,
        ``gpupanel.js:204-306``)."""
        import aiohttp

        from comfyui_distributed_tpu.utils.net import get_client_session
        from comfyui_distributed_tpu.workflow.dispatcher import worker_url
        loop = asyncio.get_running_loop()
        cfg = await loop.run_in_executor(
            None, lambda: cfg_mod.load_config(state.config_path))
        session = await get_client_session()
        results: Dict[str, Any] = {}

        async def hit(w):
            try:
                async with session.post(
                        worker_url(w) + path,
                        timeout=aiohttp.ClientTimeout(total=10)) as r:
                    results[str(w["id"])] = r.status
            except Exception as e:  # noqa: BLE001 - report per-worker
                results[str(w["id"])] = str(e)

        await asyncio.gather(*(hit(w) for w in cfg_mod.enabled_workers(cfg)))
        return results

    async def cluster_clear_memory(request):
        """Clear caches here AND on every enabled worker (reference
        ``_handleClearMemory``, ``gpupanel.js:259-306``)."""
        results = await _fanout_to_workers("/distributed/clear_memory")
        await clear_memory(request)
        return ok({"workers": results})

    async def cluster_interrupt(request):
        """Interrupt here AND on every enabled worker (reference
        ``_handleInterruptWorkers``, ``gpupanel.js:204-257``)."""
        results = await _fanout_to_workers("/interrupt")
        state.interrupt_event.set()
        return ok({"workers": results})

    async def worker_log(request):
        wid = request.query.get("id", "")
        try:
            text = state.manager.tail_log(wid, max_bytes=int(
                request.query.get("bytes", LOG_TAIL_BYTES)))
        except FileNotFoundError as e:
            return web.json_response({"error": str(e)}, status=404)
        return web.json_response({"log": text})

    async def clear_launching(request):
        data = await request.json()
        state.manager.clear_launching(str(data.get("id")))
        return ok()

    # --- job data plane -----------------------------------------------------

    async def prepare_job(request):
        data = await request.json()
        mj = data.get("multi_job_id")
        if not mj:
            return web.json_response({"error": "missing multi_job_id"},
                                     status=400)
        if data.get("kind") == "tile":
            await state.jobs.prepare_tile_job(mj)
        else:
            await state.jobs.prepare_job(mj)
        debug_log(f"prepared {data.get('kind', 'image')} job {mj}")
        return ok()

    async def queue_status(request):
        mj = request.query.get("multi_job_id", "")
        exists = await state.jobs.has_tile_job(mj) or \
            await state.jobs.has_job(mj)
        return web.json_response({"exists": exists})

    async def job_complete(request):
        form = await request.post()
        mj = form.get("multi_job_id", "")
        img_field = form.get("image")
        if not mj or img_field is None:
            return web.json_response({"error": "missing fields"}, status=400)
        # PNG decode off the event loop: concurrent uploads must not stall
        # the control plane (a stalled /prompt fails preflight's 300ms probe)
        loop = asyncio.get_running_loop()
        tensor = await loop.run_in_executor(
            None, lambda: decode_png(img_field.file.read()))
        item = {
            "worker_id": form.get("worker_id", ""),
            "is_last": str(form.get("is_last", "false")).lower() == "true",
            "tensor": tensor,
        }
        # only pass the index through when the sender set one: the collector
        # dedups retransmits by (worker, index), and defaulting indexless
        # uploads to 0 would collapse them into a single image
        if form.get("image_index") is not None:
            item["image_index"] = int(form["image_index"])
        if not await state.jobs.put_result(mj, item):
            # unknown job -> 404 so the worker's retry loop backs off
            return web.json_response({"error": f"unknown job {mj}"},
                                     status=404)
        state.metrics["images_received"] += 1
        return ok()

    async def tile_complete(request):
        form = await request.post()
        mj = form.get("multi_job_id", "")
        tile_field = form.get("tile")
        if not mj or tile_field is None:
            return web.json_response({"error": "missing fields"}, status=400)
        item = {
            "worker_id": form.get("worker_id", ""),
            "tile_idx": int(form.get("tile_idx", 0)),
            "x": int(form.get("x", 0)),
            "y": int(form.get("y", 0)),
            "extracted_width": int(form.get("extracted_width", 0)),
            "extracted_height": int(form.get("extracted_height", 0)),
            "padding": int(form.get("padding", 0)),
            "is_last": str(form.get("is_last", "false")).lower() == "true",
            "tensor": await asyncio.get_running_loop().run_in_executor(
                None, lambda: decode_png(tile_field.file.read())),
        }
        if not await state.jobs.put_tile(mj, item):
            # unknown/expired tile job -> 404; the worker's retry loop backs
            # off instead of resurrecting an orphan queue
            return web.json_response({"error": f"unknown tile job {mj}"},
                                     status=404)
        state.metrics["tiles_received"] += 1
        return ok()

    async def load_image(request):
        """Input-image staging for remote workers (reference
        ``distributed.py:1135-1173``): name -> base64 PNG."""
        data = await request.json()
        name = str(data.get("image_name", ""))
        safe = os.path.normpath(name).lstrip(os.sep)
        if safe.startswith(".."):
            return web.json_response({"error": "bad path"}, status=400)
        path = os.path.join(state.input_dir, safe)
        if not os.path.exists(path):
            return web.json_response({"error": f"not found: {name}"},
                                     status=404)
        def read_b64():
            with open(path, "rb") as f:
                return base64.b64encode(f.read()).decode()
        b64 = await asyncio.get_running_loop().run_in_executor(None, read_b64)
        return web.json_response({"image_data": b64, "name": name})

    # --- ComfyUI-compatible worker surface ---------------------------------

    async def get_prompt(request):
        return web.json_response(
            {"exec_info": {"queue_remaining": state.queue_remaining()}})

    async def post_prompt(request):
        data = await request.json()
        prompt = data.get("prompt")
        if not isinstance(prompt, dict) or not prompt:
            return web.json_response({"error": "missing prompt"}, status=400)
        # master-mode tile jobs: pre-create their queues at prompt-queue
        # time, before the exec thread gets anywhere near the upscale node
        # (reference pre-inits at validation time, distributed_upscale.py:
        # 85-105) — otherwise a fast worker's tiles 404 through its retries
        for node in prompt.values():
            if not isinstance(node, dict) \
                    or node.get("class_type") != "UltimateSDUpscaleDistributed":
                continue
            h = {**node.get("inputs", {}), **node.get("hidden", {})}
            mj = h.get("multi_job_id")
            if mj and not h.get("is_worker"):
                await state.jobs.prepare_tile_job(str(mj))
        client_id = data.get("client_id", "unknown")
        # ComfyUI contract: extra_data.extra_pnginfo.workflow rides every
        # dispatch so saved PNGs embed the source workflow (reference
        # gpupanel.js:1344-1358)
        extra_data = data.get("extra_data") or {}
        try:
            cfg = await _orchestration_config(prompt)
            if cfg is not None:
                # headless interceptor (reference setupInterceptor,
                # gpupanel.js:819-834): fan out to enabled HTTP workers,
                # enqueue the master's prepared share locally
                from comfyui_distributed_tpu.workflow.orchestrate import (
                    run_distributed)

                async def enqueue_graph(g):
                    return state.enqueue_prompt(g.to_api_format(),
                                                client_id, extra_data)

                host = cfg.get("master", {}).get("host") or "127.0.0.1"
                master_url = f"http://{host}:{state.port or 8288}"
                out = await run_distributed(
                    prompt, master_url,
                    workers=cfg_mod.enabled_workers(cfg),
                    master_dispatch=enqueue_graph, job_store=state.jobs,
                    client_id=client_id, extra_data=extra_data)
                return web.json_response({
                    "prompt_id": out["result"],
                    "number": state.queue_remaining(),
                    "workers": out["workers"],
                    "failed_workers": out.get("failed", []),
                })
            pid = state.enqueue_prompt(prompt, client_id, extra_data)
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response({"prompt_id": pid,
                                  "number": state.queue_remaining()})

    async def _orchestration_config(prompt: Dict[str, Any]):
        """Return the loaded config when this prompt should fan out, else
        None.  Conditions: we're a master, the graph has distributed nodes,
        they are not already prepared (no hidden multi_job_id — i.e. not a
        graph some other orchestrator dispatched to us), and HTTP workers
        are enabled (reference routing condition, ``gpupanel.js:826-833``).
        The config is loaded ONCE, off the event loop, and reused for the
        master URL and worker list."""
        if state.is_worker:
            return None
        found = False
        for node in prompt.values():
            if not isinstance(node, dict):
                continue
            if node.get("class_type") in ("DistributedCollector",
                                          "UltimateSDUpscaleDistributed"):
                h = {**node.get("inputs", {}), **node.get("hidden", {})}
                if h.get("multi_job_id"):
                    return None  # already orchestrated elsewhere
                found = True
        if not found:
            return None
        loop = asyncio.get_running_loop()
        cfg = await loop.run_in_executor(
            None, lambda: cfg_mod.load_config(state.config_path))
        return cfg if cfg_mod.enabled_workers(cfg) else None

    async def panel(request):
        """Visual cluster panel (status dots, worker lifecycle, metrics,
        log tail) — one static dependency-free page over the JSON routes;
        the capability analog of the reference's sidebar
        (``gpupanel.js:327-801, 1519-2085``)."""
        return web.FileResponse(
            os.path.join(os.path.dirname(__file__), "panel.html"))

    async def interrupt(request):
        state.interrupt_event.set()
        log("interrupt requested")
        return ok()

    async def upload_image(request):
        form = await request.post()
        img = form.get("image")
        if img is None:
            return web.json_response({"error": "missing image"}, status=400)
        os.makedirs(state.input_dir, exist_ok=True)
        name = os.path.basename(img.filename or "upload.png")
        with open(os.path.join(state.input_dir, name), "wb") as f:
            f.write(img.file.read())
        return web.json_response({"name": name, "subfolder": "",
                                  "type": "input"})

    async def history(request):
        return web.json_response(state._history)

    r.add_get("/distributed/config", get_config)
    r.add_post("/distributed/config/update_worker", update_worker)
    r.add_post("/distributed/config/delete_worker", delete_worker)
    r.add_post("/distributed/config/update_setting", update_setting)
    r.add_post("/distributed/config/update_master", update_master)
    r.add_get("/distributed/network_info", network_info)
    r.add_get("/distributed/status", status)
    r.add_get("/distributed/metrics", metrics)
    r.add_post("/distributed/warmup", warmup)
    r.add_get("/distributed/workers_status", workers_status)
    r.add_post("/distributed/cluster/clear_memory", cluster_clear_memory)
    r.add_post("/distributed/cluster/interrupt", cluster_interrupt)
    r.add_post("/distributed/profile/start", profile_start)
    r.add_post("/distributed/profile/stop", profile_stop)
    r.add_get("/distributed/profile/status", profile_status)
    r.add_post("/distributed/clear_memory", clear_memory)
    r.add_post("/distributed/launch_worker", launch_worker)
    r.add_post("/distributed/stop_worker", stop_worker)
    r.add_get("/distributed/managed_workers", managed_workers)
    r.add_get("/distributed/worker_log", worker_log)
    r.add_post("/distributed/worker/clear_launching", clear_launching)
    r.add_post("/distributed/prepare_job", prepare_job)
    r.add_get("/distributed/queue_status", queue_status)
    r.add_post("/distributed/job_complete", job_complete)
    r.add_post("/distributed/tile_complete", tile_complete)
    r.add_post("/distributed/load_image", load_image)
    r.add_get("/prompt", get_prompt)
    r.add_post("/prompt", post_prompt)
    r.add_post("/interrupt", interrupt)
    r.add_get("/panel", panel)
    r.add_post("/upload/image", upload_image)
    r.add_get("/history", history)
    return app


def serve(host: str = "0.0.0.0", port: int = 8288,
          state: Optional[ServerState] = None,
          auto_launch: bool = True) -> None:
    """Blocking server entry point."""
    state = state or ServerState()
    state.port = port
    # compilation is a one-time cost: persistent XLA cache across restarts
    # (spawned workers inherit the resolved dir and share it), plus an
    # optional startup warmup — DTPU_WARMUP='{"ckpt_name": ..., "width":
    # ..., ...}' AOT-compiles the serving shape before the first request
    from comfyui_distributed_tpu.runtime.manager import \
        enable_persistent_compile_cache
    enable_persistent_compile_cache()
    # NOTE: the warmup thread compiles while the server is already
    # accepting requests; jax.monitoring events are process-wide, so a
    # prompt executed DURING warmup may report the warmup's traces in its
    # ExecutionResult.retraces — read the zero-retrace steady-state
    # signal only after warmup completes (its completion is logged).
    warmup_spec = os.environ.get("DTPU_WARMUP")
    if warmup_spec and not state.is_worker:
        def startup_warmup():
            try:
                spec = json.loads(warmup_spec)
                from comfyui_distributed_tpu.models import registry
                ckpt = spec.pop("ckpt_name", "model.safetensors")
                registry.load_pipeline(
                    ckpt, models_dir=state.models_dir).warmup(**spec)
            except Exception as e:  # noqa: BLE001 - warmup is best-effort
                log(f"startup warmup failed: {type(e).__name__}: {e}")

        threading.Thread(target=startup_warmup, daemon=True,
                         name="dtpu-warmup").start()
    app = build_app(state)
    if not state.is_worker:
        # master-IP autodetect: save the recommended private-range IP as
        # master.host when unset (reference detectMasterIP/saveMasterIp,
        # gpupanel.js:2114-2190) so dispatched remote workers can reach us.
        # Skipped when binding loopback-only — the LAN IP would then be
        # unreachable and 127.0.0.1 (the master_url fallback) is correct.
        if host not in ("127.0.0.1", "localhost"):
            def autodetect(cfg):
                if not cfg.get("master", {}).get("host"):
                    cfg.setdefault("master", {})["host"] = \
                        net_mod.get_recommended_ip()
            cfg_mod.mutate_config(autodetect, state.config_path)
        state.health.start()
    if auto_launch and not state.is_worker:
        auto_launch_workers(state.manager)
    role = "worker" if state.is_worker else "master"
    log(f"{role} server listening on {host}:{port}")
    web.run_app(app, host=host, port=port, print=None)
