"""HTTP control + data plane (aiohttp)."""

from comfyui_distributed_tpu.server.app import build_app, ServerState  # noqa: F401
