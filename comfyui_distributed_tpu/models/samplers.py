"""k-diffusion-family samplers, written for XLA.

The reference drives ComfyUI's ``common_ksampler`` (reference
``distributed_upscale.py:521``; KSampler node in
``workflows/distributed-txt2img.json`` with widgets
``[seed, control, steps, cfg, sampler_name, scheduler, denoise]``).  These are
native implementations with the same sampler-name surface, built TPU-first:

- every sampler is a pure function stepping a ``lax.scan`` over the sigma
  sequence — one traced step, no Python loop in the compiled program;
- per-sample PRNG: callers pass per-sample keys (shape ``[B, 2]``); step
  noise is ``fold_in(key, step)`` so replica/batch streams stay independent
  and reproducible (seed-offset parity with reference
  ``distributed.py:1491-1514`` lives in the keys, not here);
- the model is called once per step on the full batch (CFG doubling happens
  inside the denoiser wrapper) — large batched matmuls for the MXU.

Model convention: ``model(x, sigma) -> denoised`` (x0-prediction), k-diffusion
style, where ``x`` is NHWC latent and ``sigma`` a scalar.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from comfyui_distributed_tpu.parallel import sharding as shd

Model = Callable[..., jax.Array]  # model(x, sigma, **extra) -> denoised


def sample_keys(seeds, idx=None) -> jax.Array:
    """Per-sample PRNG keys from per-sample seeds: fold a per-sample index
    into each seed so rows sharing a seed still get distinct streams.

    ``idx`` defaults to the global batch position; the distributed layer
    passes *replica-local* indices instead, so two replicas given the same
    seed produce identical sub-batches (reference parity: a run without a
    DistributedSeed node yields duplicate images on every participant).

    Accepts 64-bit host seeds (numpy/python ints) without collision: the high
    word is folded in separately, so seeds differing by 2^32 stay distinct
    (the reference's seed widget is 64-bit).  Traced jax arrays are treated
    as 32-bit (x64 is disabled under jit)."""
    import numpy as _np
    if isinstance(seeds, jax.Array):
        lo = seeds.astype(jnp.uint32)
        hi = jnp.zeros_like(lo)
    else:
        s = _np.asarray(seeds, dtype=_np.uint64)
        lo = jnp.asarray((s & _np.uint64(0xFFFFFFFF)).astype(_np.uint32))
        hi = jnp.asarray((s >> _np.uint64(32)).astype(_np.uint32))
    if idx is None:
        idx = jnp.arange(lo.shape[0], dtype=jnp.uint32)
    else:
        idx = jnp.asarray(idx).astype(jnp.uint32)
    return jax.vmap(lambda l, h, i: jax.random.fold_in(
        jax.random.fold_in(jax.random.PRNGKey(l), h), i))(lo, hi, idx)


def make_noise_fn(keys: jax.Array) -> Callable[[jax.Array, Tuple[int, ...]], jax.Array]:
    """Per-sample step-noise generator: ``noise(step, shape)`` returns
    ``[B, *shape]`` with each sample drawn from ``fold_in(keys[b], step)``."""
    def noise(step: jax.Array, sample_shape: Tuple[int, ...]) -> jax.Array:
        def one(k):
            return jax.random.normal(jax.random.fold_in(k, step), sample_shape)
        return jax.vmap(one)(keys)
    return noise


def make_noise_fn_rowwise(keys: jax.Array) -> Callable:
    """Row-wise variant of :func:`make_noise_fn` for the continuous-
    batching step executor: ``steps`` is a PER-SAMPLE ``[B]`` vector (a
    padded batch's slots sit at different iteration indices), each row
    drawing from ``fold_in(keys[b], steps[b])``.  With a broadcast
    scalar step this is bit-identical to ``make_noise_fn`` — the same
    fold-in, vmapped over the same keys."""
    def noise(steps: jax.Array, sample_shape: Tuple[int, ...]) -> jax.Array:
        def one(k, st):
            return jax.random.normal(jax.random.fold_in(k, st),
                                     sample_shape)
        return jax.vmap(one)(keys, jnp.broadcast_to(
            jnp.asarray(steps), (keys.shape[0],)))
    return noise


def _broadcast_sigma(sigma: jax.Array, x: jax.Array) -> jax.Array:
    return jnp.reshape(sigma, (-1,) + (1,) * (x.ndim - 1))


def _ancestral_sigmas(sigma: jax.Array, sigma_next: jax.Array,
                      eta: float = 1.0) -> Tuple[jax.Array, jax.Array]:
    """sigma_down/sigma_up split for ancestral samplers."""
    sigma_up = jnp.minimum(
        sigma_next,
        eta * jnp.sqrt(jnp.maximum(
            sigma_next ** 2 * (sigma ** 2 - sigma_next ** 2)
            / jnp.maximum(sigma ** 2, 1e-20), 0.0)))
    sigma_down = jnp.sqrt(jnp.maximum(sigma_next ** 2 - sigma_up ** 2, 0.0))
    return sigma_down, sigma_up


def _to_d(x: jax.Array, sigma: jax.Array, denoised: jax.Array) -> jax.Array:
    return (x - denoised) / jnp.maximum(sigma, 1e-20)


def _interrupt_stop(operand) -> jax.Array:
    """Traced poll of the process-global interrupt flag.

    io_callback, not pure_callback: the poll reads mutable host state,
    and an effectful callback can't be CSE'd/elided when the operand
    repeats (it does once interrupted — the carry goes constant).
    Ordering comes from the data-derived ``operand``, so ordered=False
    keeps it compatible with sharded (SPMD) sampling.  The ONE copy of
    this subtle idiom — the scan body and uni_pc's priming call both use
    it."""
    import numpy as _np

    from jax.experimental import io_callback

    from comfyui_distributed_tpu.runtime import interrupt as itr
    return io_callback(itr.poll, jax.ShapeDtypeStruct((), _np.bool_),
                       operand)


def _scan_sampler(step_fn, x, sigmas, carry_init=None):
    """Run ``step_fn`` over consecutive sigma pairs with lax.scan.

    Memory contract (buffer donation): ``x`` rides the scan as the carry,
    and the registry jits the enclosing denoise loop with the latent
    argument donated (``registry.sample``: ``donate_argnums`` on the
    core) — XLA aliases the carry onto the caller's input buffer, so the
    loop holds ONE latent-sized buffer per carry slot instead of
    input + carry.  Samplers must keep the latent flowing THROUGH the
    carry (never closing over ``x`` from an outer scope) or the aliasing
    breaks and peak memory doubles; history slots (``carry_init``) are
    extra buffers by design (multistep samplers need them).

    Per-step interrupt (reference parity with ComfyUI's in-sampler
    interrupt): each iteration polls the process-global flag
    (:mod:`comfyui_distributed_tpu.runtime.interrupt`) via a host callback
    and, once set, skips the model call — the scan still runs its remaining
    (now trivial) iterations and returns the partially-denoised latent.
    The poll's operand is a carry-derived scalar purely to sequence the
    callback after the previous step."""
    from comfyui_distributed_tpu.runtime import interrupt as itr

    pairs = jnp.stack([sigmas[:-1], sigmas[1:]], axis=1)
    steps = jnp.arange(pairs.shape[0])
    poll = itr.polling_enabled()

    def body(carry, inp):
        step, (s, s_next) = inp
        if not poll:
            return step_fn(carry, step, s, s_next)
        stop = _interrupt_stop(carry[0].reshape(-1)[0])
        new_carry = jax.lax.cond(
            stop,
            lambda c: c,
            lambda c: step_fn(c, step, s, s_next)[0],
            carry)
        return new_carry, None

    carry = (x, carry_init) if carry_init is not None else (x, None)
    (x_final, _), _ = jax.lax.scan(body, carry, (steps, pairs))
    return x_final


# --- extracted single-step callables (continuous batching) -------------------
#
# The step-granular batch executor (workflow/batch_executor.py) advances
# a padded batch ONE sigma pair at a time, with every slot at its own
# iteration index — so these samplers' per-step math is extracted into
# standalone ``<name>_step(model, x, sigma, sigma_next, step_i, keys)``
# callables that accept PER-SAMPLE ``[B]`` sigma/step vectors (scalars
# still work: ``_broadcast_sigma`` reshapes either form identically).
# The scan samplers below are expressed THROUGH these callables, so the
# serial loop and the continuous-batching loop execute literally the
# same per-step expressions — the bit-exactness guarantee is structural,
# not a parallel implementation kept in sync by hand.  Only samplers
# whose step is stateless across iterations (no multistep history
# carry) are extracted; SAMPLER_STEPS is the executor's whitelist.

def euler_step(model: Model, x: jax.Array, sigma: jax.Array,
               sigma_next: jax.Array, step_i: jax.Array = 0,
               keys: Optional[jax.Array] = None,
               extra_args: Optional[Dict[str, Any]] = None) -> jax.Array:
    """One Euler (== deterministic DDIM) step; ``keys``/``step_i`` are
    accepted for signature uniformity and unused (no step noise)."""
    extra = extra_args or {}
    denoised = model(x, sigma, **extra)
    d = _to_d(x, _broadcast_sigma(jnp.asarray(sigma, jnp.float32), x),
              denoised)
    return x + d * _broadcast_sigma(
        jnp.asarray(sigma_next, jnp.float32)
        - jnp.asarray(sigma, jnp.float32), x)


def euler_ancestral_step(model: Model, x: jax.Array, sigma: jax.Array,
                         sigma_next: jax.Array, step_i: jax.Array,
                         keys: jax.Array,
                         extra_args: Optional[Dict[str, Any]] = None,
                         eta: float = 1.0) -> jax.Array:
    """One ancestral Euler step: deterministic move to sigma_down, then
    per-sample ``fold_in(keys[b], step_i[b])`` noise at sigma_up."""
    extra = extra_args or {}
    s = jnp.asarray(sigma, jnp.float32)
    s_next = jnp.asarray(sigma_next, jnp.float32)
    denoised = model(x, s, **extra)
    sd, su = _ancestral_sigmas(s, s_next, eta)
    d = _to_d(x, _broadcast_sigma(s, x), denoised)
    x = x + d * _broadcast_sigma(sd - s, x)
    noise = make_noise_fn_rowwise(keys)(step_i, x.shape[1:])
    return x + noise * _broadcast_sigma(su, x)


# sampler name -> extracted step callable; THE eligibility surface for
# the continuous-batching executor (constants.CB_SAFE_SAMPLERS mirrors
# the keys so the registry-drift story stays in one obvious place)
SAMPLER_STEPS: Dict[str, Callable] = {
    "euler": euler_step,
    "ddim": euler_step,
    "euler_ancestral": euler_ancestral_step,
}


def get_sampler_step(name: str) -> Callable:
    if name not in SAMPLER_STEPS:
        raise ValueError(
            f"sampler {name!r} has no extracted step callable; "
            f"continuous batching supports: {sorted(SAMPLER_STEPS)}")
    return SAMPLER_STEPS[name]


# --- samplers ---------------------------------------------------------------

def sample_euler(model: Model, x: jax.Array, sigmas: jax.Array,
                 extra_args: Optional[Dict[str, Any]] = None,
                 keys: Optional[jax.Array] = None) -> jax.Array:
    """Euler (= DDIM with eta=0 in this parameterization: the update
    ``x0 + s_next * (x - x0)/s`` is exactly the deterministic DDIM step)."""
    extra = extra_args or {}

    def step(carry, step_i, s, s_next):
        x, _ = carry
        x = euler_step(model, x, s, s_next, step_i, keys,
                       extra_args=extra)
        return (x, None), None

    return _scan_sampler(step, x, sigmas)


sample_ddim = sample_euler  # deterministic DDIM == euler in sigma space


def _last_uncond(model: Model, denoised: jax.Array) -> jax.Array:
    """CFG++ side-channel: the cfg denoiser stashes its uncond denoised
    on itself each call (a traced value read back within the same trace
    step); a bare model (no CFG wrapper) falls back to the denoised."""
    return getattr(model, "last_uncond", denoised)


def sample_euler_cfg_pp(model: Model, x: jax.Array, sigmas: jax.Array,
                        extra_args: Optional[Dict[str, Any]] = None,
                        keys: Optional[jax.Array] = None) -> jax.Array:
    """Euler CFG++ (the reference's euler_cfg_pp): the step direction
    comes from the UNCOND denoised while the anchor is the CFG result —
    ``x' = denoised + sigma_next * (x - uncond_denoised) / sigma``."""
    extra = extra_args or {}

    def step(carry, step_i, s, s_next):
        x, _ = carry
        denoised = model(x, s, **extra)
        d = _to_d(x, s, _last_uncond(model, denoised))
        x = denoised + d * s_next
        return (x, None), None

    return _scan_sampler(step, x, sigmas)


def sample_euler_ancestral_cfg_pp(
        model: Model, x: jax.Array, sigmas: jax.Array,
        extra_args: Optional[Dict[str, Any]] = None,
        keys: Optional[jax.Array] = None,
        eta: float = 1.0) -> jax.Array:
    """Ancestral Euler CFG++ (euler_ancestral_cfg_pp)."""
    extra = extra_args or {}
    if keys is None:
        raise ValueError("euler_ancestral_cfg_pp requires per-sample "
                         "keys")
    noise_fn = make_noise_fn(keys)
    sample_shape = x.shape[1:]

    def step(carry, step_i, s, s_next):
        x, _ = carry
        denoised = model(x, s, **extra)
        sd, su = _ancestral_sigmas(s, s_next, eta)
        d = _to_d(x, s, _last_uncond(model, denoised))
        x = denoised + d * sd
        x = x + noise_fn(step_i, sample_shape) * su
        return (x, None), None

    return _scan_sampler(step, x, sigmas)


def sample_euler_ancestral(model: Model, x: jax.Array, sigmas: jax.Array,
                           extra_args: Optional[Dict[str, Any]] = None,
                           keys: Optional[jax.Array] = None,
                           eta: float = 1.0) -> jax.Array:
    extra = extra_args or {}
    if keys is None:
        raise ValueError("euler_ancestral requires per-sample keys")

    def step(carry, step_i, s, s_next):
        x, _ = carry
        x = euler_ancestral_step(model, x, s, s_next, step_i, keys,
                                 extra_args=extra, eta=eta)
        return (x, None), None

    return _scan_sampler(step, x, sigmas)


def sample_heun(model: Model, x: jax.Array, sigmas: jax.Array,
                extra_args: Optional[Dict[str, Any]] = None,
                keys: Optional[jax.Array] = None) -> jax.Array:
    extra = extra_args or {}

    def step(carry, step_i, s, s_next):
        x, _ = carry
        denoised = model(x, s, **extra)
        d = _to_d(x, s, denoised)
        x_euler = x + d * (s_next - s)

        def heun_branch(_):
            denoised2 = model(x_euler, s_next, **extra)
            d2 = _to_d(x_euler, s_next, denoised2)
            return x + (d + d2) / 2 * (s_next - s)

        x = jax.lax.cond(s_next > 0, heun_branch, lambda _: x_euler, None)
        return (x, None), None

    return _scan_sampler(step, x, sigmas)


def sample_dpm_2(model: Model, x: jax.Array, sigmas: jax.Array,
                 extra_args: Optional[Dict[str, Any]] = None,
                 keys: Optional[jax.Array] = None) -> jax.Array:
    """DPM-Solver-2 (midpoint in log-sigma)."""
    extra = extra_args or {}

    def step(carry, step_i, s, s_next):
        x, _ = carry
        denoised = model(x, s, **extra)
        d = _to_d(x, s, denoised)

        def mid_branch(_):
            s_mid = jnp.exp((jnp.log(s) + jnp.log(jnp.maximum(s_next, 1e-20))) / 2)
            x_mid = x + d * (s_mid - s)
            denoised2 = model(x_mid, s_mid, **extra)
            d2 = _to_d(x_mid, s_mid, denoised2)
            return x + d2 * (s_next - s)

        x = jax.lax.cond(s_next > 0, mid_branch,
                         lambda _: x + d * (s_next - s), None)
        return (x, None), None

    return _scan_sampler(step, x, sigmas)


def sample_dpm_2_ancestral(model: Model, x: jax.Array, sigmas: jax.Array,
                           extra_args: Optional[Dict[str, Any]] = None,
                           keys: Optional[jax.Array] = None,
                           eta: float = 1.0) -> jax.Array:
    extra = extra_args or {}
    if keys is None:
        raise ValueError("dpm_2_ancestral requires per-sample keys")
    noise_fn = make_noise_fn(keys)
    sample_shape = x.shape[1:]

    def step(carry, step_i, s, s_next):
        x, _ = carry
        denoised = model(x, s, **extra)
        sd, su = _ancestral_sigmas(s, s_next, eta)
        d = _to_d(x, s, denoised)

        def mid_branch(_):
            s_mid = jnp.exp((jnp.log(s) + jnp.log(jnp.maximum(sd, 1e-20))) / 2)
            x_mid = x + d * (s_mid - s)
            denoised2 = model(x_mid, s_mid, **extra)
            d2 = _to_d(x_mid, s_mid, denoised2)
            x2 = x + d2 * (sd - s)
            return x2 + noise_fn(step_i, sample_shape) * su

        x = jax.lax.cond(sd > 0, mid_branch,
                         lambda _: x + d * (s_next - s), None)
        return (x, None), None

    return _scan_sampler(step, x, sigmas)


def sample_dpmpp_2s_ancestral(model: Model, x: jax.Array, sigmas: jax.Array,
                              extra_args: Optional[Dict[str, Any]] = None,
                              keys: Optional[jax.Array] = None,
                              eta: float = 1.0) -> jax.Array:
    """DPM-Solver++(2S) ancestral."""
    extra = extra_args or {}
    if keys is None:
        raise ValueError("dpmpp_2s_ancestral requires per-sample keys")
    noise_fn = make_noise_fn(keys)
    sample_shape = x.shape[1:]

    def t_of(s):
        return -jnp.log(jnp.maximum(s, 1e-20))

    def s_of(t):
        return jnp.exp(-t)

    def step(carry, step_i, s, s_next):
        x, _ = carry
        denoised = model(x, s, **extra)
        sd, su = _ancestral_sigmas(s, s_next, eta)

        def solver_branch(_):
            t, t_next = t_of(s), t_of(sd)
            r = 1 / 2
            h = t_next - t
            s_mid = s_of(t + r * h)
            x_2 = (s_mid / s) * x - jnp.expm1(-h * r) * denoised
            denoised_2 = model(x_2, s_mid, **extra)
            x_out = (sd / s) * x - jnp.expm1(-h) * denoised_2
            return x_out + noise_fn(step_i, sample_shape) * su

        def euler_branch(_):
            d = _to_d(x, s, denoised)
            return x + d * (s_next - s)

        x = jax.lax.cond(sd > 0, solver_branch, euler_branch, None)
        return (x, None), None

    return _scan_sampler(step, x, sigmas)


def sample_dpmpp_2m(model: Model, x: jax.Array, sigmas: jax.Array,
                    extra_args: Optional[Dict[str, Any]] = None,
                    keys: Optional[jax.Array] = None) -> jax.Array:
    """DPM-Solver++(2M): multistep, carries the previous denoised."""
    extra = extra_args or {}
    n = sigmas.shape[0] - 1
    sig = sigmas

    def t_of(s):
        return -jnp.log(jnp.maximum(s, 1e-20))

    def step(carry, step_i, s, s_next):
        x, old_denoised = carry
        denoised = model(x, s, **extra)
        t, t_next = t_of(s), t_of(jnp.maximum(s_next, 1e-20))
        h = t_next - t
        s_prev = sig[jnp.maximum(step_i - 1, 0)]
        h_last = t_of(s) - t_of(s_prev)

        def multistep(_):
            r = h_last / h
            denoised_d = (1 + 1 / (2 * r)) * denoised - (1 / (2 * r)) * old_denoised
            return denoised_d

        use_ms = jnp.logical_and(step_i > 0, s_next > 0)
        denoised_d = jax.lax.cond(use_ms, multistep, lambda _: denoised, None)
        x_new = (jnp.maximum(s_next, 0.0) / s) * x - jnp.expm1(-h) * denoised_d
        x = jnp.where(s_next > 0, x_new, denoised_d)
        return (x, denoised), None

    return _scan_sampler(step, x, sigmas, carry_init=jnp.zeros_like(x))


def sample_dpmpp_2m_sde(model: Model, x: jax.Array, sigmas: jax.Array,
                        extra_args: Optional[Dict[str, Any]] = None,
                        keys: Optional[jax.Array] = None,
                        eta: float = 1.0) -> jax.Array:
    """DPM-Solver++(2M) SDE, midpoint noise schedule."""
    extra = extra_args or {}
    if keys is None:
        raise ValueError("dpmpp_2m_sde requires per-sample keys")
    noise_fn = make_noise_fn(keys)
    sample_shape = x.shape[1:]
    sig = sigmas
    n = sigmas.shape[0] - 1

    def step(carry, step_i, s, s_next):
        x, (old_denoised, h_last) = carry
        denoised = model(x, s, **extra)

        def final(_):
            return denoised, (denoised, h_last)

        def sde_step(_):
            t, t_next = -jnp.log(s), -jnp.log(s_next)
            h = t_next - t
            x_out = (s_next / s) * jnp.exp(-h * eta) * x \
                + (-jnp.expm1(-h * (1 + eta))) * denoised

            def with_ms(xo):
                # 'midpoint' solver variant — ComfyUI's default for this
                # sampler name (heun variant differs numerically)
                r = h_last / h
                xo = xo + 0.5 * (-jnp.expm1(-h * (1 + eta))) \
                    * (1 / r) * (denoised - old_denoised)
                return xo

            x_out = jax.lax.cond(step_i > 0, with_ms, lambda xo: xo, x_out)
            noise_amt = s_next * jnp.sqrt(jnp.maximum(-jnp.expm1(-2 * eta * h), 0.0))
            x_out = x_out + noise_fn(step_i, sample_shape) * noise_amt
            return x_out, (denoised, h)

        x, new_carry = jax.lax.cond(s_next > 0, sde_step, final, None)
        return (x, new_carry), None

    return _scan_sampler(
        step, x, sigmas,
        carry_init=(jnp.zeros_like(x), jnp.asarray(1.0, x.dtype)))


def sample_dpmpp_sde(model: Model, x: jax.Array, sigmas: jax.Array,
                     extra_args: Optional[Dict[str, Any]] = None,
                     keys: Optional[jax.Array] = None,
                     eta: float = 1.0, r: float = 1.0 / 2) -> jax.Array:
    """DPM-Solver++ (stochastic): 2S with an ancestral noise split at BOTH
    the midpoint and the full step (two model calls, two independent noise
    draws per step; the per-sample streams use fold-ins 2i / 2i+1)."""
    extra = extra_args or {}
    if keys is None:
        raise ValueError("dpmpp_sde requires per-sample keys")
    noise_fn = make_noise_fn(keys)
    sample_shape = x.shape[1:]
    fac = 1.0 / (2.0 * r)

    def step(carry, step_i, s, s_next):
        x, _ = carry
        denoised = model(x, s, **extra)

        def euler_branch(_):
            d = _to_d(x, s, denoised)
            return x + d * (s_next - s)

        def sde_branch(_):
            t = -jnp.log(s)
            h = -jnp.log(jnp.maximum(s_next, 1e-20)) - t
            s_mid = jnp.exp(-(t + h * r))
            # step 1: to the midpoint, ancestral split s -> s_mid.
            # exp(t - t_of(sd)) = sd/s, so the k-diffusion update
            # (sd/s)*x - expm1(log(sd/s))*denoised reduces to the
            # interpolation below
            sd1, su1 = _ancestral_sigmas(s, s_mid, eta)
            x_2 = (sd1 / s) * (x - denoised) + denoised
            x_2 = x_2 + noise_fn(step_i * 2, sample_shape) * su1
            denoised_2 = model(x_2, s_mid, **extra)
            # step 2: full step with the blended denoised
            sd2, su2 = _ancestral_sigmas(s, s_next, eta)
            denoised_d = (1 - fac) * denoised + fac * denoised_2
            x_out = (sd2 / s) * (x - denoised_d) + denoised_d
            return x_out + noise_fn(step_i * 2 + 1, sample_shape) * su2

        x = jax.lax.cond(s_next > 0, sde_branch, euler_branch, None)
        return (x, None), None

    return _scan_sampler(step, x, sigmas)


def sample_dpmpp_3m_sde(model: Model, x: jax.Array, sigmas: jax.Array,
                        extra_args: Optional[Dict[str, Any]] = None,
                        keys: Optional[jax.Array] = None,
                        eta: float = 1.0) -> jax.Array:
    """DPM-Solver++(3M) SDE: multistep, carries the TWO previous denoiseds
    and step sizes; order ramps 1 -> 2 -> 3 over the first steps."""
    extra = extra_args or {}
    if keys is None:
        raise ValueError("dpmpp_3m_sde requires per-sample keys")
    noise_fn = make_noise_fn(keys)
    sample_shape = x.shape[1:]

    def step(carry, step_i, s, s_next):
        x, (den_1, den_2, h_1, h_2) = carry
        denoised = model(x, s, **extra)

        def final(_):
            return denoised, (den_1, den_2, h_1, h_2)

        def sde_step(_):
            h = -jnp.log(s_next) + jnp.log(s)
            h_eta = h * (eta + 1.0)
            x_out = jnp.exp(-h_eta) * x - jnp.expm1(-h_eta) * denoised
            phi_2 = jnp.expm1(-h_eta) / h_eta + 1.0

            def order1(_):
                return x_out

            def order2(_):
                rr = h_1 / h
                d = (denoised - den_1) / rr
                return x_out + phi_2 * d

            def order3(_):
                r0, r1 = h_1 / h, h_2 / h
                d1_0 = (denoised - den_1) / r0
                d1_1 = (den_1 - den_2) / r1
                d1 = d1_0 + (d1_0 - d1_1) * r0 / (r0 + r1)
                d2 = (d1_0 - d1_1) / (r0 + r1)
                phi_3 = phi_2 / h_eta - 0.5
                return x_out + phi_2 * d1 - phi_3 * d2

            x_out = jax.lax.switch(jnp.minimum(step_i, 2),
                                   [order1, order2, order3], None)
            if eta:
                amt = s_next * jnp.sqrt(
                    jnp.maximum(-jnp.expm1(-2.0 * h * eta), 0.0))
                x_out = x_out + noise_fn(step_i, sample_shape) * amt
            return x_out, (denoised, den_1, h, h_1)

        x, new_carry = jax.lax.cond(s_next > 0, sde_step, final, None)
        return (x, new_carry), None

    zero = jnp.zeros_like(x)
    one = jnp.asarray(1.0, x.dtype)
    return _scan_sampler(step, x, sigmas,
                         carry_init=(zero, zero, one, one))


# 4-point Gauss-Legendre on [-1, 1]: exact for polynomials to degree 7 —
# the LMS coefficient integrand is degree <= 3, so the quadrature is exact
# (matching k-diffusion's adaptive quad without host-side scipy, which
# cannot run under jit where sigmas are traced)
_GL4_NODES = (-0.8611363115940526, -0.3399810435848563,
              0.3399810435848563, 0.8611363115940526)
_GL4_WEIGHTS = (0.3478548451374538, 0.6521451548625461,
                0.6521451548625461, 0.3478548451374538)


def _lms_coeff(order: int, sig_hist, s, s_next):
    """∫_{s}^{s_next} Π_{k≠j} (τ - σ[i-k])/(σ[i-j] - σ[i-k]) dτ for each j
    in range(order).  ``sig_hist[k]`` = σ[i-k] (k = 0..order-1)."""
    half = (s_next - s) / 2.0
    mid = (s_next + s) / 2.0
    coeffs = []
    for j in range(order):
        total = 0.0
        for node, w in zip(_GL4_NODES, _GL4_WEIGHTS):
            tau = mid + half * node
            prod = 1.0
            for k in range(order):
                if k == j:
                    continue
                prod = prod * (tau - sig_hist[k]) \
                    / (sig_hist[j] - sig_hist[k])
            total = total + w * prod
        coeffs.append(half * total)
    return coeffs


def sample_lms(model: Model, x: jax.Array, sigmas: jax.Array,
               extra_args: Optional[Dict[str, Any]] = None,
               keys: Optional[jax.Array] = None,
               order: int = 4) -> jax.Array:
    """Linear multistep (Adams-Bashforth over the sigma axis): carries a
    ring of the last ``order`` derivative estimates; the Lagrange-basis
    integrals are computed in-graph by exact Gauss-Legendre quadrature."""
    extra = extra_args or {}
    sig = sigmas
    order = max(1, min(int(order), 4))

    def step(carry, step_i, s, s_next):
        x, d_hist = carry                      # d_hist[k] = d at step i-k
        denoised = model(x, s, **extra)
        d = _to_d(x, s, denoised)
        # shift the ring: newest first
        d_hist = jnp.concatenate([d[None], d_hist[:-1]], axis=0)
        sig_hist = [sig[jnp.maximum(step_i - k, 0)] for k in range(order)]

        def make_branch(cur_order):
            def branch(_):
                cs = _lms_coeff(cur_order, sig_hist[:cur_order], s, s_next)
                upd = x
                for j in range(cur_order):
                    upd = upd + cs[j] * d_hist[j]
                return upd
            return branch

        branches = [make_branch(o + 1) for o in range(order)]
        x = jax.lax.switch(jnp.minimum(step_i, order - 1), branches, None)
        return (x, d_hist), None

    d0 = jnp.zeros((order,) + x.shape, x.dtype)
    return _scan_sampler(step, x, sigmas, carry_init=d0)


def _unipc_rb(order: int, h: jax.Array, lam0, lam_hist, variant: str):
    """UniPC's R matrix / b vector (x0-prediction, so ``hh = -h``) and the
    r_k ratios for the D1 differences.  ``lam_hist[k]`` = lambda k steps
    back (k >= 1).  Returns (rks, b, B_h, h_phi_1)."""
    hh = -h
    h_phi_1 = jnp.expm1(hh)
    B_h = hh if variant == "bh1" else jnp.expm1(hh)
    rks = [(lam_hist[k] - lam0) / h for k in range(1, order)] + [1.0]
    b = []
    h_phi_k = h_phi_1 / hh - 1.0
    factorial_i = 1.0
    for i in range(1, order + 1):
        b.append(h_phi_k * factorial_i / B_h)
        factorial_i *= i + 1
        h_phi_k = h_phi_k / hh - 1.0 / factorial_i
    return rks, b, B_h, h_phi_1


def _make_unipc(variant: str):
    def sample(model: Model, x: jax.Array, sigmas: jax.Array,
               extra_args: Optional[Dict[str, Any]] = None,
               keys: Optional[jax.Array] = None) -> jax.Array:
        """UniPC (unified predictor-corrector, order 3, x0-prediction):
        multistep like dpmpp_2m but each step also CORRECTS using the
        model evaluated at the predicted point — that evaluation is then
        reused as the next step's current output, so the cost stays one
        model call per step (plus one priming call before the scan).
        ``lower_order_final`` semantics: order ramps 1->2->3 at the start
        and back down near the end."""
        extra = extra_args or {}
        sig = sigmas
        n = int(sigmas.shape[0]) - 1

        def lam_at(i):
            return -jnp.log(jnp.maximum(sig[jnp.maximum(i, 0)], 1e-20))

        # priming call under the same interrupt poll as the scan steps
        # (without it, an already-interrupted run would still pay one
        # full model forward before the scan's own polls kick in)
        from comfyui_distributed_tpu.runtime import interrupt as itr
        if itr.polling_enabled():
            stop0 = _interrupt_stop(x.reshape(-1)[0])
            m_init = jax.lax.cond(
                stop0, lambda _: jnp.zeros_like(x),
                lambda _: model(x, sigmas[0], **extra), None)
        else:
            m_init = model(x, sigmas[0], **extra)

        def step(carry, step_i, s, s_next):
            x, (m0, m1, m2) = carry
            lam0 = -jnp.log(s)
            lam_hist = [None, lam_at(step_i - 1), lam_at(step_i - 2)]
            m_hist = [m0, m1, m2]

            def final(_):
                # sigma 0: the corrector-free limit of the reference's
                # last step toward t~0 is exactly x = m0
                return m0, (m0, m0, m1)

            def full(_):
                lam_t = -jnp.log(s_next)
                h = lam_t - lam0

                def order_branch(order):
                    # model-free per-order coefficients: the single model
                    # call happens OUTSIDE the switch (tracing the UNet
                    # in every branch would ~4x the compiled program)
                    def branch(_):
                        rks, b, B_h, h_phi_1 = _unipc_rb(
                            order, h, lam0, lam_hist, variant)
                        d1s = [(m_hist[k] - m0) / rks[k - 1]
                               for k in range(1, order)]
                        x_t_ = (s_next / s) * x - h_phi_1 * m0
                        # predictor (UniP)
                        if order == 1:
                            x_pred = x_t_
                        elif order == 2:
                            # ComfyUI hardcodes rhos_p=[0.5] at order 2
                            x_pred = x_t_ - B_h * (0.5 * d1s[0])
                        else:
                            rr = jnp.stack([
                                jnp.stack([jnp.ones_like(rks[0]),
                                           jnp.ones_like(rks[0])]),
                                jnp.stack([rks[0], rks[1]])])
                            bb = jnp.stack([b[0], b[1]])
                            rhos_p = jnp.linalg.solve(rr, bb)
                            x_pred = x_t_ - B_h * (rhos_p[0] * d1s[0]
                                                   + rhos_p[1] * d1s[1])
                        # corrector coefficients (UniC): x_corr =
                        # x_t_ - B_h*(corr_base + rho_last*(m_t - m0))
                        if order == 1:
                            corr_base = jnp.zeros_like(x)
                            rho_last = jnp.asarray(0.5, x.dtype)
                        else:
                            rows = []
                            for i in range(order):
                                rows.append(jnp.stack(
                                    [jnp.asarray(rk) ** i for rk in rks]))
                            rhos_c = jnp.linalg.solve(jnp.stack(rows),
                                                      jnp.stack(b))
                            corr_base = jnp.zeros_like(x)
                            for k in range(order - 1):
                                corr_base = corr_base + rhos_c[k] * d1s[k]
                            rho_last = rhos_c[-1]
                        return x_pred, x_t_, B_h, corr_base, rho_last
                    return branch

                # order = min(history, 3, steps-left) — the UniPC
                # lower_order_final ramp at both ends
                sel = jnp.minimum(jnp.minimum(step_i + 1, 3),
                                  n - step_i) - 1
                x_pred, x_t_, B_h, corr_base, rho_last = jax.lax.switch(
                    sel, [order_branch(1), order_branch(2),
                          order_branch(3)], None)
                # the ONE model call; the reference skips the corrector
                # (and its evaluation) on the last step of a window that
                # ends above sigma 0
                is_last = step_i == n - 1
                m_t = jax.lax.cond(
                    is_last, lambda _: m0,
                    lambda _: model(x_pred, s_next, **extra), None)
                x_corr = x_t_ - B_h * (corr_base + rho_last * (m_t - m0))
                x_out = jnp.where(is_last, x_pred, x_corr)
                return x_out, (m_t, m0, m1)

            x, new_m = jax.lax.cond(s_next > 0, full, final, None)
            return (x, new_m), None

        zero = jnp.zeros_like(x)
        return _scan_sampler(step, x, sigmas,
                             carry_init=(m_init, zero, zero))

    sample.__name__ = f"sample_uni_pc_{variant}"
    return sample


sample_uni_pc = _make_unipc("bh1")
sample_uni_pc_bh2 = _make_unipc("bh2")


def sample_ddpm(model: Model, x: jax.Array, sigmas: jax.Array,
                extra_args: Optional[Dict[str, Any]] = None,
                keys: Optional[jax.Array] = None) -> jax.Array:
    """Classic DDPM ancestral step in sigma space (ComfyUI's ddpm): the
    posterior-mean update runs in the VP-scaled frame x/sqrt(1+sigma^2),
    rescaled back between steps."""
    extra = extra_args or {}
    if keys is None:
        raise ValueError("ddpm requires per-sample keys")
    noise_fn = make_noise_fn(keys)
    sample_shape = x.shape[1:]

    def step(carry, step_i, s, s_next):
        x, _ = carry
        denoised = model(x, s, **extra)
        eps = _to_d(x, s, denoised)             # noise estimate
        xs = x / jnp.sqrt(1.0 + s ** 2)         # VP-scaled frame
        ac = 1.0 / (s * s + 1.0)                # alpha_cumprod
        ac_prev = 1.0 / (jnp.maximum(s_next, 0.0) ** 2 + 1.0)
        alpha = ac / ac_prev
        mu = jnp.sqrt(1.0 / alpha) * (
            xs - (1.0 - alpha) * eps / jnp.sqrt(1.0 - ac))
        std = jnp.sqrt(jnp.maximum(
            (1.0 - alpha) * (1.0 - ac_prev) / (1.0 - ac), 0.0))
        mu = jnp.where(s_next > 0,
                       mu + noise_fn(step_i, sample_shape) * std, mu)
        x = jnp.where(s_next > 0, mu * jnp.sqrt(1.0 + s_next ** 2), mu)
        return (x, None), None

    return _scan_sampler(step, x, sigmas)


# Adams-Bashforth coefficients for uniform steps, order 1..4 (the
# classic iPNDM table)
_IPNDM_COEFFS = (
    (1.0,),
    (3.0 / 2, -1.0 / 2),
    (23.0 / 12, -16.0 / 12, 5.0 / 12),
    (55.0 / 24, -59.0 / 24, 37.0 / 24, -9.0 / 24),
)


def sample_ipndm(model: Model, x: jax.Array, sigmas: jax.Array,
                 extra_args: Optional[Dict[str, Any]] = None,
                 keys: Optional[jax.Array] = None,
                 max_order: int = 4) -> jax.Array:
    """iPNDM: Adams-Bashforth multistep over the derivative history with
    the classic fixed coefficient table (order ramps 1 -> 4)."""
    extra = extra_args or {}
    max_order = max(1, min(int(max_order), 4))

    def step(carry, step_i, s, s_next):
        x, d_hist = carry                      # d_hist[k] = d at i-1-k
        denoised = model(x, s, **extra)
        d = _to_d(x, s, denoised)
        dt = s_next - s

        def make_branch(order):
            def branch(_):
                cs = _IPNDM_COEFFS[order - 1]
                upd = cs[0] * d
                for k in range(1, order):
                    upd = upd + cs[k] * d_hist[k - 1]
                return x + dt * upd
            return branch

        branches = [make_branch(o + 1) for o in range(max_order)]
        x = jax.lax.switch(jnp.minimum(step_i, max_order - 1), branches,
                           None)
        d_hist = jnp.concatenate([d[None], d_hist[:-1]], axis=0)
        return (x, d_hist), None

    d0 = jnp.zeros((max(max_order - 1, 1),) + x.shape, x.dtype)
    return _scan_sampler(step, x, sigmas, carry_init=d0)


def sample_heunpp2(model: Model, x: jax.Array, sigmas: jax.Array,
                   extra_args: Optional[Dict[str, Any]] = None,
                   keys: Optional[jax.Array] = None) -> jax.Array:
    """Heun++ (MEDS, arXiv:2305.14267 — k-diffusion's heunpp2): Euler on
    the final step, weighted Heun on the second-to-last, and a 3-eval
    weighted combination elsewhere.  Branches select by position in the
    schedule (traced comparisons under lax.cond — no dynamic shapes)."""
    extra = extra_args or {}
    s_end = sigmas[-1]
    s0 = sigmas[0]
    sig_ext = jnp.concatenate([sigmas, sigmas[-1:]])

    def step(carry, step_i, s, s_next):
        x, _ = carry
        s2 = sig_ext[step_i + 2]
        denoised = model(x, s, **extra)
        d = _to_d(x, s, denoised)
        dt = s_next - s
        x_euler = x + d * dt

        def heun_branch(_):
            x_2 = x_euler
            d_2 = _to_d(x_2, s_next, model(x_2, s_next, **extra))
            w = 2.0 * s0
            w2 = s_next / w
            return x + (d * (1.0 - w2) + d_2 * w2) * dt

        def heunpp_branch(_):
            x_2 = x_euler
            d_2 = _to_d(x_2, s_next, model(x_2, s_next, **extra))
            x_3 = x_2 + d_2 * (s2 - s_next)
            d_3 = _to_d(x_3, s2, model(x_3, s2, **extra))
            w = 3.0 * s0
            w2 = s_next / w
            w3 = s2 / w
            return x + (d * (1.0 - w2 - w3) + d_2 * w2 + d_3 * w3) * dt

        x_out = jax.lax.cond(
            s_next == s_end, lambda _: x_euler,
            lambda _: jax.lax.cond(s2 == s_end, heun_branch,
                                   heunpp_branch, None), None)
        return (x_out, None), None

    return _scan_sampler(step, x, sigmas)


def _ab_vs_coeffs(nodes, t_cur, t_next):
    """Variable-step Adams-Bashforth weights: c_j = mean over
    [t_cur, t_next] of the Lagrange basis L_j on ``nodes`` (newest
    first).  2-point Gauss-Legendre is exact for the <=cubic basis, so
    the classic iPNDM-v / DEIS(tab) step-ratio formulas fall out
    without hand-tabulated coefficients (uniform steps reduce to the
    _IPNDM_COEFFS table)."""
    mid = (t_cur + t_next) / 2.0
    half = (t_next - t_cur) / 2.0
    qs = (mid - half / jnp.sqrt(3.0), mid + half / jnp.sqrt(3.0))

    def basis(j, t):
        out = 1.0
        for m, tm in enumerate(nodes):
            if m != j:
                out = out * (t - tm) / (nodes[j] - tm)
        return out

    return [(basis(j, qs[0]) + basis(j, qs[1])) / 2.0
            for j in range(len(nodes))]


def _make_ab_variable(max_order: int):
    """Variable-step multistep sampler over the derivative history —
    the shared core of ipndm_v (order 4) and DEIS 'tab' mode (order 3):
    both integrate the Lagrange interpolation of d = (x - x0)/sigma
    over the sigma step."""
    def sampler(model: Model, x: jax.Array, sigmas: jax.Array,
                extra_args: Optional[Dict[str, Any]] = None,
                keys: Optional[jax.Array] = None) -> jax.Array:
        extra = extra_args or {}

        def step(carry, step_i, s, s_next):
            x, d_hist = carry
            denoised = model(x, s, **extra)
            d = _to_d(x, s, denoised)
            dt = s_next - s

            def make_branch(order):
                def branch(_):
                    nodes = [s] + [
                        sigmas[jnp.maximum(step_i - k, 0)]
                        for k in range(1, order)]
                    cs = _ab_vs_coeffs(nodes, s, s_next)
                    upd = cs[0] * d
                    for k in range(1, order):
                        upd = upd + cs[k] * d_hist[k - 1]
                    return x + dt * upd
                return branch

            branches = [make_branch(o + 1) for o in range(max_order)]
            x = jax.lax.switch(jnp.minimum(step_i, max_order - 1),
                               branches, None)
            d_hist = jnp.concatenate([d[None], d_hist[:-1]], axis=0)
            return (x, d_hist), None

        d0 = jnp.zeros((max(max_order - 1, 1),) + x.shape, x.dtype)
        return _scan_sampler(step, x, sigmas, carry_init=d0)

    return sampler


sample_ipndm_v = _make_ab_variable(4)
sample_deis = _make_ab_variable(3)


def _dpm_eps(model, x, s, extra):
    return _to_d(x, s, model(x, s, **extra))


def _dpm1_step(model, x, t, t_next, extra):
    """DPM-Solver-1 in t = -log sigma (sigma(t) = exp(-t))."""
    h = t_next - t
    eps = _dpm_eps(model, x, jnp.exp(-t), extra)
    return x - jnp.exp(-t_next) * jnp.expm1(h) * eps


def _dpm2_step(model, x, t, t_next, extra, r1=0.5):
    h = t_next - t
    eps = _dpm_eps(model, x, jnp.exp(-t), extra)
    s1 = t + r1 * h
    u1 = x - jnp.exp(-s1) * jnp.expm1(r1 * h) * eps
    eps_r1 = _dpm_eps(model, u1, jnp.exp(-s1), extra)
    return (x - jnp.exp(-t_next) * jnp.expm1(h) * eps
            - jnp.exp(-t_next) / (2.0 * r1) * jnp.expm1(h)
            * (eps_r1 - eps))


def _dpm3_step(model, x, t, t_next, extra, r1=1.0 / 3, r2=2.0 / 3):
    h = t_next - t
    eps = _dpm_eps(model, x, jnp.exp(-t), extra)
    s1 = t + r1 * h
    s2 = t + r2 * h
    u1 = x - jnp.exp(-s1) * jnp.expm1(r1 * h) * eps
    eps_r1 = _dpm_eps(model, u1, jnp.exp(-s1), extra)
    u2 = (x - jnp.exp(-s2) * jnp.expm1(r2 * h) * eps
          - jnp.exp(-s2) * (r2 / r1)
          * (jnp.expm1(r2 * h) / (r2 * h) - 1.0) * (eps_r1 - eps))
    eps_r2 = _dpm_eps(model, u2, jnp.exp(-s2), extra)
    return (x - jnp.exp(-t_next) * jnp.expm1(h) * eps
            - jnp.exp(-t_next) / r2 * (jnp.expm1(h) / h - 1.0)
            * (eps_r2 - eps))


def sample_dpm_fast(model: Model, x: jax.Array, sigmas: jax.Array,
                    extra_args: Optional[Dict[str, Any]] = None,
                    keys: Optional[jax.Array] = None) -> jax.Array:
    """DPM-Solver fast (k-diffusion): the NFE budget len(sigmas)-1
    splits into third-order solver steps on a uniform t = -log sigma
    grid (orders [3..3, 2, 1] / [3..3, rem]).  The schedule endpoints
    come from the caller's sigmas (sigma_min falls back past a trailing
    0 like ComfyUI's wrapper); the solver places its own grid, so only
    the ENDPOINTS and COUNT of ``sigmas`` matter.  Deterministic; runs
    unrolled (static order list), so no per-step interrupt poll."""
    extra = extra_args or {}
    nfe = int(sigmas.shape[0]) - 1
    if nfe < 1:
        return x
    sig_min = jnp.where(sigmas[-1] > 0, sigmas[-1], sigmas[-2])
    t_start = -jnp.log(sigmas[0])
    t_end = -jnp.log(sig_min)
    m = nfe // 3 + 1
    ts = [t_start + (t_end - t_start) * (i / m) for i in range(m + 1)]
    if nfe % 3 == 0:
        orders = [3] * (m - 2) + [2, 1]
    else:
        orders = [3] * (m - 1) + [nfe % 3]
    steps = {1: _dpm1_step, 2: _dpm2_step, 3: _dpm3_step}
    from comfyui_distributed_tpu.runtime import interrupt as itr
    poll = itr.polling_enabled()
    stop = jnp.asarray(False)
    for i, order in enumerate(orders):
        if poll:
            # same per-step interrupt contract as _scan_sampler, chained
            # through the unrolled solver steps
            stop = jnp.logical_or(stop,
                                  _interrupt_stop(x.reshape(-1)[0]))
            x = jax.lax.cond(
                stop, lambda c: c,
                lambda c, _i=i, _o=order: steps[_o](model, c, ts[_i],
                                                    ts[_i + 1], extra),
                x)
        else:
            x = steps[order](model, x, ts[i], ts[i + 1], extra)
    return x


def sample_dpm_adaptive(model: Model, x: jax.Array, sigmas: jax.Array,
                        extra_args: Optional[Dict[str, Any]] = None,
                        keys: Optional[jax.Array] = None,
                        order: int = 3, rtol: float = 0.05,
                        atol: float = 0.0078, h_init: float = 0.05,
                        pcoeff: float = 0.0, icoeff: float = 1.0,
                        dcoeff: float = 0.0,
                        accept_safety: float = 0.81,
                        max_iters: int = 512) -> jax.Array:
    """DPM-Solver-12/23 adaptive (k-diffusion's dpm_adaptive): embedded
    2nd/3rd-order solver pair in t = -log sigma with a PID step-size
    controller — TPU-shaped as a lax.while_loop (data-dependent trip
    count is the whole point; ``max_iters`` bounds a pathological
    controller).  Only the ENDPOINTS of ``sigmas`` matter; the
    controller places its own steps.  The eps evaluations are shared
    between the embedded orders (3 NFE per attempt, like k-diffusion's
    eps_cache)."""
    extra = extra_args or {}
    if int(sigmas.shape[0]) < 2:
        return x
    sig_min = jnp.where(sigmas[-1] > 0, sigmas[-1], sigmas[-2])
    t_start = -jnp.log(sigmas[0])
    t_end = -jnp.log(sig_min)
    b1 = (pcoeff + icoeff + dcoeff) / order
    b2 = -(pcoeff + 2.0 * dcoeff) / order
    b3 = dcoeff / order
    n_sqrt = float(x.size) ** 0.5
    from comfyui_distributed_tpu.runtime import interrupt as itr
    poll = itr.polling_enabled()

    def cond(carry):
        x_, x_prev, s, h, errs, it, stopped = carry
        return jnp.logical_and(
            jnp.logical_and(s < t_end - 1e-5, it < max_iters),
            jnp.logical_not(stopped))

    def body(carry):
        if poll:
            # per-step interrupt: poll BEFORE the attempt; a set flag
            # ends the loop without paying the 3 model evals
            stopped = _interrupt_stop(carry[0].reshape(-1)[0])
            return jax.lax.cond(
                stopped,
                lambda c: (*c[:6], jnp.asarray(True)),
                _attempt, carry)
        return _attempt(carry)

    def _attempt(carry):
        x_, x_prev, s, h, errs, it, stopped = carry
        t = jnp.minimum(t_end, s + h)
        hh = t - s
        # shared-eps embedded pair (k-diffusion r1=1/3 cache sharing)
        r1, r2 = 1.0 / 3, 2.0 / 3
        eps = _dpm_eps(model, x_, jnp.exp(-s), extra)
        s1 = s + r1 * hh
        s2 = s + r2 * hh
        u1 = x_ - jnp.exp(-s1) * jnp.expm1(r1 * hh) * eps
        eps_r1 = _dpm_eps(model, u1, jnp.exp(-s1), extra)
        x_low = (x_ - jnp.exp(-t) * jnp.expm1(hh) * eps
                 - jnp.exp(-t) / (2.0 * r1) * jnp.expm1(hh)
                 * (eps_r1 - eps))
        u2 = (x_ - jnp.exp(-s2) * jnp.expm1(r2 * hh) * eps
              - jnp.exp(-s2) * (r2 / r1)
              * (jnp.expm1(r2 * hh) / (r2 * hh) - 1.0) * (eps_r1 - eps))
        eps_r2 = _dpm_eps(model, u2, jnp.exp(-s2), extra)
        x_high = (x_ - jnp.exp(-t) * jnp.expm1(hh) * eps
                  - jnp.exp(-t) / r2 * (jnp.expm1(hh) / hh - 1.0)
                  * (eps_r2 - eps))
        # elementwise tolerance (k-diffusion): low-magnitude regions get
        # their own |x|-scaled delta, not the tensor-global max
        delta = jnp.maximum(
            atol, rtol * jnp.maximum(jnp.abs(x_low), jnp.abs(x_prev)))
        error = jnp.sqrt(jnp.sum(((x_low - x_high) / delta) ** 2)) \
            / n_sqrt
        e0 = 1.0 / (1e-8 + error)
        # k-diffusion seeds the whole PID history with the FIRST step's
        # inverse error (errs = [inv_error]*3), so nonzero pcoeff/dcoeff
        # see a neutral history, not a placeholder
        e1 = jnp.where(it == 0, e0, errs[0])
        e2 = jnp.where(it == 0, e0, errs[1])
        factor = e0 ** b1 * e1 ** b2 * e2 ** b3
        factor = 1.0 + jnp.arctan(factor - 1.0)     # k-diffusion limiter
        accept = factor >= accept_safety
        x_new = jnp.where(accept, x_high, x_)
        x_prev_new = jnp.where(accept, x_low, x_prev)
        s_new = jnp.where(accept, t, s)
        # accept shifts the history; reject keeps it (incl. the it==0
        # seeding, which persists either way in k-diffusion)
        errs_new = jnp.where(accept, jnp.stack([e0, e1]),
                             jnp.stack([e1, e2]))
        return (x_new, x_prev_new, s_new, h * factor, errs_new, it + 1,
                stopped)

    errs0 = jnp.full((2,), 1.0 / 1e-8, jnp.float32)
    out = jax.lax.while_loop(
        cond, body, (x, x, t_start, jnp.asarray(h_init, jnp.float32),
                     errs0, jnp.asarray(0, jnp.int32),
                     jnp.asarray(False)))
    return out[0]


def sample_lcm(model: Model, x: jax.Array, sigmas: jax.Array,
               extra_args: Optional[Dict[str, Any]] = None,
               keys: Optional[jax.Array] = None) -> jax.Array:
    """Latent consistency sampling: jump to x0, re-noise to next sigma."""
    extra = extra_args or {}
    if keys is None:
        raise ValueError("lcm requires per-sample keys")
    noise_fn = make_noise_fn(keys)
    sample_shape = x.shape[1:]

    def step(carry, step_i, s, s_next):
        x, _ = carry
        denoised = model(x, s, **extra)
        x = jnp.where(s_next > 0,
                      denoised + noise_fn(step_i, sample_shape) * s_next,
                      denoised)
        return (x, None), None

    return _scan_sampler(step, x, sigmas)


def _phi1(neg_h: jax.Array) -> jax.Array:
    """phi_1(z) = expm1(z)/z, z = -h (h > 0 in the descending-sigma
    half-log-SNR parameterization used by every solver here)."""
    return jnp.expm1(neg_h) / neg_h


def _phi2(neg_h: jax.Array) -> jax.Array:
    """phi_2(z) = (phi_1(z) - 1)/z."""
    return (_phi1(neg_h) - 1.0) / neg_h


def sample_res_multistep(model: Model, x: jax.Array, sigmas: jax.Array,
                         extra_args: Optional[Dict[str, Any]] = None,
                         keys: Optional[jax.Array] = None) -> jax.Array:
    """RES second-order exponential multistep (Refined Exponential
    Solver, arXiv:2308.02157 — the ecosystem's ``res_multistep``),
    deterministic variant: one model call per step, the previous
    denoised extrapolates via phi-weighted Adams-Bashforth coefficients
    (first step falls back to the first-order exponential update).
    One shared body serves all four variants (``_res_multistep_core``)."""
    return _res_multistep_core(model, x, sigmas, extra_args, keys,
                               eta=0.0, cfg_pp=False)


def _res_multistep_core(model: Model, x: jax.Array, sigmas: jax.Array,
                        extra_args: Optional[Dict[str, Any]],
                        keys: Optional[jax.Array], eta: float,
                        cfg_pp: bool) -> jax.Array:
    """Shared RES multistep body: deterministic (eta=0) or ancestral
    (sigma_down/up split + per-step noise), optionally CFG++ (the step's
    exponential decay anchors on the uncond denoised — the same
    ``last_uncond`` side-channel the euler CFG++ samplers read)."""
    extra = extra_args or {}
    if eta > 0 and keys is None:
        raise ValueError("res_multistep_ancestral requires per-sample "
                         "keys")
    noise_fn = make_noise_fn(keys) if eta > 0 else None
    sample_shape = x.shape[1:]
    sig = sigmas

    def step(carry, step_i, s, s_next):
        x, old_denoised = carry
        denoised = model(x, s, **extra)
        anchor = _last_uncond(model, denoised) if cfg_pp else denoised
        sd, su = (_ancestral_sigmas(s, s_next, eta) if eta > 0
                  else (s_next, jnp.asarray(0.0, x.dtype)))
        t = -jnp.log(s)
        t_next = -jnp.log(jnp.maximum(sd, 1e-20))
        h = t_next - t
        t_old = -jnp.log(sig[jnp.maximum(step_i - 1, 0)])
        c2 = jnp.where(step_i > 0, (t_old - t) / h, -1.0)
        b2 = _phi2(-h) / c2
        # first-order part: plain = e^-h x - expm1(-h) D; cfg_pp anchors
        # the exponential decay on the UNCOND (D + e^-h (x - anchor) —
        # euler_cfg_pp's update in exponential form); both reduce to the
        # same thing for a bare model.  The 2nd-order correction
        # h*b2*(D_old - D) is identical algebra either way:
        # h*(b1 D + b2 D_old) == -expm1(-h) D + h b2 (D_old - D).
        base = (denoised + jnp.exp(-h) * (x - anchor)) if cfg_pp \
            else (jnp.exp(-h) * x - jnp.expm1(-h) * denoised)
        x_ms = base + h * b2 * (old_denoised - denoised)
        x_new = jnp.where(step_i > 0, x_ms, base)
        if eta > 0:
            x_new = x_new + noise_fn(step_i, sample_shape) * su
        x = jnp.where(s_next > 0, x_new, denoised)
        return (x, denoised), None

    return _scan_sampler(step, x, sigmas, carry_init=jnp.zeros_like(x))


def sample_res_multistep_cfg_pp(model: Model, x: jax.Array,
                                sigmas: jax.Array,
                                extra_args: Optional[Dict[str, Any]] = None,
                                keys: Optional[jax.Array] = None
                                ) -> jax.Array:
    """res_multistep with the CFG++ anchor (uncond denoised drives the
    exponential decay; reduces to res_multistep for a bare model)."""
    return _res_multistep_core(model, x, sigmas, extra_args, keys,
                               eta=0.0, cfg_pp=True)


def sample_res_multistep_ancestral(model: Model, x: jax.Array,
                                   sigmas: jax.Array,
                                   extra_args: Optional[Dict[str, Any]] = None,
                                   keys: Optional[jax.Array] = None,
                                   eta: float = 1.0) -> jax.Array:
    """Ancestral res_multistep: the multistep update targets sigma_down
    and fresh noise tops back up to sigma_next."""
    return _res_multistep_core(model, x, sigmas, extra_args, keys,
                               eta=eta, cfg_pp=False)


def sample_res_multistep_ancestral_cfg_pp(
        model: Model, x: jax.Array, sigmas: jax.Array,
        extra_args: Optional[Dict[str, Any]] = None,
        keys: Optional[jax.Array] = None, eta: float = 1.0) -> jax.Array:
    """Ancestral res_multistep with the CFG++ anchor."""
    return _res_multistep_core(model, x, sigmas, extra_args, keys,
                               eta=eta, cfg_pp=True)


def sample_dpmpp_2m_cfg_pp(model: Model, x: jax.Array, sigmas: jax.Array,
                           extra_args: Optional[Dict[str, Any]] = None,
                           keys: Optional[jax.Array] = None) -> jax.Array:
    """DPM-Solver++(2M) with the CFG++ anchor: the multistep
    extrapolation uses the CFG denoised, the exponential decay anchors
    on the uncond (``denoised + e^-h * (x - uncond)``) — reduces to
    dpmpp_2m exactly for a bare model."""
    extra = extra_args or {}
    sig = sigmas

    def t_of(s):
        return -jnp.log(jnp.maximum(s, 1e-20))

    def step(carry, step_i, s, s_next):
        x, old_denoised = carry
        denoised = model(x, s, **extra)
        anchor = _last_uncond(model, denoised)
        t, t_next = t_of(s), t_of(jnp.maximum(s_next, 1e-20))
        h = t_next - t
        s_prev = sig[jnp.maximum(step_i - 1, 0)]
        h_last = t_of(s) - t_of(s_prev)

        def ms_term(_):
            r = h_last / h
            return -jnp.expm1(-h) * (1.0 / (2.0 * r)) \
                * (denoised - old_denoised)

        extra_ms = jax.lax.cond(step_i > 0, ms_term,
                                lambda _: jnp.zeros_like(denoised), None)
        # D + e^-h (x - anchor): euler_cfg_pp's exponential-decay-on-
        # uncond form; adding the standard 2M correction term reduces
        # EXACTLY to dpmpp_2m for a bare model (anchor == D):
        # D(1 - e^-h) + e^-h x - expm1(-h)(1/2r)(D - D_old)
        #   == e^-h x - expm1(-h) D_d
        x_new = denoised + jnp.exp(-h) * (x - anchor) + extra_ms
        x = jnp.where(s_next > 0, x_new, denoised)
        return (x, denoised), None

    return _scan_sampler(step, x, sigmas, carry_init=jnp.zeros_like(x))


def sample_gradient_estimation(model: Model, x: jax.Array,
                               sigmas: jax.Array,
                               extra_args: Optional[Dict[str, Any]] = None,
                               keys: Optional[jax.Array] = None,
                               ge_gamma: float = 2.0) -> jax.Array:
    """Gradient-estimation sampler (the ecosystem's
    ``gradient_estimation``): euler steps whose direction extrapolates
    the previous step's, ``d_bar = gamma*d + (1-gamma)*d_old`` — for an
    ideal (constant-x0) denoiser the directions coincide and the
    trajectory equals euler exactly."""
    extra = extra_args or {}

    def step(carry, step_i, s, s_next):
        x, old_d = carry
        denoised = model(x, s, **extra)
        d = _to_d(x, s, denoised)
        d_bar = jnp.where(step_i > 0,
                          ge_gamma * d + (1.0 - ge_gamma) * old_d, d)
        x = x + d_bar * (s_next - s)
        return (x, d), None

    return _scan_sampler(step, x, sigmas, carry_init=jnp.zeros_like(x))


def sample_er_sde(model: Model, x: jax.Array, sigmas: jax.Array,
                  extra_args: Optional[Dict[str, Any]] = None,
                  keys: Optional[jax.Array] = None,
                  s_noise: float = 1.0, max_stage: int = 3) -> jax.Array:
    """Extended Reverse-time SDE solver, VE ER-SDE-Solver-3
    (arXiv:2309.06169 — the ecosystem's ``er_sde``): stage ramps 1->3
    over the first steps; the noise-scale function lambda(sigma) =
    sigma*(exp(sigma^0.3)+10) and its integrals (200-point midpointless
    Riemann sum, static shapes) drive the higher-order corrections."""
    extra = extra_args or {}
    if keys is None:
        raise ValueError("er_sde requires per-sample keys")
    noise_fn = make_noise_fn(keys)
    sample_shape = x.shape[1:]
    sig = sigmas
    n_int = 200

    def scaler(sigma):
        return sigma * (jnp.exp(sigma ** 0.3) + 10.0)

    def step(carry, step_i, s, s_next):
        x, (old_den, old_den_d) = carry
        denoised = model(x, s, **extra)
        r = scaler(jnp.maximum(s_next, 1e-20)) / scaler(s)
        x1 = r * x + (1.0 - r) * denoised
        # stage 2: first divided difference of the denoised
        s_prev = sig[jnp.maximum(step_i - 1, 0)]
        den_d = (denoised - old_den) \
            / jnp.where(step_i > 0, s - s_prev, 1.0)
        dt = s_next - s
        pos = s_next + jnp.arange(n_int, dtype=x.dtype) * (-dt / n_int)
        int1 = jnp.sum(1.0 / scaler(jnp.maximum(pos, 1e-20))) \
            * (-dt / n_int)
        x2 = x1 + (dt + int1 * scaler(jnp.maximum(s_next, 1e-20))) * den_d
        # stage 3: second divided difference
        s_prev2 = sig[jnp.maximum(step_i - 2, 0)]
        den_u = (den_d - old_den_d) \
            / jnp.where(step_i > 1, (s - s_prev2) / 2.0, 1.0)
        int2 = jnp.sum((pos - s) / scaler(jnp.maximum(pos, 1e-20))) \
            * (-dt / n_int)
        x3 = x2 + ((dt ** 2) / 2.0
                   + int2 * scaler(jnp.maximum(s_next, 1e-20))) * den_u
        stage = jnp.minimum(step_i + 1, max_stage)
        x_new = jnp.where(stage >= 3, x3, jnp.where(stage >= 2, x2, x1))
        noise_amt = jnp.sqrt(jnp.maximum(s_next ** 2 - (s * r) ** 2, 0.0))
        x_new = x_new + noise_fn(step_i, sample_shape) * s_noise * noise_amt
        x = jnp.where(s_next > 0, x_new, denoised)
        return (x, (denoised, den_d)), None

    return _scan_sampler(
        step, x, sigmas,
        carry_init=(jnp.zeros_like(x), jnp.zeros_like(x)))


def sample_sa_solver(model: Model, x: jax.Array, sigmas: jax.Array,
                     extra_args: Optional[Dict[str, Any]] = None,
                     keys: Optional[jax.Array] = None) -> jax.Array:
    """SA-Solver (Stochastic Adams, arXiv:2309.05019 — the ecosystem's
    ``sa_solver``), deterministic tau=0 PECE variant at order 2: the
    RES-style Adams-Bashforth predictor takes a trial step, the model
    evaluates AT the target sigma, and the exponential trapezoidal
    Adams-Moulton corrector (weights phi_1 - phi_2 / phi_2) recombines
    — two model calls per step."""
    extra = extra_args or {}
    sig = sigmas

    def step(carry, step_i, s, s_next):
        x, old_denoised = carry
        denoised = model(x, s, **extra)

        def pece(_):
            t = -jnp.log(s)
            t_next = -jnp.log(s_next)
            h = t_next - t
            t_old = -jnp.log(sig[jnp.maximum(step_i - 1, 0)])
            c2 = jnp.where(step_i > 0, (t_old - t) / h, -1.0)
            phi1, phi2 = _phi1(-h), _phi2(-h)
            b2 = phi2 / c2
            b1 = phi1 - b2
            x_pred = jnp.exp(-h) * x \
                + h * (b1 * denoised + b2 * old_denoised)
            x_pred = jnp.where(step_i > 0, x_pred,
                               jnp.exp(-h) * x + h * phi1 * denoised)
            denoised_p = model(x_pred, s_next, **extra)
            return jnp.exp(-h) * x + h * ((phi1 - phi2) * denoised
                                          + phi2 * denoised_p)

        x = jax.lax.cond(s_next > 0, pece, lambda _: denoised, None)
        return (x, denoised), None

    return _scan_sampler(step, x, sigmas, carry_init=jnp.zeros_like(x))


def sample_seeds_2(model: Model, x: jax.Array, sigmas: jax.Array,
                   extra_args: Optional[Dict[str, Any]] = None,
                   keys: Optional[jax.Array] = None,
                   eta: float = 1.0, s_noise: float = 1.0,
                   r: float = 0.5) -> jax.Array:
    """SEEDS-2 (Stochastic Explicit Exponential Derivative-free Solver,
    arXiv:2305.14267 — the ecosystem's ``seeds_2``): 2-stage exponential
    solver in the eta-augmented half-log-SNR time ``h_eta = h*(1+eta)``,
    with Brownian increments coupled across the midpoint and full step
    (independent per-sample fold-ins 2i / 2i+1); eta=0 degenerates to
    the deterministic exponential midpoint method."""
    extra = extra_args or {}
    inject = eta > 0 and s_noise > 0
    if inject and keys is None:
        raise ValueError("seeds_2 requires per-sample keys when eta > 0")
    noise_fn = make_noise_fn(keys) if inject else None
    sample_shape = x.shape[1:]
    fac = 1.0 / (2.0 * r)

    def step(carry, step_i, s, s_next):
        x, _ = carry
        denoised = model(x, s, **extra)

        def solver(_):
            t = -jnp.log(s)
            t_next = -jnp.log(s_next)
            h = t_next - t
            h_eta = h * (eta + 1.0)
            sigma_mid = jnp.exp(-(t + r * h))
            coeff_1 = jnp.expm1(-r * h_eta)
            coeff_2 = jnp.expm1(-h_eta)
            # stage 1: to the midpoint
            x_2 = (coeff_1 + 1.0) * x - coeff_1 * denoised
            if inject:
                nc1 = jnp.sqrt(-jnp.expm1(-2.0 * r * h * eta))
                n1 = noise_fn(step_i * 2, sample_shape)
                x_2 = x_2 + sigma_mid * nc1 * n1 * s_noise
            denoised_2 = model(x_2, sigma_mid, **extra)
            # stage 2: full step with the blended denoised
            denoised_d = (1.0 - fac) * denoised + fac * denoised_2
            x_out = (coeff_2 + 1.0) * x - coeff_2 * denoised_d
            if inject:
                nc2 = jnp.sqrt(jnp.maximum(
                    jnp.expm1(-2.0 * r * h * eta)
                    - jnp.expm1(-2.0 * h * eta), 0.0))
                n2 = noise_fn(step_i * 2 + 1, sample_shape)
                x_out = x_out + s_next * (nc2 * n1 + nc1 * n2) * s_noise
            return x_out

        x = jax.lax.cond(s_next > 0, solver, lambda _: denoised, None)
        return (x, None), None

    return _scan_sampler(step, x, sigmas)


def sample_seeds_3(model: Model, x: jax.Array, sigmas: jax.Array,
                   extra_args: Optional[Dict[str, Any]] = None,
                   keys: Optional[jax.Array] = None,
                   eta: float = 1.0, s_noise: float = 1.0,
                   r_1: float = 1.0 / 3, r_2: float = 2.0 / 3) -> jax.Array:
    """SEEDS-3 (arXiv:2305.14267 — the ecosystem's ``seeds_3``):
    3-stage exponential solver at stage fractions r_1/r_2 of the
    eta-augmented step, noise coupled down the stage chain (fold-ins
    3i, 3i+1, 3i+2); eta=0 degenerates to a deterministic 3-stage
    exponential Runge-Kutta."""
    extra = extra_args or {}
    inject = eta > 0 and s_noise > 0
    if inject and keys is None:
        raise ValueError("seeds_3 requires per-sample keys when eta > 0")
    noise_fn = make_noise_fn(keys) if inject else None
    sample_shape = x.shape[1:]

    def step(carry, step_i, s, s_next):
        x, _ = carry
        denoised = model(x, s, **extra)

        def solver(_):
            t = -jnp.log(s)
            t_next = -jnp.log(s_next)
            h = t_next - t
            h_eta = h * (eta + 1.0)
            sigma_1 = jnp.exp(-(t + r_1 * h))
            sigma_2 = jnp.exp(-(t + r_2 * h))
            coeff_1 = jnp.expm1(-r_1 * h_eta)
            coeff_2 = jnp.expm1(-r_2 * h_eta)
            coeff_3 = jnp.expm1(-h_eta)
            if inject:
                nc1 = jnp.sqrt(-jnp.expm1(-2.0 * r_1 * h * eta))
                nc2 = jnp.sqrt(jnp.maximum(
                    jnp.expm1(-2.0 * r_1 * h * eta)
                    - jnp.expm1(-2.0 * r_2 * h * eta), 0.0))
                nc3 = jnp.sqrt(jnp.maximum(
                    jnp.expm1(-2.0 * r_2 * h * eta)
                    - jnp.expm1(-2.0 * h * eta), 0.0))
                n1 = noise_fn(step_i * 3, sample_shape)
                n2 = noise_fn(step_i * 3 + 1, sample_shape)
                n3 = noise_fn(step_i * 3 + 2, sample_shape)
            # stage 1
            x_2 = (coeff_1 + 1.0) * x - coeff_1 * denoised
            if inject:
                x_2 = x_2 + sigma_1 * nc1 * n1 * s_noise
            denoised_2 = model(x_2, sigma_1, **extra)
            # stage 2
            x_3 = (coeff_2 + 1.0) * x - coeff_2 * denoised \
                + (r_2 / r_1) * (coeff_2 / (r_2 * h_eta) + 1.0) \
                * (denoised_2 - denoised)
            if inject:
                x_3 = x_3 + sigma_2 * (nc2 * n1 + nc1 * n2) * s_noise
            denoised_3 = model(x_3, sigma_2, **extra)
            # stage 3
            x_out = (coeff_3 + 1.0) * x - coeff_3 * denoised \
                + (1.0 / r_2) * (coeff_3 / h_eta + 1.0) \
                * (denoised_3 - denoised)
            if inject:
                x_out = x_out + s_next * (nc3 * n1 + nc2 * n2
                                          + nc1 * n3) * s_noise
            return x_out

        x = jax.lax.cond(s_next > 0, solver, lambda _: denoised, None)
        return (x, None), None

    return _scan_sampler(step, x, sigmas)


SAMPLERS: Dict[str, Callable] = {
    "euler": sample_euler,
    "ddim": sample_ddim,
    "euler_cfg_pp": sample_euler_cfg_pp,
    "euler_ancestral": sample_euler_ancestral,
    "euler_ancestral_cfg_pp": sample_euler_ancestral_cfg_pp,
    "heun": sample_heun,
    "dpm_2": sample_dpm_2,
    "dpm_2_ancestral": sample_dpm_2_ancestral,
    "dpmpp_2s_ancestral": sample_dpmpp_2s_ancestral,
    "dpmpp_sde": sample_dpmpp_sde,
    "dpmpp_2m": sample_dpmpp_2m,
    "dpmpp_2m_sde": sample_dpmpp_2m_sde,
    "dpmpp_3m_sde": sample_dpmpp_3m_sde,
    "lms": sample_lms,
    "ddpm": sample_ddpm,
    "ipndm": sample_ipndm,
    "ipndm_v": sample_ipndm_v,
    "deis": sample_deis,
    "heunpp2": sample_heunpp2,
    "dpm_fast": sample_dpm_fast,
    "dpm_adaptive": sample_dpm_adaptive,
    "lcm": sample_lcm,
    "uni_pc": sample_uni_pc,
    "uni_pc_bh2": sample_uni_pc_bh2,
    "res_multistep": sample_res_multistep,
    "res_multistep_cfg_pp": sample_res_multistep_cfg_pp,
    "res_multistep_ancestral": sample_res_multistep_ancestral,
    "res_multistep_ancestral_cfg_pp": sample_res_multistep_ancestral_cfg_pp,
    "dpmpp_2m_cfg_pp": sample_dpmpp_2m_cfg_pp,
    "gradient_estimation": sample_gradient_estimation,
    "er_sde": sample_er_sde,
    "sa_solver": sample_sa_solver,
    "seeds_2": sample_seeds_2,
    "seeds_3": sample_seeds_3,
}

SAMPLER_NAMES = tuple(SAMPLERS.keys())


def get_sampler(name: str) -> Callable:
    if name not in SAMPLERS:
        raise ValueError(f"unknown sampler {name!r}; available: {SAMPLER_NAMES}")
    return SAMPLERS[name]


def cfg_denoiser(model: Model, cond: Any, uncond: Any,
                 cfg_scale: float) -> Model:
    """Classifier-free guidance wrapper: one doubled-batch model call per step
    (cond rows then uncond rows) so the MXU sees a single large matmul —
    the TPU-friendly layout of what ComfyUI does per-sample."""
    return cfg_denoiser_multi(model, [(cond, None, 1.0)], uncond, cfg_scale)


def _norm_entries(entries):
    """(ctx, mask, strength[, sigma_range]) -> uniform 4-tuples."""
    return [e if len(e) == 4 else (*e, None) for e in entries]


def _mask_blend(entries, parts, sigma):
    """sum_i(w_i * den_i) / max(sum_i(w_i), eps), w_i = strength_i *
    mask_i * active_i(sigma) — the per-entry denoised blend both CFG
    sides use.  ``active_i``: ComfyUI's timestep-range gate (a traced
    elementwise select on the step's sigma; entries outside their range
    contribute nothing that step)."""
    acc = None
    wsum = None
    for (c, m, s, srange), p in zip(entries, parts):
        w = jnp.full((1, 1, 1, 1), float(s), p.dtype) if m is None \
            else jnp.asarray(m, p.dtype) * float(s)
        if srange is not None:
            s_start, s_end = float(srange[0]), float(srange[1])
            sig = jnp.max(jnp.asarray(sigma))
            active = jnp.logical_and(sig <= s_start, sig >= s_end)
            w = w * active.astype(p.dtype)
        term = p * w
        wb = jnp.broadcast_to(w, p.shape[:-1] + (1,))
        acc = term if acc is None else acc + term
        wsum = wb if wsum is None else wsum + wb
    return acc / jnp.maximum(wsum, 1e-9)


def cfg_denoiser_multi(model: Model, conds, uncond: Any,
                       cfg_scale: float,
                       cfg_rescale: float = 0.0) -> Model:
    """Area/mask conditioning (ComfyUI's multi-entry cond lists): every
    entry of BOTH CFG sides is evaluated in ONE stacked model call
    ([cond_1..cond_N, uncond_1..uncond_M] rows — still a single large
    matmul for the MXU), then each side's denoised predictions blend by
    their latent-resolution masks and strengths (``_mask_blend``) before
    the CFG combine.

    ``conds`` (and optionally ``uncond``): list of ``(context [B,T,C],
    mask [.,h,w,1] or None, strength[, sigma_range])``; a plain
    ``uncond`` array is a single unmasked entry.  Masks/strengths/ranges
    are trace-time constants of the compiled program (static shapes, no
    dynamic control flow); a region covered by no mask gets ~zero
    prediction — cover the canvas, like ComfyUI (its uncovered regions
    behave the same way)."""
    conds = _norm_entries(conds)
    unconds = _norm_entries(uncond) if isinstance(uncond, (list, tuple)) \
        else [(uncond, None, 1.0, None)]
    n, nu = len(conds), len(unconds)

    def wrapped(x, sigma, **extra):
        use_uncond = cfg_scale != 1.0
        reps = n + (nu if use_uncond else 0)
        if reps == 1 and conds[0][1] is None and conds[0][3] is None:
            den = model(x, sigma, context=conds[0][0], **extra)
            wrapped.last_uncond = den      # cfg==1: no separate uncond
            return den
        # CFG row-stack: a batch-dim concat whose concat dim picks up a
        # mesh axis hits the same XLA CPU SPMD miscompile as the UNet
        # skip concat (tp-concat-cpu-miscompile) — shd.stack_rows /
        # shd.unstack_rows keep the stack/split seams off shard
        # boundaries (inert without an engaged tensor axis)
        x_rep = shd.stack_rows([x] * reps)
        ctx = shd.stack_rows(
            [c for c, _, _, _ in conds]
            + ([c for c, _, _, _ in unconds] if use_uncond else []))
        # per-sample sigma (continuous batching: a padded batch's slots
        # sit at different sigmas) tiles in lockstep with the CFG-stacked
        # rows; scalar sigma broadcasts exactly as before
        sigma_rep = sigma
        if getattr(sigma, "ndim", 0):
            sigma_rep = shd.stack_rows([jnp.asarray(sigma)] * reps)
        out = model(x_rep, sigma_rep, context=ctx, **extra)
        parts = shd.unstack_rows(out, reps)
        den_cond = _mask_blend(conds, parts[:n], sigma)
        if not use_uncond:
            wrapped.last_uncond = den_cond
            return den_cond
        d_uncond = _mask_blend(unconds, parts[n:], sigma)
        # side-channel for CFG++ samplers: the UNCOND denoised of THIS
        # call (a traced value read back within the same trace step)
        wrapped.last_uncond = d_uncond
        if cfg_rescale:
            return _rescale_cfg(x, sigma, den_cond, d_uncond, cfg_scale,
                                cfg_rescale)
        return d_uncond + (den_cond - d_uncond) * cfg_scale
    return wrapped


def cfg_denoiser_dual(model: Model, cond: jax.Array, middle: jax.Array,
                      uncond: jax.Array, cfg1: float, cfg2: float,
                      cfg_rescale: float = 0.0) -> Model:
    """Dual-CFG guidance (ComfyUI's DualCFGGuider / the InstructPix2Pix
    combine): one tripled-batch model call per step ([cond, middle,
    uncond] rows — still a single large matmul for the MXU), combined as

        result = (uncond + cfg2 * (middle - uncond)) + cfg1 * (cond - middle)

    i.e. the middle conditioning is CFG'd against the negative at
    ``cfg2``, then the positive steers against the middle at ``cfg1`` —
    reference semantics: ComfyUI ``nodes_custom_sampler.Guider_DualCFG``.
    A RescaleCFG patch applies to the middle/negative combine (ComfyUI:
    the sampler_cfg_function rides ``cfg_function`` there)."""
    def wrapped(x, sigma, **extra):
        # seam-safe CFG stack/split (tp-concat-cpu-miscompile; see
        # cfg_denoiser_multi)
        x_rep = shd.stack_rows([x, x, x])
        ctx = shd.stack_rows([cond, middle, uncond])
        out = model(x_rep, sigma, context=ctx, **extra)
        pos, mid, neg = shd.unstack_rows(out, 3)
        wrapped.last_uncond = neg       # CFG++ side-channel
        if cfg_rescale:
            base = _rescale_cfg(x, sigma, mid, neg, cfg2, cfg_rescale)
        else:
            base = neg + (mid - neg) * cfg2
        return base + (pos - mid) * cfg1
    return wrapped


def _gaussian_blur_nhwc(x: jax.Array, ksize: int = 9,
                        sigma: float = 2.0) -> jax.Array:
    """Separable gaussian blur with reflect padding (the SAG reference's
    gaussian_blur_2d), [B, H, W, C]."""
    r = ksize // 2
    xs = jnp.arange(-r, r + 1, dtype=jnp.float32)
    k = jnp.exp(-(xs ** 2) / max(2.0 * sigma * sigma, 1e-8))
    k = (k / k.sum()).astype(x.dtype)
    h = jnp.pad(x, ((0, 0), (r, r), (0, 0), (0, 0)), mode="reflect")
    x = sum(k[i] * h[:, i:i + x.shape[1]] for i in range(ksize))
    h = jnp.pad(x, ((0, 0), (0, 0), (r, r), (0, 0)), mode="reflect")
    return sum(k[i] * h[:, :, i:i + x.shape[2]] for i in range(ksize))


def cfg_denoiser_sag(model_capture: Model, model_plain: Model,
                     cond: jax.Array, uncond: jax.Array,
                     cfg_scale: float, sag_scale: float,
                     blur_sigma: float, mid_hw: tuple,
                     cfg_rescale: float = 0.0) -> Model:
    """Self-Attention Guidance (Hong et al.; the reference ecosystem's
    SelfAttentionGuidance patch): per step, the stacked CFG call also
    captures the mid-block self-attention weights; tokens the UNCOND
    pass attends strongly (mean over heads, summed over queries > 1)
    mark where the uncond denoised image gets gaussian-blurred, the
    degraded latent is re-noised and denoised once more under the
    uncond prompt, and the result steers away from what degradation
    would produce:

        out = cfg(cond, uncond) + sag_scale * (degraded - den_degraded)

    (the reference's post-CFG combine; in eps-space this is the paper's
    s*(eps(x̂) - eps(x)) direction).  3 UNet evals per step, like the
    reference."""
    mh, mw = mid_hw

    def wrapped(x, sigma, **extra):
        B = x.shape[0]
        # seam-safe CFG stack/split (tp-concat-cpu-miscompile; see
        # cfg_denoiser_multi)
        x_rep = shd.stack_rows([x, x])
        ctx = shd.stack_rows([cond, uncond])
        out, probs = model_capture(x_rep, sigma, context=ctx, **extra)
        den_cond, den_unc = shd.unstack_rows(out, 2)
        wrapped.last_uncond = den_unc   # CFG++ side-channel
        # probs [2B, heads, N, N]: uncond rows second; mean over heads,
        # sum over the QUERY axis -> per-key attention mass
        a = probs[B:].mean(axis=1).sum(axis=1)          # [B, N]
        mask = (a > 1.0).astype(x.dtype)
        mask = mask.reshape(B, mh, mw, 1)
        mask = jax.image.resize(mask, (B, x.shape[1], x.shape[2], 1),
                                method="nearest")
        blurred = _gaussian_blur_nhwc(den_unc, 9, float(blur_sigma))
        degraded = blurred * mask + den_unc * (1.0 - mask)
        # re-noise the degraded estimate to the current level and run
        # one more UNCOND denoise on it
        degraded_noised = degraded + x - den_unc
        extra_1 = dict(extra)
        for k2 in ("y", "objs"):    # per-block extras: take the uncond
            if extra_1.get(k2) is not None:     # block's rows
                extra_1[k2] = extra_1[k2][B:2 * B]
        den_sag = model_plain(degraded_noised, sigma, context=uncond,
                              **extra_1)
        if cfg_rescale:
            cfg_out = _rescale_cfg(x, sigma, den_cond, den_unc,
                                   cfg_scale, cfg_rescale)
        else:
            cfg_out = den_unc + (den_cond - den_unc) * cfg_scale
        return cfg_out + (degraded - den_sag) * sag_scale
    return wrapped


def cfg_denoiser_perp_neg(model: Model, cond: jax.Array,
                          empty: jax.Array, uncond: jax.Array,
                          cfg_scale: float, neg_scale: float,
                          cfg_rescale: float = 0.0) -> Model:
    """Perp-Neg guidance (Armandpour et al.; ComfyUI's PerpNeg /
    PerpNegGuider): one tripled-batch call with rows [cond, empty,
    uncond]; the negative's component PERPENDICULAR to the positive
    direction (both relative to the empty prompt) is subtracted at
    ``neg_scale`` — the parallel component, which CFG would misread as
    "less positive", is discarded:

        pos  = den_cond - den_empty
        neg  = den_unc - den_empty
        perp = neg - (<neg, pos>/|pos|^2) pos       (per sample)
        out  = den_empty + cfg * (pos - neg_scale * perp)

    Projections reduce per-SAMPLE (the reference ecosystem's global-sum
    reduction cross-talks a batch; x0-space is equivalent to its
    eps-space math — the shared -sigma factor cancels in the
    projection).  A RescaleCFG patch re-stds the combine toward the
    cond prediction like the plain CFG path."""
    def wrapped(x, sigma, **extra):
        # seam-safe CFG stack/split (tp-concat-cpu-miscompile; see
        # cfg_denoiser_multi)
        x_rep = shd.stack_rows([x, x, x])
        ctx = shd.stack_rows([cond, empty, uncond])
        out = model(x_rep, sigma, context=ctx, **extra)
        den_cond, den_empty, den_unc = shd.unstack_rows(out, 3)
        wrapped.last_uncond = den_unc   # CFG++ side-channel
        pos = den_cond - den_empty
        neg = den_unc - den_empty
        axes = tuple(range(1, x.ndim))
        dot = jnp.sum(neg * pos, axis=axes, keepdims=True)
        sq = jnp.maximum(jnp.sum(pos * pos, axis=axes, keepdims=True),
                         1e-12)
        perp = neg - (dot / sq) * pos
        direction = pos - neg_scale * perp
        if cfg_rescale:
            return _rescale_cfg(x, sigma, den_empty + direction,
                                den_empty, cfg_scale, cfg_rescale)
        return den_empty + cfg_scale * direction
    return wrapped


def _rescale_cfg(x: jax.Array, sigma: jax.Array, den_cond: jax.Array,
                 den_uncond: jax.Array, cfg_scale: float,
                 multiplier: float) -> jax.Array:
    """RescaleCFG (Lin et al., "Common Diffusion Noise Schedules..."):
    re-std the CFG combination toward the cond prediction's statistics in
    v-space, blended by ``multiplier`` — tames the over-saturation of
    high CFG, especially on v-prediction models.  Port of the reference
    ecosystem's RescaleCFG patch (x0 predictions in, x0 out)."""
    s = _broadcast_sigma(jnp.asarray(sigma, x.dtype), x)
    s2 = s * s
    xs = x / (s2 + 1.0)
    root = jnp.sqrt(s2 + 1.0)
    v_cond = (xs - (x - den_cond)) * root / s
    v_unc = (xs - (x - den_uncond)) * root / s
    v_cfg = v_unc + (v_cond - v_unc) * cfg_scale
    axes = tuple(range(1, x.ndim))
    ro_pos = jnp.std(v_cond, axis=axes, keepdims=True)
    ro_cfg = jnp.std(v_cfg, axis=axes, keepdims=True)
    v_res = v_cfg * (ro_pos / jnp.maximum(ro_cfg, 1e-9))
    v_fin = multiplier * v_res + (1.0 - multiplier) * v_cfg
    return x - (xs - v_fin * s / root)
