"""Shared neural blocks (flax.linen, NHWC, bf16-friendly).

TPU-first conventions used across the model zoo:
- channels-last (NHWC) everywhere — XLA's native conv layout on TPU;
- compute dtype bfloat16 by default with fp32 params and fp32 normalization
  statistics (GroupNorm in fp32 to avoid bf16 variance underflow);
- attention shaped as large batched matmuls for the MXU; heads stay a
  separate dim so tensor-parallel sharding can split them.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from comfyui_distributed_tpu.parallel import sharding as shd

Dtype = Any


def timestep_embedding(t: jax.Array, dim: int,
                       max_period: float = 10000.0) -> jax.Array:
    """Sinusoidal timestep embedding (DDPM convention)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    emb = jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
    if dim % 2:
        emb = jnp.concatenate([emb, jnp.zeros_like(emb[:, :1])], axis=-1)
    # pin: time_fc1's kernel layout (input-dim fallback split) must not
    # back-propagate a tensor sharding onto the cos/sin concat dim
    # (tp-concat-cpu-miscompile); the embedding is tiny, replication
    # is free
    return shd.replicate(emb)


class GroupNorm32(nn.Module):
    """GroupNorm computed in fp32 regardless of compute dtype."""
    num_groups: int = 32
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        orig = x.dtype
        groups = min(self.num_groups, x.shape[-1])
        while x.shape[-1] % groups:
            groups -= 1
        out = nn.GroupNorm(num_groups=groups, epsilon=self.epsilon,
                           dtype=jnp.float32)(x.astype(jnp.float32))
        return out.astype(orig)


class Attention(nn.Module):
    """Multi-head attention over flattened tokens.

    Self-attention when ``context`` is None, cross-attention otherwise.
    Shapes: q from ``x [B, N, C]``, k/v from ``context [B, M, Cc]``.
    ``attn_impl`` selects the math: "xla" (fused by the compiler),
    "pallas" (custom flash kernel, ops/pallas/flash_attention.py), or
    "ring" (sequence-parallel over the mesh's ``seq`` axis,
    parallel/ring.py; falls back to "xla" when the sequence is short,
    indivisible, or the mesh has no seq axis — e.g. the 77-token text
    cross-attention).
    """
    num_heads: int
    head_dim: Optional[int] = None
    dtype: Dtype = jnp.bfloat16
    attn_impl: str = "xla"
    # SAG capture: materialize + sow the softmax weights so the sampler
    # can read them back (mutable=["intermediates"]).  Only the UNet
    # mid-block's self-attention sets this — its token count is small,
    # so the explicit [B, H, N, N] weights are cheap
    sow_probs: bool = False

    @nn.compact
    def __call__(self, x: jax.Array,
                 context: Optional[jax.Array] = None,
                 context_v: Optional[jax.Array] = None) -> jax.Array:
        """``context_v``: separate value-side context (hypernetworks
        transform the k and v context streams independently); defaults
        to ``context``."""
        c = x.shape[-1]
        hd = self.head_dim or c // self.num_heads
        inner = hd * self.num_heads
        ctx = x if context is None else context
        ctx_v = ctx if context_v is None else context_v

        q = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_q")(x)
        k = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_k")(ctx)
        v = nn.Dense(inner, use_bias=False, dtype=self.dtype,
                     name="to_v")(ctx_v)

        B, N, _ = q.shape
        M = k.shape[1]
        # megatron head split: q/k/v heads ride the tensor axis (inert on
        # dp-only meshes; see parallel/sharding.py rule table)
        q = shd.constrain(q.reshape(B, N, self.num_heads, hd),
                          "batch", None, "heads", None)
        k = shd.constrain(k.reshape(B, M, self.num_heads, hd),
                          "batch", None, "heads", None)
        v = shd.constrain(v.reshape(B, M, self.num_heads, hd),
                          "batch", None, "heads", None)

        if self.sow_probs:
            logits = jnp.einsum("bnhd,bmhd->bhnm", q, k,
                                preferred_element_type=jnp.float32) \
                * (1.0 / math.sqrt(hd))
            weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
            self.sow("intermediates", "attn_probs", weights)
            out = jnp.einsum("bhnm,bmhd->bnhd", weights.astype(v.dtype),
                             v)
        else:
            out = scaled_dot_product_attention(q, k, v,
                                               impl=self.attn_impl)
        out = out.reshape(B, N, inner)
        return nn.Dense(c, dtype=self.dtype, name="to_out")(out)


def scaled_dot_product_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                                 impl: str = "xla") -> jax.Array:
    """[B, N, H, D] attention. fp32 softmax accumulation."""
    if impl == "ring":
        out = _maybe_ring_attention(q, k, v)
        if out is not None:
            return out
        impl = "xla"
    if impl == "pallas":
        from comfyui_distributed_tpu.ops.pallas.flash_attention import (
            flash_attention)
        return flash_attention(q, k, v)
    return xla_attention(q, k, v, 1.0 / math.sqrt(q.shape[-1]))


def _attn_scores_block(q: jax.Array, k: jax.Array, v: jax.Array,
                       scale: float) -> jax.Array:
    """One materialized-score attention block (einsum -> fp32 softmax ->
    einsum)."""
    logits = jnp.einsum("bnhd,bmhd->bhnm", q, k,
                        preferred_element_type=jnp.float32) * scale
    weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhnm,bmhd->bnhd", weights.astype(v.dtype), v)


def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                  scale: float) -> jax.Array:
    """The reference attention math with a memory ceiling.  The single
    copy both the default impl and the flash kernel's over-VMEM fallback
    use — duplicates would drift.

    The fp32 score tensor is [B, H, N, M]; at SDXL 1024px (N=M=4096)
    with a CFG-stacked batch that is ~10 GB — more than a v5e chip's
    HBM (the r4 on-chip OOM).  Softmax is per-QUERY-row, so scanning
    over query chunks is numerically EXACT (no online rescaling
    needed); each chunk materializes only [B, H, chunk, M].  The chunk
    choice is static (shapes + env), so there is no dynamic control
    flow under jit; ``DTPU_ATTN_SCORES_BYTES`` tunes the ceiling
    (default 512 MB)."""
    import os

    B, N, H, D = q.shape
    M = k.shape[1]
    limit = int(os.environ.get("DTPU_ATTN_SCORES_BYTES",
                               str(512 * 1024 * 1024)))
    if 4 * B * H * N * M <= limit or N <= 128:
        return _attn_scores_block(q, k, v, scale)
    want = max(1, limit // (4 * B * H * M))
    chunk = 1
    for d in range(min(want, N), 0, -1):    # largest divisor of N <= want
        if N % d == 0:
            chunk = d
            break
    n_chunks = N // chunk
    qr = q.reshape(B, n_chunks, chunk, H, D).transpose(1, 0, 2, 3, 4)

    def body(_, qc):
        return None, _attn_scores_block(qc, k, v, scale)

    _, out = jax.lax.scan(body, None, qr)
    return out.transpose(1, 0, 2, 3, 4).reshape(B, N, H, D)


def _maybe_ring_attention(q: jax.Array, k: jax.Array,
                          v: jax.Array) -> Optional[jax.Array]:
    """Ring attention over the runtime mesh's ``seq`` axis when it applies.

    Returns None (-> caller falls back to "xla") when the mesh has no seq
    axis, the token count is below ``DTPU_RING_MIN_TOKENS`` (ring's ICI
    rotation only pays off on long sequences), or either sequence length
    doesn't divide the axis.  All conditions are static shapes/env, so the
    choice is fixed at trace time — no dynamic control flow under jit."""
    import os

    from comfyui_distributed_tpu.parallel.mesh import get_runtime
    from comfyui_distributed_tpu.parallel.ring import ring_attention
    from comfyui_distributed_tpu.utils.constants import SEQ_AXIS

    mesh = get_runtime().mesh
    n = int(mesh.shape.get(SEQ_AXIS, 1))
    min_tokens = int(os.environ.get("DTPU_RING_MIN_TOKENS", "256"))
    if (n <= 1 or q.shape[1] < min_tokens
            or q.shape[1] % n or k.shape[1] % n):
        return None
    return ring_attention(q, k, v, mesh)


class GEGLU(nn.Module):
    dim_out: int
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = nn.Dense(self.dim_out * 2, dtype=self.dtype, name="proj")(x)
        # column-split ffn hidden over the tensor axis (rule table "mlp");
        # the gate/value halves split at dim_out, which is also a shard
        # boundary for any tensor size dividing dim_out
        h = shd.constrain(h, "batch", None, "mlp")
        a, b = jnp.split(h, 2, axis=-1)
        # exact (erf) gelu: torch F.gelu's default, what SD was trained
        # with — flax's default tanh approximation drifts ~1e-3
        return a * nn.gelu(b, approximate=False)


class FeedForward(nn.Module):
    mult: int = 4
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = x.shape[-1]
        h = GEGLU(dim_out=c * self.mult, dtype=self.dtype, name="geglu")(x)
        return nn.Dense(c, dtype=self.dtype, name="out")(h)


class GatedSelfAttention(nn.Module):
    """GLIGEN fuser (GatedSelfAttentionDense): self-attention over
    [visual tokens; grounding tokens] and a FF, each gated by a learned
    tanh(alpha) scalar so an untrained fuser starts as a near-no-op.
    Grounding tokens project from their 768-d space to the block width
    first (the reference layout's ``linear``)."""
    num_heads: int
    dtype: Dtype = jnp.bfloat16
    attn_impl: str = "xla"

    @nn.compact
    def __call__(self, x: jax.Array, objs: jax.Array) -> jax.Array:
        n = x.shape[1]
        o = nn.Dense(x.shape[-1], dtype=self.dtype, name="linear")(objs)
        alpha_attn = self.param("alpha_attn", nn.initializers.zeros, ())
        alpha_dense = self.param("alpha_dense", nn.initializers.zeros,
                                 ())
        h = jnp.concatenate([x, o.astype(x.dtype)], axis=1)
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="norm1")(h)
        att = Attention(self.num_heads, dtype=self.dtype,
                        attn_impl=self.attn_impl,
                        name="attn")(h)[:, :n]
        x = x + jnp.tanh(alpha_attn).astype(x.dtype) * att
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="norm2")(x)
        x = x + jnp.tanh(alpha_dense).astype(x.dtype) \
            * FeedForward(dtype=self.dtype, name="ff")(h)
        return x


class TransformerBlock(nn.Module):
    """Self-attn -> cross-attn -> FF, pre-LN residuals (SD spatial
    transformer block layout)."""
    num_heads: int
    dtype: Dtype = jnp.bfloat16
    attn_impl: str = "xla"
    sow_probs: bool = False        # SAG: capture attn1's softmax weights
    # ToMe: merge this fraction of attn1's QUERY tokens into their most
    # similar destinations (models/tome.py); needs the token grid dims
    tome_ratio: float = 0.0
    hw: Optional[tuple] = None
    gligen: int = 0      # >0: create the GLIGEN fuser (grounding dim)

    @nn.compact
    def __call__(self, x: jax.Array, context: Optional[jax.Array],
                 context_v: Optional[jax.Array] = None,
                 objs: Optional[jax.Array] = None) -> jax.Array:
        xn = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32,
                          name="norm1")(x)
        attn1 = Attention(self.num_heads, dtype=self.dtype,
                          attn_impl=self.attn_impl,
                          sow_probs=self.sow_probs, name="attn1")
        if (self.tome_ratio > 0.0 and self.hw is not None
                and not self.sow_probs):
            from comfyui_distributed_tpu.models.tome import build_merge
            th, tw = self.hw
            merge, unmerge, r = build_merge(
                xn.astype(jnp.float32), th, tw, self.tome_ratio)
            if r > 0:
                # merged queries attend the FULL token set (k/v
                # unmerged, the reference's attn1 patch): kept tokens'
                # outputs are exact, merged ones adopt their dst's
                x = x + unmerge(attn1(merge(xn), context=xn))
            else:
                x = x + attn1(xn)
        else:
            x = x + attn1(xn)
        if self.gligen:
            # GLIGEN fuser between attn1 and attn2 (the reference's
            # insertion point); zero grounding tokens + zero-init gates
            # make the untrained/unused case a near-no-op
            o = objs if objs is not None \
                else jnp.zeros((x.shape[0], 1, int(self.gligen)),
                               x.dtype)
            x = GatedSelfAttention(self.num_heads, dtype=self.dtype,
                                   attn_impl=self.attn_impl,
                                   name="fuser")(x, o)
        x = x + Attention(self.num_heads, dtype=self.dtype,
                          attn_impl=self.attn_impl, name="attn2")(
            nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="norm2")(x), context=context,
            context_v=context_v)
        x = x + FeedForward(dtype=self.dtype, name="ff")(
            nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="norm3")(x))
        return x


def _hypertile_divisor(n: int, min_tile: int) -> int:
    """Largest divisor d of n with n // d >= min_tile (the most tiling
    that keeps tiles at least ``min_tile`` on a side).  Static shapes:
    deterministic, unlike the reference ecosystem's random divisor."""
    best = 1
    for d in range(1, n + 1):
        if n % d == 0 and n // d >= min_tile:
            best = d
    return best


class SpatialTransformer(nn.Module):
    """Project NHWC feature map to tokens, run transformer blocks with
    text cross-attention, project back (SD UNet attention block).

    ``hypertile_tile`` > 0 (HyperTile patch): the token grid splits into
    spatial tiles of >= that many latent units per side, riding the
    BATCH axis through the blocks — self-attention then costs
    O(tiles * (N/tiles)^2).  Cross-attention and the FF are per-token /
    per-query, so tiling changes nothing for them (context repeats per
    tile); only self-attention is approximated, by construction."""
    num_heads: int
    depth: int = 1
    dtype: Dtype = jnp.bfloat16
    attn_impl: str = "xla"
    hypertile_tile: int = 0
    sow_probs: bool = False        # SAG: first block's attn1 sows
    tome_ratio: float = 0.0        # ToMe query merging (models/tome.py)
    gligen: int = 0                # GLIGEN fusers (grounding dim)

    @nn.compact
    def __call__(self, x: jax.Array, context: Optional[jax.Array],
                 context_v: Optional[jax.Array] = None,
                 objs: Optional[jax.Array] = None) -> jax.Array:
        B, H, W, C = x.shape
        # CompVis attention.py Normalize: GroupNorm eps=1e-6 (the UNet's
        # ResBlock GroupNorm32 uses torch's 1e-5 default instead)
        h = GroupNorm32(epsilon=1e-6, name="norm")(x)
        h = nn.Dense(C, dtype=self.dtype, name="proj_in")(h)
        nh = nw = 1
        if self.hypertile_tile > 0:
            nh = _hypertile_divisor(H, self.hypertile_tile)
            nw = _hypertile_divisor(W, self.hypertile_tile)
        ctx = context
        ctx_v = context_v
        if nh * nw > 1:
            th, tw = H // nh, W // nw
            h = h.reshape(B, nh, th, nw, tw, C) \
                .transpose(0, 1, 3, 2, 4, 5) \
                .reshape(B * nh * nw, th * tw, C)
            if context is not None:
                ctx = jnp.repeat(context, nh * nw, axis=0)
            if context_v is not None:
                ctx_v = jnp.repeat(context_v, nh * nw, axis=0)
            if objs is not None:
                objs = jnp.repeat(objs, nh * nw, axis=0)
        else:
            h = h.reshape(B, H * W, C)
        th, tw = (H // nh, W // nw) if nh * nw > 1 else (H, W)
        for i in range(self.depth):
            h = TransformerBlock(self.num_heads, dtype=self.dtype,
                                 attn_impl=self.attn_impl,
                                 sow_probs=self.sow_probs and i == 0,
                                 tome_ratio=self.tome_ratio,
                                 hw=(th, tw), gligen=self.gligen,
                                 name=f"blocks_{i}")(h, ctx,
                                                     context_v=ctx_v,
                                                     objs=objs)
        if nh * nw > 1:
            th, tw = H // nh, W // nw
            h = h.reshape(B, nh, nw, th, tw, C) \
                .transpose(0, 1, 3, 2, 4, 5) \
                .reshape(B, H, W, C)
        else:
            h = h.reshape(B, H, W, C)
        h = nn.Dense(C, dtype=self.dtype, name="proj_out")(h)
        return x + h


class ResBlock(nn.Module):
    """UNet residual block with timestep-embedding injection."""
    out_channels: int
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, emb: jax.Array) -> jax.Array:
        h = GroupNorm32(name="in_norm")(x)
        h = nn.silu(h)
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype,
                    name="in_conv")(h)
        eproj = nn.Dense(self.out_channels, dtype=self.dtype,
                         name="emb_proj")(nn.silu(emb))
        h = h + eproj[:, None, None, :]
        h = GroupNorm32(name="out_norm")(h)
        h = nn.silu(h)
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype,
                    name="out_conv")(h)
        if x.shape[-1] != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                        name="skip")(x)
        return x + h


class Downsample(nn.Module):
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        return nn.Conv(x.shape[-1], (3, 3), strides=(2, 2), padding=1,
                       dtype=self.dtype, name="conv")(x)


class Upsample(nn.Module):
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        B, H, W, C = x.shape
        x = jax.image.resize(x, (B, H * 2, W * 2, C), method="nearest")
        return nn.Conv(C, (3, 3), padding=1, dtype=self.dtype, name="conv")(x)
