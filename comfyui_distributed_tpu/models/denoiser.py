"""Denoiser wrapper: UNet (eps/v prediction) -> k-diffusion interface.

Bridges :mod:`comfyui_distributed_tpu.models.unet` to the samplers'
``denoised = model(x, sigma)`` convention using the discrete VP schedule:
the UNet input is pre-scaled by ``1/sqrt(sigma^2+1)`` and the timestep is the
continuous index of sigma in the model table.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from comfyui_distributed_tpu.models.schedules import DiscreteSchedule
from comfyui_distributed_tpu.parallel import sharding as shd


def _run_one_controlnet(spec, xin, ts, context, y, sigma):
    """One ControlNet spec -> (scaled skip residuals, scaled mid).

    ``spec`` = (cn_apply, cn_params, hint, strength[, windows]).
    Optional (sigma_start, sigma_end) window(s) — ControlNetApplyAdvanced
    start/end percents: a block's control contributes only while
    s_end <= sigma <= s_start (traced select, same convention as the
    conditioning timestep-range gate).  Window forms: None | one
    (start, end) pair | a per-stacked-block tuple of pairs/None matching
    the strength tuple — each entry keeps its OWN window.  When every
    block is windowed the encoder forward is skipped entirely on
    inactive steps (the reference skips out-of-range controls; paying a
    full encoder forward for residuals multiplied by zero would double
    the out-of-window step cost)."""
    cn_apply, cn_params, hint, strength = spec[:4]
    swindow = spec[4] if len(spec) > 4 else None
    per_block = (isinstance(swindow, (tuple, list)) and swindow
                 and isinstance(swindow[0], (tuple, list, type(None))))

    def _gate(w):
        if w is None:
            return None
        sig = jnp.max(sigma)
        return jnp.logical_and(sig <= float(w[0]), sig >= float(w[1]))

    gates = None
    if swindow is not None:
        gates = [_gate(w) for w in swindow] if per_block \
            else [_gate(swindow)]
    reps = xin.shape[0] // hint.shape[0]
    hb = shd.stack_rows([hint] * reps) if reps > 1 else hint

    def run_cn(_):
        return cn_apply(cn_params, xin, ts, context, hb, y)

    if gates is not None and all(g is not None for g in gates):
        any_active = gates[0]
        for g in gates[1:]:
            any_active = jnp.logical_or(any_active, g)
        shapes = jax.eval_shape(run_cn, None)
        zeros = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        outs, mid = jax.lax.cond(any_active, run_cn, lambda _: zeros,
                                 None)
    else:
        outs, mid = run_cn(None)

    def _gated(i, v):
        if gates is None:
            return v
        g = gates[i] if per_block else gates[0]
        return v if g is None else v * g.astype(xin.dtype)

    if isinstance(strength, (tuple, list)):
        # one strength per stacked block; the producer (registry.sample)
        # sizes the tuple to the block layout
        assert len(strength) == reps, (len(strength), reps)
        if reps == 1:
            scale = _gated(0, jnp.asarray(float(strength[0]), xin.dtype))
        else:
            b = hint.shape[0]
            scale = jnp.concatenate(
                [jnp.broadcast_to(
                    _gated(i, jnp.asarray(float(s), xin.dtype)),
                    (b, 1, 1, 1))
                 for i, s in enumerate(strength)], axis=0)
    else:
        scale = _gated(0, strength) if gates is not None else strength
    return ([o * scale for o in outs], mid * scale)


def make_denoiser(apply_fn: Callable, params: Any, ds: DiscreteSchedule,
                  prediction_type: str = "eps",
                  control: Optional[tuple] = None,
                  capture: bool = False,
                  concat: Optional[jax.Array] = None,
                  hypernet: Optional[tuple] = None) -> Callable:
    """Build ``model(x, sigma, context=..., y=...) -> denoised``.

    ``apply_fn(params, x, timesteps, context, y, control)`` is the raw
    UNet.  ``control`` = (cn_apply, cn_params, hint, strength) runs a
    ControlNet on the SAME scaled input/timestep the UNet sees each call
    and feeds its residuals (scaled by strength) into the UNet; the hint
    broadcasts over the CFG-stacked batch.  ``strength`` may be a scalar
    (uniform) or a tuple with ONE strength per stacked block
    ([cond_1..cond_N, uncond_1..uncond_M] — registry.sample composes it):
    ComfyUI attaches a ControlNet to individual conditioning entries, so
    a control on one entry must only steer that entry's rows.

    ``capture``: ``apply_fn`` returns ``(prediction, attn_probs)`` (a
    sow-capturing apply — SAG) and the denoiser returns ``(denoised,
    attn_probs)``.

    ``concat`` [B_base, h, w, K]: inpaint-model channels ([mask,
    masked-image latent]) appended to every call's scaled input along
    the channel axis — NOT noise-scaled (they are clean latents), and
    tiled over the CFG-stacked batch like the control hint.

    ``hypernet``: tuple of (parsed_hypernet, strength) entries applied
    in order — chained loaders COMPOSE like the reference's stacked attn
    patches.  Each transforms the text context into separate k/v streams
    ONCE per call (the context is layer-independent, so this equals the
    reference's per-attn2 patch at 1/N the evaluations).  A ControlNet
    keeps the untransformed context.  KNOWN LIMITATION (logged at load):
    self-attention entries (hidden-width dims) do not apply — only the
    text cross-attention streams are transformed.
    """
    log_sigmas = jnp.asarray(jnp.log(jnp.asarray(ds.sigmas)))

    def t_from_sigma(sigma: jax.Array) -> jax.Array:
        # piecewise-linear interp of log sigma into the table index, traced
        log_s = jnp.log(jnp.maximum(sigma, 1e-10))
        idx = jnp.searchsorted(log_sigmas, log_s, side="left")
        idx = jnp.clip(idx, 1, log_sigmas.shape[0] - 1)
        lo, hi = log_sigmas[idx - 1], log_sigmas[idx]
        frac = (log_s - lo) / jnp.maximum(hi - lo, 1e-12)
        return (idx - 1).astype(jnp.float32) + frac

    def denoiser(x: jax.Array, sigma: jax.Array,
                 context: Optional[jax.Array] = None,
                 y: Optional[jax.Array] = None,
                 objs: Optional[jax.Array] = None,
                 **_: Any) -> jax.Array:
        sigma = jnp.asarray(sigma, jnp.float32)
        # per-sample sigma (continuous batching: each padded-batch slot
        # at its own schedule position) broadcasts over the sample dims;
        # the scalar path is untouched — ``sb`` IS ``sigma`` then, so
        # every existing compiled program keeps its exact expressions
        sb = sigma if sigma.ndim == 0 \
            else jnp.reshape(sigma, (-1,) + (1,) * (x.ndim - 1))
        c_in = 1.0 / jnp.sqrt(sb ** 2 + 1.0)
        t = t_from_sigma(sigma)
        ts = jnp.broadcast_to(t, (x.shape[0],))
        xin = x * c_in
        ctrl = None
        if control is not None:
            # one spec or a CHAIN of specs (ComfyUI's previous_controlnet
            # accumulation): every net runs on the same scaled input and
            # their scaled residuals SUM into the UNet
            chain = control if isinstance(control, (list,)) \
                or (isinstance(control, tuple) and control
                    and isinstance(control[0], tuple)) else [control]
            acc = None
            for spec in chain:
                one = _run_one_controlnet(spec, xin, ts, context, y, sigma)
                if acc is None:
                    acc = one
                else:
                    acc = ([a + b for a, b in zip(acc[0], one[0])],
                           acc[1] + one[1])
            ctrl = acc
        if concat is not None:
            # AFTER the control block: a ControlNet sees the plain
            # 4-channel scaled input, only the UNet gets the 9 channels
            creps = xin.shape[0] // concat.shape[0]
            cb = shd.stack_rows([concat] * creps) \
                if creps > 1 else concat
            # channel concat: pin the result so conv_in's kernel layout
            # can't back-propagate a sharding onto the concat dim
            # (tp-concat-cpu-miscompile)
            xin = shd.constrain_rows(
                jnp.concatenate([xin, cb.astype(xin.dtype)], axis=-1))
        ctx_in, kw = context, {}
        if hypernet is not None and context is not None:
            from comfyui_distributed_tpu.models.hypernetwork import \
                apply_hypernetwork_pair
            ctx_in = ctx_v = context
            for hn, s in hypernet:
                ctx_in, ctx_v = apply_hypernetwork_pair(
                    hn, float(s), ctx_in, ctx_v)
            kw = {"context_v": ctx_v}
        if objs is not None:
            kw["objs"] = objs
        out = apply_fn(params, xin, ts, ctx_in, y, ctrl, **kw)
        eps_or_v, probs = out if capture else (out, None)
        if prediction_type == "v":
            # v-prediction: denoised = c_skip*x - c_out*v  (VP parameterization)
            c_skip = 1.0 / (sb ** 2 + 1.0)
            c_out = sb / jnp.sqrt(sb ** 2 + 1.0)
            den = x * c_skip - eps_or_v * c_out
        elif prediction_type == "x0":
            # the model predicts the clean sample directly
            # (ModelSamplingDiscrete sampling="x0")
            den = eps_or_v
        else:
            den = x - eps_or_v * sb
        return (den, probs) if capture else den

    return denoiser
