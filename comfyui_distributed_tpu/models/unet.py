"""Diffusion UNet (SD1.x / SDXL families), flax NHWC.

The denoise backbone the reference borrows from ComfyUI (its KSampler executes
a torch UNet; see SURVEY.md §7 — "the sampler/VAE stack itself" is the biggest
new code).  Configurable to the SD1.5 and SDXL layouts used by the reference
workflows' checkpoints (``workflows/distributed-txt2img.json`` loads an SDXL
checkpoint), plus a tiny config for tests.

Model convention: eps-prediction by default; the sampler-side
:class:`comfyui_distributed_tpu.models.denoiser.Denoiser` wraps it into the
k-diffusion ``denoised = f(x, sigma)`` form.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from comfyui_distributed_tpu.models.layers import (
    Downsample,
    GroupNorm32,
    ResBlock,
    SpatialTransformer,
    Upsample,
    timestep_embedding,
)
from comfyui_distributed_tpu.parallel import sharding as shd


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    model_channels: int = 320
    channel_mult: Tuple[int, ...] = (1, 2, 4, 4)
    num_res_blocks: int = 2
    # transformer depth per level; 0 = no attention at that level
    transformer_depth: Tuple[int, ...] = (1, 1, 1, 0)
    context_dim: int = 768
    num_head_channels: int = 64
    num_heads: Optional[int] = None  # fixed head count overrides head_channels
    # middle-block transformer depth; None = max(transformer_depth[-1], 1)
    # (SGM's transformer_depth_middle — the SDXL refiner has NO attention
    # at its last level but a depth-4 middle)
    transformer_depth_middle: Optional[int] = None
    # SDXL class/vector conditioning (text-emb pooled + size conds)
    adm_in_channels: Optional[int] = None
    # checkpoint-layout metadata only: torch stores spatial-transformer
    # proj_in/proj_out as 1x1 convs (SD1.x) or nn.Linear (SD2.x/SDXL); the
    # flax module always uses Dense (mathematically identical)
    use_linear_in_transformer: bool = False
    # FreeU (Si et al.): decoder backbone/skip re-weighting — (b1, b2,
    # s1, s2) or None; version 2 scales by the normalized hidden mean.
    # Static config: each setting compiles its own executable (the
    # derived-pipeline cache keeps them apart)
    freeu: Optional[Tuple[float, float, float, float]] = None
    freeu_version: int = 1
    # HyperTile: (tile_size_px, max_depth, scale_depth) or None — levels
    # <= max_depth tile their self-attention into >= tile_size//8-latent
    # blocks (models/layers.py SpatialTransformer).  Static config like
    # freeu: each setting compiles its own executable
    hypertile: Optional[Tuple[int, int, bool]] = None
    # SAG: the mid-block's first self-attention materializes + sows its
    # softmax weights for the sampler's blur mask (models/layers.py)
    sag_capture: bool = False
    # Deep shrink (PatchModelAddDownscale): (level, factor) — THIS trace
    # bilinearly downscales the hidden at the given level's entry and
    # upsamples at the first skip-concat mismatch.  The sigma-window
    # branch lives OUTSIDE the module (registry builds a lax.cond over a
    # shrunk-config and a plain-config apply sharing one param tree)
    deep_shrink: Optional[Tuple[int, float]] = None
    # ToMe (TomePatchModel): merge this fraction of attn1 query tokens
    # at the HIGHEST-resolution attention level only (the reference's
    # max_downsample=1 — deep levels would degrade quality for no
    # savings); 0 = off.  Static config like freeu
    tome_ratio: float = 0.0
    # GLIGEN: >0 creates GatedSelfAttention fusers in every transformer
    # block at this grounding-token width (params live in the unet tree
    # under .../fuser); grounding tokens arrive per call via ``objs``
    gligen: int = 0
    dtype: Any = jnp.bfloat16
    attn_impl: str = "xla"
    prediction_type: str = "eps"  # "eps" | "v"

    @property
    def num_levels(self) -> int:
        return len(self.channel_mult)


# SD1.5 uses a fixed 8 heads at every resolution (not head_channels=64)
SD15_CONFIG = UNetConfig(num_heads=8)

SDXL_CONFIG = UNetConfig(
    channel_mult=(1, 2, 4),
    transformer_depth=(0, 2, 10),
    context_dim=2048,
    adm_in_channels=2816,
    use_linear_in_transformer=True,
)

# SDXL refiner (sd_xl_refiner.yaml): 384 base channels over 4 levels,
# depth-4 transformers at the two middle levels only, bigG-only context
# (1280), ADM = pooled(1280) + 5 scalar embeddings (height, width,
# crop_h, crop_w, aesthetic_score) x 256 = 2560
SDXL_REFINER_CONFIG = UNetConfig(
    model_channels=384,
    channel_mult=(1, 2, 4, 4),
    transformer_depth=(0, 4, 4, 0),
    transformer_depth_middle=4,
    context_dim=1280,
    adm_in_channels=2560,
    use_linear_in_transformer=True,
)


def mid_depth(cfg: "UNetConfig") -> int:
    """Middle-block transformer depth — ONE copy of the rule, shared
    with the checkpoint converter's key walk."""
    if cfg.transformer_depth_middle is not None:
        return int(cfg.transformer_depth_middle)
    return max(cfg.transformer_depth[-1], 1)

# SD2.1: SD1.x topology with per-level head_channels=64 (not fixed 8
# heads), OpenCLIP-H context (1024), linear transformer projections;
# the 768-v checkpoint line is v-prediction, the 512-base line is eps
SD21_CONFIG = UNetConfig(
    context_dim=1024,
    use_linear_in_transformer=True,
    prediction_type="v",
)
SD21_BASE_CONFIG = dataclasses.replace(SD21_CONFIG, prediction_type="eps")

TINY_CONFIG = UNetConfig(
    model_channels=32,
    channel_mult=(1, 2),
    num_res_blocks=1,
    transformer_depth=(1, 1),
    context_dim=64,
    num_head_channels=16,
    dtype=jnp.float32,  # deterministic CPU tests; real families use bf16
)


def _fourier_filter(x: jax.Array, threshold: int,
                    scale: float) -> jax.Array:
    """FreeU's skip-feature filter: scale the centered low-frequency box
    of the 2D spectrum by ``scale`` (torch reference Fourier_filter)."""
    dtype = x.dtype
    xf = jnp.fft.fftn(x.astype(jnp.float32), axes=(1, 2))
    xf = jnp.fft.fftshift(xf, axes=(1, 2))
    _, H, W, _ = x.shape
    cr, cc = H // 2, W // 2
    mask = jnp.ones((1, H, W, 1), jnp.float32)
    mask = mask.at[:, max(cr - threshold, 0):cr + threshold,
                   max(cc - threshold, 0):cc + threshold, :].set(scale)
    xf = jnp.fft.ifftshift(xf * mask, axes=(1, 2))
    return jnp.real(jnp.fft.ifftn(xf, axes=(1, 2))).astype(dtype)


def _apply_freeu(cfg: "UNetConfig", h: jax.Array, hsp: jax.Array):
    """FreeU at a decoder concat: boost the first half of the backbone
    channels (v2: scaled by the per-pixel normalized hidden mean) and
    low-pass-attenuate the skip.  Applies only at the torch reference's
    two channel widths (model_channels*4 / *2)."""
    b1, b2, s1, s2 = cfg.freeu
    scales = {cfg.model_channels * 4: (float(b1), float(s1)),
              cfg.model_channels * 2: (float(b2), float(s2))}
    sc = scales.get(int(h.shape[-1]))
    if sc is None:
        return h, hsp
    b, s = sc
    half = h.shape[-1] // 2
    if cfg.freeu_version == 2:
        hm = jnp.mean(h.astype(jnp.float32), axis=-1, keepdims=True)
        hmin = jnp.min(hm.reshape(h.shape[0], -1), axis=1) \
            .reshape(-1, 1, 1, 1)
        hmax = jnp.max(hm.reshape(h.shape[0], -1), axis=1) \
            .reshape(-1, 1, 1, 1)
        hm = (hm - hmin) / jnp.maximum(hmax - hmin, 1e-6)
        boost = ((b - 1.0) * hm + 1.0).astype(h.dtype)
    else:
        boost = jnp.asarray(b, h.dtype)
    # pin: channel concat of backbone halves must keep an unsharded
    # concat dim (tp-concat-cpu-miscompile)
    h = shd.constrain_rows(
        jnp.concatenate([h[..., :half] * boost, h[..., half:]], axis=-1))
    return h, _fourier_filter(hsp, 1, s)


class UNet(nn.Module):
    cfg: UNetConfig

    @nn.compact
    def __call__(self, x: jax.Array, timesteps: jax.Array,
                 context: jax.Array, y: Optional[jax.Array] = None,
                 control=None,
                 context_v: Optional[jax.Array] = None,
                 objs: Optional[jax.Array] = None) -> jax.Array:
        """x: [B,H,W,C_in] latent; timesteps: [B]; context: [B,M,Cc] text
        tokens; y: [B, adm_in] optional vector conditioning (SDXL);
        control: optional ControlNet residuals ``(skip_list, middle)`` —
        one entry per skip in down-path order, added torch-style
        (``hs[i] + control[i]``, middle added after the middle block)."""
        cfg = self.cfg
        ch = cfg.model_channels
        time_dim = ch * 4

        emb = timestep_embedding(timesteps, ch)
        emb = nn.Dense(time_dim, dtype=cfg.dtype, name="time_fc1")(emb)
        emb = nn.Dense(time_dim, dtype=cfg.dtype, name="time_fc2")(nn.silu(emb))
        if cfg.adm_in_channels is not None:
            if y is None:
                y = jnp.zeros((x.shape[0], cfg.adm_in_channels), x.dtype)
            lab = nn.Dense(time_dim, dtype=cfg.dtype, name="label_fc1")(y)
            lab = nn.Dense(time_dim, dtype=cfg.dtype,
                           name="label_fc2")(nn.silu(lab))
            emb = emb + lab

        def heads(c: int) -> int:
            if cfg.num_heads is not None:
                return cfg.num_heads
            return max(c // cfg.num_head_channels, 1)

        def ht_tile(level: int) -> int:
            """HyperTile minimum latent tile for this level (0 = off)."""
            if cfg.hypertile is None:
                return 0
            tile_px, max_depth, scale_depth = cfg.hypertile
            if level > int(max_depth):
                return 0
            lt = max(32, int(tile_px)) // 8
            return lt * (2 ** level if scale_depth else 1)

        h = nn.Conv(ch, (3, 3), padding=1, dtype=cfg.dtype, name="conv_in")(x)
        skips = [h]

        # down path
        for level, mult in enumerate(cfg.channel_mult):
            if cfg.deep_shrink is not None and level == cfg.deep_shrink[0]:
                f = float(cfg.deep_shrink[1])
                nh = max(1, int(round(h.shape[1] / f)))
                nw = max(1, int(round(h.shape[2] / f)))
                h = jax.image.resize(
                    h, (h.shape[0], nh, nw, h.shape[3]),
                    method="bilinear").astype(h.dtype)
            out_ch = ch * mult
            for i in range(cfg.num_res_blocks):
                h = ResBlock(out_ch, dtype=cfg.dtype,
                             name=f"down_{level}_res_{i}")(h, emb)
                if cfg.transformer_depth[level] > 0:
                    h = SpatialTransformer(
                        heads(out_ch), depth=cfg.transformer_depth[level],
                        dtype=cfg.dtype, attn_impl=cfg.attn_impl,
                        hypertile_tile=ht_tile(level),
                        tome_ratio=cfg.tome_ratio if level == 0
                        else 0.0,
                        gligen=cfg.gligen,
                        name=f"down_{level}_attn_{i}")(
                            h, context, context_v=context_v,
                            objs=objs)
                skips.append(h)
            if level != cfg.num_levels - 1:
                h = Downsample(dtype=cfg.dtype, name=f"down_{level}_ds")(h)
                skips.append(h)

        if control is not None:
            ctrl_skips, ctrl_mid = control
            # strict: a count mismatch (encoder drift between UNet and
            # ControlNet) must fail loudly, not silently drop residuals
            skips = [s + c for s, c in zip(skips, ctrl_skips, strict=True)]

        # middle
        mid_ch = ch * cfg.channel_mult[-1]
        h = ResBlock(mid_ch, dtype=cfg.dtype, name="mid_res_0")(h, emb)
        h = SpatialTransformer(
            heads(mid_ch), depth=mid_depth(cfg),
            dtype=cfg.dtype, attn_impl=cfg.attn_impl,
            hypertile_tile=ht_tile(cfg.num_levels - 1),
            sow_probs=cfg.sag_capture, gligen=cfg.gligen,
            name="mid_attn")(h, context, context_v=context_v,
                             objs=objs)
        h = ResBlock(mid_ch, dtype=cfg.dtype, name="mid_res_1")(h, emb)
        if control is not None:
            h = h + ctrl_mid

        # up path
        for level in reversed(range(cfg.num_levels)):
            out_ch = ch * cfg.channel_mult[level]
            for i in range(cfg.num_res_blocks + 1):
                skip = skips.pop()
                if h.shape[1:3] != skip.shape[1:3]:
                    # deep shrink: back to full size at the first
                    # mismatching skip (the reference's output patch)
                    h = jax.image.resize(
                        h, (h.shape[0], skip.shape[1], skip.shape[2],
                            h.shape[3]),
                        method="bilinear").astype(h.dtype)
                if cfg.freeu is not None:
                    h, skip = _apply_freeu(cfg, h, skip)
                # replicate-before-concat (tp-concat-cpu-miscompile,
                # ROADMAP item 8): XLA's CPU SPMD partitioner miscompiles
                # a channel concat whose operands or result carry a
                # tensor-axis layout on the concat dim (shard boundaries
                # misalign with the operand seam) — pin operands AND the
                # result to batch-only sharding so consumer-side
                # propagation (e.g. the ResBlock skip projection) cannot
                # re-shard the concat (inert without an engaged tensor
                # axis)
                h = shd.constrain_rows(h)
                skip = shd.constrain_rows(skip)
                h = shd.constrain_rows(
                    jnp.concatenate([h, skip], axis=-1))
                h = ResBlock(out_ch, dtype=cfg.dtype,
                             name=f"up_{level}_res_{i}")(h, emb)
                if cfg.transformer_depth[level] > 0:
                    h = SpatialTransformer(
                        heads(out_ch), depth=cfg.transformer_depth[level],
                        dtype=cfg.dtype, attn_impl=cfg.attn_impl,
                        hypertile_tile=ht_tile(level),
                        tome_ratio=cfg.tome_ratio if level == 0
                        else 0.0,
                        gligen=cfg.gligen,
                        name=f"up_{level}_attn_{i}")(
                            h, context, context_v=context_v,
                            objs=objs)
            if level != 0:
                h = Upsample(dtype=cfg.dtype, name=f"up_{level}_us")(h)

        h = GroupNorm32(name="out_norm")(h)
        h = nn.silu(h)
        h = nn.Conv(cfg.out_channels, (3, 3), padding=1, dtype=jnp.float32,
                    name="conv_out")(h)
        return h.astype(jnp.float32)
