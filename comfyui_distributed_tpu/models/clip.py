"""CLIP text encoders (ViT-L/14 text tower + OpenCLIP bigG) in flax.

The reference's CLIPTextEncode node is ComfyUI's torch CLIP
(``workflows/distributed-txt2img.json`` nodes 5/6); this is the native
equivalent producing the cross-attention ``context`` and (for SDXL) pooled
embeddings.  Causal transformer, pre-LN, fp32 layernorms.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn


@dataclasses.dataclass(frozen=True)
class CLIPConfig:
    vocab_size: int = 49408
    width: int = 768
    layers: int = 12
    heads: int = 12
    max_length: int = 77
    act: str = "quick_gelu"          # ViT-L; bigG uses "gelu"
    # which hidden layer feeds cross-attention: -1 final, -2 penultimate
    output_layer: int = -1
    projection_dim: Optional[int] = None  # pooled-output projection (bigG)
    # checkpoint layout this tower serializes as: "hf" (CLIPTextModel,
    # q/k/v split) or "openclip" (resblocks, packed in_proj) — drives the
    # converter's key walk (checkpoints.py)
    layout: str = "hf"
    dtype: Any = jnp.bfloat16


CLIP_L_CONFIG = CLIPConfig()
# SDXL pairs CLIP-L (penultimate) with OpenCLIP bigG (penultimate):
CLIP_L_SDXL_CONFIG = dataclasses.replace(CLIP_L_CONFIG, output_layer=-2)
OPEN_CLIP_BIGG_CONFIG = CLIPConfig(width=1280, layers=32, heads=20,
                                   act="gelu", output_layer=-2,
                                   projection_dim=1280, layout="openclip")
# SD2.x text tower: OpenCLIP ViT-H, penultimate layer (FrozenOpenCLIP
# Embedder layer="penultimate"); text_projection ships in the checkpoint
OPEN_CLIP_H_CONFIG = CLIPConfig(width=1024, layers=24, heads=16,
                                act="gelu", output_layer=-2,
                                projection_dim=1024, layout="openclip")
TINY_CLIP_CONFIG = CLIPConfig(vocab_size=4096, width=64, layers=2, heads=4,
                              max_length=77, dtype=jnp.float32)


def _act(name: str):
    if name == "quick_gelu":
        return lambda x: x * jax.nn.sigmoid(1.702 * x)
    # OpenCLIP's nn.GELU is the exact (erf) form, not flax's default tanh
    return lambda x: nn.gelu(x, approximate=False)


class CLIPLayer(nn.Module):
    cfg: CLIPConfig

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array) -> jax.Array:
        cfg = self.cfg
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln1")(x)
        B, N, C = h.shape
        hd = cfg.width // cfg.heads
        q = nn.Dense(cfg.width, dtype=cfg.dtype, name="q")(h)
        k = nn.Dense(cfg.width, dtype=cfg.dtype, name="k")(h)
        v = nn.Dense(cfg.width, dtype=cfg.dtype, name="v")(h)
        q = q.reshape(B, N, cfg.heads, hd)
        k = k.reshape(B, N, cfg.heads, hd)
        v = v.reshape(B, N, cfg.heads, hd)
        logits = jnp.einsum("bnhd,bmhd->bhnm", q, k,
                            preferred_element_type=jnp.float32)
        logits = logits / jnp.sqrt(jnp.float32(hd)) + mask
        w = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum("bhnm,bmhd->bnhd", w.astype(v.dtype), v)
        attn = attn.reshape(B, N, cfg.width)
        x = x + nn.Dense(cfg.width, dtype=cfg.dtype, name="proj")(attn)

        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln2")(x)
        h = nn.Dense(cfg.width * 4, dtype=cfg.dtype, name="fc1")(h)
        h = _act(self.cfg.act)(h)
        h = nn.Dense(cfg.width, dtype=cfg.dtype, name="fc2")(h)
        return x + h


class CLIPTextModel(nn.Module):
    cfg: CLIPConfig

    @nn.compact
    def __call__(self, tokens: jax.Array,
                 emb_override: Optional[jax.Array] = None,
                 emb_mask: Optional[jax.Array] = None,
                 ) -> Tuple[jax.Array, jax.Array]:
        """tokens: [B, max_length] int32.  Returns (hidden [B, N, width],
        pooled [B, width or projection_dim]).

        ``emb_override`` [B, N, width] + ``emb_mask`` [B, N] (textual
        inversion): positions with mask=1 replace the looked-up token
        embedding with the supplied vector (their token id is a
        placeholder 0, which never wins the EOT argmax)."""
        cfg = self.cfg
        B, N = tokens.shape
        tok_emb = nn.Embed(cfg.vocab_size, cfg.width, name="token_embedding",
                           dtype=cfg.dtype)(tokens)
        if emb_override is not None:
            sel = emb_mask[..., None].astype(bool)
            tok_emb = jnp.where(sel, emb_override.astype(tok_emb.dtype),
                                tok_emb)
        pos_emb = self.param("position_embedding",
                             nn.initializers.normal(0.01),
                             (cfg.max_length, cfg.width))
        x = tok_emb + pos_emb[None, :N, :].astype(cfg.dtype)

        causal = jnp.triu(jnp.full((N, N), -jnp.inf, jnp.float32), k=1)
        mask = causal[None, None, :, :]

        hidden = []
        for i in range(cfg.layers):
            x = CLIPLayer(cfg, name=f"layers_{i}")(x, mask)
            hidden.append(x)

        # ln_final is shared: applied to the last layer for pooling and to the
        # selected output layer (clip-skip reuses the same checkpoint weights,
        # matching ComfyUI's behavior)
        ln_final = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="ln_final")
        out = ln_final(hidden[cfg.output_layer])
        final = out if cfg.output_layer == -1 else ln_final(hidden[-1])

        # pooled: hidden state at the EOT token (highest token id position)
        eot = jnp.argmax(tokens, axis=-1)
        pooled = final[jnp.arange(B), eot]
        if cfg.projection_dim is not None:
            pooled = nn.Dense(cfg.projection_dim, use_bias=False,
                              dtype=jnp.float32, name="text_projection")(pooled)
        return out.astype(jnp.float32), pooled.astype(jnp.float32)
