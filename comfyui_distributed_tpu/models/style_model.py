"""Style adapter (the reference ecosystem's StyleModelLoader /
StyleModelApply surface — T2I "coadapter-style"): a small transformer
turns CLIP-vision hidden states into a handful of style tokens that
APPEND to the text context, steering sampling toward the reference
image's style through ordinary cross-attention.

Mechanism implemented faithfully (learned style queries + transformer
over [vision tokens; queries] -> projected trailing tokens); converting
the reference's trained .pth weights is NOT implemented — loading a
real file logs loudly and virtual-initializes, the same policy as the
unCLIP checkpoint's embedded vision tower."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
from flax import linen as nn

from comfyui_distributed_tpu.models.clip import CLIPConfig, CLIPLayer
from comfyui_distributed_tpu.utils.logging import log


@dataclasses.dataclass(frozen=True)
class StyleAdapterConfig:
    width: int = 1024
    layers: int = 3
    heads: int = 8
    num_tokens: int = 8
    context_dim: int = 768      # output token width (the text context's)
    dtype: Any = jnp.float32


STYLE_CONFIG = StyleAdapterConfig()
TINY_STYLE_CONFIG = StyleAdapterConfig(width=64, layers=1, heads=4,
                                       num_tokens=2, context_dim=64)


class StyleAdapter(nn.Module):
    cfg: StyleAdapterConfig

    @nn.compact
    def __call__(self, vision_hidden: jax.Array) -> jax.Array:
        """[B, P, D_vision] -> [B, num_tokens, context_dim]."""
        cfg = self.cfg
        B = vision_hidden.shape[0]
        h = nn.Dense(cfg.width, dtype=cfg.dtype,
                     name="proj_in")(vision_hidden)
        queries = self.param("style_embedding",
                             nn.initializers.normal(0.02),
                             (cfg.num_tokens, cfg.width))
        h = jnp.concatenate(
            [h, jnp.broadcast_to(queries,
                                 (B,) + queries.shape).astype(h.dtype)],
            axis=1)
        lcfg = CLIPConfig(width=cfg.width, layers=cfg.layers,
                          heads=cfg.heads, act="gelu", dtype=cfg.dtype)
        mask = jnp.zeros((1, 1, h.shape[1], h.shape[1]), jnp.float32)
        for i in range(cfg.layers):
            h = CLIPLayer(lcfg, name=f"layers_{i}")(h, mask)
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32,
                         name="ln_post")(h[:, -cfg.num_tokens:])
        return nn.Dense(cfg.context_dim, dtype=jnp.float32,
                        name="proj_out")(h)


@dataclasses.dataclass
class StyleModelTower:
    """STYLE_MODEL wire object."""
    name: str
    cfg: StyleAdapterConfig
    params: Any
    _jitted: Any = None

    def get_cond(self, vision_output) -> jax.Array:
        if self._jitted is None:
            module = StyleAdapter(self.cfg)
            self._jitted = jax.jit(
                lambda p, x: module.apply({"params": p}, x))
        # the reference's style-model path consumes the PENULTIMATE
        # vision hiddens (hidden_states[-2]), not the final layer
        hidden = getattr(vision_output, "penultimate_hidden", None)
        if hidden is None:
            hidden = vision_output.last_hidden
        return self._jitted(self.params, jnp.asarray(hidden))


_cache: Dict[str, StyleModelTower] = {}


def load_style_model(name: str, models_dir=None,
                     context_dim: int = 768) -> StyleModelTower:
    import os
    key = f"{name}:{context_dim}:{models_dir or ''}"
    if key in _cache:
        return _cache[key]
    lowered = name.lower()
    cfg = TINY_STYLE_CONFIG if ("tiny" in lowered or "test" in lowered) \
        else dataclasses.replace(STYLE_CONFIG, context_dim=context_dim)
    if models_dir:
        for cand in (name, os.path.join("style_models", name)):
            p = os.path.join(models_dir, cand.replace("\\", "/"))
            if os.path.isfile(p):
                log(f"style model {name}: converting trained adapter "
                    "weights is not implemented — using a deterministic "
                    "virtual adapter (known limitation)")
                break
    from comfyui_distributed_tpu.models.registry import (_name_seed,
                                                         _virtual_params)
    seed = _name_seed(name)
    vis = jnp.zeros((1, 10, cfg.width))
    params = _virtual_params(StyleAdapter(cfg), seed, vis)
    log(f"virtual style model {name!r} (tokens {cfg.num_tokens} -> "
        f"{cfg.context_dim}d), deterministic init (seed {seed})")
    tower = StyleModelTower(name=name, cfg=cfg, params=params)
    _cache[key] = tower
    return tower


def clear_style_model_cache() -> None:
    _cache.clear()
