"""LoRA patching: kohya-format low-rank adapters onto the flax model zoo.

The reference delegates LoRA to ComfyUI's ``LoraLoader`` node (the single
most common model-patching node in workflows the reference fans out);
here the equivalent applies ``lora_up @ lora_down`` deltas to the UNet
and text-encoder weights.

Key resolution uses the same trick ComfyUI's loader uses: kohya module
names are the base checkpoint's torch module paths with dots flattened
to underscores (``lora_unet_input_blocks_1_1_transformer_blocks_0_attn1
_to_q`` <- ``model.diffusion_model.input_blocks.1.1...to_q.weight``), so
instead of parsing the underscored names (ambiguous — segment names
contain underscores) we enumerate the torch keys our own exporter
produces and index them flattened.  Application happens in torch layout
(export -> add deltas -> convert back), so every layout transform the
converter knows (conv OIHW, transposed linears, packed qkv) is reused
rather than re-implemented.

Text-encoder prefixes: ``lora_te_`` (single-tower families),
``lora_te1_``/``lora_te2_`` (SDXL's CLIP-L + bigG).
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from comfyui_distributed_tpu.models import checkpoints as ckpt
from comfyui_distributed_tpu.utils.logging import debug_log, log

UNET_LORA_PREFIX = "lora_unet_"


def _te_prefixes(n_clips: int) -> List[str]:
    if n_clips == 1:
        return ["lora_te_"]
    return [f"lora_te{i + 1}_" for i in range(n_clips)]


def build_key_index(sd: Dict[str, np.ndarray], family
                    ) -> Dict[str, Tuple[str, Optional[slice]]]:
    """kohya module name -> (torch weight key, row slice or None),
    generated from the exported state dict's own keys (never by parsing
    underscored names — those are ambiguous).

    For OpenCLIP-layout towers (SD2.x, SDXL's te2) kohya trains against
    the HF-converted tower, so the HF module names are ALSO indexed as
    aliases: ``..._self_attn_q_proj`` maps onto the packed
    ``attn.in_proj_weight`` rows [0:W] (k: [W:2W], v: [2W:3W]),
    ``mlp_fc1/fc2`` onto ``mlp.c_fc/c_proj``."""
    index: Dict[str, Tuple[str, Optional[slice]]] = {}
    te_pre = _te_prefixes(len(family.clips))
    clip_prefixes = ckpt._clip_prefixes(family)
    for key in sd:
        if key.endswith(".in_proj_weight"):
            # packed qkv: "...attn.in_proj_weight" — underscore, not dot
            module = key[: -len("_weight")]
        elif key.endswith(".weight"):
            module = key[: -len(".weight")]
        else:
            continue
        if key.startswith(ckpt.UNET_PREFIX):
            flat = module[len(ckpt.UNET_PREFIX):].replace(".", "_")
            index[UNET_LORA_PREFIX + flat] = (key, None)
            continue
        for pre, lora_pre in zip(clip_prefixes, te_pre):
            if not key.startswith(pre.rsplit("text_model.", 1)[0]):
                continue
            if pre.endswith("text_model."):
                # HF tower: kohya names start at "text_model." — the part
                # after "cond_stage_model.transformer."
                root = pre[: -len("text_model.")]
                flat = module[len(root):].replace(".", "_")
                index[lora_pre + flat] = (key, None)
            elif module.startswith(pre):
                _index_openclip_aliases(index, lora_pre, pre, module, key,
                                        family)
            break
    return index


def _index_openclip_aliases(index, lora_pre: str, prefix: str, module: str,
                            key: str, family) -> None:
    """HF-converted kohya names for an OpenCLIP-serialized tower."""
    width = next(c.width for c, p in zip(family.clips,
                                         ckpt._clip_prefixes(family))
                 if p == prefix)
    rel = module[len(prefix):]                     # e.g. transformer.resblocks.0.attn.in_proj
    parts = rel.split(".")
    if len(parts) >= 4 and parts[0] == "transformer" \
            and parts[1] == "resblocks":
        i = parts[2]
        tail = ".".join(parts[3:])
        hf_base = f"text_model_encoder_layers_{i}_"
        if tail == "attn.in_proj":
            for j, name in enumerate(("q_proj", "k_proj", "v_proj")):
                index[f"{lora_pre}{hf_base}self_attn_{name}"] = \
                    (key, slice(j * width, (j + 1) * width))
        elif tail == "attn.out_proj":
            index[f"{lora_pre}{hf_base}self_attn_out_proj"] = (key, None)
        elif tail == "mlp.c_fc":
            index[f"{lora_pre}{hf_base}mlp_fc1"] = (key, None)
        elif tail == "mlp.c_proj":
            index[f"{lora_pre}{hf_base}mlp_fc2"] = (key, None)
    # the native openclip spelling stays available too (some tools emit it)
    index[lora_pre + rel.replace(".", "_")] = (key, None)


def load_lora_state_dict(path: str) -> Dict[str, np.ndarray]:
    return ckpt.load_state_dict(path)


def virtual_lora_state_dict(name: str,
                            index: Dict[str, Tuple[str, Optional[slice]]],
                            sd: Dict[str, np.ndarray],
                            rank: int = 4,
                            max_modules: int = 8) -> Dict[str, np.ndarray]:
    """Deterministic synthetic LoRA (zero-egress parity with virtual
    checkpoints): small rank, a few attention modules, seeded from the
    file name so every host materializes identical adapters."""
    from comfyui_distributed_tpu.models.registry import _name_seed
    rng = np.random.default_rng(_name_seed(name))
    out: Dict[str, np.ndarray] = {}
    picked = [m for m in sorted(index)
              if m.endswith(("to_q", "to_k", "to_v", "q_proj", "k_proj",
                             "v_proj"))][:max_modules]
    for mod in picked:
        key, rows = index[mod]
        w = sd[key]
        if w.ndim < 2:
            continue
        out_f = (rows.stop - rows.start) if rows is not None else w.shape[0]
        in_f = int(np.prod(w.shape[1:]))
        out[f"{mod}.lora_down.weight"] = rng.standard_normal(
            (rank, in_f)).astype(np.float32) * 0.01
        out[f"{mod}.lora_up.weight"] = rng.standard_normal(
            (out_f, rank)).astype(np.float32) * 0.01
        out[f"{mod}.alpha"] = np.full((), rank, np.float32)
    return out


def _delta(up: np.ndarray, down: np.ndarray,
           target_shape: Tuple[int, ...]) -> np.ndarray:
    """lora_up @ lora_down in torch layout, reshaped to the base weight.

    Linear: up [out, r] @ down [r, in].  Conv: up [out, r, 1, 1], down
    [r, in, kh, kw] (or both 1x1) — flatten ranks, matmul, reshape."""
    u = up.reshape(up.shape[0], -1)
    d = down.reshape(down.shape[0], -1)
    return (u @ d).reshape(target_shape)


def apply_lora_to_state_dict(sd: Dict[str, np.ndarray],
                             lora_sd: Dict[str, np.ndarray],
                             index: Dict[str, Tuple[str, Optional[slice]]],
                             strength_model: float,
                             strength_clip: float) -> Tuple[int, List[str]]:
    """Add scaled deltas into ``sd`` in place.  Returns (n_applied,
    unmatched kohya module names)."""
    modules = sorted({k.split(".")[0] for k in lora_sd
                      if ".lora_down." in k or ".lora_up." in k})
    applied, unmatched = 0, []
    for mod in modules:
        entry = index.get(mod)
        if entry is None:
            unmatched.append(mod)
            continue
        key, rows = entry
        strength = strength_model if mod.startswith(UNET_LORA_PREFIX) \
            else strength_clip
        if strength == 0.0:
            continue
        down = lora_sd.get(f"{mod}.lora_down.weight")
        up = lora_sd.get(f"{mod}.lora_up.weight")
        if down is None or up is None:
            unmatched.append(mod)
            continue
        rank = down.shape[0]
        alpha = float(lora_sd.get(f"{mod}.alpha", rank))
        w = sd[key].copy()
        target = w[rows] if rows is not None else w
        target = target + (strength * alpha / rank) * _delta(
            np.asarray(up, np.float32), np.asarray(down, np.float32),
            target.shape).astype(w.dtype)
        if rows is not None:
            w[rows] = target        # packed-qkv row block (HF alias)
            sd[key] = w
        else:
            sd[key] = target
        applied += 1
    return applied, unmatched


# Patched pipelines cached by (base, lora, strengths): re-running the same
# graph must reuse the SAME pipeline object, or every run would recompile
# its jit caches from scratch.  LRU-bounded — each entry is a full copy of
# UNet+CLIP weights, so a strength-tuning sweep would otherwise leak one
# model per value (same leak class registry's _jit_cache documents).
_lora_cache: "collections.OrderedDict[Tuple, Any]" = collections.OrderedDict()
_lora_cache_cap = int(os.environ.get("DTPU_LORA_CACHE_CAP", "4"))
_lora_lock = threading.Lock()


def clear_lora_cache() -> None:
    with _lora_lock:
        _lora_cache.clear()


def apply_lora_to_pipeline(pipe, lora_name: str,
                           strength_model: float, strength_clip: float,
                           models_dir: Optional[str] = None):
    """Return a NEW pipeline with the named LoRA merged into UNet/CLIP
    weights (the base pipeline and its jit caches stay untouched; merged
    weights mean zero per-step overhead — the deltas ride the same
    compiled executables).

    Missing files virtually initialize (deterministic from the name),
    mirroring virtual checkpoints."""
    cache_key = (getattr(pipe, "cache_token", pipe.name), lora_name,
                 float(strength_model), float(strength_clip),
                 models_dir or "")
    with _lora_lock:
        if cache_key in _lora_cache:
            _lora_cache.move_to_end(cache_key)
            return _lora_cache[cache_key]

    fam = pipe.family
    # export ONLY the towers a nonzero strength can touch: the VAE never,
    # the UNet not on the clip-only path (LoraLoader with split MODEL/CLIP
    # edges), the text towers not on the model-only path — untouched trees
    # are shared by reference into the patched pipeline, not copied
    sd: Dict[str, np.ndarray] = {}
    if strength_model != 0.0:
        sd.update(ckpt._run_unet(
            ckpt._ExportMapper(pipe.unet_params, ckpt.UNET_PREFIX),
            fam.unet))
    if strength_clip != 0.0:
        for ccfg, tree, prefix in zip(fam.clips, pipe.clip_params,
                                      ckpt._clip_prefixes(fam)):
            sd.update(ckpt._clip_runner(ccfg)(
                ckpt._ExportMapper(tree, prefix), ccfg))
    index = build_key_index(sd, fam)

    path = None
    if models_dir:
        cand = os.path.join(models_dir, lora_name.replace("\\", "/"))
        if os.path.exists(cand):
            path = cand
    if path is not None:
        lora_sd = load_lora_state_dict(path)
        log(f"LoRA {lora_name!r}: {len(lora_sd)} tensors from {path}")
    else:
        lora_sd = virtual_lora_state_dict(lora_name, index, sd)
        log(f"virtual LoRA {lora_name!r}: no file on disk, deterministic "
            f"init ({len(lora_sd)} tensors)")

    applied, unmatched = apply_lora_to_state_dict(
        sd, lora_sd, index, strength_model, strength_clip)
    if unmatched:
        log(f"LoRA {lora_name!r}: {len(unmatched)} modules matched no "
            f"weight (first: {unmatched[:3]})")
    debug_log(f"LoRA {lora_name!r}: applied {applied} modules "
              f"(model={strength_model}, clip={strength_clip})")

    if strength_model != 0.0:
        unet_p = ckpt._run_unet(ckpt._LoadMapper(sd, ckpt.UNET_PREFIX),
                                fam.unet)
    else:
        unet_p = pipe.unet_params       # untouched: share, don't copy
    if strength_clip != 0.0:
        clip_ps = [ckpt._clip_runner(c)(ckpt._LoadMapper(sd, p), c)
                   for c, p in zip(fam.clips, ckpt._clip_prefixes(fam))]
    else:
        clip_ps = pipe.clip_params
    from comfyui_distributed_tpu.models.registry import (
        DiffusionPipeline, copy_sampler_patches)
    patched = DiffusionPipeline(
        f"{pipe.name}+{lora_name}", fam, unet_p, clip_ps,
        pipe.vae_params,                # LoRA never touches the VAE
        prediction_type=pipe.prediction_type,
        assets_dir=getattr(pipe, "assets_dir", None))
    # sampling patches ride derivation chains (RescaleCFG / zsnr
    # schedule / PerpNeg -> LoRA): the ONE copy in registry
    copy_sampler_patches(pipe, patched)
    with _lora_lock:
        _lora_cache[cache_key] = patched
        while len(_lora_cache) > _lora_cache_cap:
            old, _ = _lora_cache.popitem(last=False)
            debug_log(f"lora cache: evicting {old!r} "
                      f"(cap {_lora_cache_cap})")
    return patched
