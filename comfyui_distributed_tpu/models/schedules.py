"""Noise schedules and sigma tables.

The reference passes scheduler names ("normal", "karras", ...) straight into
ComfyUI's sampler stack (KSampler widget values in
``workflows/distributed-txt2img.json``; ``common_ksampler`` call at reference
``distributed_upscale.py:521``).  This module provides those schedules
natively: a discrete VP (DDPM) sigma table plus the step-schedule generators,
all as plain numpy (they run once per job at trace time — only the denoise
loop itself is compiled).

Conventions: sigmas are returned **descending**, with a trailing 0.0, shape
``[steps + 1]`` — the k-diffusion convention ComfyUI uses.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class DiscreteSchedule:
    """Discrete VP schedule: sigma_t = sqrt((1 - abar_t) / abar_t).

    SD1.x/SDXL use scaled-linear betas in [0.00085, 0.012] over 1000 steps.
    """

    sigmas: np.ndarray          # ascending, [T]
    alphas_cumprod: np.ndarray  # [T]

    @property
    def sigma_min(self) -> float:
        return float(self.sigmas[0])

    @property
    def sigma_max(self) -> float:
        return float(self.sigmas[-1])

    def t_from_sigma(self, sigma: np.ndarray) -> np.ndarray:
        """Continuous timestep index for a sigma via log-linear interp —
        what gets fed to the UNet's timestep embedding."""
        log_sigmas = np.log(self.sigmas)
        log_s = np.log(np.maximum(np.asarray(sigma, dtype=np.float64), 1e-10))
        return np.interp(log_s, log_sigmas, np.arange(len(self.sigmas)))

    def sigma_from_t(self, t: np.ndarray) -> np.ndarray:
        return np.interp(np.asarray(t, dtype=np.float64),
                         np.arange(len(self.sigmas)), self.sigmas)

    def percent_to_sigma(self, percent: float) -> float:
        """ComfyUI's sampling-percent convention: 0.0 = the very start of
        sampling (sigma_max side), 1.0 = the end (sigma 0) — used by
        ConditioningSetTimestepRange."""
        if percent <= 0.0:
            return float(self.sigmas[-1]) * 1e3   # effectively +inf
        if percent >= 1.0:
            return 0.0
        t = (1.0 - percent) * (len(self.sigmas) - 1)
        # log-sigma interpolation, matching t_from_sigma's (and the
        # reference's) convention — linear interp would shift the gate
        # boundary by a fraction of a step
        return float(np.exp(np.interp(t, np.arange(len(self.sigmas)),
                                      np.log(self.sigmas))))


def make_discrete_schedule(beta_schedule: str = "scaled_linear",
                           beta_start: float = 0.00085,
                           beta_end: float = 0.012,
                           num_timesteps: int = 1000) -> DiscreteSchedule:
    if beta_schedule == "linear":
        betas = np.linspace(beta_start, beta_end, num_timesteps,
                            dtype=np.float64)
    elif beta_schedule == "scaled_linear":
        betas = np.linspace(beta_start ** 0.5, beta_end ** 0.5, num_timesteps,
                            dtype=np.float64) ** 2
    elif beta_schedule == "cosine":
        s = 0.008
        ts = np.arange(num_timesteps + 1, dtype=np.float64) / num_timesteps
        f = np.cos((ts + s) / (1 + s) * math.pi / 2) ** 2
        abar = f / f[0]
        betas = np.clip(1 - abar[1:] / abar[:-1], 0, 0.999)
    else:
        raise ValueError(f"unknown beta schedule {beta_schedule!r}")
    abar = np.cumprod(1.0 - betas)
    sigmas = np.sqrt((1 - abar) / abar)
    return DiscreteSchedule(sigmas=sigmas.astype(np.float32),
                            alphas_cumprod=abar.astype(np.float32))


# --- step-schedule generators ----------------------------------------------

def _append_zero(sigmas: np.ndarray) -> np.ndarray:
    return np.concatenate([sigmas, [0.0]]).astype(np.float32)


def rescale_zero_terminal_snr(ds: DiscreteSchedule) -> DiscreteSchedule:
    """Zero-terminal-SNR rescale (Lin et al., "Common Diffusion Noise
    Schedules and Sample Steps are Flawed") — ModelSamplingDiscrete's
    ``zsnr`` toggle: shift+scale sqrt(abar) so the final step carries
    zero signal.  The exact rescale sends the terminal sigma to
    infinity; the terminal abar clamps at 4.8973451890853435e-08
    (sigma ~ 4519) — the reference ecosystem's pinned constant, so
    zsnr-patched models start sampling from the same sigma_max."""
    abar_sqrt = np.sqrt(ds.alphas_cumprod)
    a0, aT = abar_sqrt[0], abar_sqrt[-1]
    abar_sqrt = (abar_sqrt - aT) * (a0 / (a0 - aT))
    abar = np.clip(abar_sqrt ** 2, 4.8973451890853435e-08, 1.0)
    sigmas = np.sqrt((1.0 - abar) / abar)
    return DiscreteSchedule(sigmas=sigmas.astype(np.float32),
                            alphas_cumprod=abar.astype(np.float32))


def normal_scheduler(ds: DiscreteSchedule, steps: int, sgm: bool = False) -> np.ndarray:
    """Uniform in timestep space over the model's sigma table."""
    start = ds.t_from_sigma(ds.sigma_max)
    end = ds.t_from_sigma(ds.sigma_min)
    if sgm:
        ts = np.linspace(start, end, steps + 1)[:-1]
    else:
        ts = np.linspace(start, end, steps)
    return _append_zero(ds.sigma_from_t(ts))


def karras_scheduler(ds: Optional[DiscreteSchedule], steps: int,
                     rho: float = 7.0,
                     sigma_min: Optional[float] = None,
                     sigma_max: Optional[float] = None) -> np.ndarray:
    """Karras et al. 2022 rho-schedule.  Bounds default to the model
    schedule's; explicit bounds serve the KarrasScheduler node (ds may
    then be None) — ONE copy of the ramp math."""
    lo = float(sigma_min if sigma_min is not None else ds.sigma_min)
    hi = float(sigma_max if sigma_max is not None else ds.sigma_max)
    ramp = np.linspace(0, 1, steps)
    min_r, max_r = lo ** (1 / rho), hi ** (1 / rho)
    sigmas = (max_r + ramp * (min_r - max_r)) ** rho
    return _append_zero(sigmas)


def polyexponential_sigmas(steps: int, sigma_max: float,
                           sigma_min: float,
                           rho: float = 1.0) -> np.ndarray:
    """k-diffusion get_sigmas_polyexponential: polynomial ramp in
    log-sigma (PolyexponentialScheduler node)."""
    ramp = np.linspace(1.0, 0.0, steps) ** rho
    sig = np.exp(ramp * (math.log(sigma_max) - math.log(sigma_min))
                 + math.log(sigma_min))
    return np.concatenate([sig, [0.0]]).astype(np.float32)


def vp_sigmas(steps: int, beta_d: float = 19.9, beta_min: float = 0.1,
              eps_s: float = 1e-3) -> np.ndarray:
    """k-diffusion get_sigmas_vp: the continuous VP-SDE noise schedule
    (VPScheduler node)."""
    t = np.linspace(1.0, eps_s, steps)
    sig = np.sqrt(np.exp(beta_d * t ** 2 / 2 + beta_min * t) - 1.0)
    return np.concatenate([sig, [0.0]]).astype(np.float32)


def laplace_sigmas(steps: int, sigma_max: float, sigma_min: float,
                   mu: float = 0.0, beta: float = 0.5) -> np.ndarray:
    """k-diffusion get_sigmas_laplace (LaplaceScheduler node): inverse
    Laplace CDF spacing in log-sigma, clipped to the bounds."""
    epsilon = 1e-5
    x = np.linspace(0.0, 1.0, steps)
    lmb = mu - beta * np.sign(0.5 - x) * np.log(1 - 2 * np.abs(0.5 - x)
                                                + epsilon)
    sig = np.clip(np.exp(lmb), sigma_min, sigma_max)
    return np.concatenate([sig, [0.0]]).astype(np.float32)


# NVIDIA Align-Your-Steps 10-step reference tables (the public release's
# noise levels); other step counts log-linearly interpolate like the
# reference ecosystem's AlignYourStepsScheduler
AYS_TABLES = {
    "SD1": [14.615, 6.475, 3.861, 2.697, 1.886, 1.396, 0.963, 0.652,
            0.399, 0.152, 0.029],
    "SDXL": [14.615, 6.315, 3.771, 2.181, 1.342, 0.862, 0.555, 0.380,
             0.234, 0.113, 0.029],
    "SVD": [700.00, 54.5, 15.886, 7.977, 4.248, 1.789, 0.981, 0.403,
            0.173, 0.034, 0.002],
}


def ays_sigmas(model_type: str, steps: int) -> np.ndarray:
    """AlignYourSteps: log-linear interpolation of the model line's
    reference table to the requested step count, trailing 0."""
    key = str(model_type).upper().replace("1.5", "1").replace("SD15",
                                                              "SD1")
    if key not in AYS_TABLES:
        raise ValueError(f"unknown AYS model type {model_type!r}; "
                         f"available: {tuple(AYS_TABLES)}")
    table = np.asarray(AYS_TABLES[key], np.float64)
    xs = np.linspace(0.0, 1.0, table.shape[0])
    xq = np.linspace(0.0, 1.0, int(steps) + 1)
    return np.exp(np.interp(xq, xs, np.log(table))).astype(np.float32)


def sd_turbo_sigmas(ds: DiscreteSchedule, steps: int,
                    denoise: float = 1.0) -> np.ndarray:
    """SDTurboScheduler: the distilled-model schedule samples the LAST
    ``steps`` of 1000//denoise-spaced timesteps (the reference node's
    arange/flip indexing), trailing 0."""
    steps = max(int(steps), 1)
    # reference: 10 - int(10*denoise), NOT int(10 - 10*denoise) — the
    # forms differ for fractional denoise (0.25 -> start 8 vs 7)
    start = max(10 - int(10 * float(denoise)), 0)
    ts = np.flip(np.arange(1, 11) * 100 - 1)[start:start + steps]
    sig = ds.sigmas[ts.astype(int)]
    return np.concatenate([sig, [0.0]]).astype(np.float32)


def exponential_scheduler(ds: DiscreteSchedule, steps: int) -> np.ndarray:
    sigmas = np.exp(np.linspace(math.log(ds.sigma_max),
                                math.log(ds.sigma_min), steps))
    return _append_zero(sigmas)


def simple_scheduler(ds: DiscreteSchedule, steps: int) -> np.ndarray:
    """Every (T/steps)-th entry of the model table, descending."""
    ss = len(ds.sigmas) / steps
    sigmas = [float(ds.sigmas[-(1 + int(i * ss))]) for i in range(steps)]
    return _append_zero(np.asarray(sigmas))


def ddim_uniform_scheduler(ds: DiscreteSchedule, steps: int) -> np.ndarray:
    T = len(ds.sigmas)
    ss = max(T // steps, 1)
    timesteps = np.asarray(list(range(1, T + 1, ss))[:steps], dtype=np.int64)
    sigmas = ds.sigmas[timesteps - 1][::-1]
    return _append_zero(sigmas)


def beta_scheduler(ds: DiscreteSchedule, steps: int,
                   alpha: float = 0.6, beta: float = 0.6) -> np.ndarray:
    """Beta-distribution spacing (comfy 'beta'); falls back to uniform
    timesteps if scipy is unavailable."""
    try:
        import scipy.stats as st
        ts = 1.0 - np.linspace(0, 1, steps, endpoint=False)
        ts = st.beta.ppf(ts, alpha, beta)
    except ImportError:  # pragma: no cover
        ts = 1.0 - np.linspace(0, 1, steps, endpoint=False)
    T = len(ds.sigmas)
    idx = np.clip((ts * (T - 1)).round().astype(np.int64), 0, T - 1)
    # dedupe while preserving order, keep descending sigma
    seen, chosen = set(), []
    for i in idx:
        if int(i) not in seen:
            seen.add(int(i))
            chosen.append(int(i))
    sigmas = ds.sigmas[np.asarray(chosen)]
    return _append_zero(sigmas)


def linear_quadratic_scheduler(ds: DiscreteSchedule, steps: int,
                               threshold_noise: float = 0.025,
                               linear_steps: Optional[int] = None) -> np.ndarray:
    """Linear-then-quadratic denoising progress (comfy 'linear_quadratic'):
    progress p(i) rises linearly to ``threshold_noise`` over the first
    ``linear_steps``, then follows the quadratic that matches value and slope
    there and reaches 1 at the final step.  Sigmas are (1 - p) * sigma_max."""
    if steps == 1:
        return _append_zero(np.asarray([ds.sigma_max]))
    L = linear_steps if linear_steps is not None else steps // 2
    L = int(np.clip(L, 1, steps - 1))
    i = np.arange(steps + 1, dtype=np.float64)
    slope = threshold_noise / L
    # quadratic a*u^2 + slope*u + threshold_noise on u = i - L, with p(steps)=1
    u_end = steps - L
    a = (1.0 - threshold_noise - slope * u_end) / (u_end ** 2)
    u = i - L
    p = np.where(i <= L, slope * i, a * u ** 2 + slope * u + threshold_noise)
    sigmas = (1.0 - p[:-1]) * ds.sigma_max
    return _append_zero(sigmas)


def kl_optimal_scheduler(ds: DiscreteSchedule, steps: int) -> np.ndarray:
    """AYS 'KL-optimal' spacing (arctan interpolation), Sabour et al. 2024."""
    t = np.linspace(0, 1, steps)
    sigmas = np.tan((1 - t) * math.atan(ds.sigma_max)
                    + t * math.atan(ds.sigma_min))
    return _append_zero(sigmas)


SCHEDULERS: Dict[str, Callable[[DiscreteSchedule, int], np.ndarray]] = {
    "normal": normal_scheduler,
    "karras": karras_scheduler,
    "exponential": exponential_scheduler,
    "sgm_uniform": lambda ds, n: normal_scheduler(ds, n, sgm=True),
    "simple": simple_scheduler,
    "ddim_uniform": ddim_uniform_scheduler,
    "beta": beta_scheduler,
    "linear_quadratic": linear_quadratic_scheduler,
    "kl_optimal": kl_optimal_scheduler,
}

SCHEDULER_NAMES = tuple(SCHEDULERS.keys())


def compute_sigmas(ds: DiscreteSchedule, scheduler: str, steps: int,
                   denoise: float = 1.0) -> np.ndarray:
    """Full sigma sequence for a run; ``denoise < 1`` truncates to the final
    fraction of steps — img2img semantics matching the reference's tiled
    refine (``denoise`` widget, reference ``distributed_upscale.py:50-79``)."""
    if scheduler not in SCHEDULERS:
        raise ValueError(f"unknown scheduler {scheduler!r}; "
                         f"available: {SCHEDULER_NAMES}")
    if denoise >= 0.9999:
        return SCHEDULERS[scheduler](ds, steps)
    if denoise <= 0.0:
        return np.asarray([0.0], dtype=np.float32)
    total = max(int(steps / denoise), steps)
    full = SCHEDULERS[scheduler](ds, total)
    return full[-(steps + 1):]
