"""Model zoo: diffusion backbones, text encoders, VAEs, samplers, schedules.

The reference outsources 100% of its compute to ComfyUI's model stack
(``common_ksampler``, VAE, CLIP — see SURVEY.md §7 "Hard parts"); this package
is the from-scratch TPU-native equivalent: flax/linen modules in NHWC layout
with bfloat16 compute, jit/scan-friendly samplers, and XLA-compiled schedules.
"""
