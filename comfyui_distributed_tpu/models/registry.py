"""Model families, virtual checkpoints, and the DiffusionPipeline bundle.

The reference's CheckpointLoaderSimple hands back ComfyUI (MODEL, CLIP, VAE)
objects; here the equivalent bundle is a :class:`DiffusionPipeline`.  When the
named checkpoint file exists it is loaded (safetensors, torch key mapping —
``checkpoints.py``); when it does not (zero-egress dev boxes, CI), parameters
are **virtually initialized**: deterministic random init seeded from the
checkpoint name, so every mesh host materializes identical weights without
any file — the reference's "same models on all machines" requirement
(``README.md:189-193``) satisfied by construction.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import os
import threading
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from comfyui_distributed_tpu.models import clip as clip_mod
from comfyui_distributed_tpu.models import samplers as smp
from comfyui_distributed_tpu.models import schedules as sch
from comfyui_distributed_tpu.models import unet as unet_mod
from comfyui_distributed_tpu.models import vae as vae_mod
from comfyui_distributed_tpu.models.denoiser import make_denoiser
from comfyui_distributed_tpu.models.tokenizer import make_tokenizer
from comfyui_distributed_tpu.parallel import sharding as shd
from comfyui_distributed_tpu.models.upscalers import (
    ESRGAN_4X_CONFIG,
    TINY_RRDB_CONFIG,
    RRDBNet,
)
from comfyui_distributed_tpu.utils.logging import log


@dataclasses.dataclass(frozen=True)
class ModelFamily:
    name: str
    unet: unet_mod.UNetConfig
    vae: vae_mod.VAEConfig
    clips: Tuple[clip_mod.CLIPConfig, ...]
    latent_channels: int = 4
    # how the UNet's ADM vector is built: "sdxl" (pooled text + size
    # embeds) or "unclip" (noise-augmented CLIP-vision embed + noise
    # level embedding — ops/basic.py _sdxl_vector_cond)
    adm_kind: str = "sdxl"
    # in-checkpoint key prefixes for the text tower(s), when the family
    # deviates from the standard cond_stage_model/conditioner layouts
    # (checkpoints._clip_prefixes falls back to those when None)
    clip_prefixes: Optional[Tuple[str, ...]] = None


FAMILIES: Dict[str, ModelFamily] = {
    "sd15": ModelFamily(
        name="sd15",
        unet=unet_mod.SD15_CONFIG,
        vae=vae_mod.SD_VAE_CONFIG,
        clips=(clip_mod.CLIP_L_CONFIG,),
    ),
    "sdxl": ModelFamily(
        name="sdxl",
        unet=unet_mod.SDXL_CONFIG,
        vae=vae_mod.SDXL_VAE_CONFIG,
        clips=(clip_mod.CLIP_L_SDXL_CONFIG, clip_mod.OPEN_CLIP_BIGG_CONFIG),
    ),
    # SDXL refiner: bigG tower only (embedder 0 in the refiner file),
    # 2560-channel ADM with the 5-scalar (h, w, crop_h, crop_w,
    # aesthetic_score) embedding layout CLIPTextEncodeSDXLRefiner emits
    "sdxl_refiner": ModelFamily(
        name="sdxl_refiner",
        unet=unet_mod.SDXL_REFINER_CONFIG,
        vae=vae_mod.SDXL_VAE_CONFIG,
        clips=(clip_mod.OPEN_CLIP_BIGG_CONFIG,),
        # the refiner stores its (only) bigG tower as embedder 0 of the
        # SGM conditioner, not under cond_stage_model
        clip_prefixes=("conditioner.embedders.0.model.",),
    ),
    "sd21": ModelFamily(
        name="sd21",
        unet=unet_mod.SD21_CONFIG,          # v-prediction (768-v line)
        vae=vae_mod.SD_VAE_CONFIG,
        clips=(clip_mod.OPEN_CLIP_H_CONFIG,),
    ),
    "sd21_base": ModelFamily(
        name="sd21_base",
        unet=unet_mod.SD21_BASE_CONFIG,     # eps (512-base line)
        vae=vae_mod.SD_VAE_CONFIG,
        clips=(clip_mod.OPEN_CLIP_H_CONFIG,),
    ),
    # inpaint model lines: the UNet consumes [latent(4), mask(1),
    # masked-image latent(4)] = 9 input channels (RunwayML
    # sd-v1.5-inpainting layout); everything else matches the base family
    "sd15_inpaint": ModelFamily(
        name="sd15_inpaint",
        unet=dataclasses.replace(unet_mod.SD15_CONFIG, in_channels=9),
        vae=vae_mod.SD_VAE_CONFIG,
        clips=(clip_mod.CLIP_L_CONFIG,),
    ),
    # InstructPix2Pix: [latent(4), source-image latent(4)] = 8 input
    # channels, no mask (timbrooks/instruct-pix2pix layout)
    "sd15_ip2p": ModelFamily(
        name="sd15_ip2p",
        unet=dataclasses.replace(unet_mod.SD15_CONFIG, in_channels=8),
        vae=vae_mod.SD_VAE_CONFIG,
        clips=(clip_mod.CLIP_L_CONFIG,),
    ),
    "sd21_inpaint": ModelFamily(       # 512-inpainting-ema (eps line)
        name="sd21_inpaint",
        unet=dataclasses.replace(unet_mod.SD21_BASE_CONFIG,
                                 in_channels=9),
        vae=vae_mod.SD_VAE_CONFIG,
        clips=(clip_mod.OPEN_CLIP_H_CONFIG,),
    ),
    "sdxl_inpaint": ModelFamily(
        name="sdxl_inpaint",
        unet=dataclasses.replace(unet_mod.SDXL_CONFIG, in_channels=9),
        vae=vae_mod.SDXL_VAE_CONFIG,
        clips=(clip_mod.CLIP_L_SDXL_CONFIG,
               clip_mod.OPEN_CLIP_BIGG_CONFIG),
    ),
    # SD2.1-unclip (stable-diffusion-2-1-unclip, "h" line): the SD21
    # v-pred UNet grown an ADM head consuming the noise-augmented ViT-H
    # image embedding (1024) + the noise-level timestep embedding (1024)
    "sd21_unclip": ModelFamily(
        name="sd21_unclip",
        unet=dataclasses.replace(unet_mod.SD21_CONFIG,
                                 adm_in_channels=2048),
        vae=vae_mod.SD_VAE_CONFIG,
        clips=(clip_mod.OPEN_CLIP_H_CONFIG,),
        adm_kind="unclip",
    ),
    "tiny": ModelFamily(
        name="tiny",
        unet=unet_mod.TINY_CONFIG,
        vae=vae_mod.TINY_VAE_CONFIG,
        clips=(clip_mod.TINY_CLIP_CONFIG,),
    ),
    "tiny_unclip": ModelFamily(
        name="tiny_unclip",
        unet=dataclasses.replace(unet_mod.TINY_CONFIG,
                                 adm_in_channels=64),
        vae=vae_mod.TINY_VAE_CONFIG,
        clips=(clip_mod.TINY_CLIP_CONFIG,),
        adm_kind="unclip",
    ),
    # SDXL-shaped tiny family: an ADM head wide enough (128 > the tiny
    # pooled width 64) that CLIPTextEncodeSDXL's size embeddings
    # actually reach the UNet — the sdxl fixture's CPU test target
    "tiny_sdxl": ModelFamily(
        name="tiny_sdxl",
        unet=dataclasses.replace(unet_mod.TINY_CONFIG,
                                 adm_in_channels=128),
        vae=vae_mod.TINY_VAE_CONFIG,
        clips=(clip_mod.TINY_CLIP_CONFIG,),
    ),
    "tiny_inpaint": ModelFamily(
        name="tiny_inpaint",
        unet=dataclasses.replace(unet_mod.TINY_CONFIG, in_channels=9),
        vae=vae_mod.TINY_VAE_CONFIG,
        clips=(clip_mod.TINY_CLIP_CONFIG,),
    ),
    "tiny_ip2p": ModelFamily(
        name="tiny_ip2p",
        unet=dataclasses.replace(unet_mod.TINY_CONFIG, in_channels=8),
        vae=vae_mod.TINY_VAE_CONFIG,
        clips=(clip_mod.TINY_CLIP_CONFIG,),
    ),
}

FAMILY_ENV = "DTPU_DEFAULT_FAMILY"


def _window_key(w):
    """Hashable form of a ControlNet sigma-window spec: None, one
    (start, end) pair, or the ops-layer nested per-block structure."""
    if w is None:
        return None
    if isinstance(w, (tuple, list)) and w \
            and isinstance(w[0], (tuple, list, type(None))):
        return tuple(_window_key(x) for x in w)
    return (float(w[0]), float(w[1]))


def _strength_key(strength):
    """ControlNet strength as a hashable static value: a scalar, a flat
    per-block tuple, or ops/basic.py's ``(pos_strengths, neg_strengths)``
    nested pair (see models/denoiser.py for the block semantics)."""
    if isinstance(strength, (tuple, list)):
        return tuple(tuple(float(v) for v in s)
                     if isinstance(s, (tuple, list)) else float(s)
                     for s in strength)
    return float(strength)


def detect_family(ckpt_name: str) -> str:
    """Family from checkpoint-name heuristics; ``DTPU_DEFAULT_FAMILY``
    overrides (tests/CI force 'tiny')."""
    env = os.environ.get(FAMILY_ENV)
    if env:
        return env
    lowered = ckpt_name.lower()
    inpaint = "inpaint" in lowered
    if "tiny" in lowered or "test" in lowered:
        if "unclip" in lowered:
            return "tiny_unclip"
        if "ip2p" in lowered or "pix2pix" in lowered:
            return "tiny_ip2p"
        return "tiny_inpaint" if inpaint else "tiny"
    # timbrooks/instruct-pix2pix style finetunes (8-channel UNet)
    if "ip2p" in lowered or "pix2pix" in lowered:
        return "sd15_ip2p"
    if "unclip" in lowered:
        return "sd21_unclip"
    if "xl" in lowered:
        if "refiner" in lowered:
            return "sdxl_refiner"
        return "sdxl_inpaint" if inpaint else "sdxl"
    # Stability SD2 naming only — a bare "v2" would misroute SD1.5
    # community finetunes like anything-v2 / counterfeit-v2.5
    # (512-inpainting-ema is the SD2 line's inpaint checkpoint)
    if ("sd2" in lowered or "v2-0" in lowered or "v2-1" in lowered
            or "768-v" in lowered or "512-base" in lowered
            or "512-inpainting" in lowered):
        if inpaint:
            return "sd21_inpaint"
        # v2-1_768-ema-pruned is the v-pred line; v2-1_512-ema-pruned /
        # 512-base-ema the eps line
        return "sd21" if ("768" in lowered or "v-pred" in lowered
                          or "vpred" in lowered) else "sd21_base"
    # sd-v1-5-inpainting / *-inpainting finetunes (9-channel UNet)
    return "sd15_inpaint" if inpaint else "sd15"


def _name_seed(name: str) -> int:
    return int.from_bytes(hashlib.sha256(name.encode()).digest()[:4], "little")


class DiffusionPipeline:
    """(MODEL, CLIP, VAE) bundle + tokenizer + schedule + jit caches."""

    def __init__(self, name: str, family: ModelFamily,
                 unet_params: Any, clip_params: List[Any], vae_params: Any,
                 prediction_type: str = "eps",
                 assets_dir: Optional[str] = None):
        self.name = name
        self.family = family
        self.unet = unet_mod.UNet(family.unet)
        self.clip_models = [clip_mod.CLIPTextModel(c) for c in family.clips]
        self.vae = vae_mod.VAE(family.vae)
        self.unet_params = unet_params
        self.clip_params = clip_params
        self.vae_params = vae_params
        self.prediction_type = prediction_type
        self.assets_dir = assets_dir
        # unique identity for derived-pipeline caches: ``name`` alone is
        # just the ckpt filename, which two pipelines of different
        # families/models_dirs can share (load_pipeline overwrites this
        # with its full cache key)
        self.cache_token = f"{name}:{family.name}:{assets_dir or ''}"
        self.schedule = sch.make_discrete_schedule()
        # real CLIP BPE when vocab.json/merges.txt sit in the models dir
        # (zero-egress asset drop); deterministic hash tokenizer otherwise
        # pad convention follows the text tower: CLIP (SD1.x/SDXL) pads
        # with EOT, OpenCLIP (SD2.x) pads with 0 — ComfyUI's sd2 tokenizer
        self.tokenizer = make_tokenizer(
            assets_dir=assets_dir,
            vocab_size=min(c.vocab_size for c in family.clips),
            pad_with_end=not all(c.layout == "openclip"
                                 for c in family.clips))
        # LRU-bounded: every (resolution, batch, sampler...) combination is
        # its own compiled executable; an unbounded dict leaks one per shape
        # seen.  16 live entries cover a realistic session (clip×2, vae×2,
        # and a dozen sample configs); evictions are logged.
        self._jit_cache: "collections.OrderedDict[Any, Any]" = \
            collections.OrderedDict()
        self._jit_cache_cap = int(os.environ.get("DTPU_JIT_CACHE_CAP", "16"))
        self._lock = threading.Lock()
        self._tp_mesh = None   # mesh the params are currently tp-laid-out for

    # --- tensor parallelism -------------------------------------------------

    def _ensure_tp_sharded(self) -> None:
        """Lay the UNet/CLIP/VAE params out for tensor parallelism
        when the live mesh has a ``tensor`` axis (megatron-style column splits via
        ``parallel/sharding.params_shardings``; GSPMD inserts the
        matching collectives inside the jitted sample core).  No-op on
        tensor==1 meshes and when already laid out for this mesh, so the
        single-chip serving path pays nothing.  This is the serving-side
        counterpart of ``parallel/train.shard_train_step`` — without it
        tp was train-only and inference weights stayed replicated.
        Floor override for tiny test models: ``DTPU_TP_MIN_SHARD_ELEMENTS``."""
        from comfyui_distributed_tpu.parallel.mesh import get_live_runtime
        from comfyui_distributed_tpu.utils.constants import TENSOR_AXIS
        rt = get_live_runtime()
        if rt is None or rt.mesh is None:
            return
        mesh = rt.mesh
        if int(mesh.shape.get(TENSOR_AXIS, 1)) <= 1 \
                or self._tp_mesh is mesh:
            return
        from comfyui_distributed_tpu.parallel import sharding as shd
        min_el = int(os.environ.get("DTPU_TP_MIN_SHARD_ELEMENTS",
                                    shd.MIN_SHARD_ELEMENTS))
        with self._lock:
            if self._tp_mesh is mesh:
                return

            def lay_out(tree):
                if not tree:
                    return tree
                sh = shd.params_shardings(tree, mesh,
                                          min_elements=min_el)
                return shd.apply_shardings(tree, sh)

            self.unet_params = lay_out(self.unet_params)
            self.clip_params = [lay_out(p) for p in self.clip_params]
            self.vae_params = lay_out(self.vae_params)
            self._tp_mesh = mesh
            # Cached cores were TRACED while no mesh was live, so every
            # activation constraint (shd.constrain*) resolved to a no-op
            # inside the cached jaxpr — jit re-lowers for the new param
            # shardings but never re-traces, which would serve the
            # tp-concat-cpu-miscompile graph.  A layout transition is a
            # serve-boot one-off; drop the cache so post-layout traces
            # re-resolve the gates against the live mesh.
            self._jit_cache.clear()
            log(f"tp: UNet/CLIP/VAE params laid out over tensor="
                f"{int(mesh.shape[TENSOR_AXIS])} for serving")

    # --- text ---------------------------------------------------------------

    def encode_prompt(self, texts: List[str],
                      texts_alt: Optional[List[str]] = None,
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Returns (context [B, 77, sum(widths)], pooled [B, pooled_dim]).
        Multi-encoder families (SDXL) concatenate hidden widths; pooled comes
        from the last encoder.  Token weights scale the hidden states around
        the per-sequence mean (comfy-style emphasis).

        ``texts_alt``: optional prompts for towers AFTER the first —
        ComfyUI's CLIPTextEncodeSDXL text_g/text_l split (text_l feeds
        CLIP-L, text_g the OpenCLIP tower whose pooled output becomes
        the ADM vector).  Single-tower families ignore it.

        ``embedding:name`` references (textual inversion) splice learned
        vectors from ``<models_dir>/embeddings/`` into the token stream,
        per tower (SDXL files carry clip_l/clip_g keys)."""
        from comfyui_distributed_tpu.models.tokenizer import (
            encode_with_embeddings, has_embedding_refs)

        self._ensure_tp_sharded()
        outs, pooled = [], None
        for i, (m, p) in enumerate(zip(self.clip_models,
                                       self.clip_params)):
            ts = texts if i == 0 or texts_alt is None else texts_alt
            width = int(m.cfg.width)
            if any(has_embedding_refs(t) for t in ts):
                def _look(nm, _i=i, _w=width):
                    return load_textual_embedding(
                        nm, self.assets_dir, _w, tower_idx=_i)

                quads = [encode_with_embeddings(self.tokenizer, t,
                                                _look, width) for t in ts]
                ia = jnp.asarray(np.stack([q[0] for q in quads]))
                wa = jnp.asarray(np.stack([q[1] for q in quads]))
                ov = jnp.asarray(np.stack([q[2] for q in quads]))
                mk = jnp.asarray(np.stack([q[3] for q in quads]))
                fn = self._jitted(("clip_ov", id(m)), partial(m.apply))
                hidden, pool = fn({"params": p}, ia, ov, mk)
            else:
                pairs = [self.tokenizer.encode(t) for t in ts]
                ia = jnp.asarray(np.stack([x for x, _ in pairs]))
                wa = jnp.asarray(np.stack([w for _, w in pairs]))
                fn = self._jitted(("clip", id(m)), partial(m.apply))
                hidden, pool = fn({"params": p}, ia)
            mean = hidden.mean(axis=1, keepdims=True)
            hidden = mean + (hidden - mean) * wa[..., None]
            outs.append(hidden)
            pooled = pool
        return jnp.concatenate(outs, axis=-1), pooled

    # --- latents ------------------------------------------------------------

    def vae_encode(self, images: jnp.ndarray) -> jnp.ndarray:
        self._ensure_tp_sharded()
        fn = self._jitted("vae_enc", lambda p, x: self.vae.apply(
            {"params": p}, x, method=self.vae.encode))
        return fn(self.vae_params, images)

    def vae_encode_tiled(self, images: jnp.ndarray, tile_size: int = 512,
                         overlap: int = 64,
                         check_interrupt=None) -> jnp.ndarray:
        """Encode in overlapping pixel tiles, feather-blending at latent
        resolution (ComfyUI's VAEEncodeTiled): bounds encoder activation
        memory for 4K+ sources.  Like the tiled decode, per-tile
        GroupNorm statistics make it close to — not bit-identical with —
        the one-shot encode."""
        ds = self.family.vae.downscale
        B, H, W, _ = images.shape
        lt = max(tile_size // ds, 2 * max(overlap // ds, 1))
        lo = max(overlap // ds, 1)
        if H // ds <= lt and W // ds <= lt:
            return self.vae_encode(images)
        from comfyui_distributed_tpu.ops.tiling import tiled_apply_down
        return jnp.asarray(tiled_apply_down(
            self.vae_encode, np.asarray(images, np.float32), lt, lo, ds,
            out_channels=self.family.latent_channels,
            check_interrupt=check_interrupt))

    def vae_decode(self, latents: jnp.ndarray) -> jnp.ndarray:
        self._ensure_tp_sharded()
        fn = self._jitted("vae_dec", lambda p, z: self.vae.apply(
            {"params": p}, z, method=self.vae.decode))
        return fn(self.vae_params, latents)

    def vae_decode_tiled(self, latents: jnp.ndarray, tile_size: int = 512,
                         overlap: int = 64,
                         check_interrupt=None) -> jnp.ndarray:
        """Decode in overlapping latent tiles with feathered blending
        (ComfyUI's VAEDecodeTiled): bounds decoder activation memory at 4K+
        where a one-shot decode would OOM a chip.  Tiles are uniform
        (clamped start positions), so one executable serves every tile.

        Like the torch ecosystem's tiled VAE, per-tile GroupNorm statistics
        differ slightly from a full decode — the overlap feather hides the
        seams; it is not bit-identical to ``vae_decode``."""
        ds = self.family.vae.downscale
        B, H, W, _ = latents.shape
        lt = max(tile_size // ds, 2 * max(overlap // ds, 1))
        lo = max(overlap // ds, 1)
        if H <= lt and W <= lt:
            return self.vae_decode(latents)
        from comfyui_distributed_tpu.ops.tiling import tiled_apply
        return jnp.asarray(tiled_apply(
            self.vae_decode, np.asarray(latents, np.float32), lt, lo, ds,
            out_channels=3, check_interrupt=check_interrupt))

    # --- denoising ----------------------------------------------------------

    def raw_unet_apply(self, params, x, t, context, y=None, control=None,
                       context_v=None, objs=None):
        return self.unet.apply({"params": params}, x, t, context, y=y,
                               control=control, context_v=context_v,
                               objs=objs)

    def raw_unet_apply_capture(self, params, x, t, context, y=None,
                               control=None, context_v=None, objs=None):
        """Like raw_unet_apply but returns (prediction, attn_probs): the
        sag_capture family flag makes the mid-block attn1 sow its
        softmax weights (SAG's blur mask source)."""
        out, inters = self.unet.apply(
            {"params": params}, x, t, context, y=y, control=control,
            context_v=context_v, objs=objs, mutable=["intermediates"])
        leaves = jax.tree_util.tree_leaves(inters)
        if len(leaves) != 1:
            raise RuntimeError(
                f"SAG capture expected exactly one sown attn-probs "
                f"tensor, got {len(leaves)} (is sag_capture set on the "
                "family?)")
        return out, leaves[0]

    def denoiser(self):
        return make_denoiser(self.raw_unet_apply, self.unet_params,
                             self.schedule, self.prediction_type)

    def denoise_step_fn(self, sampler_name: str, cfg: float,
                        rows: int, latent_hw: tuple,
                        has_y: bool = False):
        """One jitted denoise STEP over a padded ``rows``-sample batch —
        the continuous-batching executor's per-bucket kernel
        (workflow/batch_executor.py).  Signature:

            step(unet_params, x, ctx, unc, y, keys, sigma, sigma_next,
                 step_i, active) -> x'

        where ``sigma``/``sigma_next``/``step_i`` are per-sample ``[rows]``
        vectors (each slot at its own schedule position), ``keys`` the
        per-sample PRNG keys, and ``active`` a ``[rows]`` bool mask —
        inactive (padding / retired-slot) rows pass through unchanged.

        The model construction mirrors :meth:`sample`'s ``make_core``
        for the plain single-entry CFG case EXACTLY (same
        ``make_denoiser`` + ``cfg_denoiser_multi`` wrapping, same y
        stacking), and the per-step math is the SAME extracted step
        callable the scan samplers run (samplers.SAMPLER_STEPS) — so a
        slot stepped here is bit-identical to its serial run.  Cached in
        the same LRU jit cache as the full-loop cores (one executable
        per (sampler, cfg, padded shape): zero steady-state retraces);
        ``x`` is donated, so the persistent batch updates in place."""
        self._ensure_tp_sharded()
        cfg_rescale = float(getattr(self, "cfg_rescale", 0.0) or 0.0)
        static_key = ("cb_step", sampler_name, float(cfg), cfg_rescale,
                      int(rows), tuple(latent_hw), bool(has_y),
                      self.prediction_type)

        def make_step():
            step_impl = smp.get_sampler_step(sampler_name)
            cfg_scale = float(cfg)
            reps = 1 + (1 if cfg_scale != 1.0 else 0)

            def step(unet_params, x, ctx, unc, y_in, keys, sigma,
                     sigma_next, step_i, active):
                # 2-D CB composition (ISSUE 16): pin the persistent batch
                # to its canonical rows-on-data layout on BOTH ends of the
                # step, so the donated output sharding always matches the
                # input and every steady-state call sees one layout —
                # anything else would re-lower per call and break the
                # zero-retrace invariant.  Inert without a tensor axis.
                x = shd.constrain_rows(x)
                den = make_denoiser(self.raw_unet_apply, unet_params,
                                    self.schedule, self.prediction_type)
                model = smp.cfg_denoiser_multi(
                    den, [(ctx, None, 1.0, None)],
                    [(unc, None, 1.0, None)], cfg_scale,
                    cfg_rescale=cfg_rescale)
                if not has_y:
                    extra = {}
                else:
                    y2 = shd.stack_rows([y_in] * reps) \
                        if reps > 1 else y_in
                    extra = {"y": y2}
                x_new = step_impl(model, x, sigma, sigma_next, step_i,
                                  keys, extra_args=extra)
                act = jnp.reshape(active, (-1,) + (1,) * (x.ndim - 1))
                return shd.constrain_rows(jnp.where(act, x_new, x))

            return jax.jit(step, donate_argnums=(1,))

        return self._cache_get_or_make(static_key, make_step)

    def sample(self, latents: jnp.ndarray, context: jnp.ndarray,
               uncond_context: jnp.ndarray, seeds,
               steps: int, cfg: float, sampler_name: str, scheduler: str,
               denoise: float = 1.0, y: Optional[jnp.ndarray] = None,
               add_noise: bool = True, sample_idx=None,
               start_step: int = 0, end_step: Optional[int] = None,
               force_full_denoise: bool = False,
               noise_mask: Optional[jnp.ndarray] = None,
               control=None,
               sigmas_override=None,
               middle_context=None,
               cfg2: float = 1.0,
               guidance: str = "dual",
               c_concat=None,
               gligen_objs=None,
               donate_latents: bool = False) -> jnp.ndarray:
        """Full ksampler: schedule -> noise -> scan-sampler -> latents.

        ``seeds``: per-sample host seed array [B] (64-bit ok; replica offsets
        already applied by the distributed layer).  ``sample_idx``: optional
        per-sample fold-in indices (replica-local positions in SPMD runs).
        ``start_step``/``end_step`` run a window of the schedule (ComfyUI's
        KSamplerAdvanced): noise scales by the window's FIRST sigma, and
        stopping early returns a still-noisy latent for a later stage
        unless ``force_full_denoise`` zeroes the final sigma.
        ``donate_latents``: the caller warrants no other reference to the
        ``latents`` buffer exists — the jitted denoise loop DONATES it to
        XLA (the scan carry aliases it), halving peak latent memory per
        replica; the input ``jax.Array`` is invalidated.  With it False
        a defensive on-device copy is donated instead (one extra latent
        buffer, identical numerics, upstream buffer untouched).
        ``noise_mask`` [B_or_1, h, w, 1] in latent resolution inpaints: 1 =
        resample, 0 = keep source.  ComfyUI's KSamplerX0Inpaint semantics —
        every model call sees the source re-noised to the current sigma
        outside the mask and its denoised output re-anchored to the clean
        source there.
        ``context`` / ``uncond_context`` are single cond arrays OR LISTS
        of ``(context, area_mask_or_None, strength)`` entries (ComfyUI
        multi-entry cond lists — regional prompting): all entries of
        both CFG sides evaluate in one stacked model call and blend by
        mask (samplers.cfg_denoiser_multi).  ``y`` may be a single
        per-sample ADM array (replicated over every block) or a list
        with one array per entry, conds first then unconds.
        The denoise loop is jit-compiled and cached per static config."""
        # serving-side tensor parallelism: lay the tower params out
        # over the mesh's tensor axis before they enter the jitted core
        self._ensure_tp_sharded()

        def _norm(entries):
            if not isinstance(entries, (list, tuple)):
                return [(entries, None, 1.0, None)]
            return smp._norm_entries(entries)  # ONE copy of the contract

        conds = _norm(context)
        unconds = _norm(uncond_context)
        dual = middle_context is not None
        if dual:
            # DualCFGGuider path: plain [cond, middle, uncond] arrays only
            # (ComfyUI's dual guider likewise takes bare conds — regional
            # multi-entry lists don't compose with the 3-way combine)
            if len(conds) != 1 or len(unconds) != 1 or any(
                    m is not None or s != 1.0 or sr is not None
                    for _, m, s, sr in conds + unconds):
                raise ValueError(
                    f"3-row guidance ({guidance}) requires plain "
                    "single-entry positive/negative conditionings")
            conds = conds + [(jnp.asarray(middle_context), None, 1.0, None)]
        if sigmas_override is not None:
            # custom-sampling path (SamplerCustom): the caller supplies
            # the exact sigma sequence; scheduler/steps/denoise/window
            # args are ignored.  Only the LENGTH is static (scan trip
            # count) — the values ride in as a traced argument, so a
            # KarrasScheduler rho sweep reuses one executable per length
            sig_np = np.asarray(sigmas_override, np.float32)
            if sig_np.ndim != 1:
                raise ValueError("sigmas_override must be a 1-D sigma "
                                 "sequence (order is the sampler's "
                                 "business — FlipSigmas feeds ascending)")
            if sig_np.shape[0] < 2:
                # ComfyUI's denoise<=0 / empty-schedule no-op: the
                # latent passes through unchanged (same precedent as the
                # degenerate KSamplerAdvanced window below)
                return latents
            sigmas = jnp.asarray(sig_np)
            steps = int(sig_np.shape[0]) - 1
            start, end = 0, steps
        else:
            sigmas = jnp.asarray(sch.compute_sigmas(
                self.schedule, scheduler, steps, denoise))
            start = max(int(start_step), 0)
            end = steps if end_step is None else min(int(end_step), steps)
            if start >= end:
                # degenerate window (start_at_step beyond the schedule):
                # ComfyUI returns the latent unchanged rather than erroring
                return latents
            if start > 0 or end < steps:
                sigmas = sigmas[start:end + 1]
                if force_full_denoise:
                    sigmas = sigmas.at[-1].set(0.0)
        keys = smp.sample_keys(seeds, sample_idx)

        from comfyui_distributed_tpu.runtime.interrupt import polling_enabled

        def _entries_key(entries):
            return tuple((tuple(c.shape), m is not None,
                          tuple(m.shape) if m is not None else (),
                          float(s),
                          tuple(float(v) for v in sr) if sr is not None
                          else None) for c, m, s, sr in entries)

        # normalize control to a CHAIN of per-net wire specs (the ops
        # layer sends a tuple of (module, params, hint, strengths[,
        # windows]) — ComfyUI's previous_controlnet chain; a single
        # legacy spec becomes a 1-chain for direct callers)
        if control is not None and not isinstance(control[0], tuple):
            control = (control,)
        cfg_rescale = float(getattr(self, "cfg_rescale", 0.0) or 0.0)
        hn_spec = getattr(self, "hypernets", None) or None
        ds_spec = getattr(self, "deep_shrink_spec", None)
        if ds_spec is not None and control is not None:
            log("deep shrink: ControlNet residual shapes can't follow "
                "the shrunk encoder; sampling WITHOUT the downscale "
                "patch")
            ds_spec = None
        sag = getattr(self, "sag_params", None)
        sag_ok = False
        if sag is not None:
            ht = self.family.unet.hypertile
            mid_hypertiled = (ht is not None
                              and self.family.unet.num_levels - 1
                              <= int(ht[1]))
            sag_ok = (not dual and float(cfg) != 1.0
                      and len(conds) == 1 and len(unconds) == 1
                      and control is None and not mid_hypertiled
                      and not any(m is not None or s != 1.0
                                  or sr is not None
                                  for _, m, s, sr in conds + unconds))
            if not sag_ok:
                log("SAG: unsupported combination (regional/dual/"
                    "control/cfg==1/hypertiled mid-block); sampling "
                    "WITHOUT self-attention guidance")
        if ds_spec is not None and sag_ok:
            log("deep shrink: does not compose with SAG's capture "
                "branch; sampling WITHOUT the downscale patch")
            ds_spec = None
        if sag_ok:
            # mid-block spatial dims (stride-2 SAME convs: ceil halving
            # per level) — the attn-probs token grid the mask reshapes to
            mh, mw = int(latents.shape[1]), int(latents.shape[2])
            for _ in range(self.family.unet.num_levels - 1):
                mh, mw = (mh + 1) // 2, (mw + 1) // 2
        y_is_list = isinstance(y, (list, tuple))
        static_key = ("sample", sampler_name, scheduler, steps,
                      sigmas_override is not None,
                      cfg_rescale, float(cfg),
                      float(denoise), bool(add_noise), y is not None,
                      y_is_list, tuple(latents.shape), _entries_key(conds),
                      _entries_key(unconds),
                      polling_enabled(), start, end, dual, float(cfg2),
                      guidance,
                      (tuple(float(v) for v in sag), ) if sag_ok else (),
                      tuple(float(v) for v in ds_spec)
                      if ds_spec is not None else (),
                      tuple((float(s), tuple(sorted(h)))
                            for h, s in hn_spec)
                      if hn_spec is not None else (),
                      c_concat is not None,
                      tuple(c_concat.shape) if c_concat is not None
                      else (),
                      (tuple(gligen_objs[0].shape),
                       tuple(gligen_objs[2]))
                      if gligen_objs is not None else (),
                      bool(force_full_denoise), noise_mask is not None,
                      tuple((_strength_key(c[3]),
                             _window_key(c[4]) if len(c) > 4 else None)
                            for c in control)
                      if control is not None else None)

        def make_core():
            has_y = y is not None
            has_mask = noise_mask is not None
            has_control = control is not None
            cfg_scale = float(cfg)
            n_conds, n_unconds = len(conds), len(unconds)
            has_area = [m is not None for _, m, _, _ in conds + unconds]
            strengths = [float(s) for _, _, s, _ in conds + unconds]
            sranges = [sr for _, _, _, sr in conds + unconds]
            sampler = smp.get_sampler(sampler_name)
            if has_control:
                cn_modules = [c[0] for c in control]
                cn_strengths = [c[3] for c in control]
                cn_windows = [c[4] if len(c) > 4 else None
                              for c in control]

                def _make_apply(mod):
                    def cn_apply(p, xi, ts, ctx, hint, y_in):
                        return mod.apply({"params": p}, xi, ts, ctx,
                                         hint, y_in)
                    return cn_apply

                cn_applies = [_make_apply(m) for m in cn_modules]

            has_concat = c_concat is not None

            def core(unet_params, latents, ctx_list, area_list,
                     keys, sigmas, y_in, mask_in, cn_params, hint_in,
                     concat_in, objs_in):
                ctrl_spec = None
                if has_control:
                    ctrl_spec = []
                    for k in range(len(cn_applies)):
                        sk = _strength_key(cn_strengths[k])
                        cw = cn_windows[k]
                        if (isinstance(sk, tuple) and len(sk) == 2
                                and isinstance(sk[0], tuple)):
                            # ops-layer (pos_strengths, neg_strengths):
                            # flat per-block tuples sized to the actual
                            # layout — windows flatten IN LOCKSTEP with
                            # strengths so block i's gate stays block i's
                            pos_s, neg_s = sk
                            sk = tuple(pos_s) + (tuple(neg_s)
                                                 if cfg_scale != 1.0
                                                 else ())
                            if cw is not None:
                                pos_w, neg_w = cw
                                cw = tuple(pos_w) + (tuple(neg_w)
                                                     if cfg_scale != 1.0
                                                     else ())
                        spec = (cn_applies[k], cn_params[k], hint_in[k],
                                sk)
                        ctrl_spec.append(spec if cw is None
                                         else spec + (cw,))
                use_apply = self.raw_unet_apply
                if ds_spec is not None:
                    # deep shrink: a lax.cond over two config-variant
                    # UNet applies SHARING one param tree — the shrunk
                    # branch runs only inside the sigma window, so the
                    # early steps pay the small graph
                    lvl, fac, t_lo, t_hi = ds_spec
                    shrunk_mod = unet_mod.UNet(dataclasses.replace(
                        self.family.unet,
                        deep_shrink=(int(lvl), float(fac))))

                    def _shrunk(p, x, t, c, y=None, control=None,
                                context_v=None, objs=None):
                        return shrunk_mod.apply({"params": p}, x, t, c,
                                                y=y, control=control,
                                                context_v=context_v,
                                                objs=objs)

                    def use_apply(p, x, t, c, y=None, control=None,
                                  context_v=None, objs=None):
                        pred = jnp.logical_and(t[0] > t_lo, t[0] <= t_hi)
                        return jax.lax.cond(
                            pred,
                            lambda a: _shrunk(*a),
                            lambda a: self.raw_unet_apply(*a),
                            (p, x, t, c, y, control, context_v, objs))

                den = make_denoiser(
                    use_apply, unet_params, self.schedule,
                    self.prediction_type, control=ctrl_spec,
                    concat=concat_in if has_concat else None,
                    hypernet=hn_spec)
                entries = [(ctx_list[i],
                            area_list[i] if has_area[i] else None,
                            strengths[i], sranges[i])
                           for i in range(n_conds + n_unconds)]
                if dual:
                    # ctx_list rows: [cond, middle, uncond] (see sample())
                    combine = smp.cfg_denoiser_perp_neg \
                        if guidance == "perp_neg" else smp.cfg_denoiser_dual
                    model = combine(
                        den, ctx_list[0], ctx_list[1], ctx_list[2],
                        cfg_scale, float(cfg2), cfg_rescale=cfg_rescale)
                    reps = 3
                elif sag_ok:
                    den_cap = make_denoiser(
                        self.raw_unet_apply_capture, unet_params,
                        self.schedule, self.prediction_type,
                        capture=True,
                        concat=concat_in if has_concat else None,
                        hypernet=hn_spec)
                    model = smp.cfg_denoiser_sag(
                        den_cap, den, ctx_list[0], ctx_list[1],
                        cfg_scale, float(sag[0]), float(sag[1]),
                        (mh, mw), cfg_rescale=cfg_rescale)
                    reps = 2
                else:
                    model = smp.cfg_denoiser_multi(den, entries[:n_conds],
                                                   entries[n_conds:],
                                                   cfg_scale,
                                                   cfg_rescale=cfg_rescale)
                    reps = n_conds + (n_unconds if cfg_scale != 1.0
                                      else 0)
                if gligen_objs is not None:
                    # per-block grounding tokens: each block whose
                    # conditioning entry carries a gligen spec gets THAT
                    # spec's token set (the reference applies gligen
                    # per-cond); the rest get the null set.  Index order
                    # matches the ctx_list block layout (conds first,
                    # then unconds) — ops/basic.py.  og: [S, B, N, D]
                    # stacked per-spec sets; index -1 = null set
                    og, on = objs_in
                    idxs = tuple(gligen_objs[2])[:max(reps, 1)]
                    parts = [og[i] if i >= 0 else on for i in idxs]
                    parts += [on] * (max(reps, 1) - len(parts))
                    extra_objs = shd.stack_rows(parts) \
                        if reps > 1 else parts[0]
                else:
                    extra_objs = None
                if not has_y:
                    y2 = y_in
                elif y_is_list:
                    # one ADM vector per entry (regional SDXL: each
                    # region's own pooled), conds first then unconds
                    y2 = shd.stack_rows(list(y_in)[:reps]) \
                        if reps > 1 else y_in[0]
                else:
                    # a single ADM vector rides every block
                    y2 = shd.stack_rows([y_in] * reps) \
                        if reps > 1 else y_in
                # init noise uses a reserved fold-in index so it never
                # collides with per-step ancestral noise (steps from 0)
                noise = smp.make_noise_fn(keys)(
                    jnp.asarray(0x7FFFFFFF, jnp.uint32), latents.shape[1:])
                # noise always lands ON the latent (ComfyUI convention) —
                # txt2img passes zeros, so pure-noise starts fall out
                x = latents + noise * sigmas[0] if add_noise else latents
                extra = {"y": y2} if has_y else {}
                if extra_objs is not None:
                    extra["objs"] = extra_objs
                if has_mask:
                    # inpainting (KSamplerX0Inpaint): every model call sees
                    # the source re-noised to the CURRENT sigma outside the
                    # mask, and its denoised output re-anchored to the
                    # clean source there — so sampler math can't drift the
                    # protected region.  With add_noise disabled the blend
                    # noise is zero (ComfyUI's disable_noise: the input
                    # latent IS the noised state already)
                    inner = model
                    mnoise = noise if add_noise else jnp.zeros_like(noise)

                    def model(xi, sigma, **kw):  # noqa: F811
                        s = sigma.reshape((-1,) + (1,) * (xi.ndim - 1))
                        xi = xi * mask_in + (latents + mnoise * s) \
                            * (1.0 - mask_in)
                        out = inner(xi, sigma, **kw)
                        # CFG++ side-channel must survive the wrapper:
                        # samplers read ``model.last_uncond`` off the
                        # OUTER callable, so re-expose the inner CFG
                        # denoiser's uncond, re-anchored through the
                        # same blend as the cond output (without this,
                        # masked euler_cfg_pp silently degraded to
                        # plain euler semantics)
                        lu = getattr(inner, "last_uncond", out)
                        model.last_uncond = lu * mask_in \
                            + latents * (1.0 - mask_in)
                        return out * mask_in + latents * (1.0 - mask_in)

                out = sampler(model, x, sigmas, extra_args=extra, keys=keys)
                if has_mask:
                    out = out * mask_in + latents * (1.0 - mask_in)
                return out

            # the latent arg is donated: the scan carry (one latent-sized
            # buffer per step) aliases the input instead of doubling it.
            # sample() guards shared buffers by donating a copy.
            return jax.jit(core, donate_argnums=(1,))

        core = self._cache_get_or_make(static_key, make_core)
        if y is None:
            y_arg = jnp.zeros((latents.shape[0], 1))
        elif isinstance(y, (list, tuple)):
            y_arg = [jnp.asarray(v) for v in y]
        else:
            y_arg = y
        mask_arg = noise_mask if noise_mask is not None \
            else jnp.ones((1, 1, 1, 1))
        cn_params_arg = [c[1] for c in control] if control is not None \
            else [{}]
        hint_arg = [c[2] for c in control] if control is not None \
            else [jnp.zeros((1, 8, 8, 3))]
        ctx_list = [jnp.asarray(c) for c, _, _, _ in conds + unconds]
        area_list = [jnp.asarray(m) if m is not None
                     else jnp.ones((1, 1, 1, 1))
                     for _, m, _, _ in conds + unconds]
        concat_arg = c_concat if c_concat is not None \
            else jnp.zeros((1, 1, 1, 1))
        objs_arg = gligen_objs[:2] if gligen_objs is not None \
            else (jnp.zeros((1, 1, 1)), jnp.zeros((1, 1, 1)))
        lat_arg = jnp.asarray(latents)
        if not donate_latents:
            # core always donates its latent arg; protect a buffer the
            # caller (or the workflow graph) still references by donating
            # a fresh on-device copy instead
            lat_arg = jnp.copy(lat_arg)
        return core(self.unet_params, lat_arg, ctx_list, area_list,
                    keys, sigmas, y_arg, mask_arg,
                    cn_params_arg, hint_arg, concat_arg, objs_arg)

    # --- warmup -------------------------------------------------------------

    def warmup(self, height: int = 512, width: int = 512, batch: int = 1,
               steps: int = 20, cfg: float = 7.5,
               sampler_name: str = "euler", scheduler: str = "normal",
               denoise: float = 1.0, with_vae: bool = True) -> Dict[str, float]:
        """Ahead-of-time warmup for one serving shape: trace, compile and
        execute the CLIP encode, the jitted denoise loop and the VAE
        decode on zero inputs, exactly shaped like a txt2img request of
        ``batch`` images at ``width`` x ``height`` (ComfyUI //8 latent
        convention — the shapes EmptyLatentImage -> KSampler produce).

        Call at server startup (``POST /distributed/warmup`` or
        ``DTPU_WARMUP``): the first real request then hits the in-memory
        jit cache — time-to-first-image drops to dispatch cost — and,
        with the persistent compilation cache enabled
        (``runtime.manager.enable_persistent_compile_cache``), even a
        fresh process pays trace+deserialize instead of an XLA compile.

        When a live mesh with a >1 data axis exists, the warmup batch is
        fanned out and SHARDED exactly like a distributed run
        (jit keys compilations on input shardings: an unsharded warmup
        would leave the flagship SPMD program cold and the first real
        fan-out request would recompile anyway).
        Returns per-stage wall-clock seconds."""
        import time as _time

        from comfyui_distributed_tpu.ops.base import Conditioning
        from comfyui_distributed_tpu.parallel import collectives as coll
        from comfyui_distributed_tpu.parallel.mesh import get_live_runtime
        from comfyui_distributed_tpu.utils.trace import install_jax_monitoring
        install_jax_monitoring()
        timings: Dict[str, float] = {}
        t_all = _time.perf_counter()

        t0 = _time.perf_counter()
        ctx1, pooled = self.encode_prompt([""])
        jax.block_until_ready(ctx1)
        timings["clip_s"] = _time.perf_counter() - t0

        rt = get_live_runtime()
        mesh = rt.mesh if rt is not None and rt.num_participants > 1 \
            else None
        total = batch * (rt.num_participants if mesh is not None else 1)

        lh, lw = max(int(height) // 8, 1), max(int(width) // 8, 1)
        context = jnp.repeat(ctx1, total, axis=0)
        uncond = jnp.repeat(ctx1, total, axis=0)
        y = None
        if self.family.unet.adm_in_channels is not None:
            from comfyui_distributed_tpu.ops.basic import _sdxl_vector_cond
            y = _sdxl_vector_cond(
                self, Conditioning(context=ctx1, pooled=pooled),
                total, lh * 8, lw * 8)
        lat = jnp.zeros((total, lh, lw, self.family.latent_channels),
                        jnp.float32)
        if mesh is not None:
            lat = coll.shard_batch(lat, mesh)
            context = coll.shard_batch(context, mesh)
            uncond = coll.shard_batch(uncond, mesh)
            if y is not None:
                y = coll.shard_batch(y, mesh)
        t0 = _time.perf_counter()
        out = self.sample(lat, context, uncond,
                          np.zeros((total,), np.uint64),
                          steps=int(steps), cfg=float(cfg),
                          sampler_name=str(sampler_name),
                          scheduler=str(scheduler), denoise=float(denoise),
                          y=y, donate_latents=True)
        jax.block_until_ready(out)
        timings["sample_s"] = _time.perf_counter() - t0

        if with_vae:
            t0 = _time.perf_counter()
            jax.block_until_ready(self.vae_decode(out))
            timings["vae_s"] = _time.perf_counter() - t0
        timings["total_s"] = _time.perf_counter() - t_all
        log(f"warmup {self.name}: {total}x{width}x{height} "
            f"{sampler_name}x{steps}"
            + (f" sharded over data={rt.num_participants}"
               if mesh is not None else "")
            + f" in {timings['total_s']:.2f}s "
            f"(clip {timings['clip_s']:.2f}s, "
            f"sample {timings['sample_s']:.2f}s)")
        return timings

    # --- internals ----------------------------------------------------------

    def _jitted(self, key, fn):
        return self._cache_get_or_make(key, lambda: jax.jit(fn))

    def _cache_get_or_make(self, key, make):
        with self._lock:
            if key in self._jit_cache:
                self._jit_cache.move_to_end(key)
                return self._jit_cache[key]
            fn = self._jit_cache[key] = make()
            while len(self._jit_cache) > self._jit_cache_cap:
                old_key, _ = self._jit_cache.popitem(last=False)
                log(f"jit cache: evicting {old_key!r} "
                    f"(cap {self._jit_cache_cap})")
            return fn


def _virtual_params(module, seed: int, *shaped_args) -> Any:
    """Deterministic random init WITHOUT compiling the model's init graph.

    ``module.init`` traces the full forward pass — for SDXL that is a
    multi-minute XLA compile before a single weight exists.  Virtual
    checkpoints only need *deterministic, sanely-scaled* weights, so we
    eval_shape the init (trace only, no compile) and fill each leaf with
    seeded numpy: fan-in-scaled normals for kernels, zeros for biases, ones
    for norm scales.  Per-leaf streams are keyed by crc32 of the tree path —
    stable across processes and hosts, so every mesh host materializes
    identical weights (the reference's "same models on all machines"
    requirement, ``README.md:189-193``)."""
    import zlib

    shapes = jax.eval_shape(module.init, jax.random.PRNGKey(0), *shaped_args)
    leaf = _virtual_leaf(seed)
    return jax.tree_util.tree_map_with_path(leaf, shapes)["params"]


def _virtual_leaf(seed: int):
    """The ONE copy of the virtual-init fill rules (shared with partial
    initializers like gligen_attach's missing-leaf graft)."""
    import zlib

    def leaf(path, sd):
        name = jax.tree_util.keystr(path)
        leaf_name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        rng = np.random.default_rng(
            (np.uint64(seed), np.uint64(zlib.crc32(name.encode()))))
        shape = tuple(sd.shape)
        dtype = sd.dtype
        if leaf_name in ("scale",):
            arr = np.ones(shape, np.float32)
        elif leaf_name in ("bias",) or len(shape) <= 1:
            arr = np.zeros(shape, np.float32)
        else:
            fan_in = int(np.prod(shape[:-1])) or 1
            arr = rng.standard_normal(shape, dtype=np.float32) \
                / np.sqrt(fan_in)
        return jnp.asarray(arr, dtype=dtype)

    return leaf


# pipelines under plain names, (module, params) tuples under "cn:" keys,
# standalone-VAE pipelines under "vae:" keys — one model-asset cache, all
# cleared together by clear_pipeline_cache
_pipeline_cache: Dict[str, Any] = {}
_pipeline_lock = threading.Lock()


def load_pipeline(ckpt_name: str, models_dir: Optional[str] = None,
                  family_name: Optional[str] = None) -> DiffusionPipeline:
    """Load or virtually-initialize the named checkpoint (cached)."""
    # models_dir is part of the identity: it decides both which file loads
    # AND which tokenizer assets (vocab/merges) the pipeline picks up
    key = f"{ckpt_name}:{family_name or ''}:{models_dir or ''}"
    with _pipeline_lock:
        if key in _pipeline_cache:
            return _pipeline_cache[key]

    fam = FAMILIES[family_name or detect_family(ckpt_name)]
    path = None
    if models_dir:
        cand = os.path.join(models_dir, ckpt_name.replace("\\", "/"))
        if os.path.exists(cand):
            path = cand

    from comfyui_distributed_tpu.runtime.checkpointing import (
        is_native_checkpoint, load_pipeline_checkpoint)
    if path is not None and is_native_checkpoint(path):
        # native orbax directory checkpoint (runtime/checkpointing.py) —
        # its manifest carries the family, overriding name heuristics
        native_family, unet_p, clip_ps, vae_p = load_pipeline_checkpoint(path)
        fam = FAMILIES[family_name or native_family]
    elif path is not None:
        from comfyui_distributed_tpu.models.checkpoints import load_checkpoint
        unet_p, clip_ps, vae_p = load_checkpoint(path, fam)
        log(f"loaded checkpoint {ckpt_name} ({fam.name}) from {path}")
    else:
        seed = _name_seed(ckpt_name)
        ds = fam.vae.downscale
        h = w = 8 * ds
        ctx_dim = fam.unet.context_dim
        # the UNet's input width, not the latent width: inpaint models
        # consume [latent, mask, masked-latent] = 9 channels
        x = jnp.zeros((1, h // ds, w // ds, fam.unet.in_channels))
        ts = jnp.zeros((1,))
        ctx = jnp.zeros((1, 77, ctx_dim))
        unet_p = _virtual_params(unet_mod.UNet(fam.unet), seed, x, ts, ctx)
        clip_ps = []
        for i, ccfg in enumerate(fam.clips):
            tok = jnp.zeros((1, ccfg.max_length), jnp.int32)
            clip_ps.append(_virtual_params(
                clip_mod.CLIPTextModel(ccfg), seed + 1 + i, tok))
        img = jnp.zeros((1, h, w, 3))
        vae_p = _virtual_params(vae_mod.VAE(fam.vae), seed + 100, img)
        log(f"virtual checkpoint {ckpt_name!r} ({fam.name}): no file on disk, "
            f"deterministic init (seed {seed})")

    if _bf16_weights_enabled(fam):
        # bf16 WEIGHT STORAGE for the compute towers (UNet + CLIP): the
        # UNet computes in bf16 anyway, so fp32 storage only doubles the
        # HBM weight traffic every denoise step (and fp32 SDXL weights
        # would crowd a 16 GB v5e chip).  The VAE stays fp32 — its
        # GroupNorm/attention decode path is the one place bf16 weights
        # visibly cost quality.  Opt out: DTPU_BF16_WEIGHTS=0.
        unet_p = _cast_bf16(unet_p)
        clip_ps = [_cast_bf16(p) for p in clip_ps]
        log(f"{ckpt_name}: UNet/CLIP weights stored bf16 "
            f"(DTPU_BF16_WEIGHTS=0 for fp32)")

    pipe = DiffusionPipeline(ckpt_name, fam, unet_p, clip_ps, vae_p,
                             prediction_type=fam.unet.prediction_type,
                             assets_dir=models_dir)
    pipe.cache_token = key
    with _pipeline_lock:
        _pipeline_cache[key] = pipe
    return pipe


def _bf16_weights_enabled(fam: ModelFamily) -> bool:
    """bf16 weight storage default: on for the real families (their UNet
    dtype is bf16), off for 'tiny' (fp32 module — deterministic CPU
    tests)."""
    env = os.environ.get("DTPU_BF16_WEIGHTS")
    if env is not None:
        return env not in ("0", "false", "")
    return fam.unet.dtype == jnp.bfloat16


def _cast_bf16(tree):
    return jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16)
        if hasattr(a, "dtype") and a.dtype == jnp.float32 else a, tree)


def clear_pipeline_cache() -> None:
    """Free model memory (feeds the control plane's clear_memory route —
    the reference's VRAM-clear endpoint, ``distributed.py:383-426``)."""
    with _pipeline_lock:
        _pipeline_cache.clear()
        _derived_cache.clear()
        _cn_family_cache.clear()
        _embedding_cache.clear()
        _clip_vision_cache.clear()
    from comfyui_distributed_tpu.models import hypernetwork as hn_mod
    from comfyui_distributed_tpu.models import lora as lora_mod
    lora_mod.clear_lora_cache()
    hn_mod.clear_hypernetwork_cache()
    from comfyui_distributed_tpu.models import gligen as gg_mod
    from comfyui_distributed_tpu.models import style_model as sm_mod
    sm_mod.clear_style_model_cache()
    gg_mod.clear_gligen_cache()


# derived pipelines (clip-skip variants, external VAEs): param trees are
# SHARED with the base — only configs/modules differ — but each clone
# carries its own jit caches, so keep identity stable across runs
_derived_cache: "collections.OrderedDict[Tuple, DiffusionPipeline]" = \
    collections.OrderedDict()
_DERIVED_CACHE_CAP = 8

# ControlNet file -> inferred family name (load_controlnet): lets the
# repeat call hit the pipeline cache without re-reading the file
_cn_family_cache: Dict[str, str] = {}


def derived_cached(base: DiffusionPipeline,
                   tag: str) -> Optional[DiffusionPipeline]:
    """Cache probe for derive_pipeline — ops that pay a real cost to
    BUILD their derivation inputs (weight-space merges) check this
    first instead of recomputing a tree the cache would discard."""
    with _pipeline_lock:
        return _derived_cache.get((base.cache_token, tag))


def copy_sampler_patches(src: DiffusionPipeline,
                         dst: DiffusionPipeline) -> None:
    """Sampler-visible patches that must ride EVERY derivation chain
    (derive_pipeline AND the LoRA loader's direct construction):
    RescaleCFG's rescale, a zsnr-patched schedule, and every attr ever
    applied via derive_pipeline(extra_attrs=...) (PerpNeg's empty cond +
    scale, ...)."""
    dst.cfg_rescale = getattr(src, "cfg_rescale", 0.0)
    dst.schedule = src.schedule
    riding = set(getattr(src, "_riding_attrs", ()))
    for attr in riding:
        if hasattr(src, attr):
            setattr(dst, attr, getattr(src, attr))
    dst._riding_attrs = frozenset(riding)


def derive_pipeline(base: DiffusionPipeline, tag: str,
                    family: Optional[ModelFamily] = None,
                    vae_params: Any = None,
                    cfg_rescale: Optional[float] = None,
                    prediction_type: Optional[str] = None,
                    schedule: Any = None,
                    extra_attrs: Optional[Dict[str, Any]] = None,
                    unet_params: Any = None,
                    clip_params: Any = None) -> DiffusionPipeline:
    """Cached clone of ``base`` with a replacement family (e.g. clip-skip
    configs), VAE params, and/or sampling patches; everything else shared
    by reference."""
    key = (base.cache_token, tag)
    with _pipeline_lock:
        if key in _derived_cache:
            _derived_cache.move_to_end(key)
            return _derived_cache[key]
    clone = DiffusionPipeline(
        f"{base.name}|{tag}", family or base.family,
        unet_params if unet_params is not None else base.unet_params,
        clip_params if clip_params is not None else base.clip_params,
        vae_params if vae_params is not None else base.vae_params,
        prediction_type=prediction_type or base.prediction_type,
        assets_dir=base.assets_dir)
    # sampling patches ride derivation chains (RescaleCFG -> clip-skip
    # -> LoRA must keep the rescale); set BEFORE the clone is published
    # to the cache so a concurrent sampler can't observe the default
    copy_sampler_patches(base, clone)
    if cfg_rescale is not None:
        clone.cfg_rescale = cfg_rescale
    # a patched schedule (ModelSamplingDiscrete zsnr) must also survive
    # further derivations (LoRA/clip-skip after the patch)
    if schedule is not None:
        clone.schedule = schedule
    # new patch attrs join the riding set (see copy_sampler_patches)
    if extra_attrs:
        for k, v in extra_attrs.items():
            setattr(clone, k, v)
        clone._riding_attrs = frozenset(
            set(clone._riding_attrs) | set(extra_attrs))
    with _pipeline_lock:
        _derived_cache[key] = clone
        while len(_derived_cache) > _DERIVED_CACHE_CAP:
            _derived_cache.popitem(last=False)
    return clone


_embedding_cache: Dict[tuple, Optional[np.ndarray]] = {}


def load_textual_embedding(name: str, assets_dir: Optional[str],
                           width: int, tower_idx: int = 0,
                           ) -> Optional[np.ndarray]:
    """Textual-inversion vectors for ``embedding:name`` prompt refs:
    ``<assets_dir>/embeddings/<name>[.safetensors]``.  SDXL-style files
    carry per-tower ``clip_l``/``clip_g`` keys (tower 0 / 1); SD1.x
    A1111 exports carry a single ``emb_params`` tensor.  Returns
    [K, width] float32, or None (missing file / width mismatch) — the
    tokenizer drops the reference with a log, like ComfyUI's warning."""
    if not assets_dir:
        return None
    key = (assets_dir, name, width, tower_idx)
    if key in _embedding_cache:
        return _embedding_cache[key]
    base = os.path.join(assets_dir, "embeddings")
    path = None
    for cand in (name, name + ".safetensors"):
        p = os.path.join(base, cand.replace("\\", "/"))
        if os.path.isfile(p):
            path = p
            break
    result = None
    if path is not None and path.endswith(".safetensors"):
        from safetensors import safe_open
        with safe_open(path, framework="numpy") as f:
            keys = set(f.keys())
            per_tower = {0: "clip_l", 1: "clip_g"}
            if keys & {"clip_l", "clip_g"}:
                chosen = per_tower.get(tower_idx)
                chosen = chosen if chosen in keys else None
            elif "emb_params" in keys:
                chosen = "emb_params"
            else:
                chosen = next(iter(sorted(keys)), None)
            if chosen is not None:
                arr = np.asarray(f.get_tensor(chosen), np.float32)
                arr = arr.reshape(-1, arr.shape[-1])
                if arr.shape[-1] == width:
                    result = arr
                else:
                    log(f"textual inversion {name!r}: width "
                        f"{arr.shape[-1]} != tower width {width}; "
                        "dropping")
    _embedding_cache[key] = result
    return result


def load_controlnet(cn_name: str, models_dir: Optional[str] = None,
                    family_name: Optional[str] = None):
    """ControlNetLoader equivalent -> (module, params); virtual when no
    file exists (deterministic from the name, zero-convs start at zero so
    a fresh virtual ControlNet is an exact no-op on the UNet).

    When a file IS on disk the family comes from the checkpoint itself
    (cross-attention width), not from env/default — an SDXL workflow
    must not build a 768-context sd15 net just because the default says
    so (parity with the reference ecosystem's infer-from-file loaders)."""
    fam = FAMILIES[family_name or os.environ.get(FAMILY_ENV) or "sd15"]
    path = None
    sd = None
    if models_dir:
        cand = os.path.join(models_dir, cn_name.replace("\\", "/"))
        if os.path.exists(cand):
            path = cand
    if path is not None and family_name is None:
        # inferred family memoized per path: the repeat call must hit the
        # pipeline cache below without re-reading a multi-GB file
        with _pipeline_lock:
            cached_fam = _cn_family_cache.get(path)
        if cached_fam is not None:
            fam = FAMILIES[cached_fam]
        else:
            from comfyui_distributed_tpu.models.checkpoints import (
                controlnet_context_dim, load_state_dict)
            sd = load_state_dict(path)
            ctx_dim = controlnet_context_dim(sd)
            if ctx_dim is not None and ctx_dim != fam.unet.context_dim:
                for cand_fam in ("sd15", "sd21", "sdxl", "tiny"):
                    if FAMILIES[cand_fam].unet.context_dim == ctx_dim:
                        fam = FAMILIES[cand_fam]
                        break
            with _pipeline_lock:
                _cn_family_cache[path] = fam.name

    key = f"cn:{cn_name}:{fam.name}:{models_dir or ''}"
    with _pipeline_lock:
        if key in _pipeline_cache:
            return _pipeline_cache[key]

    from comfyui_distributed_tpu.models.controlnet import ControlNet
    module = ControlNet(fam.unet)
    if path is not None:
        from comfyui_distributed_tpu.models.checkpoints import (
            load_controlnet as load_cn_file)
        params = load_cn_file(path, fam.unet, state_dict=sd)
        log(f"loaded ControlNet {cn_name} ({fam.name}) from {path}")
    else:
        seed = _name_seed(cn_name)
        x = jnp.zeros((1, 8, 8, fam.latent_channels))
        ts = jnp.zeros((1,))
        ctx = jnp.zeros((1, 77, fam.unet.context_dim))
        hint = jnp.zeros((1, 64, 64, 3))
        params = _virtual_params(module, seed, x, ts, ctx, hint)
        # restore the untrained-ControlNet invariant _virtual_params'
        # random fill breaks: zero projections make a fresh net an exact
        # UNet no-op (the property real zero-init checkpoints have)
        from comfyui_distributed_tpu.models.controlnet import HINT_CHANNELS
        final_hint = f"hint_conv_{len(HINT_CHANNELS)}"
        for name in list(params):
            if name.startswith("zero_conv_") or name in ("mid_out",
                                                         final_hint):
                params[name] = jax.tree_util.tree_map(
                    lambda a: np.zeros_like(a), params[name])
        log(f"virtual ControlNet {cn_name!r} ({fam.name}): no file on "
            f"disk, deterministic init (seed {seed}, zero projections)")

    entry = (module, params)
    with _pipeline_lock:
        _pipeline_cache[key] = entry
    return entry


_clip_vision_cache: Dict[str, Any] = {}


def load_clip_vision(clip_name: str, models_dir: Optional[str] = None,
                     config_name: Optional[str] = None):
    """CLIPVisionLoader equivalent: ``<models_dir>/clip_vision/<name>``
    in the HF CLIPVisionModel safetensors layout; virtual-initializes
    when no file exists.  The config is inferred from the file's hidden
    width (ViT-H vs ViT-L), or forced by ``config_name``
    ('vit_h' | 'vit_l' | 'tiny')."""
    from comfyui_distributed_tpu.models import clip_vision as cv
    key = f"{clip_name}:{config_name or ''}:{models_dir or ''}"
    with _pipeline_lock:
        if key in _clip_vision_cache:
            return _clip_vision_cache[key]
    cfgs = {"vit_h": cv.VIT_H_CONFIG, "vit_l": cv.VIT_L_CONFIG,
            "tiny": cv.TINY_VISION_CONFIG}
    path = None
    if models_dir:
        for cand in (clip_name,
                     os.path.join("clip_vision", clip_name)):
            p = os.path.join(models_dir, cand.replace("\\", "/"))
            if os.path.isfile(p):
                path = p
                break
    if path is not None:
        from comfyui_distributed_tpu.models.checkpoints import (
            _LoadMapper, _run_clip_vision, load_state_dict)
        sd = load_state_dict(path)
        if config_name:
            cfg = cfgs[config_name]
        else:
            w = sd.get("vision_model.embeddings.class_embedding")
            width = int(w.shape[-1]) if w is not None else 1280
            cfg = cv.VIT_H_CONFIG if width >= 1280 else cv.VIT_L_CONFIG
        params = _run_clip_vision(_LoadMapper(sd, ""), cfg)
        log(f"loaded CLIP vision {clip_name} (width {cfg.width}) "
            f"from {path}")
    else:
        lowered = clip_name.lower()
        cfg = cfgs.get(config_name or "", None)
        if cfg is None:
            cfg = cv.TINY_VISION_CONFIG if ("tiny" in lowered
                                            or "test" in lowered) \
                else cv.VIT_H_CONFIG
        seed = _name_seed(clip_name)
        px = jnp.zeros((1, cfg.image_size, cfg.image_size, 3))
        params = _virtual_params(cv.CLIPVisionModel(cfg), seed, px)
        log(f"virtual CLIP vision {clip_name!r} (width {cfg.width}): "
            f"no file on disk, deterministic init (seed {seed})")
    tower = cv.CLIPVisionTower(name=clip_name, cfg=cfg, params=params)
    with _pipeline_lock:
        _clip_vision_cache[key] = tower
    return tower


def load_vae(vae_name: str, models_dir: Optional[str] = None,
             family_name: Optional[str] = None) -> DiffusionPipeline:
    """VAELoader equivalent: a standalone VAE usable wherever a pipeline's
    VAE output is (VAEDecode/VAEEncode/tiled).  Accepts both serialization
    forms real VAE files use — full-checkpoint style (``first_stage_model.
    encoder...``) and bare (``encoder...``, e.g. vae-ft-mse-840000) —
    and virtually initializes when no file exists."""
    # 'tiny' only — a broader 'test' substring would match real names
    # like 'latest' and map a real VAE onto tiny geometry
    default = "tiny" if "tiny" in vae_name.lower() else "sd15"
    fam = FAMILIES[family_name or os.environ.get(FAMILY_ENV) or default]
    key = f"vae:{vae_name}:{fam.name}:{models_dir or ''}"
    with _pipeline_lock:
        if key in _pipeline_cache:
            return _pipeline_cache[key]

    path = None
    if models_dir:
        cand = os.path.join(models_dir, vae_name.replace("\\", "/"))
        if os.path.exists(cand):
            path = cand
    if path is not None:
        from comfyui_distributed_tpu.models.checkpoints import (
            VAE_PREFIX, _LoadMapper, _run_vae, load_state_dict)
        sd = load_state_dict(path)
        prefix = VAE_PREFIX if any(k.startswith(VAE_PREFIX) for k in sd) \
            else ""
        vae_p = _run_vae(_LoadMapper(sd, prefix), fam.vae)
        log(f"loaded VAE {vae_name} ({fam.name}) from {path}")
    else:
        seed = _name_seed(vae_name)
        ds = fam.vae.downscale
        img = jnp.zeros((1, 8 * ds, 8 * ds, 3))
        vae_p = _virtual_params(vae_mod.VAE(fam.vae), seed, img)
        log(f"virtual VAE {vae_name!r} ({fam.name}): no file on disk, "
            f"deterministic init (seed {seed})")

    pipe = DiffusionPipeline(f"vae:{vae_name}", fam, {}, [{}], vae_p)
    with _pipeline_lock:
        _pipeline_cache[key] = pipe
    return pipe


# ComfyUI CLIPLoader/DualCLIPLoader "type" widget -> model family whose
# text-tower geometry the file(s) must match
CLIP_TYPE_FAMILIES = {
    "stable_diffusion": "sd15",
    "sd1": "sd15",
    "sd2": "sd21",
    "sdxl": "sdxl",
    "tiny": "tiny",    # test geometry (same convention as the other
                       # standalone loaders' tiny-name detection)
}


def load_clip(clip_names: List[str], models_dir: Optional[str] = None,
              family_name: Optional[str] = None) -> DiffusionPipeline:
    """CLIPLoader/DualCLIPLoader equivalent: standalone text tower(s)
    usable wherever a checkpoint's CLIP output is (CLIPTextEncode and
    friends).  Accepts each tower's in-checkpoint prefix (as CLIPSave
    writes), an HF-standalone ``text_model.`` prefix, or bare keys; one
    file per tower (DualCLIPLoader: [clip_l, clip_g] for sdxl); virtual
    init per missing file."""
    fam = FAMILIES[family_name or os.environ.get(FAMILY_ENV) or "sd15"]
    if len(clip_names) != len(fam.clips):
        raise ValueError(
            f"family {fam.name} has {len(fam.clips)} text tower(s), got "
            f"{len(clip_names)} file name(s) — use "
            f"{'DualCLIPLoader' if len(fam.clips) == 2 else 'CLIPLoader'}")
    key = f"clip:{':'.join(clip_names)}:{fam.name}:{models_dir or ''}"
    with _pipeline_lock:
        if key in _pipeline_cache:
            return _pipeline_cache[key]

    from comfyui_distributed_tpu.models.checkpoints import (
        _clip_prefixes, _clip_runner, _LoadMapper, load_state_dict)
    clip_ps = []
    for i, (name, ccfg) in enumerate(zip(clip_names, fam.clips)):
        path = None
        if models_dir:
            for sub in (name, os.path.join("clip", name),
                        os.path.join("text_encoders", name)):
                cand = os.path.join(models_dir, sub.replace("\\", "/"))
                if os.path.exists(cand):
                    path = cand
                    break
        if path is not None:
            sd = load_state_dict(path)
            in_ckpt = _clip_prefixes(fam)[i]
            prefix = next((p for p in (in_ckpt, "text_model.")
                           if any(k.startswith(p) for k in sd)), "")
            clip_ps.append(_clip_runner(ccfg)(_LoadMapper(sd, prefix),
                                              ccfg))
            log(f"loaded CLIP tower {i} from {path} (prefix {prefix!r})")
        else:
            seed = _name_seed(name) + i
            tok = jnp.zeros((1, ccfg.max_length), jnp.int32)
            clip_ps.append(_virtual_params(
                clip_mod.CLIPTextModel(ccfg), seed, tok))
            log(f"virtual CLIP tower {name!r} ({fam.name}[{i}]): no file "
                f"on disk, deterministic init (seed {seed})")

    if _bf16_weights_enabled(fam):
        # same storage policy as load_pipeline: CLIP towers loaded here
        # must not diverge (dtype or HBM traffic) from the identical
        # towers arriving via CheckpointLoaderSimple
        clip_ps = [_cast_bf16(p) for p in clip_ps]
    pipe = DiffusionPipeline(f"clip:{':'.join(clip_names)}", fam, {},
                             clip_ps, {}, assets_dir=models_dir)
    with _pipeline_lock:
        _pipeline_cache[key] = pipe
    return pipe


def load_unet(unet_name: str, models_dir: Optional[str] = None,
              family_name: Optional[str] = None) -> DiffusionPipeline:
    """UNETLoader equivalent: a standalone diffusion model (family
    detected from the filename unless given).  Accepts full-checkpoint
    ``model.diffusion_model.`` keys or bare UNet keys; text/VAE towers
    virtually initialize so the result is a complete MODEL wire (swap
    them via CLIPLoader/VAELoader outputs downstream)."""
    fam_name = family_name or detect_family(unet_name)
    key = f"unet:{unet_name}:{fam_name}:{models_dir or ''}"
    with _pipeline_lock:
        if key in _pipeline_cache:
            return _pipeline_cache[key]
    fam = FAMILIES[fam_name]

    seed = _name_seed(unet_name)
    path = None
    if models_dir:
        for sub in (unet_name, os.path.join("unet", unet_name),
                    os.path.join("diffusion_models", unet_name)):
            cand = os.path.join(models_dir, sub.replace("\\", "/"))
            if os.path.exists(cand):
                path = cand
                break
    if path is not None:
        from comfyui_distributed_tpu.models.checkpoints import (
            UNET_PREFIX, _LoadMapper, _run_unet, load_state_dict)
        sd = load_state_dict(path)
        prefix = UNET_PREFIX if any(k.startswith(UNET_PREFIX)
                                    for k in sd) else ""
        unet_p = _run_unet(_LoadMapper(sd, prefix), fam.unet)
        log(f"loaded UNet {unet_name} ({fam.name}) from {path}")
    else:
        x = jnp.zeros((1, 8, 8, fam.unet.in_channels))
        unet_p = _virtual_params(
            unet_mod.UNet(fam.unet), seed, x, jnp.zeros((1,)),
            jnp.zeros((1, 77, fam.unet.context_dim)))
        log(f"virtual UNet {unet_name!r} ({fam.name}): no file on disk, "
            f"deterministic init (seed {seed})")

    clip_ps = []
    for i, ccfg in enumerate(fam.clips):
        tok = jnp.zeros((1, ccfg.max_length), jnp.int32)
        clip_ps.append(_virtual_params(
            clip_mod.CLIPTextModel(ccfg), seed + 1 + i, tok))
    img = jnp.zeros((1, 8 * fam.vae.downscale, 8 * fam.vae.downscale, 3))
    vae_p = _virtual_params(vae_mod.VAE(fam.vae), seed + 100, img)
    if _bf16_weights_enabled(fam):
        unet_p = _cast_bf16(unet_p)
        clip_ps = [_cast_bf16(p) for p in clip_ps]
    pipe = DiffusionPipeline(f"unet:{unet_name}", fam, unet_p, clip_ps,
                             vae_p, prediction_type=fam.unet.prediction_type,
                             assets_dir=models_dir)
    pipe.cache_token = key
    with _pipeline_lock:
        _pipeline_cache[key] = pipe
    return pipe


# --- upscalers --------------------------------------------------------------

_upscaler_cache: Dict[str, Tuple[RRDBNet, Any]] = {}


def load_upscaler(model_name: str, models_dir: Optional[str] = None):
    """UpscaleModelLoader equivalent: RRDB net + params (virtual when the
    .pth is absent).  Returns (module, params, scale)."""
    with _pipeline_lock:
        if model_name in _upscaler_cache:
            return _upscaler_cache[model_name]
    lowered = model_name.lower()
    if "tiny" in lowered or os.environ.get(FAMILY_ENV) == "tiny":
        cfg = TINY_RRDB_CONFIG
    else:
        scale = 4
        for s in (8, 4, 2, 1):
            if f"{s}x" in lowered:
                scale = s
                break
        cfg = dataclasses.replace(ESRGAN_4X_CONFIG, scale=scale)
    net = RRDBNet(cfg)
    path = None
    if models_dir:
        cand = os.path.join(models_dir, model_name.replace("\\", "/"))
        if os.path.exists(cand):
            path = cand
    if path is not None:
        from comfyui_distributed_tpu.models.checkpoints import load_upscaler_checkpoint
        params = load_upscaler_checkpoint(path, cfg)
    else:
        params = _virtual_params(net, _name_seed(model_name),
                                 jnp.zeros((1, 16, 16, 3)))
        log(f"virtual upscaler {model_name!r} (scale {cfg.scale})")
    entry = (net, params, cfg.scale)
    with _pipeline_lock:
        _upscaler_cache[model_name] = entry
    return entry
