"""Prompt tokenization with ComfyUI-style attention weighting.

Two backends:
- :class:`BPETokenizer` — real CLIP byte-pair encoding when vocab/merges
  files are present on disk (zero-egress environments can drop them next to
  checkpoints);
- :class:`HashTokenizer` — deterministic fallback mapping words to stable
  hashed ids, used with virtual checkpoints so workflows run end-to-end
  without any downloaded assets.

Both parse the ``(text:1.2)``/``((emphasis))`` weighting syntax ComfyUI's
CLIPTextEncode accepts, returning per-token weights alongside ids.
"""

from __future__ import annotations

import hashlib
import os
import re
from typing import List, Optional, Tuple

import numpy as np

SPECIAL_START = 49406
SPECIAL_END = 49407


def parse_weighted_prompt(text: str) -> List[Tuple[str, float]]:
    """Parse ComfyUI emphasis syntax into (fragment, weight) pairs.

    ``(foo)`` -> 1.1x, ``((foo))`` -> 1.21x, ``[foo]`` -> /1.1,
    ``(foo:1.5)`` -> exactly 1.5.  Unbalanced brackets are treated as
    literal text."""
    out: List[Tuple[str, float]] = []
    stack: List[Tuple[str, float]] = []  # (bracket char, weight at open)
    buf = ""
    cur = 1.0
    i = 0
    explicit_re = re.compile(r":([+-]?\d+(?:\.\d+)?)\)")

    def flush(w: float):
        nonlocal buf
        if buf:
            out.append((buf, w))
            buf = ""

    while i < len(text):
        c = text[i]
        if c == "(":
            flush(cur)
            stack.append(("(", cur))
            cur *= 1.1
            i += 1
        elif c == "[":
            flush(cur)
            stack.append(("[", cur))
            cur /= 1.1
            i += 1
        elif (c == ":" and stack and stack[-1][0] == "("
              and (m := explicit_re.match(text, i))):
            # "(foo:1.5)" — explicit weight replaces the 1.1x default
            base = stack.pop()[1]
            flush(base * float(m.group(1)))
            cur = base
            i = m.end()
        elif c == ")" and stack and stack[-1][0] == "(":
            flush(cur)
            cur = stack.pop()[1]
            i += 1
        elif c == "]" and stack and stack[-1][0] == "[":
            flush(cur)
            cur = stack.pop()[1]
            i += 1
        else:
            buf += c
            i += 1
    flush(cur)  # unbalanced brackets: remaining text keeps its open weight
    return [(t, w) for t, w in out if t.strip()]


class HashTokenizer:
    """Deterministic word-hash tokenizer (no external assets).

    Stable across processes/hosts: ids come from md5 of the lowercased word,
    so distributed participants agree on tokenization without sharing files —
    important for the SPMD path where every mesh slot traces the same
    program."""

    def __init__(self, vocab_size: int = 49408, max_length: int = 77,
                 pad_with_end: bool = True):
        self.vocab_size = vocab_size
        self.max_length = max_length
        self.start = min(SPECIAL_START, vocab_size - 2)
        self.end = min(SPECIAL_END, vocab_size - 1)
        self.pad_id = self.end if pad_with_end else 0

    def _word_id(self, word: str) -> int:
        h = int.from_bytes(hashlib.md5(word.encode()).digest()[:4], "little")
        usable = max(self.start - 1, 1)
        return 1 + (h % (usable - 1))

    def _frag_ids(self, frag: str) -> List[int]:
        return [self._word_id(w)
                for w in re.findall(r"[a-z0-9]+|[^\sa-z0-9]", frag.lower())]

    def encode(self, text: str) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (ids [max_length] int32, weights [max_length] float32)."""
        ids: List[int] = [self.start]
        weights: List[float] = [1.0]
        for frag, w in parse_weighted_prompt(text):
            for wid in self._frag_ids(frag):
                ids.append(wid)
                weights.append(w)
        ids = ids[: self.max_length - 1] + [self.end]
        weights = weights[: self.max_length - 1] + [1.0]
        pad = self.max_length - len(ids)
        ids = ids + [self.pad_id] * pad
        weights = weights + [1.0] * pad
        return (np.asarray(ids, dtype=np.int32),
                np.asarray(weights, dtype=np.float32))


class BPETokenizer:
    """Real CLIP BPE; activates when ``vocab.json`` + ``merges.txt`` exist.

    File format matches openai/CLIP's ``bpe_simple_vocab_16e6``-derived
    assets as shipped by HF tokenizers."""

    def __init__(self, vocab_path: str, merges_path: str,
                 max_length: int = 77, pad_with_end: bool = True):
        import json
        with open(vocab_path, "r", encoding="utf-8") as f:
            self.encoder = json.load(f)
        with open(merges_path, "r", encoding="utf-8") as f:
            merges = f.read().split("\n")
        merges = [tuple(m.split()) for m in merges
                  if m and not m.startswith("#version")]
        self.bpe_ranks = dict(zip(merges, range(len(merges))))
        self.max_length = max_length
        self.start = self.encoder.get("<|startoftext|>", SPECIAL_START)
        self.end = self.encoder.get("<|endoftext|>", SPECIAL_END)
        self.pad_id = self.end if pad_with_end else 0
        self._cache = {}

    def _bpe(self, token: str) -> List[str]:
        if token in self._cache:
            return self._cache[token]
        word = tuple(token[:-1]) + (token[-1] + "</w>",)
        while len(word) > 1:
            pairs = set(zip(word[:-1], word[1:]))
            bigram = min(pairs, key=lambda p: self.bpe_ranks.get(p, 1 << 30))
            if bigram not in self.bpe_ranks:
                break
            first, second = bigram
            new_word: List[str] = []
            i = 0
            while i < len(word):
                if (i < len(word) - 1 and word[i] == first
                        and word[i + 1] == second):
                    new_word.append(first + second)
                    i += 2
                else:
                    new_word.append(word[i])
                    i += 1
            word = tuple(new_word)
        self._cache[token] = list(word)
        return list(word)

    def _frag_ids(self, frag: str) -> List[int]:
        pat = re.compile(r"[a-z0-9]+|[^\sa-z0-9]+")
        out: List[int] = []
        for word in pat.findall(frag.lower()):
            for piece in self._bpe(word):
                out.append(self.encoder.get(
                    piece, self.encoder.get(piece + "</w>", 0)))
        return out

    def encode(self, text: str) -> Tuple[np.ndarray, np.ndarray]:
        ids: List[int] = [self.start]
        weights: List[float] = [1.0]
        for frag, w in parse_weighted_prompt(text):
            for wid in self._frag_ids(frag):
                ids.append(wid)
                weights.append(w)
        ids = ids[: self.max_length - 1] + [self.end]
        weights = weights[: self.max_length - 1] + [1.0]
        pad = self.max_length - len(ids)
        return (np.asarray(ids + [self.pad_id] * pad, dtype=np.int32),
                np.asarray(weights + [1.0] * pad, dtype=np.float32))


EMBEDDING_RE = re.compile(r"embedding:([\w\.\-]+)", re.IGNORECASE)


def has_embedding_refs(text: str) -> bool:
    return bool(EMBEDDING_RE.search(text))


def encode_with_embeddings(tok, text: str, lookup, emb_dim: int):
    """Tokenize with ComfyUI's ``embedding:name`` textual-inversion
    syntax: each reference splices the embedding's learned vectors into
    the token stream at that position (id 0 placeholder; the CLIP tower
    swaps its looked-up embedding for the supplied vector where
    ``mask`` is set — models/clip.py).  Emphasis weights apply to
    spliced vectors like any other token.

    ``lookup(name) -> np [K, emb_dim] | None``; unknown names are
    dropped with a debug log (ComfyUI warns and skips the same way).
    Returns (ids [T] int32, weights [T] f32, override [T, emb_dim] f32,
    mask [T] f32)."""
    from comfyui_distributed_tpu.utils.logging import debug_log

    ids: List[int] = [tok.start]
    weights: List[float] = [1.0]
    override = [np.zeros((emb_dim,), np.float32)]
    mask: List[float] = [0.0]
    for frag, w in parse_weighted_prompt(text):
        # re.split with one capture group alternates [text, name, text,
        # name, ...]: odd indices are embedding names
        for j, piece in enumerate(EMBEDDING_RE.split(frag)):
            if not piece:
                continue
            if j % 2 == 1:
                vecs = lookup(piece)
                if vecs is None:
                    debug_log(f"textual inversion {piece!r} not found; "
                              "dropping the reference")
                    continue
                for v in np.asarray(vecs,
                                    np.float32).reshape(-1, emb_dim):
                    ids.append(0)
                    weights.append(w)
                    override.append(v)
                    mask.append(1.0)
                continue
            for wid in tok._frag_ids(piece):
                ids.append(wid)
                weights.append(w)
                override.append(np.zeros((emb_dim,), np.float32))
                mask.append(0.0)
    T = tok.max_length
    ids = ids[: T - 1] + [tok.end]
    weights = weights[: T - 1] + [1.0]
    override = override[: T - 1] + [np.zeros((emb_dim,), np.float32)]
    mask = mask[: T - 1] + [0.0]
    pad = T - len(ids)
    ids += [tok.pad_id] * pad
    weights += [1.0] * pad
    override += [np.zeros((emb_dim,), np.float32)] * pad
    mask += [0.0] * pad
    return (np.asarray(ids, np.int32), np.asarray(weights, np.float32),
            np.stack(override).astype(np.float32),
            np.asarray(mask, np.float32))


def make_tokenizer(assets_dir: Optional[str] = None,
                   vocab_size: int = 49408,
                   max_length: int = 77,
                   pad_with_end: bool = True):
    """BPE if assets exist, hash fallback otherwise.  ``pad_with_end``:
    SD1.x/SDXL CLIP pads with EOT; SD2.x OpenCLIP pads with 0."""
    if assets_dir:
        vocab = os.path.join(assets_dir, "vocab.json")
        merges = os.path.join(assets_dir, "merges.txt")
        if os.path.exists(vocab) and os.path.exists(merges):
            return BPETokenizer(vocab, merges, max_length=max_length,
                                pad_with_end=pad_with_end)
    return HashTokenizer(vocab_size=vocab_size, max_length=max_length,
                         pad_with_end=pad_with_end)
