"""KL-regularized VAE (SD latent codec), flax NHWC.

The reference calls ComfyUI's VAE for every tile round-trip
(``VAEEncode``/``VAEDecode`` inside ``process_tile``, reference
``distributed_upscale.py:516-541``); this is the native equivalent.
Images are NHWC in [0,1] at the op boundary; internally mapped to [-1,1].
Latents are NHWC with ``latent_channels`` channels, scaled by
``scaling_factor`` (0.18215 SD1.x, 0.13025 SDXL).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from comfyui_distributed_tpu.models.layers import GroupNorm32


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    base_channels: int = 128
    channel_mult: Tuple[int, ...] = (1, 2, 4, 4)
    num_res_blocks: int = 2
    latent_channels: int = 4
    scaling_factor: float = 0.18215
    dtype: Any = jnp.bfloat16

    @property
    def downscale(self) -> int:
        return 2 ** (len(self.channel_mult) - 1)


SD_VAE_CONFIG = VAEConfig()
SDXL_VAE_CONFIG = VAEConfig(scaling_factor=0.13025)
TINY_VAE_CONFIG = VAEConfig(base_channels=16, channel_mult=(1, 2),
                            num_res_blocks=1, dtype=jnp.float32)


class VAEResBlock(nn.Module):
    out_channels: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = nn.silu(GroupNorm32(epsilon=1e-6, name="norm1")(x))
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype,
                    name="conv1")(h)
        h = nn.silu(GroupNorm32(epsilon=1e-6, name="norm2")(h))
        h = nn.Conv(self.out_channels, (3, 3), padding=1, dtype=self.dtype,
                    name="conv2")(h)
        if x.shape[-1] != self.out_channels:
            x = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                        name="skip")(x)
        return x + h


class VAEAttnBlock(nn.Module):
    """Single-head spatial self-attention at the bottleneck."""
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        B, H, W, C = x.shape
        h = GroupNorm32(epsilon=1e-6, name="norm")(x)
        q = nn.Dense(C, dtype=self.dtype, name="q")(h).reshape(B, H * W, C)
        k = nn.Dense(C, dtype=self.dtype, name="k")(h).reshape(B, H * W, C)
        v = nn.Dense(C, dtype=self.dtype, name="v")(h).reshape(B, H * W, C)
        logits = jnp.einsum("bnc,bmc->bnm", q, k,
                            preferred_element_type=jnp.float32)
        w = jax.nn.softmax(logits / jnp.sqrt(jnp.float32(C)), axis=-1)
        out = jnp.einsum("bnm,bmc->bnc", w.astype(v.dtype), v)
        out = nn.Dense(C, dtype=self.dtype,
                       name="proj_out")(out.reshape(B, H, W, C))
        return x + out


class Encoder(nn.Module):
    cfg: VAEConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        h = nn.Conv(cfg.base_channels, (3, 3), padding=1, dtype=cfg.dtype,
                    name="conv_in")(x)
        for level, mult in enumerate(cfg.channel_mult):
            out_ch = cfg.base_channels * mult
            for i in range(cfg.num_res_blocks):
                h = VAEResBlock(out_ch, dtype=cfg.dtype,
                                name=f"down_{level}_res_{i}")(h)
            if level != len(cfg.channel_mult) - 1:
                # CompVis VAE Downsample pads (0,1,0,1) — right/bottom only —
                # then convs stride 2 pad 0; symmetric padding would shift
                # the whole grid half a stride vs real checkpoints
                h = nn.Conv(out_ch, (3, 3), strides=(2, 2),
                            padding=((0, 1), (0, 1)),
                            dtype=cfg.dtype, name=f"down_{level}_ds")(h)
        h = VAEResBlock(h.shape[-1], dtype=cfg.dtype, name="mid_res_0")(h)
        h = VAEAttnBlock(dtype=cfg.dtype, name="mid_attn")(h)
        h = VAEResBlock(h.shape[-1], dtype=cfg.dtype, name="mid_res_1")(h)
        h = nn.silu(GroupNorm32(epsilon=1e-6, name="out_norm")(h))
        return nn.Conv(2 * cfg.latent_channels, (3, 3), padding=1,
                       dtype=jnp.float32, name="conv_out")(h).astype(jnp.float32)


class Decoder(nn.Module):
    cfg: VAEConfig

    @nn.compact
    def __call__(self, z: jax.Array) -> jax.Array:
        cfg = self.cfg
        ch = cfg.base_channels * cfg.channel_mult[-1]
        h = nn.Conv(ch, (3, 3), padding=1, dtype=cfg.dtype, name="conv_in")(z)
        h = VAEResBlock(ch, dtype=cfg.dtype, name="mid_res_0")(h)
        h = VAEAttnBlock(dtype=cfg.dtype, name="mid_attn")(h)
        h = VAEResBlock(ch, dtype=cfg.dtype, name="mid_res_1")(h)
        for level in reversed(range(len(cfg.channel_mult))):
            out_ch = cfg.base_channels * cfg.channel_mult[level]
            for i in range(cfg.num_res_blocks + 1):
                h = VAEResBlock(out_ch, dtype=cfg.dtype,
                                name=f"up_{level}_res_{i}")(h)
            if level != 0:
                B, H, W, C = h.shape
                h = jax.image.resize(h, (B, H * 2, W * 2, C), method="nearest")
                h = nn.Conv(C, (3, 3), padding=1, dtype=cfg.dtype,
                            name=f"up_{level}_us")(h)
        h = nn.silu(GroupNorm32(epsilon=1e-6, name="out_norm")(h))
        return nn.Conv(3, (3, 3), padding=1, dtype=jnp.float32,
                       name="conv_out")(h).astype(jnp.float32)


class VAE(nn.Module):
    """Full autoencoder with encode/decode methods (images [0,1] <-> scaled
    latents)."""
    cfg: VAEConfig

    def setup(self):
        self.encoder = Encoder(self.cfg, name="encoder")
        self.decoder = Decoder(self.cfg, name="decoder")
        # 1x1 moment/latent projections — part of the SD VAE weight layout
        # (torch keys ``quant_conv``/``post_quant_conv``), kept so real
        # checkpoints load losslessly (models/checkpoints.py)
        self.quant_conv = nn.Conv(2 * self.cfg.latent_channels, (1, 1),
                                  dtype=jnp.float32, name="quant_conv")
        self.post_quant_conv = nn.Conv(self.cfg.latent_channels, (1, 1),
                                       dtype=jnp.float32, name="post_quant_conv")

    def encode(self, images: jax.Array,
               key: Optional[jax.Array] = None) -> jax.Array:
        x = images * 2.0 - 1.0
        moments = self.quant_conv(self.encoder(x))
        mean, logvar = jnp.split(moments, 2, axis=-1)
        if key is not None:
            std = jnp.exp(0.5 * jnp.clip(logvar, -30.0, 20.0))
            mean = mean + std * jax.random.normal(key, mean.shape)
        return mean * self.cfg.scaling_factor

    def decode(self, latents: jax.Array) -> jax.Array:
        z = latents / self.cfg.scaling_factor
        x = self.decoder(self.post_quant_conv(z))
        return jnp.clip((x + 1.0) / 2.0, 0.0, 1.0)

    def __call__(self, images: jax.Array) -> jax.Array:
        return self.decode(self.encode(images))
