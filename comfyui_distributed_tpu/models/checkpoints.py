"""Checkpoint interop: torch SD/SDXL single-file weights <-> flax param trees.

The reference delegates checkpoint loading to ComfyUI's CheckpointLoaderSimple
(node 4 in ``/root/reference/workflows/distributed-txt2img.json``) and simply
requires the same files on every machine (``/root/reference/README.md:
189-193``).  Here the equivalent is a bidirectional converter for the
standard single-file SD checkpoint layout (safetensors or torch pickle):

- ``model.diffusion_model.*``            <-> :class:`..models.unet.UNet`
- ``first_stage_model.*``                <-> :class:`..models.vae.VAE`
- ``cond_stage_model.transformer.*``     <-> CLIP-L (SD1.x, HF layout)
- ``cond_stage_model.model.*``           <-> OpenCLIP ViT-H (SD2.x)
- ``conditioner.embedders.0.transformer.*`` <-> CLIP-L (SDXL)
- ``conditioner.embedders.1.model.*``    <-> OpenCLIP bigG (SDXL)

Conversions are pure layout transforms: conv kernels OIHW <-> HWIO, linear
weights transposed, norm ``weight`` <-> ``scale``, OpenCLIP's packed
``in_proj_weight`` split into q/k/v.  The same mapping tables drive both
directions (one ``_run_*`` walk per model, load/export mappers), so
round-tripping is exact by construction.  Weights load as fp32 numpy; dtype
policy (bf16 compute) is applied by the modules at apply time — EXCEPT
that ``registry.load_pipeline`` may then drop UNet/CLIP STORAGE to bf16
(``DTPU_BF16_WEIGHTS``, HBM bandwidth); an export after that is bf16, not
a bit-exact round-trip of an fp32/fp16 source (CheckpointSave warns).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from comfyui_distributed_tpu.models.clip import CLIPConfig
from comfyui_distributed_tpu.models.unet import UNetConfig, mid_depth
from comfyui_distributed_tpu.models.vae import VAEConfig
from comfyui_distributed_tpu.utils.logging import debug_log, log

Params = Dict[str, Any]


# --- state-dict IO ----------------------------------------------------------

def load_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Read a checkpoint file into {torch_key: fp32 numpy}."""
    if path.endswith(".safetensors"):
        from safetensors import safe_open
        out: Dict[str, np.ndarray] = {}
        with safe_open(path, framework="np") as f:
            for k in f.keys():
                out[k] = _to_f32_np(f.get_tensor(k))
        return out
    import torch
    sd = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(sd, dict) and "state_dict" in sd:
        sd = sd["state_dict"]
    return {k: _to_f32_np(v) for k, v in sd.items()}


def save_state_dict(sd: Dict[str, np.ndarray], path: str) -> None:
    from safetensors.numpy import save_file
    save_file({k: np.ascontiguousarray(v) for k, v in sd.items()}, path)


def _to_f32_np(t: Any) -> np.ndarray:
    try:
        import torch
        if isinstance(t, torch.Tensor):
            return t.detach().to(torch.float32).cpu().numpy()
    except ImportError:  # pragma: no cover
        pass
    arr = np.asarray(t)
    if arr.dtype == np.float16 or str(arr.dtype) == "bfloat16":
        arr = arr.astype(np.float32)
    return arr


# --- tensor layout transforms ----------------------------------------------

def t_conv(w: np.ndarray) -> np.ndarray:
    """torch conv OIHW -> flax HWIO."""
    return np.transpose(w, (2, 3, 1, 0))


def t_conv_inv(w: np.ndarray) -> np.ndarray:
    return np.transpose(w, (3, 2, 0, 1))


def t_lin(w: np.ndarray) -> np.ndarray:
    """torch linear [out, in] <-> flax kernel [in, out]."""
    return np.transpose(w)


def _set(tree: Params, path: str, value: np.ndarray) -> None:
    parts = path.split("/")
    node = tree
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value


def _get_path(tree: Params, path: str) -> Optional[np.ndarray]:
    node: Any = tree
    for p in path.split("/"):
        if not isinstance(node, dict) or p not in node:
            return None
        node = node[p]
    return np.asarray(node)


# --- mappers: one mapping walk, two directions ------------------------------

class _LoadMapper:
    """torch state dict -> flax tree."""

    def __init__(self, sd: Dict[str, np.ndarray], prefix: str,
                 consumed: Optional[set] = None):
        self.sd = sd
        self.prefix = prefix
        self.tree: Params = {}
        self.missing: List[str] = []
        # torch keys actually read — lets callers detect unexpected keys
        self.consumed = consumed if consumed is not None else set()

    def _get(self, key: str) -> Optional[np.ndarray]:
        full = self.prefix + key
        if full in self.sd:
            self.consumed.add(full)
            return self.sd[full]
        return None

    def _pair(self, tkey: str, fpath: str, wtrans, wname: str = "kernel",
              bias: bool = True, required: bool = True) -> None:
        w = self._get(tkey + ".weight")
        if w is None:
            if required:
                self.missing.append(self.prefix + tkey)
            return
        _set(self.tree, f"{fpath}/{wname}", wtrans(w))
        if bias:
            b = self._get(tkey + ".bias")
            if b is not None:
                _set(self.tree, fpath + "/bias", b)

    def conv(self, tkey, fpath):
        self._pair(tkey, fpath, t_conv)

    def conv_optional(self, tkey, fpath):
        self._pair(tkey, fpath, t_conv, required=False)

    def conv_as_dense(self, tkey, fpath, export_conv=False):
        # export_conv is export-side metadata; loading accepts both forms
        def tr(w):
            return t_lin(w[:, :, 0, 0] if w.ndim == 4 else w)
        self._pair(tkey, fpath, tr)

    def linear(self, tkey, fpath, bias=True):
        self._pair(tkey, fpath, t_lin, bias=bias)

    def norm(self, tkey, fpath):
        self._pair(tkey, fpath, lambda w: w, wname="scale")

    def raw(self, tkey, fpath, transform=None):
        w = self._get(tkey)
        if w is None:
            self.missing.append(self.prefix + tkey)
            return
        _set(self.tree, fpath, transform(w) if transform else w)

    def packed_qkv(self, tkey: str, fpath: str, width: int) -> None:
        """OpenCLIP ``attn.in_proj_weight`` [3W, W] -> q/k/v Dense."""
        w = self._get(tkey + ".in_proj_weight")
        b = self._get(tkey + ".in_proj_bias")
        if w is None:
            self.missing.append(self.prefix + tkey + ".in_proj_weight")
            return
        for j, name in enumerate(("q", "k", "v")):
            _set(self.tree, f"{fpath}/{name}/kernel",
                 t_lin(w[j * width:(j + 1) * width]))
            if b is not None:
                _set(self.tree, f"{fpath}/{name}/bias",
                     b[j * width:(j + 1) * width])

    def projection(self, tkey: str, fpath: str) -> None:
        """OpenCLIP text_projection: plain [W, P] param (x @ P) or nn.Linear."""
        if self._get(tkey + ".weight") is not None:
            self.linear(tkey, fpath, bias=False)
        else:
            self.raw(tkey, fpath + "/kernel")

    def finish(self, what: str) -> Params:
        if self.missing:
            raise KeyError(f"{what} checkpoint missing {len(self.missing)} "
                           f"keys, first: {self.missing[:5]}")
        return self.tree


class _ExportMapper:
    """flax tree -> torch state dict (inverse transforms, same walk)."""

    def __init__(self, tree: Params, prefix: str):
        self.tree = tree
        self.prefix = prefix
        self.sd: Dict[str, np.ndarray] = {}
        self.missing: List[str] = []

    def _pair(self, tkey, fpath, wtrans, wname="kernel", bias=True,
              required=True):
        w = _get_path(self.tree, f"{fpath}/{wname}")
        if w is None:
            if required:
                self.missing.append(fpath)
            return
        self.sd[self.prefix + tkey + ".weight"] = wtrans(w)
        if bias:
            b = _get_path(self.tree, fpath + "/bias")
            if b is not None:
                self.sd[self.prefix + tkey + ".bias"] = b

    def conv(self, tkey, fpath):
        self._pair(tkey, fpath, t_conv_inv)

    def conv_optional(self, tkey, fpath):
        self._pair(tkey, fpath, t_conv_inv, required=False)

    def conv_as_dense(self, tkey, fpath, export_conv=False):
        """Dense kernel [in, out] -> torch linear [out, in], or — when the
        canonical torch layout is a 1x1 conv (VAE attention always, SD1.x
        transformer proj) — [out, in, 1, 1] so strict-shape torch loaders
        accept the export."""
        if export_conv:
            self._pair(tkey, fpath, lambda w: t_lin(w)[:, :, None, None])
        else:
            self._pair(tkey, fpath, t_lin)

    def linear(self, tkey, fpath, bias=True):
        self._pair(tkey, fpath, t_lin, bias=bias)

    def norm(self, tkey, fpath):
        self._pair(tkey, fpath, lambda w: w, wname="scale")

    def raw(self, tkey, fpath, transform=None):
        w = _get_path(self.tree, fpath)
        if w is None:
            self.missing.append(fpath)
            return
        self.sd[self.prefix + tkey] = transform(w) if transform else w

    def packed_qkv(self, tkey, fpath, width):
        ws, bs = [], []
        for name in ("q", "k", "v"):
            w = _get_path(self.tree, f"{fpath}/{name}/kernel")
            if w is None:
                self.missing.append(f"{fpath}/{name}")
                return
            ws.append(t_lin(w))
            b = _get_path(self.tree, f"{fpath}/{name}/bias")
            if b is not None:
                bs.append(b)
        self.sd[self.prefix + tkey + ".in_proj_weight"] = np.concatenate(ws, 0)
        if len(bs) == 3:
            self.sd[self.prefix + tkey + ".in_proj_bias"] = np.concatenate(bs, 0)

    def projection(self, tkey, fpath):
        self.raw(tkey, fpath + "/kernel")

    def finish(self, what: str) -> Dict[str, np.ndarray]:
        if self.missing:
            raise KeyError(f"{what} export missing {len(self.missing)} "
                           f"params, first: {self.missing[:5]}")
        return self.sd


def _groupnorm(m, tkey: str, fpath: str) -> None:
    # GroupNorm32 wraps an anonymous nn.GroupNorm
    m.norm(tkey, fpath + "/GroupNorm_0")


# --- UNet walk ---------------------------------------------------------------

def _map_resblock(m, tkey: str, fpath: str) -> None:
    _groupnorm(m, f"{tkey}.in_layers.0", f"{fpath}/in_norm")
    m.conv(f"{tkey}.in_layers.2", f"{fpath}/in_conv")
    m.linear(f"{tkey}.emb_layers.1", f"{fpath}/emb_proj")
    _groupnorm(m, f"{tkey}.out_layers.0", f"{fpath}/out_norm")
    m.conv(f"{tkey}.out_layers.3", f"{fpath}/out_conv")
    m.conv_optional(f"{tkey}.skip_connection", f"{fpath}/skip")


def _map_spatial_transformer(m, tkey: str, fpath: str, depth: int,
                             linear_proj: bool = False) -> None:
    _groupnorm(m, f"{tkey}.norm", f"{fpath}/norm")
    m.conv_as_dense(f"{tkey}.proj_in", f"{fpath}/proj_in",
                    export_conv=not linear_proj)
    for j in range(depth):
        b = f"{tkey}.transformer_blocks.{j}"
        fb = f"{fpath}/blocks_{j}"
        for attn in ("attn1", "attn2"):
            m.linear(f"{b}.{attn}.to_q", f"{fb}/{attn}/to_q", bias=False)
            m.linear(f"{b}.{attn}.to_k", f"{fb}/{attn}/to_k", bias=False)
            m.linear(f"{b}.{attn}.to_v", f"{fb}/{attn}/to_v", bias=False)
            m.linear(f"{b}.{attn}.to_out.0", f"{fb}/{attn}/to_out")
        m.norm(f"{b}.norm1", f"{fb}/norm1")
        m.norm(f"{b}.norm2", f"{fb}/norm2")
        m.norm(f"{b}.norm3", f"{fb}/norm3")
        m.linear(f"{b}.ff.net.0.proj", f"{fb}/ff/geglu/proj")
        m.linear(f"{b}.ff.net.2", f"{fb}/ff/out")
    m.conv_as_dense(f"{tkey}.proj_out", f"{fpath}/proj_out",
                    export_conv=not linear_proj)


def _run_unet(m, cfg: UNetConfig):
    """Walk the LDM UNet layout (torch ``input_blocks.N`` enumeration) against
    this framework's level/index names (``models/unet.py``)."""
    m.linear("time_embed.0", "time_fc1")
    m.linear("time_embed.2", "time_fc2")
    if cfg.adm_in_channels is not None:
        m.linear("label_emb.0.0", "label_fc1")
        m.linear("label_emb.0.2", "label_fc2")
    m.conv("input_blocks.0.0", "conv_in")

    L = cfg.num_levels
    idx = 1
    for level in range(L):
        for i in range(cfg.num_res_blocks):
            _map_resblock(m, f"input_blocks.{idx}.0", f"down_{level}_res_{i}")
            if cfg.transformer_depth[level] > 0:
                _map_spatial_transformer(
                    m, f"input_blocks.{idx}.1", f"down_{level}_attn_{i}",
                    cfg.transformer_depth[level],
                    linear_proj=cfg.use_linear_in_transformer)
            idx += 1
        if level != L - 1:
            m.conv(f"input_blocks.{idx}.0.op", f"down_{level}_ds/conv")
            idx += 1

    _map_resblock(m, "middle_block.0", "mid_res_0")
    _map_spatial_transformer(m, "middle_block.1", "mid_attn",
                             mid_depth(cfg),
                             linear_proj=cfg.use_linear_in_transformer)
    _map_resblock(m, "middle_block.2", "mid_res_1")

    idx = 0
    for level in reversed(range(L)):
        for i in range(cfg.num_res_blocks + 1):
            _map_resblock(m, f"output_blocks.{idx}.0", f"up_{level}_res_{i}")
            sub = 1
            if cfg.transformer_depth[level] > 0:
                _map_spatial_transformer(
                    m, f"output_blocks.{idx}.{sub}", f"up_{level}_attn_{i}",
                    cfg.transformer_depth[level],
                    linear_proj=cfg.use_linear_in_transformer)
                sub += 1
            if level != 0 and i == cfg.num_res_blocks:
                m.conv(f"output_blocks.{idx}.{sub}.conv", f"up_{level}_us/conv")
            idx += 1

    _groupnorm(m, "out.0", "out_norm")
    m.conv("out.2", "conv_out")
    return m.finish("UNet")


def _run_controlnet(m, cfg: UNetConfig):
    """Walk the torch ControlNet layout (``control_model.*``): the UNet
    encoder enumeration plus input_hint_block / zero_convs /
    middle_block_out (models/controlnet.py mirrors the flax names)."""
    from comfyui_distributed_tpu.models.controlnet import HINT_CHANNELS
    m.linear("time_embed.0", "time_fc1")
    m.linear("time_embed.2", "time_fc2")
    if cfg.adm_in_channels is not None:
        m.linear("label_emb.0.0", "label_fc1")
        m.linear("label_emb.0.2", "label_fc2")
    m.conv("input_blocks.0.0", "conv_in")

    # hint encoder: torch Sequential with SiLU between convs — conv
    # modules sit at even indices 0,2,4,...,14
    for i in range(len(HINT_CHANNELS) + 1):
        m.conv(f"input_hint_block.{2 * i}", f"hint_conv_{i}")

    L = cfg.num_levels
    idx, zi = 1, 1
    m.conv("zero_convs.0.0", "zero_conv_0")
    for level in range(L):
        for i in range(cfg.num_res_blocks):
            _map_resblock(m, f"input_blocks.{idx}.0", f"down_{level}_res_{i}")
            if cfg.transformer_depth[level] > 0:
                _map_spatial_transformer(
                    m, f"input_blocks.{idx}.1", f"down_{level}_attn_{i}",
                    cfg.transformer_depth[level],
                    linear_proj=cfg.use_linear_in_transformer)
            m.conv(f"zero_convs.{zi}.0", f"zero_conv_{zi}")
            idx += 1
            zi += 1
        if level != L - 1:
            m.conv(f"input_blocks.{idx}.0.op", f"down_{level}_ds/conv")
            m.conv(f"zero_convs.{zi}.0", f"zero_conv_{zi}")
            idx += 1
            zi += 1

    _map_resblock(m, "middle_block.0", "mid_res_0")
    _map_spatial_transformer(m, "middle_block.1", "mid_attn",
                             mid_depth(cfg),
                             linear_proj=cfg.use_linear_in_transformer)
    _map_resblock(m, "middle_block.2", "mid_res_1")
    m.conv("middle_block_out.0", "mid_out")
    return m.finish("ControlNet")


CONTROLNET_PREFIX = "control_model."


def load_controlnet(path: str, cfg: UNetConfig, state_dict=None):
    """ControlNet ``.pth``/``.safetensors`` -> flax params."""
    sd = state_dict if state_dict is not None else load_state_dict(path)
    prefix = CONTROLNET_PREFIX if any(
        k.startswith(CONTROLNET_PREFIX) for k in sd) else ""
    return _run_controlnet(_LoadMapper(sd, prefix), cfg)


def controlnet_context_dim(sd) -> Optional[int]:
    """Cross-attention width of a ControlNet state dict — the one
    dimension that discriminates the SD families (768/1024/2048), used to
    infer the right UNet config from the file itself (the reference
    ecosystem infers ControlNet configs from the checkpoint, not from
    whatever model the user happens to have loaded)."""
    for k, v in sd.items():
        if k.endswith("attn2.to_k.weight"):
            return int(v.shape[-1])
    return None


def export_controlnet(params, cfg: UNetConfig):
    return _run_controlnet(_ExportMapper(params, CONTROLNET_PREFIX), cfg)


# --- VAE walk ----------------------------------------------------------------

def _map_vae_resblock(m, tkey: str, fpath: str) -> None:
    _groupnorm(m, f"{tkey}.norm1", f"{fpath}/norm1")
    m.conv(f"{tkey}.conv1", f"{fpath}/conv1")
    _groupnorm(m, f"{tkey}.norm2", f"{fpath}/norm2")
    m.conv(f"{tkey}.conv2", f"{fpath}/conv2")
    m.conv_optional(f"{tkey}.nin_shortcut", f"{fpath}/skip")


def _map_vae_attn(m, tkey: str, fpath: str) -> None:
    _groupnorm(m, f"{tkey}.norm", f"{fpath}/norm")
    # torch stores q/k/v/proj_out as 1x1 convs; our block uses Dense.
    # Exports MUST be 4D [O, I, 1, 1] — strict torch VAE loaders
    # shape-check and drop 2D tensors here.
    for name in ("q", "k", "v", "proj_out"):
        m.conv_as_dense(f"{tkey}.{name}", f"{fpath}/{name}",
                        export_conv=True)


def _run_vae(m, cfg: VAEConfig):
    L = len(cfg.channel_mult)
    m.conv("encoder.conv_in", "encoder/conv_in")
    for level in range(L):
        for i in range(cfg.num_res_blocks):
            _map_vae_resblock(m, f"encoder.down.{level}.block.{i}",
                              f"encoder/down_{level}_res_{i}")
        if level != L - 1:
            m.conv(f"encoder.down.{level}.downsample.conv",
                   f"encoder/down_{level}_ds")
    _map_vae_resblock(m, "encoder.mid.block_1", "encoder/mid_res_0")
    _map_vae_attn(m, "encoder.mid.attn_1", "encoder/mid_attn")
    _map_vae_resblock(m, "encoder.mid.block_2", "encoder/mid_res_1")
    _groupnorm(m, "encoder.norm_out", "encoder/out_norm")
    m.conv("encoder.conv_out", "encoder/conv_out")

    m.conv("decoder.conv_in", "decoder/conv_in")
    _map_vae_resblock(m, "decoder.mid.block_1", "decoder/mid_res_0")
    _map_vae_attn(m, "decoder.mid.attn_1", "decoder/mid_attn")
    _map_vae_resblock(m, "decoder.mid.block_2", "decoder/mid_res_1")
    # torch decoder.up is indexed by resolution level (up.0 = full res)
    for level in range(L):
        for i in range(cfg.num_res_blocks + 1):
            _map_vae_resblock(m, f"decoder.up.{level}.block.{i}",
                              f"decoder/up_{level}_res_{i}")
        if level != 0:
            m.conv(f"decoder.up.{level}.upsample.conv",
                   f"decoder/up_{level}_us")
    _groupnorm(m, "decoder.norm_out", "decoder/out_norm")
    m.conv("decoder.conv_out", "decoder/conv_out")

    m.conv("quant_conv", "quant_conv")
    m.conv("post_quant_conv", "post_quant_conv")
    return m.finish("VAE")


# --- CLIP walks --------------------------------------------------------------

def _run_clip_hf(m, cfg: CLIPConfig):
    """HF CLIPTextModel layout (SD1.x ``cond_stage_model.transformer`` and
    SDXL's first embedder)."""
    m.raw("embeddings.token_embedding.weight", "token_embedding/embedding")
    m.raw("embeddings.position_embedding.weight", "position_embedding")
    for i in range(cfg.layers):
        t, f = f"encoder.layers.{i}", f"layers_{i}"
        m.norm(f"{t}.layer_norm1", f"{f}/ln1")
        m.linear(f"{t}.self_attn.q_proj", f"{f}/q")
        m.linear(f"{t}.self_attn.k_proj", f"{f}/k")
        m.linear(f"{t}.self_attn.v_proj", f"{f}/v")
        m.linear(f"{t}.self_attn.out_proj", f"{f}/proj")
        m.norm(f"{t}.layer_norm2", f"{f}/ln2")
        m.linear(f"{t}.mlp.fc1", f"{f}/fc1")
        m.linear(f"{t}.mlp.fc2", f"{f}/fc2")
    m.norm("final_layer_norm", "ln_final")
    return m.finish("CLIP")


def _run_clip_vision(m, cfg):
    """HF CLIPVisionModel layout (the ``clip_vision/*.safetensors``
    exports the reference ecosystem's CLIPVisionLoader consumes).
    Note HF's actual key spelling ``pre_layrnorm``."""
    m.raw("vision_model.embeddings.class_embedding", "class_embedding")
    m.raw("vision_model.embeddings.position_embedding.weight",
          "position_embedding")
    m.conv("vision_model.embeddings.patch_embedding", "patch_embed")
    m.norm("vision_model.pre_layrnorm", "pre_ln")
    for i in range(cfg.layers):
        t = f"vision_model.encoder.layers.{i}"
        f = f"layers_{i}"
        m.norm(f"{t}.layer_norm1", f"{f}/ln1")
        m.linear(f"{t}.self_attn.q_proj", f"{f}/q")
        m.linear(f"{t}.self_attn.k_proj", f"{f}/k")
        m.linear(f"{t}.self_attn.v_proj", f"{f}/v")
        m.linear(f"{t}.self_attn.out_proj", f"{f}/proj")
        m.norm(f"{t}.layer_norm2", f"{f}/ln2")
        m.linear(f"{t}.mlp.fc1", f"{f}/fc1")
        m.linear(f"{t}.mlp.fc2", f"{f}/fc2")
    m.norm("vision_model.post_layernorm", "post_ln")
    m.linear("visual_projection", "visual_projection", bias=False)
    return m.finish("CLIPVision")


def _run_openclip(m, cfg: CLIPConfig):
    """OpenCLIP text-tower layout (SDXL's bigG embedder)."""
    m.raw("token_embedding.weight", "token_embedding/embedding")
    m.raw("positional_embedding", "position_embedding")
    for i in range(cfg.layers):
        t, f = f"transformer.resblocks.{i}", f"layers_{i}"
        m.norm(f"{t}.ln_1", f"{f}/ln1")
        m.packed_qkv(f"{t}.attn", f, cfg.width)
        m.linear(f"{t}.attn.out_proj", f"{f}/proj")
        m.norm(f"{t}.ln_2", f"{f}/ln2")
        m.linear(f"{t}.mlp.c_fc", f"{f}/fc1")
        m.linear(f"{t}.mlp.c_proj", f"{f}/fc2")
    m.norm("ln_final", "ln_final")
    if cfg.projection_dim is not None:
        m.projection("text_projection", "text_projection")
    return m.finish("OpenCLIP")


# --- top level ---------------------------------------------------------------

UNET_PREFIX = "model.diffusion_model."
VAE_PREFIX = "first_stage_model."
CLIP_PREFIX_SD15 = "cond_stage_model.transformer.text_model."
# SD2.x: FrozenOpenCLIPEmbedder stores the OpenCLIP text tower directly
CLIP_PREFIX_SD2 = "cond_stage_model.model."
CLIP_PREFIXES_SDXL = ("conditioner.embedders.0.transformer.text_model.",
                      "conditioner.embedders.1.model.")


def _clip_prefixes(family) -> List[str]:
    declared = getattr(family, "clip_prefixes", None)
    if declared is not None:   # layout fact lives ON the family (e.g.
        return list(declared)  # sdxl_refiner's SGM embedder-0 bigG)
    if len(family.clips) == 1:
        layout = getattr(family.clips[0], "layout", "hf")
        return [CLIP_PREFIX_SD2 if layout == "openclip" else CLIP_PREFIX_SD15]
    return list(CLIP_PREFIXES_SDXL)


def _clip_runner(ccfg):
    return _run_openclip if getattr(ccfg, "layout", "hf") == "openclip" \
        else _run_clip_hf


def convert_state_dict(sd: Dict[str, np.ndarray], family,
                       consumed: Optional[set] = None,
                       include_vae: bool = True,
                       ) -> Tuple[Params, List[Params], Optional[Params]]:
    unet = _run_unet(_LoadMapper(sd, UNET_PREFIX, consumed), family.unet)
    vae = _run_vae(_LoadMapper(sd, VAE_PREFIX, consumed), family.vae) \
        if include_vae else None
    clips: List[Params] = []
    for ccfg, prefix in zip(family.clips, _clip_prefixes(family)):
        clips.append(_clip_runner(ccfg)(_LoadMapper(sd, prefix, consumed),
                                        ccfg))
    return unet, clips, vae


# non-parameter keys real checkpoints carry that no model weight maps to:
# diffusion schedule buffers, EMA copies, CLIP position ids / logit scale
EXPECTED_NONPARAM_KEYS = (
    "betas", "alphas_cumprod", "alphas_cumprod_prev",
    "sqrt_alphas_cumprod", "sqrt_one_minus_alphas_cumprod",
    "log_one_minus_alphas_cumprod", "sqrt_recip_alphas_cumprod",
    "sqrt_recipm1_alphas_cumprod", "posterior_variance",
    "posterior_log_variance_clipped", "posterior_mean_coef1",
    "posterior_mean_coef2", "logvar",
    "model_ema.",
    "cond_stage_model.transformer.text_model.embeddings.position_ids",
    "conditioner.embedders.0.transformer.text_model.embeddings.position_ids",
    "conditioner.embedders.1.model.logit_scale",
    # refiner: the bigG tower is embedder 0
    "conditioner.embedders.0.model.logit_scale",
    "cond_stage_model.logit_scale",
    # SD2.x OpenCLIP tower buffers (FrozenOpenCLIPEmbedder keeps the
    # causal mask and logit scale in the state dict)
    "cond_stage_model.model.attn_mask",
    "cond_stage_model.model.logit_scale",
)


def unconsumed_keys(sd: Dict[str, np.ndarray], family) -> List[str]:
    """Checkpoint keys that map onto no model parameter (after dropping the
    known non-parameter buffers) — a loader-coverage check: non-empty means
    either an unexpected checkpoint layout or a mapping gap."""
    consumed: set = set()
    convert_state_dict(sd, family, consumed=consumed)
    leftover = []
    for k in sd:
        if k in consumed:
            continue
        if any(k == e or k.startswith(e) for e in EXPECTED_NONPARAM_KEYS):
            continue
        leftover.append(k)
    return sorted(leftover)


def load_checkpoint(path: str, family) -> Tuple[Params, List[Params], Params]:
    """Load a single-file SD checkpoint into (unet, [clips], vae) param trees
    matching ``registry.ModelFamily`` module layouts."""
    sd = load_state_dict(path)
    debug_log(f"checkpoint {os.path.basename(path)}: {len(sd)} tensors")
    unet, clips, vae = convert_state_dict(sd, family)
    log(f"converted checkpoint {os.path.basename(path)} "
        f"({family.name}): unet/vae/{len(clips)} clip towers")
    return unet, clips, vae


def export_state_dict(unet: Params, clips: List[Params], vae: Params,
                      family, include_vae: bool = True
                      ) -> Dict[str, np.ndarray]:
    """flax param trees -> torch-layout state dict (interop back to the
    reference's ecosystem: a checkpoint exported here loads in ComfyUI).
    ``include_vae=False`` skips the VAE walk (LoRA patching never touches
    it — no point copying it through torch layout)."""
    sd: Dict[str, np.ndarray] = {}
    sd.update(_run_unet(_ExportMapper(unet, UNET_PREFIX), family.unet))
    if include_vae:
        sd.update(_run_vae(_ExportMapper(vae, VAE_PREFIX), family.vae))
    for ccfg, tree, prefix in zip(family.clips, clips, _clip_prefixes(family)):
        sd.update(_clip_runner(ccfg)(_ExportMapper(tree, prefix), ccfg))
    return sd


def save_checkpoint(path: str, unet: Params, clips: List[Params], vae: Params,
                    family) -> None:
    save_state_dict(export_state_dict(unet, clips, vae, family), path)


# --- ESRGAN/RRDB upscalers ---------------------------------------------------
#
# The ``4x*.pth`` files the reference's UpscaleModelLoader consumes
# (``workflows/distributed-upscale.json`` node 14) ship in three naming
# schemes; all normalize onto models/upscalers.py's layout
# (conv_first / rrdb_{i}/db{j}/conv{k} / trunk_conv / up_{i} / hr_conv /
# conv_last).

def _rrdb_key_norm(sd: Dict[str, np.ndarray]) -> Dict[str, str]:
    """Map torch keys -> canonical Real-ESRGAN-style names."""
    if any(k.startswith("model.1.sub.") for k in sd):  # old ESRGAN arch
        out = {}
        nb = max(int(k.split(".")[3]) for k in sd
                 if k.startswith("model.1.sub.") and k.split(".")[3].isdigit())
        # The tail layout depends on scale (one upconv per 2x plus HRconv
        # and conv_last, interleaved with param-free Upsample/LeakyReLU):
        # 4x = model.{3,6,8,10}, 2x = model.{3,5,7}, 1x = model.{2,4}.
        # Detect the parameterized indices instead of hardcoding 4x.
        tail = sorted({int(p[1]) for p in (k.split(".") for k in sd)
                       if p[0] == "model" and p[1].isdigit()
                       and int(p[1]) >= 2})
        names = ([f"upconv{i + 1}" for i in range(len(tail) - 2)]
                 + ["HRconv", "conv_last"])
        tail_map = dict(zip(tail, names))
        for k in sd:
            parts = k.split(".")
            if k.startswith("model.0."):
                out[k] = f"conv_first.{parts[-1]}"
            elif k.startswith(f"model.1.sub.{nb}."):
                out[k] = f"trunk_conv.{parts[-1]}"
            elif k.startswith("model.1.sub."):
                i, rdb, conv = parts[3], parts[4], parts[5]
                out[k] = f"body.{i}.{rdb}.{conv}.{parts[-1]}"
            elif parts[0] == "model" and parts[1].isdigit() \
                    and int(parts[1]) in tail_map:
                out[k] = f"{tail_map[int(parts[1])]}.{parts[-1]}"
        return out
    # new-arch (xinntao ESRGAN: RRDB_trunk) and Real-ESRGAN (body/conv_body)
    out = {}
    for k in sd:
        nk = (k.replace("RRDB_trunk.", "body.")
               .replace("conv_body.", "trunk_conv.")
               .replace("conv_up1.", "upconv1.")
               .replace("conv_up2.", "upconv2.")
               .replace("conv_hr.", "HRconv."))
        out[k] = nk
    return out


def load_upscaler_checkpoint(path: str, cfg) -> Params:
    """ESRGAN/RRDB ``.pth``/``.safetensors`` -> RRDBNet flax params."""
    sd = load_state_dict(path)
    norm = _rrdb_key_norm(sd)
    canon = {norm[k]: v for k, v in sd.items() if k in norm}
    tree: Params = {}

    def conv(tkeys, fpath: str) -> None:
        """Map the first present torch-key variant onto ``fpath``."""
        tkeys = (tkeys,) if isinstance(tkeys, str) else tkeys
        for tkey in tkeys:
            w = canon.get(tkey + ".weight")
            if w is not None:
                _set(tree, fpath + "/kernel", t_conv(w))
                b = canon.get(tkey + ".bias")
                if b is not None:
                    _set(tree, fpath + "/bias", b)
                return
        raise KeyError(f"upscaler checkpoint missing any of {tkeys} "
                       f"(have e.g. {sorted(canon)[:3]})")

    conv("conv_first", "conv_first")
    for i in range(cfg.num_blocks):
        for j in range(3):
            for k in range(5):
                # Real-ESRGAN uses rdb1, xinntao/old-arch use RDB1
                conv((f"body.{i}.rdb{j + 1}.conv{k + 1}",
                      f"body.{i}.RDB{j + 1}.conv{k + 1}"),
                     f"rrdb_{i}/db{j}/conv{k}")
    conv("trunk_conv", "trunk_conv")
    n_up = {1: 0, 2: 1, 4: 2, 8: 3}[cfg.scale]
    for i in range(n_up):
        conv(f"upconv{i + 1}", f"up_{i}")
    conv("HRconv", "hr_conv")
    conv("conv_last", "conv_last")
    log(f"loaded upscaler checkpoint {os.path.basename(path)} "
        f"(scale {cfg.scale}, {cfg.num_blocks} blocks)")
    return tree
