"""CLIP vision transformer (ViT) — the image tower behind the
reference ecosystem's CLIPVisionLoader / CLIPVisionEncode /
unCLIPConditioning surface.

Standard CLIP ViT: patchify conv -> [class token; patches] + position
embeddings -> pre-LN -> non-causal transformer (the text tower's
CLIPLayer with a zero mask) -> post-LN class token -> visual
projection.  The projected class embedding is what unCLIP models
consume as their ADM image conditioning.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from comfyui_distributed_tpu.models.clip import CLIPConfig, CLIPLayer

# CLIP preprocessing constants (OpenAI CLIP normalize)
CLIP_MEAN = np.asarray([0.48145466, 0.4578275, 0.40821073], np.float32)
CLIP_STD = np.asarray([0.26862954, 0.26130258, 0.27577711], np.float32)


@dataclasses.dataclass(frozen=True)
class CLIPVisionConfig:
    width: int = 1280
    layers: int = 32
    heads: int = 16
    patch: int = 14
    image_size: int = 224
    projection_dim: int = 1024
    act: str = "gelu"
    dtype: Any = jnp.float32


# ViT-H/14 (the SD2.1-unclip-h image tower: 1024-d projected embeds)
VIT_H_CONFIG = CLIPVisionConfig()
# ViT-L/14 (the IP-Adapter/SD-unclip-l line: 768-d)
VIT_L_CONFIG = CLIPVisionConfig(width=1024, layers=24, heads=16,
                                projection_dim=768, act="quick_gelu")
TINY_VISION_CONFIG = CLIPVisionConfig(width=64, layers=2, heads=4,
                                      patch=16, image_size=64,
                                      projection_dim=32)


class CLIPVisionModel(nn.Module):
    cfg: CLIPVisionConfig

    @nn.compact
    def __call__(self, pixels: jax.Array
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """pixels: [B, image_size, image_size, 3], CLIP-normalized.
        Returns (last_hidden [B, 1+P, width],
        penultimate_hidden [B, 1+P, width] — the tap before the final
        CLIPLayer, the layer the reference's style-model path consumes —
        and image_embeds [B, proj])."""
        cfg = self.cfg
        B = pixels.shape[0]
        h = nn.Conv(cfg.width, (cfg.patch, cfg.patch),
                    strides=(cfg.patch, cfg.patch), use_bias=False,
                    dtype=cfg.dtype, name="patch_embed")(pixels)
        h = h.reshape(B, -1, cfg.width)
        cls = self.param("class_embedding",
                         nn.initializers.normal(0.02), (cfg.width,))
        h = jnp.concatenate(
            [jnp.broadcast_to(cls, (B, 1, cfg.width)).astype(h.dtype),
             h], axis=1)
        n_pos = (cfg.image_size // cfg.patch) ** 2 + 1
        pos = self.param("position_embedding",
                         nn.initializers.normal(0.02),
                         (n_pos, cfg.width))
        h = h + pos[None, : h.shape[1], :].astype(h.dtype)
        h = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32,
                         name="pre_ln")(h)
        lcfg = CLIPConfig(width=cfg.width, layers=cfg.layers,
                          heads=cfg.heads, act=cfg.act, dtype=cfg.dtype)
        mask = jnp.zeros((1, 1, h.shape[1], h.shape[1]), jnp.float32)
        penultimate = h
        for i in range(cfg.layers):
            if i == cfg.layers - 1:
                penultimate = h          # tap BEFORE the final layer
            h = CLIPLayer(lcfg, name=f"layers_{i}")(h, mask)
        pooled = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32,
                              name="post_ln")(h[:, 0])
        embeds = nn.Dense(cfg.projection_dim, use_bias=False,
                          dtype=jnp.float32,
                          name="visual_projection")(pooled)
        return (h.astype(jnp.float32), penultimate.astype(jnp.float32),
                embeds.astype(jnp.float32))


def preprocess(images: np.ndarray, size: int,
               crop: str = "center") -> np.ndarray:
    """[B,H,W,3] float [0,1] -> CLIP-normalized [B,size,size,3]:
    resize-short-side + center crop (crop="center", the reference
    default) or plain squash (crop="none")."""
    from comfyui_distributed_tpu.utils.image import resize_image

    imgs = np.asarray(images, np.float32)
    B, H, W, _ = imgs.shape
    if crop != "none" and H != W:
        if H < W:
            nw = max(int(round(W * size / H)), size)
            imgs = resize_image(imgs, nw, size, "bicubic")
            x0 = (nw - size) // 2
            imgs = imgs[:, :, x0:x0 + size]
        else:
            nh = max(int(round(H * size / W)), size)
            imgs = resize_image(imgs, size, nh, "bicubic")
            y0 = (nh - size) // 2
            imgs = imgs[:, y0:y0 + size]
    else:
        imgs = resize_image(imgs, size, size, "bicubic")
    return (np.clip(imgs, 0.0, 1.0) - CLIP_MEAN) / CLIP_STD


@dataclasses.dataclass
class CLIPVisionTower:
    """CLIP_VISION wire object: module + params + jit cache."""
    name: str
    cfg: CLIPVisionConfig
    params: Any
    _jitted: Any = None

    def encode(self, images: np.ndarray, crop: str = "center"):
        """-> CLIPVisionOutput(image_embeds [B, proj],
        last_hidden [B, 1+P, width], penultimate_hidden — the
        reference's style-model contract layer)."""
        module = CLIPVisionModel(self.cfg)
        if self._jitted is None:
            self._jitted = jax.jit(
                lambda p, x: module.apply({"params": p}, x))
        px = jnp.asarray(preprocess(images, self.cfg.image_size, crop))
        hidden, penultimate, embeds = self._jitted(self.params, px)
        return CLIPVisionOutput(image_embeds=embeds,
                                last_hidden=hidden,
                                penultimate_hidden=penultimate)


@dataclasses.dataclass
class CLIPVisionOutput:
    """CLIP_VISION_OUTPUT wire object."""
    image_embeds: Any
    last_hidden: Any = None
    # hidden states BEFORE the final transformer layer: what the
    # reference's style-model (ReduxImageEncoder et al.) consumes
    penultimate_hidden: Any = None
