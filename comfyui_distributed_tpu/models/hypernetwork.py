"""A1111-format hypernetworks: per-context-width residual MLPs applied
to the cross-attention k/v context streams.

The reference ecosystem's HypernetworkLoader patches every attn2 call:
``k = to_k(ctx + MLP_k(ctx) * strength)`` (same for v with its own MLP).
The MLPs are tiny relative to the UNet, and the text context is
layer-independent, so this framework applies the transform ONCE per
model call (models/denoiser.py) and threads the two streams through the
UNet as (context, context_v) — identical math, one evaluation instead
of sixteen.

File format (torch pickle): integer keys map context widths to a
``[k_state_dict, v_state_dict]`` pair of ``nn.Sequential`` exports
(``linear.N.weight``/``bias``; 2-D weights are Linears, 1-D pairs are
LayerNorms), plus metadata (``layer_structure``, ``activation_func``,
``is_layer_norm``, ``activate_output``).  Dropout is an inference
no-op.  Loads with ``weights_only=True`` — hypernetwork files need no
arbitrary pickle execution.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from comfyui_distributed_tpu.utils.logging import log

_ACTS = {
    "linear": None,
    "relu": jax.nn.relu,
    "leakyrelu": lambda x: jax.nn.leaky_relu(x, 0.01),
    "elu": jax.nn.elu,
    "swish": jax.nn.silu,
    "silu": jax.nn.silu,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    "softsign": jax.nn.soft_sign,
}

# one parsed stream: ordered layer list of ("linear", w[in,out], b) /
# ("ln", scale, bias) / ("act", name)
Layers = List[Tuple]
# dim -> (k_layers, v_layers)
Hypernet = Dict[int, Tuple[Layers, Layers]]


def _parse_stream(sd: Dict[str, Any], activation: str,
                  activate_output: bool) -> Layers:
    """One Sequential export -> ordered layer ops.  Activations carry no
    params, so they are re-inserted from metadata: after every Linear
    except the last (plus the last when ``activate_output``)."""
    import re
    entries = []
    for key in sd:
        m = re.fullmatch(r"(?:linear\.)?(\d+)\.weight", key)
        if not m:
            continue
        idx = int(m.group(1))
        prefix = key[: -len("weight")]
        w = np.asarray(sd[key], np.float32)
        b = np.asarray(sd.get(prefix + "bias", np.zeros(w.shape[0])),
                       np.float32)
        entries.append((idx, w, b))
    entries.sort(key=lambda e: e[0])
    linear_count = sum(1 for _, w, _ in entries if w.ndim == 2)
    layers: Layers = []
    seen_linear = 0
    for _, w, b in entries:
        if w.ndim == 2:
            seen_linear += 1
            # torch Linear stores [out, in]; jnp matmul wants [in, out]
            layers.append(("linear", jnp.asarray(w.T), jnp.asarray(b)))
            if activation != "linear" and (
                    seen_linear < linear_count or activate_output):
                layers.append(("act", activation))
        else:
            layers.append(("ln", jnp.asarray(w), jnp.asarray(b)))
    return layers


def parse_hypernetwork(sd: Dict[str, Any]) -> Hypernet:
    activation = str(sd.get("activation_func", "linear")).lower()
    if activation not in _ACTS:
        log(f"hypernetwork: unknown activation {activation!r}; "
            "treating as linear")
        activation = "linear"
    activate_output = bool(sd.get("activate_output", False))
    out: Hypernet = {}
    for key, value in sd.items():
        if not isinstance(key, int):
            continue
        k_sd, v_sd = value[0], value[1]
        out[int(key)] = (_parse_stream(k_sd, activation, activate_output),
                         _parse_stream(v_sd, activation,
                                       activate_output))
    return out


def _run_stack(layers: Layers, x: jax.Array) -> jax.Array:
    h = x.astype(jnp.float32)
    for entry in layers:
        kind = entry[0]
        if kind == "linear":
            _, w, b = entry
            h = h @ w + b
        elif kind == "ln":
            _, scale, bias = entry
            mean = h.mean(axis=-1, keepdims=True)
            var = h.var(axis=-1, keepdims=True)
            h = (h - mean) / jnp.sqrt(var + 1e-5) * scale + bias
        else:
            h = _ACTS[entry[1]](h)
    return h.astype(x.dtype)


def apply_hypernetwork(hn: Hypernet, strength: float,
                       context: jax.Array
                       ) -> Tuple[jax.Array, jax.Array]:
    """context -> (context_k, context_v): ``x + MLP(x) * strength`` per
    stream when the context width has an entry, else passthrough."""
    return apply_hypernetwork_pair(hn, strength, context, context)


def apply_hypernetwork_pair(hn: Hypernet, strength: float,
                            ctx_k: jax.Array, ctx_v: jax.Array
                            ) -> Tuple[jax.Array, jax.Array]:
    """Chained form: the k stack runs on the (already-transformed) k
    stream, the v stack on the v stream — one evaluation each."""
    dim = int(ctx_k.shape[-1])
    if dim not in hn:
        return ctx_k, ctx_v
    k_layers, v_layers = hn[dim]
    return (ctx_k + _run_stack(k_layers, ctx_k) * strength,
            ctx_v + _run_stack(v_layers, ctx_v) * strength)


def _virtual_hypernet(name: str, dims: Tuple[int, ...],
                      seed: int) -> Hypernet:
    """Deterministic random hypernet (zero-egress fallback, same policy
    as virtual checkpoints): small-scale residual MLPs so sampling stays
    finite while still visibly steering."""
    out: Hypernet = {}
    for d in dims:
        rng = np.random.default_rng((seed, d))

        def stream():
            w1 = rng.standard_normal((d, d * 2)).astype(np.float32) \
                / np.sqrt(d) * 0.3
            w2 = rng.standard_normal((d * 2, d)).astype(np.float32) \
                / np.sqrt(d * 2) * 0.3
            return [("linear", jnp.asarray(w1),
                     jnp.zeros((d * 2,), jnp.float32)),
                    ("act", "relu"),
                    ("linear", jnp.asarray(w2),
                     jnp.zeros((d,), jnp.float32))]

        out[d] = (stream(), stream())
    return out


_cache: Dict[tuple, Hypernet] = {}


def load_hypernetwork(name: str, models_dir: Optional[str] = None,
                      virtual_dims: Tuple[int, ...] = (64, 320, 640,
                                                       768, 1024, 1280),
                      ) -> Hypernet:
    """``<models_dir>/hypernetworks/<name>`` (A1111 .pt); a missing file
    virtual-initializes deterministically from the name."""
    key = (models_dir or "", name)
    if key in _cache:
        return _cache[key]
    path = None
    if models_dir:
        for cand in (name, name + ".pt"):
            p = os.path.join(models_dir, "hypernetworks",
                             cand.replace("\\", "/"))
            if os.path.isfile(p):
                path = p
                break
    if path is not None:
        import torch
        sd = torch.load(path, map_location="cpu", weights_only=True)

        def _denumpy(v):
            if hasattr(v, "numpy"):
                return v.numpy()
            if isinstance(v, dict):
                return {k: _denumpy(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [_denumpy(x) for x in v]
            return v

        hn = parse_hypernetwork({k: _denumpy(v) for k, v in sd.items()})
        log(f"loaded hypernetwork {name} "
            f"(dims {sorted(hn)}) from {path}")
        self_attn_dims = sorted(d for d in hn
                                if d not in (768, 1024, 2048))
        if self_attn_dims:
            log(f"hypernetwork {name}: entries at hidden widths "
                f"{self_attn_dims} target SELF-attention, which this "
                "framework does not patch — only the text cross-"
                "attention streams apply (known parity limitation)")
    else:
        import zlib
        seed = zlib.crc32(name.encode())
        hn = _virtual_hypernet(name, virtual_dims, seed)
        log(f"virtual hypernetwork {name!r}: no file on disk, "
            f"deterministic init (seed {seed})")
    _cache[key] = hn
    return hn


def clear_hypernetwork_cache() -> None:
    _cache.clear()
