"""Single-image super-resolution models (ESRGAN/RRDB family), flax NHWC.

The reference delegates to ComfyUI's UpscaleModelLoader +
ImageUpscaleWithModel (``workflows/distributed-upscale.json`` nodes 14/15,
feeding UltimateSDUpscaleDistributed); this is the native equivalent.  The
RRDB architecture covers the common ``4x*.pth`` ESRGAN-style checkpoints.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


@dataclasses.dataclass(frozen=True)
class RRDBConfig:
    num_features: int = 64
    num_blocks: int = 23
    growth: int = 32
    scale: int = 4
    dtype: Any = jnp.bfloat16


ESRGAN_4X_CONFIG = RRDBConfig()
TINY_RRDB_CONFIG = RRDBConfig(num_features=16, num_blocks=2, growth=8, scale=2)


class DenseBlock(nn.Module):
    growth: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        feats = [x]
        for i in range(4):
            h = nn.Conv(self.growth, (3, 3), padding=1, dtype=self.dtype,
                        name=f"conv{i}")(jnp.concatenate(feats, axis=-1))
            feats.append(nn.leaky_relu(h, 0.2))
        out = nn.Conv(x.shape[-1], (3, 3), padding=1, dtype=self.dtype,
                      name="conv4")(jnp.concatenate(feats, axis=-1))
        return x + out * 0.2


class RRDB(nn.Module):
    growth: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = x
        for i in range(3):
            h = DenseBlock(self.growth, dtype=self.dtype, name=f"db{i}")(h)
        return x + h * 0.2


class RRDBNet(nn.Module):
    cfg: RRDBConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        """x: [B,H,W,3] in [0,1] -> [B, H*scale, W*scale, 3]."""
        cfg = self.cfg
        fea = nn.Conv(cfg.num_features, (3, 3), padding=1, dtype=cfg.dtype,
                      name="conv_first")(x)
        h = fea
        for i in range(cfg.num_blocks):
            h = RRDB(cfg.growth, dtype=cfg.dtype, name=f"rrdb_{i}")(h)
        h = nn.Conv(cfg.num_features, (3, 3), padding=1, dtype=cfg.dtype,
                    name="trunk_conv")(h)
        h = fea + h
        n_up = {1: 0, 2: 1, 4: 2, 8: 3}[cfg.scale]
        for i in range(n_up):
            B, H, W, C = h.shape
            h = jax.image.resize(h, (B, H * 2, W * 2, C), method="nearest")
            h = nn.leaky_relu(
                nn.Conv(cfg.num_features, (3, 3), padding=1, dtype=cfg.dtype,
                        name=f"up_{i}")(h), 0.2)
        h = nn.leaky_relu(
            nn.Conv(cfg.num_features, (3, 3), padding=1, dtype=cfg.dtype,
                    name="hr_conv")(h), 0.2)
        out = nn.Conv(3, (3, 3), padding=1, dtype=jnp.float32,
                      name="conv_last")(h)
        return jnp.clip(out.astype(jnp.float32), 0.0, 1.0)
