"""GLIGEN grounded generation (the reference ecosystem's GLIGENLoader /
GLIGENTextBoxApply): phrase embeddings + normalized boxes become
grounding tokens (PositionNet) that every transformer block's gated
self-attention fuser attends alongside the visual tokens
(models/layers.GatedSelfAttention — zero-init gates, so the patch
starts as a near-no-op).

The fuser weights live INSIDE the UNet param tree (``.../fuser``): the
loader virtual-initializes a gligen-enabled tree and grafts the base
checkpoint's weights over every shared key, so trained base weights are
preserved exactly and only the grounding-specific parameters are
synthesized.  Converting trained GLIGEN release weights is not
implemented — loading a real file logs loudly (the virtual fusers keep
the surface runnable), the same policy as other adapter files."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from comfyui_distributed_tpu.utils.logging import log

FOURIER_FREQS = 8
POS_DIM = FOURIER_FREQS * 2 * 4       # sin/cos x 4 box coords


def fourier_box_embed(boxes: jax.Array) -> jax.Array:
    """[..., 4] normalized xyxy -> [..., POS_DIM] (GLIGEN's fourier
    position encoding: freqs 2^0..2^(F-1))."""
    freqs = 2.0 ** jnp.arange(FOURIER_FREQS, dtype=jnp.float32)
    ang = boxes[..., None] * freqs * np.pi          # [..., 4, F]
    emb = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)
    return emb.reshape(boxes.shape[:-1] + (POS_DIM,))


@dataclasses.dataclass(frozen=True)
class GligenConfig:
    text_dim: int = 768
    out_dim: int = 768
    hidden: int = 512


class PositionNet(nn.Module):
    """(text_embs [B,N,text_dim], boxes [B,N,4], masks [B,N]) ->
    grounding tokens [B, N, out_dim]; masked-out entries use the
    learned null features (GLIGEN's layout)."""
    cfg: GligenConfig

    @nn.compact
    def __call__(self, text_embs, boxes, masks):
        cfg = self.cfg
        pos = fourier_box_embed(boxes)
        null_pos = self.param("null_position_feature",
                              nn.initializers.zeros, (POS_DIM,))
        null_text = self.param("null_text_feature",
                               nn.initializers.zeros, (cfg.text_dim,))
        m = masks[..., None].astype(jnp.float32)
        pos = pos * m + null_pos * (1.0 - m)
        txt = text_embs * m + null_text * (1.0 - m)
        h = jnp.concatenate([txt, pos], axis=-1)
        h = nn.Dense(cfg.hidden, name="fc1")(h)
        h = nn.silu(h)
        h = nn.Dense(cfg.hidden, name="fc2")(h)
        h = nn.silu(h)
        return nn.Dense(cfg.out_dim, name="fc3")(h)


@dataclasses.dataclass
class GligenModel:
    """GLIGEN wire object: the position net + its params."""
    name: str
    cfg: GligenConfig
    params: Any
    _jitted: Any = None

    def grounding_tokens(self, text_embs, boxes, masks) -> jax.Array:
        if self._jitted is None:
            module = PositionNet(self.cfg)
            self._jitted = jax.jit(
                lambda p, t, b, m: module.apply({"params": p}, t, b, m))
        return self._jitted(self.params, jnp.asarray(text_embs),
                            jnp.asarray(boxes, jnp.float32),
                            jnp.asarray(masks, jnp.float32))


_cache: Dict[str, GligenModel] = {}


def load_gligen(name: str, models_dir=None,
                text_dim: int = 768) -> GligenModel:
    import os
    key = f"{name}:{text_dim}:{models_dir or ''}"
    if key in _cache:
        return _cache[key]
    if models_dir:
        for cand in (name, os.path.join("gligen", name)):
            p = os.path.join(models_dir, cand.replace("\\", "/"))
            if os.path.isfile(p):
                log(f"gligen {name}: converting trained release weights "
                    "is not implemented — using deterministic virtual "
                    "fusers/position net (known limitation)")
                break
    from comfyui_distributed_tpu.models.registry import (_name_seed,
                                                         _virtual_params)
    cfg = GligenConfig(text_dim=text_dim, out_dim=text_dim)
    seed = _name_seed(name)
    t = jnp.zeros((1, 1, cfg.text_dim))
    b = jnp.zeros((1, 1, 4))
    m = jnp.zeros((1, 1))
    params = _virtual_params(PositionNet(cfg), seed, t, b, m)
    log(f"virtual gligen {name!r} (text_dim {text_dim}), deterministic "
        f"init (seed {seed})")
    model = GligenModel(name=name, cfg=cfg, params=params)
    _cache[key] = model
    return model


def clear_gligen_cache() -> None:
    _cache.clear()
