"""ControlNet (Zhang et al.): a trainable copy of the UNet encoder that
injects spatial-hint residuals into the paired UNet's skips and middle.

The reference delegates ControlNet entirely to ComfyUI
(``ControlNetLoader``/``ControlNetApply`` nodes used inside the workflows
it fans out); here the flax module mirrors this framework's own UNet
encoder **module-for-module with the same names** (``models/unet.py``
down path), so the checkpoint converter reuses the exact same mapping
walks for the shared structure (torch layout ``control_model.*`` —
input_blocks/middle_block enumeration identical to the UNet's, plus
``input_hint_block``, ``zero_convs``, ``middle_block_out``).

TPU notes: the hint is encoded once per sampling step at the CFG batch
size (one extra batched conv stack + encoder pass per step — large MXU
matmuls, no host sync); zero-convs are 1x1 convs that XLA fuses into the
adjacent adds.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from comfyui_distributed_tpu.models.layers import (
    Downsample,
    ResBlock,
    SpatialTransformer,
    timestep_embedding,
)
from comfyui_distributed_tpu.models.unet import mid_depth, UNetConfig

# input_hint_block channel/stride ladder (torch ControlNet: 8 convs, three
# stride-2 steps take the image-res hint down 8x to latent resolution)
HINT_CHANNELS = (16, 16, 32, 32, 96, 96, 256)
HINT_STRIDES = (1, 1, 2, 1, 2, 1, 2)


class ControlNet(nn.Module):
    """Returns (skip_residuals, middle_residual) for a paired UNet."""

    cfg: UNetConfig

    @nn.compact
    def __call__(self, x: jax.Array, timesteps: jax.Array,
                 context: jax.Array, hint: jax.Array,
                 y: Optional[jax.Array] = None
                 ) -> Tuple[List[jax.Array], jax.Array]:
        """x: [B,h,w,C] latent (same scaled input the UNet sees);
        hint: [B,H,W,3] image-resolution control map in [0,1]."""
        cfg = self.cfg
        ch = cfg.model_channels
        time_dim = ch * 4

        emb = timestep_embedding(timesteps, ch)
        emb = nn.Dense(time_dim, dtype=cfg.dtype, name="time_fc1")(emb)
        emb = nn.Dense(time_dim, dtype=cfg.dtype,
                       name="time_fc2")(nn.silu(emb))
        if cfg.adm_in_channels is not None:
            if y is None:
                y = jnp.zeros((x.shape[0], cfg.adm_in_channels), x.dtype)
            lab = nn.Dense(time_dim, dtype=cfg.dtype, name="label_fc1")(y)
            lab = nn.Dense(time_dim, dtype=cfg.dtype,
                           name="label_fc2")(nn.silu(lab))
            emb = emb + lab

        # hint encoder: image res -> latent res, final zero-init conv
        g = hint.astype(cfg.dtype)
        for i, (hc, st) in enumerate(zip(HINT_CHANNELS, HINT_STRIDES)):
            g = nn.Conv(hc, (3, 3), strides=(st, st), padding=1,
                        dtype=cfg.dtype, name=f"hint_conv_{i}")(g)
            g = nn.silu(g)
        g = nn.Conv(ch, (3, 3), padding=1, dtype=cfg.dtype,
                    kernel_init=nn.initializers.zeros,
                    name=f"hint_conv_{len(HINT_CHANNELS)}")(g)

        def heads(c: int) -> int:
            if cfg.num_heads is not None:
                return cfg.num_heads
            return max(c // cfg.num_head_channels, 1)

        def zero_conv(h: jax.Array, i: int) -> jax.Array:
            return nn.Conv(h.shape[-1], (1, 1), dtype=cfg.dtype,
                           kernel_init=nn.initializers.zeros,
                           name=f"zero_conv_{i}")(h)

        h = nn.Conv(ch, (3, 3), padding=1, dtype=cfg.dtype,
                    name="conv_in")(x)
        h = h + g
        outs = [zero_conv(h, 0)]
        zi = 1

        # down path — identical structure and names to the UNet encoder
        for level, mult in enumerate(cfg.channel_mult):
            out_ch = ch * mult
            for i in range(cfg.num_res_blocks):
                h = ResBlock(out_ch, dtype=cfg.dtype,
                             name=f"down_{level}_res_{i}")(h, emb)
                if cfg.transformer_depth[level] > 0:
                    h = SpatialTransformer(
                        heads(out_ch), depth=cfg.transformer_depth[level],
                        dtype=cfg.dtype, attn_impl=cfg.attn_impl,
                        name=f"down_{level}_attn_{i}")(h, context)
                outs.append(zero_conv(h, zi))
                zi += 1
            if level != cfg.num_levels - 1:
                h = Downsample(dtype=cfg.dtype, name=f"down_{level}_ds")(h)
                outs.append(zero_conv(h, zi))
                zi += 1

        mid_ch = ch * cfg.channel_mult[-1]
        h = ResBlock(mid_ch, dtype=cfg.dtype, name="mid_res_0")(h, emb)
        h = SpatialTransformer(
            heads(mid_ch), depth=mid_depth(cfg),
            dtype=cfg.dtype, attn_impl=cfg.attn_impl,
            name="mid_attn")(h, context)
        h = ResBlock(mid_ch, dtype=cfg.dtype, name="mid_res_1")(h, emb)
        mid = nn.Conv(mid_ch, (1, 1), dtype=cfg.dtype,
                      kernel_init=nn.initializers.zeros, name="mid_out")(h)

        return outs, mid
