"""Token merging (ToMe / tomesd) for stable-diffusion self-attention.

The reference ecosystem's TomePatchModel merges the ``r`` most-similar
query tokens into their nearest "destination" token before attn1 and
unmerges after: attention cost drops from O(N^2) to O((N-r)*N) with
minimal quality loss at moderate ratios.

TPU shape: everything here is static — the destination grid is the
deterministic top-left token of every 2x2 cell (tomesd's ``no_rand``
mode; the randomized grid is jit-hostile), ``r`` is a trace-time
constant from the ratio widget, and merge/unmerge are gathers plus one
segment-mean.  Following the reference's attn1 patch, only the QUERY
side merges — keys/values stay full, so the attention output for kept
tokens is mathematically unchanged and merged tokens adopt their
destination's output on unmerge.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def dst_grid_indices(h: int, w: int, sy: int = 2,
                     sx: int = 2) -> Tuple[np.ndarray, np.ndarray]:
    """Token indices of (dst, src) for an h x w grid: dst = the
    top-left token of each sy x sx cell, src = everything else."""
    idx = np.arange(h * w).reshape(h, w)
    dst = idx[::sy, ::sx].reshape(-1)
    mask = np.zeros(h * w, bool)
    mask[dst] = True
    src = np.nonzero(~mask)[0]
    return dst, src


def build_merge(metric: jax.Array, h: int, w: int, ratio: float,
                sy: int = 2, sx: int = 2
                ) -> Tuple[Callable, Callable, int]:
    """-> (merge, unmerge, r).

    ``metric`` [B, N, C]: the similarity features (the block's normed
    hidden states).  ``merge(x)`` -> [B, N - r, C'] with rows laid out
    [kept_src; dst] (merged src tokens mean-pool into their most
    similar dst).  ``unmerge(y)`` -> [B, N, C']: kept rows scatter
    back, merged src rows copy their dst's row.  r = 0 returns
    identities."""
    B, N, _ = metric.shape
    assert N == h * w, (N, h, w)
    dst_idx, src_idx = dst_grid_indices(h, w, sy, sx)
    n_src = src_idx.shape[0]
    r = min(int(N * float(ratio)), n_src)
    if r <= 0:
        return (lambda x: x), (lambda y: y), 0

    m = metric / jnp.maximum(
        jnp.linalg.norm(metric, axis=-1, keepdims=True), 1e-6)
    a = m[:, src_idx]                       # [B, n_src, C]
    b = m[:, dst_idx]                       # [B, n_dst, C]
    scores = jnp.einsum("bsc,bdc->bsd", a, b)
    node_max = scores.max(axis=-1)          # [B, n_src]
    node_idx = scores.argmax(axis=-1)       # [B, n_src] -> dst slot
    order = jnp.argsort(-node_max, axis=-1)
    merged_sel = order[:, :r]               # positions INTO src_idx
    kept_sel = order[:, r:]
    n_dst = dst_idx.shape[0]
    batch = jnp.arange(B)[:, None]

    def merge(x: jax.Array) -> jax.Array:
        src = x[:, src_idx]
        dst = x[:, dst_idx]
        kept = src[batch, kept_sel]                      # [B, n_src-r, C]
        merged = src[batch, merged_sel]                  # [B, r, C]
        tgt = node_idx[batch, merged_sel]                # [B, r]
        # mean-pool each merged token into its dst slot
        ones = jnp.ones((B, r), x.dtype)
        add = jax.vmap(
            lambda d, t, v: d.at[t].add(v))(dst, tgt, merged)
        cnt = jax.vmap(
            lambda t, o: jnp.ones((n_dst,),
                                  x.dtype).at[t].add(o))(tgt, ones)
        dst_pooled = add / cnt[..., None]
        return jnp.concatenate([kept, dst_pooled], axis=1)

    def unmerge(y: jax.Array) -> jax.Array:
        kept = y[:, : n_src - r]
        dst = y[:, n_src - r:]
        out = jnp.zeros((B, N) + y.shape[2:], y.dtype)
        # dst tokens back to their grid positions
        out = out.at[:, dst_idx].set(dst)
        # kept src tokens back to theirs
        kept_pos = jnp.asarray(src_idx)[kept_sel]        # [B, n_src-r]
        out = jax.vmap(
            lambda o, p, v: o.at[p].set(v))(out, kept_pos, kept)
        # merged src tokens adopt their destination's row
        merged_pos = jnp.asarray(src_idx)[merged_sel]
        tgt = node_idx[batch, merged_sel]
        out = jax.vmap(
            lambda o, p, d, t: o.at[p].set(d[t]))(out, merged_pos, dst,
                                                  tgt)
        return out

    return merge, unmerge, r
