"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

The reference has no attention-level sharding at all (SURVEY.md §5 —
"long-context / sequence parallelism: ABSENT"); its spatial analog is tile
scatter.  This framework makes sequence parallelism first-class: token axes
shard over the ``seq`` mesh axis, and attention runs as a ring — each device
holds its Q shard resident while K/V shards rotate around the ring via
``lax.ppermute`` (ICI neighbor exchange), with flash-style online-softmax
accumulation so no device ever materializes the full sequence or the full
attention matrix.

Math: per incoming K/V block, logits ``s = qk^T * scale`` update the running
``(max, denominator, accumulator)`` triple:

    m'   = max(m, max(s))
    corr = exp(m - m')
    l'   = l * corr + sum(exp(s - m'))
    acc' = acc * corr + exp(s - m') @ v

which is exactly blockwise-stable softmax — the same recurrence the Pallas
flash kernel uses intra-device (``ops/pallas/flash_attention.py``).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

# version-portable shard_map (check_vma/check_rep shim) — ONE shim for
# every call site, see parallel/collectives.py
from comfyui_distributed_tpu.parallel import sharding as shd
from comfyui_distributed_tpu.parallel.collectives import shard_map

from comfyui_distributed_tpu.utils.constants import (
    DATA_AXIS,
    SEQ_AXIS,
    TENSOR_AXIS,
)

NEG_INF = -1e30


def _block_update(q, k, v, m, l, acc, scale, mask=None):
    """One online-softmax accumulation step.

    q: [B, Nq, H, D]; k/v: [B, Nk, H, D]; m/l: [B, H, Nq]; acc like q.
    """
    s = jnp.einsum("bnhd,bmhd->bhnm", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * corr + jnp.sum(p, axis=-1)
    acc_new = acc * corr[..., None].transpose(0, 2, 1, 3) + jnp.einsum(
        "bhnm,bmhd->bnhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _ring_body(q, k, v, axis_name: str, n_shards: int, causal: bool,
               scale: float):
    """Per-shard ring attention (runs inside shard_map).

    q/k/v: [B, n_local, H, D] — the local sequence shard."""
    B, n_local, H, D = q.shape
    my_idx = jax.lax.axis_index(axis_name)

    q_pos = my_idx * n_local + jnp.arange(n_local)          # global q rows

    m = jnp.full((B, H, n_local), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, n_local), jnp.float32)
    acc = jnp.zeros((B, n_local, H, D), jnp.float32)

    def step(carry, step_i):
        k_cur, v_cur, m, l, acc = carry
        # the block arriving at step t originated at shard (my_idx - t) % n
        src = jnp.mod(my_idx - step_i, n_shards)
        if causal:
            k_pos = src * n_local + jnp.arange(n_local)
            mask = q_pos[:, None] >= k_pos[None, :]          # [Nq, Nk]
            mask = mask[None, None, :, :]
        else:
            mask = None
        m, l, acc = _block_update(q, k_cur, v_cur, m, l, acc, scale, mask)
        # rotate K/V to the next neighbor over ICI
        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, acc), None

    (k, v, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m, l, acc), jnp.arange(n_shards))
    out = acc / jnp.maximum(l, 1e-20)[..., None].transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mesh: Mesh, axis_name: str = SEQ_AXIS,
                   causal: bool = False,
                   scale: Optional[float] = None,
                   batch_axis: Optional[str] = DATA_AXIS,
                   head_axis: Optional[str] = TENSOR_AXIS) -> jax.Array:
    """Sequence-parallel attention over ``mesh[axis_name]``.

    q/k/v: [B, N, H, D] with the token axis N sharded over ``axis_name``
    (replicated inputs are fine too — shard_map partitions them).  Returns
    [B, N, H, D] with the same sharding.  N must divide evenly by the axis
    size (pad upstream — same pad-and-mask stance as the tile scatter,
    ``parallel/collectives.py``).

    Composes with the other mesh axes: when the batch dim divides
    ``batch_axis`` (dp) and/or the head dim divides ``head_axis`` (tp),
    those dims shard too instead of forcing an all-gather of dp-sharded
    activations into every seq shard — so dp x tp x sp runs as one
    shard_map with the K/V ring riding only the ``seq`` axis."""
    n_shards = mesh.shape[axis_name]
    if q.shape[1] % n_shards:
        raise ValueError(f"sequence length {q.shape[1]} not divisible by "
                         f"{axis_name} axis size {n_shards}")
    if k.shape[1] != v.shape[1]:
        raise ValueError(f"k/v length mismatch: {k.shape[1]} vs {v.shape[1]}")
    if k.shape[1] % n_shards:
        raise ValueError(f"k/v length {k.shape[1]} not divisible by "
                         f"{axis_name} axis size {n_shards}")
    if causal and k.shape[1] != q.shape[1]:
        # causal cross-attention (Nq != Nk) has no well-defined position
        # alignment; silently masking by local index would be wrong
        raise ValueError(f"causal ring attention requires Nq == Nk, got "
                         f"{q.shape[1]} vs {k.shape[1]}")
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if n_shards == 1:
        m = jnp.full(q.shape[:1] + (q.shape[2], q.shape[1]), NEG_INF,
                     jnp.float32)
        l = jnp.zeros_like(m)
        acc = jnp.zeros(q.shape, jnp.float32)
        mask = None
        if causal:
            n = q.shape[1]
            mask = (jnp.arange(n)[:, None] >= jnp.arange(n)[None, :]
                    )[None, None, :, :]
        m, l, acc = _block_update(q, k, v, m, l, acc, scale, mask)
        return (acc / jnp.maximum(l, 1e-20)[..., None].transpose(0, 2, 1, 3)
                ).astype(q.dtype)

    def _axis_if_divisible(name: Optional[str], dim: int) -> Optional[str]:
        if not name or name == axis_name or name not in mesh.shape:
            return None
        size = int(mesh.shape[name])
        return name if size > 1 and dim % size == 0 else None

    b_ax = _axis_if_divisible(batch_axis, q.shape[0])
    h_ax = _axis_if_divisible(head_axis, q.shape[2])
    spec = shd.mesh_spec(b_ax, axis_name, h_ax, None)
    body = partial(_ring_body, axis_name=axis_name, n_shards=n_shards,
                   causal=causal, scale=scale)
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def attention_reference(q, k, v, causal: bool = False,
                        scale: Optional[float] = None):
    """Plain softmax attention — the oracle ring_attention must match."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bnhd,bmhd->bhnm", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        n, mkv = q.shape[1], k.shape[1]
        mask = jnp.arange(n)[:, None] >= jnp.arange(mkv)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhnm,bmhd->bnhd", w.astype(v.dtype), v)
