"""Sharding rules: how params and activations lay out over the mesh.

The reference has no model parallelism — every participant holds a full model
copy (its README requires identical checkpoints on all machines,
``/root/reference/README.md:189-193``).  On TPU, tensor parallelism is nearly
free to offer because it is *layout, not code*: we annotate parameter and
activation shardings with :class:`jax.sharding.NamedSharding` and GSPMD
inserts the collectives.  This module centralises those annotations:

- **dp** — batch dims over the ``data`` axis (the reference's worker axis);
- **tp** — weight matrices over the ``tensor`` axis (output-feature dim of
  large kernels; megatron-style column split, with XLA choosing the matching
  row splits/reductions);
- **sp** — token/sequence dims over the ``seq`` axis (context tensors and
  attention inputs; ring attention in :mod:`.ring` keeps the shards resident).

Rules are shape-driven rather than name-driven so they apply uniformly to any
flax param tree (UNet, CLIP, VAE) without per-module tables.

Activation placement (ISSUE 16) goes through a **logical-axis rule table**
instead of hand-built specs: model code names what a dim *is* (``"batch"``,
``"heads"``, ``"mlp"``, ``"seq"``) and :func:`constrain` resolves it against
:data:`LOGICAL_AXIS_RULES` + the live mesh, engaging only when a tensor axis
is actually up.  This module is the ONLY place in the package that may build
a raw :class:`PartitionSpec`/:class:`NamedSharding` — dtpu-lint's
``tp-spec-discipline`` rule holds every other module to the table.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from comfyui_distributed_tpu.utils.constants import DATA_AXIS, SEQ_AXIS, TENSOR_AXIS

# Don't bother sharding tensors smaller than this many elements: the gather
# traffic would cost more than the HBM saved.
MIN_SHARD_ELEMENTS = 2 ** 11

# --- logical-axis rule table --------------------------------------------------
#
# Model code annotates dims with *logical* names; this table maps them onto
# mesh axes.  One table for the whole package means retargeting the layout
# (e.g. sharding "mlp" over a combined axis on a bigger slice) is a one-line
# change here, not a hunt through every module.

LOGICAL_BATCH = "batch"   # per-image rows (the reference's worker axis)
LOGICAL_HEADS = "heads"   # attention heads (megatron: split across tensor)
LOGICAL_MLP = "mlp"       # feed-forward hidden features (column split)
LOGICAL_SEQ = "seq"       # token axis (ring attention / sp)

LOGICAL_AXIS_RULES = {
    LOGICAL_BATCH: DATA_AXIS,
    LOGICAL_HEADS: TENSOR_AXIS,
    LOGICAL_MLP: TENSOR_AXIS,
    LOGICAL_SEQ: SEQ_AXIS,
}


def mesh_spec(*parts: Optional[str]) -> P:
    """Raw mesh-axis PartitionSpec — the package's single constructor.

    Entries are mesh axis names (``data``/``tensor``/``seq``) or None.
    Modules that genuinely speak mesh axes (shard_map in/out specs in
    collectives/ring) build their specs here instead of importing
    PartitionSpec themselves, keeping the lint discipline airtight."""
    return P(*parts)


def logical_spec(*logical: Optional[str]) -> P:
    """Resolve logical dim names through the rule table into a PartitionSpec.

    Each entry is a :data:`LOGICAL_AXIS_RULES` key or None (replicated dim).
    Unknown names raise — a typo'd logical axis must not silently replicate."""
    parts = []
    for name in logical:
        if name is None:
            parts.append(None)
            continue
        if name not in LOGICAL_AXIS_RULES:
            raise ValueError(
                f"unknown logical axis {name!r}; known: "
                f"{sorted(LOGICAL_AXIS_RULES)}")
        parts.append(LOGICAL_AXIS_RULES[name])
    return P(*parts)


def batch_axis_spec(ndim: int, batch_dim: int = 0) -> P:
    """Rows-on-``data`` spec for an ``ndim``-rank array: the bucket/batch
    layout (everything but the batch dim replicated)."""
    parts: list = [None] * ndim
    parts[batch_dim] = DATA_AXIS
    return P(*parts)


def named(mesh: Mesh, spec: P) -> NamedSharding:
    """The package's single NamedSharding constructor."""
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def put_on_mesh(x: Any, mesh: Mesh, spec: P) -> Any:
    """device_put one array onto the mesh with an explicit spec — the
    MeshHelper-style chokepoint for host->mesh placement."""
    return jax.device_put(x, NamedSharding(mesh, spec))


def serving_mesh() -> Optional[Mesh]:
    """The live runtime's mesh IFF tensor parallelism is engaged (a built
    runtime whose ``tensor`` axis is > 1); None otherwise.  The gate every
    activation constraint and the CB bucket layout share with
    ``DiffusionPipeline._ensure_tp_sharded`` — on pure data-parallel meshes
    (every pre-ISSUE-16 configuration) all of it stays inert, so the
    single-chip and dp-only paths compile exactly the HLO they always did."""
    from comfyui_distributed_tpu.parallel.mesh import get_live_runtime
    rt = get_live_runtime()
    if rt is None or getattr(rt, "mesh", None) is None:
        return None
    mesh = rt.mesh
    if int(mesh.shape.get(TENSOR_AXIS, 1)) <= 1:
        return None
    return mesh


def _resolve_constraint(mesh: Mesh, shape: Sequence[int],
                        logical: Sequence[Optional[str]]) -> Optional[P]:
    """Logical names -> a spec valid for ``shape`` on ``mesh``: axes whose
    mesh size is 1 or that don't divide the dim drop to replicated (shapes
    are static under trace, so this is a trace-time decision — e.g. a
    pad-1 bucket keeps its rows replicated while pad-4 rows ride ``data``).
    Returns None when nothing shards (skip the constraint entirely)."""
    parts: list = []
    any_sharded = False
    for dim, name in enumerate(logical):
        ax = LOGICAL_AXIS_RULES.get(name) if name is not None else None
        if ax is None:
            parts.append(None)
            continue
        size = int(mesh.shape.get(ax, 1))
        if size > 1 and int(shape[dim]) % size == 0:
            parts.append(ax)
            any_sharded = True
        else:
            parts.append(None)
    return P(*parts) if any_sharded else None


def constrain(x: Any, *logical: Optional[str]) -> Any:
    """with_sharding_constraint through the rule table (SNIPPETS [1]-[3]
    pattern): ``constrain(q, "batch", None, "heads", None)``.

    No-op unless :func:`serving_mesh` reports an engaged tensor axis, and
    per-dim no-op when the mesh axis wouldn't divide the dim.  Safe inside
    jit — all gates are trace-time (jit re-lowers when input shardings
    change, so a mesh coming up between calls is a fresh trace anyway)."""
    mesh = serving_mesh()
    if mesh is None:
        return x
    if len(logical) != x.ndim:
        raise ValueError(f"constrain got {len(logical)} logical axes for a "
                         f"rank-{x.ndim} array")
    spec = _resolve_constraint(mesh, x.shape, logical)
    if spec is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def constrain_rows(x: Any) -> Any:
    """Rows-on-``data``, everything else replicated — the canonical layout
    of a CB bucket batch, AND the replicate-before-concat workaround for
    **tp-concat-cpu-miscompile** (ROADMAP item 8): XLA's CPU SPMD partitioner
    miscompiles ``concatenate`` when one operand is tensor-sharded along the
    concat dim and the other replicated (both output halves wrong, upstream
    repro in tests/test_parallel.py).  Constraining both operands here forces
    the gather BEFORE the concat while keeping batch rows on ``data``."""
    mesh = serving_mesh()
    if mesh is None:
        return x
    spec = _resolve_constraint(mesh, x.shape,
                               (LOGICAL_BATCH,) + (None,) * (x.ndim - 1))
    if spec is None:
        spec = P()  # still dissolve any tensor sharding on the other dims
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def replicate(x: Any) -> Any:
    """Pin fully replicated (engaged mesh only) — the concat-dim firewall.
    with_sharding_constraint is a hard pin: consumer-side propagation
    cannot push a sharding back through it, which is exactly what the
    concat workarounds below need."""
    mesh = serving_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P()))


def stack_rows(parts: Sequence[Any], axis: int = 0) -> Any:
    """Concatenate along the batch/row dim WITHOUT sharding the concat dim
    (tp-concat-cpu-miscompile, ROADMAP item 8): XLA's CPU SPMD partitioner
    miscompiles a concatenate whose concat dim carries a mesh axis — on the
    CFG row-stack the operand seams land mid-shard after the reshuffle.
    Pin every operand AND the result replicated so neither operand layouts
    nor consumer back-propagation (e.g. an attention "batch" constraint
    downstream) can shard the concat itself.  Inert without an engaged
    tensor axis."""
    mesh = serving_mesh()
    if mesh is None:
        return jnp.concatenate(list(parts), axis=axis)
    return replicate(jnp.concatenate([replicate(p) for p in parts],
                                     axis=axis))


def unstack_rows(out: Any, reps: int) -> list:
    """split's dual of :func:`stack_rows`: gather the CFG-stacked model
    output before slicing it back into per-side blocks, so the split seams
    never cross a shard boundary."""
    return jnp.split(replicate(out), reps, axis=0)


def rows_sharding(mesh: Mesh, rows: int, ndim: int) -> NamedSharding:
    """Placement for a rows-leading array: dim 0 over ``data`` when the row
    count divides the axis, fully replicated otherwise (device_put — unlike
    with_sharding_constraint — refuses uneven shards, and pad-1 buckets on a
    data=2 mesh are legal)."""
    if int(mesh.shape.get(DATA_AXIS, 1)) > 1 \
            and rows % int(mesh.shape[DATA_AXIS]) == 0:
        return NamedSharding(mesh, batch_axis_spec(ndim))
    return NamedSharding(mesh, P())


def put_rows(x: Any, mesh: Mesh) -> Any:
    """Normalize a rows-leading array onto its canonical bucket layout.
    Also the chokepoint the CB executor uses after repads/writes so every
    steady-state step sees ONE input sharding per pad (anything else would
    re-lower the step executable and break the zero-retrace invariant)."""
    return jax.device_put(x, rows_sharding(mesh, int(x.shape[0]), x.ndim))


# --- parameter layout ---------------------------------------------------------

def param_spec(path: str, shape: tuple, tensor_size: int,
               min_elements: int = MIN_SHARD_ELEMENTS) -> P:
    """PartitionSpec for one parameter leaf.

    Megatron-style column parallelism by shape heuristic: shard the trailing
    (output-feature) dim of rank>=2 kernels over ``tensor`` when divisible;
    fall back to the second-to-last (input-feature) dim; replicate biases,
    norm scales, and anything too small to be worth the traffic.
    """
    if tensor_size <= 1 or len(shape) < 2:
        return P()
    n = 1
    for d in shape:
        n *= d
    if n < min_elements:
        return P()
    none_prefix = [None] * (len(shape) - 1)
    if shape[-1] % tensor_size == 0:
        return P(*none_prefix, TENSOR_AXIS)
    if shape[-2] % tensor_size == 0:
        return P(*none_prefix[:-1], TENSOR_AXIS, None)
    return P()


def param_sharding(mesh: Mesh, path: str, shape: tuple,
                   min_elements: int = MIN_SHARD_ELEMENTS) -> NamedSharding:
    """NamedSharding for one parameter leaf on ``mesh`` (the train-step and
    optimizer layout entry point)."""
    return NamedSharding(mesh, param_spec(
        path, shape, int(mesh.shape[TENSOR_AXIS]), min_elements))


def params_shardings(params: Any, mesh: Mesh,
                     min_elements: int = MIN_SHARD_ELEMENTS) -> Any:
    """NamedSharding tree matching ``params`` — tp over ``tensor``, replicated
    over ``data``/``seq`` (dp keeps full replicas, exactly the reference's
    every-worker-loads-the-checkpoint model, just within one program)."""
    tensor_size = mesh.shape[TENSOR_AXIS]

    def leaf(path, x):
        spec = param_spec(jax.tree_util.keystr(path), tuple(x.shape),
                          tensor_size, min_elements)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(leaf, params)


def batch_spec(ndim: int, seq_dim: Optional[int] = None) -> P:
    """Activation spec: dim 0 over ``data``; optionally one dim over ``seq``
    (for token axes — sequence parallelism)."""
    parts = [DATA_AXIS] + [None] * (ndim - 1)
    if seq_dim is not None and 0 < seq_dim < ndim:
        parts[seq_dim] = SEQ_AXIS
    return P(*parts)


def batch_shardings(tree: Any, mesh: Mesh, seq_dims: Optional[dict] = None) -> Any:
    """NamedSharding tree for a batch pytree (dict of arrays).  ``seq_dims``
    maps top-level key -> which dim is the token axis (sp)."""
    seq_dims = seq_dims or {}

    def leaf(path, x):
        key = path[0].key if path and hasattr(path[0], "key") else None
        return NamedSharding(mesh, batch_spec(x.ndim, seq_dims.get(key)))

    return jax.tree_util.tree_map_with_path(leaf, tree)


def apply_shardings(tree: Any, shardings: Any) -> Any:
    """device_put a pytree onto its sharding tree."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), tree, shardings)


def spec_of(x: Any) -> Optional[P]:
    """The PartitionSpec an array actually carries (None when it has no
    NamedSharding) — the bench/test probe for per-array spec assertions."""
    s = getattr(x, "sharding", None)
    return getattr(s, "spec", None)
