"""Sharding rules: how params and activations lay out over the mesh.

The reference has no model parallelism — every participant holds a full model
copy (its README requires identical checkpoints on all machines,
``/root/reference/README.md:189-193``).  On TPU, tensor parallelism is nearly
free to offer because it is *layout, not code*: we annotate parameter and
activation shardings with :class:`jax.sharding.NamedSharding` and GSPMD
inserts the collectives.  This module centralises those annotations:

- **dp** — batch dims over the ``data`` axis (the reference's worker axis);
- **tp** — weight matrices over the ``tensor`` axis (output-feature dim of
  large kernels; megatron-style column split, with XLA choosing the matching
  row splits/reductions);
- **sp** — token/sequence dims over the ``seq`` axis (context tensors and
  attention inputs; ring attention in :mod:`.ring` keeps the shards resident).

Rules are shape-driven rather than name-driven so they apply uniformly to any
flax param tree (UNet, CLIP, VAE) without per-module tables.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from comfyui_distributed_tpu.utils.constants import DATA_AXIS, SEQ_AXIS, TENSOR_AXIS

# Don't bother sharding tensors smaller than this many elements: the gather
# traffic would cost more than the HBM saved.
MIN_SHARD_ELEMENTS = 2 ** 11


def param_spec(path: str, shape: tuple, tensor_size: int,
               min_elements: int = MIN_SHARD_ELEMENTS) -> P:
    """PartitionSpec for one parameter leaf.

    Megatron-style column parallelism by shape heuristic: shard the trailing
    (output-feature) dim of rank>=2 kernels over ``tensor`` when divisible;
    fall back to the second-to-last (input-feature) dim; replicate biases,
    norm scales, and anything too small to be worth the traffic.
    """
    if tensor_size <= 1 or len(shape) < 2:
        return P()
    n = 1
    for d in shape:
        n *= d
    if n < min_elements:
        return P()
    none_prefix = [None] * (len(shape) - 1)
    if shape[-1] % tensor_size == 0:
        return P(*none_prefix, TENSOR_AXIS)
    if shape[-2] % tensor_size == 0:
        return P(*none_prefix[:-1], TENSOR_AXIS, None)
    return P()


def params_shardings(params: Any, mesh: Mesh,
                     min_elements: int = MIN_SHARD_ELEMENTS) -> Any:
    """NamedSharding tree matching ``params`` — tp over ``tensor``, replicated
    over ``data``/``seq`` (dp keeps full replicas, exactly the reference's
    every-worker-loads-the-checkpoint model, just within one program)."""
    tensor_size = mesh.shape[TENSOR_AXIS]

    def leaf(path, x):
        spec = param_spec(jax.tree_util.keystr(path), tuple(x.shape),
                          tensor_size, min_elements)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(leaf, params)


def batch_spec(ndim: int, seq_dim: Optional[int] = None) -> P:
    """Activation spec: dim 0 over ``data``; optionally one dim over ``seq``
    (for token axes — sequence parallelism)."""
    parts = [DATA_AXIS] + [None] * (ndim - 1)
    if seq_dim is not None and 0 < seq_dim < ndim:
        parts[seq_dim] = SEQ_AXIS
    return P(*parts)


def batch_shardings(tree: Any, mesh: Mesh, seq_dims: Optional[dict] = None) -> Any:
    """NamedSharding tree for a batch pytree (dict of arrays).  ``seq_dims``
    maps top-level key -> which dim is the token axis (sp)."""
    seq_dims = seq_dims or {}

    def leaf(path, x):
        key = path[0].key if path and hasattr(path[0], "key") else None
        return NamedSharding(mesh, batch_spec(x.ndim, seq_dims.get(key)))

    return jax.tree_util.tree_map_with_path(leaf, tree)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def apply_shardings(tree: Any, shardings: Any) -> Any:
    """device_put a pytree onto its sharding tree."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), tree, shardings)
