"""Sharded diffusion training step (dp + tp + sp over one mesh).

The reference is inference-only — there is no training code anywhere in
``/root/reference`` (SURVEY.md §2) — but a TPU framework whose model zoo is
native (rather than borrowed from ComfyUI) needs a way to produce and
fine-tune those weights.  This module is the canonical "full training step":
eps/v-prediction denoising MSE on the discrete VP schedule
(:mod:`comfyui_distributed_tpu.models.schedules`), optax optimizer, jitted
once over the whole mesh with explicit :class:`NamedSharding`s:

- batch dims over ``data`` (dp — the axis the reference fans workers over),
- weight matrices over ``tensor`` (tp, rules in :mod:`.sharding`),
- context token axis over ``seq`` (sp),

and GSPMD inserts the gradient ``psum``s / weight ``all_gather``s over ICI.
The same step compiles unchanged from 1 chip to a multi-host pod.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from comfyui_distributed_tpu.models.schedules import DiscreteSchedule
from comfyui_distributed_tpu.parallel import sharding as shd


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 1e-4
    weight_decay: float = 1e-2
    b1: float = 0.9
    b2: float = 0.999
    max_grad_norm: float = 1.0
    prediction_type: str = "eps"  # "eps" | "v"


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(cfg.max_grad_norm),
        optax.adamw(cfg.learning_rate, b1=cfg.b1, b2=cfg.b2,
                    weight_decay=cfg.weight_decay),
    )


def diffusion_loss(apply_fn: Callable, params: Any, batch: Dict[str, jax.Array],
                   key: jax.Array, ds: DiscreteSchedule,
                   prediction_type: str = "eps") -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Denoising MSE on the discrete VP forward process.

    ``x_t = sqrt(abar_t) x0 + sqrt(1-abar_t) eps`` with t ~ U[0, T); the UNet
    (which predicts eps in the model's native scaled space — the same
    convention the inference-side :mod:`..models.denoiser` inverts) is asked
    to recover ``eps`` (or ``v = sqrt(abar) eps - sqrt(1-abar) x0``).
    """
    x0 = batch["latents"].astype(jnp.float32)
    context = batch["context"]
    y = batch.get("y")
    B = x0.shape[0]
    T = len(ds.alphas_cumprod)
    abar = jnp.asarray(ds.alphas_cumprod)

    k_t, k_eps = jax.random.split(key)
    t = jax.random.randint(k_t, (B,), 0, T)
    eps = jax.random.normal(k_eps, x0.shape, dtype=jnp.float32)
    a = abar[t].reshape((B,) + (1,) * (x0.ndim - 1))
    x_t = jnp.sqrt(a) * x0 + jnp.sqrt(1.0 - a) * eps

    pred = apply_fn(params, x_t, t.astype(jnp.float32), context, y)
    pred = pred.astype(jnp.float32)
    if prediction_type == "v":
        target = jnp.sqrt(a) * eps - jnp.sqrt(1.0 - a) * x0
    else:
        target = eps
    loss = jnp.mean((pred - target) ** 2)
    return loss, {"loss": loss, "mean_t": jnp.mean(t.astype(jnp.float32))}


def make_train_step(apply_fn: Callable, ds: DiscreteSchedule,
                    cfg: Optional[TrainConfig] = None) -> Tuple[Callable, optax.GradientTransformation]:
    """Build the (un-jitted) train step + its optimizer.

    ``apply_fn(params, x, timesteps, context, y) -> eps_or_v`` is the raw
    UNet apply (same signature the inference denoiser wraps).
    Step signature: ``(params, opt_state, batch, key) ->
    (params, opt_state, metrics)``.
    """
    cfg = cfg or TrainConfig()
    tx = make_optimizer(cfg)

    def step(params, opt_state, batch, key):
        (loss, metrics), grads = jax.value_and_grad(
            lambda p: diffusion_loss(apply_fn, p, batch, key, ds,
                                     cfg.prediction_type),
            has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        return params, opt_state, metrics

    return step, tx


def shard_train_step(step: Callable, mesh: Mesh, params: Any, opt_state: Any,
                     batch: Dict[str, Any],
                     seq_dims: Optional[Dict[str, int]] = None,
                     min_shard_elements: int = shd.MIN_SHARD_ELEMENTS) -> Tuple[Callable, Any, Any, Dict[str, Any]]:
    """Jit ``step`` over ``mesh`` with dp/tp/sp shardings and place the state.

    Returns ``(jitted_step, params, opt_state, batch)`` with every argument
    already device_put onto its sharding so the first call doesn't pay a
    relayout.  ``seq_dims`` marks token axes for sp (default: dim 1 of
    ``context``).
    """
    seq_dims = {"context": 1} if seq_dims is None else seq_dims
    p_shard = shd.params_shardings(params, mesh, min_shard_elements)
    # optimizer state mirrors param leaves where shapes match; scalars
    # (step counters, clip state) replicate.
    def opt_leaf(x):
        if hasattr(x, "shape") and len(getattr(x, "shape", ())) >= 2:
            return shd.param_sharding(mesh, "", tuple(x.shape),
                                      min_shard_elements)
        return shd.replicated(mesh)
    o_shard = jax.tree_util.tree_map(opt_leaf, opt_state)
    b_shard = shd.batch_shardings(batch, mesh, seq_dims)
    k_shard = shd.replicated(mesh)

    jitted = jax.jit(step,
                     in_shardings=(p_shard, o_shard, b_shard, k_shard),
                     out_shardings=(p_shard, o_shard, None),
                     donate_argnums=(0, 1))
    params = shd.apply_shardings(params, p_shard)
    opt_state = shd.apply_shardings(opt_state, o_shard)
    batch = shd.apply_shardings(batch, b_shard)
    return jitted, params, opt_state, batch


def train_state_bytes(params: Any) -> int:
    return int(sum(np.prod(x.shape) * x.dtype.itemsize
                   for x in jax.tree_util.tree_leaves(params)))
