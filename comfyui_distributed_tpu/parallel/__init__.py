"""Mesh runtime, collectives and sharding rules.

This package replaces the reference's entire L1-L3 stack (worker process
manager + HTTP control/data planes, reference ``distributed.py:603-1218``)
with an in-program device mesh: participants are mesh slots, fan-out is batch
sharding, and gathering is an XLA collective over ICI.
"""

from comfyui_distributed_tpu.parallel.mesh import (  # noqa: F401
    MeshRuntime,
    build_mesh,
    describe_devices,
    get_runtime,
)
from comfyui_distributed_tpu.parallel.collectives import (  # noqa: F401
    replica_seeds,
    gather_batch,
    shard_batch,
)
from comfyui_distributed_tpu.parallel.sharding import (  # noqa: F401
    batch_shardings,
    params_shardings,
)

# parallel.train (optax optimizer stack) is imported lazily by callers —
# inference-only deployments shouldn't pay for or depend on it.
