"""Collective data plane.

TPU-native replacement for the reference's entire HTTP+PNG data plane
(SURVEY.md §2.4): scatter = batch sharding over the ``data`` mesh axis,
gather = XLA ``all_gather`` riding ICI, ordering = mesh axis order.  Tensors
never leave HBM; there is no serialization, no queue, no timeout-per-image.

Reference semantics preserved:
- seed fan-out: worker *i* samples with ``seed + i + 1``, master with ``seed``
  (``DistributedSeed.distribute``, reference ``distributed.py:1491-1514``) —
  here replica ``r`` uses ``seed + r`` with ``r = 0`` the master slot.
- collection order: master images first, then workers sorted by id
  (reference ``distributed.py:1424-1438``) — here simply the data-axis order.
"""

from __future__ import annotations

import inspect
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:                                    # JAX >= 0.6: top-level export
    from jax import shard_map as _shard_map_impl
except ImportError:                     # older JAX: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map_impl

from comfyui_distributed_tpu.parallel import sharding as shd
from comfyui_distributed_tpu.utils.constants import DATA_AXIS

# the replication-check kwarg was renamed check_rep -> check_vma across JAX
# versions; resolve the installed spelling once
_SHARD_MAP_REP_KW = next(
    (kw for kw in ("check_vma", "check_rep")
     if kw in inspect.signature(_shard_map_impl).parameters), None)


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``: one spelling for every call site
    (here, ``parallel/ring.py``, tests).  ``check_vma=False`` disables the
    static replication checker under whichever name the installed JAX
    uses (``check_vma``, formerly ``check_rep``)."""
    kwargs = {}
    if _SHARD_MAP_REP_KW is not None:
        kwargs[_SHARD_MAP_REP_KW] = check_vma
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **kwargs)


def replica_seeds(base_seed: int, num_replicas: int,
                  batch_per_replica: int = 1) -> np.ndarray:
    """Per-sample seed array for a fanned-out batch.

    Replica ``r`` (0 = master) uses ``base_seed + r`` for every image in its
    sub-batch — semantic parity with the reference's ``seed`` /
    ``seed + worker_index + 1`` split (``distributed.py:1505-1508``), where
    our ``r`` enumerates master (0) then workers (1..N).  Shape:
    ``[num_replicas * batch_per_replica]``, replica-major — i.e. exactly the
    master-first gather order of reference ``distributed.py:1424-1438``."""
    seeds = np.arange(num_replicas, dtype=np.uint64) + np.uint64(base_seed)
    return np.repeat(seeds, batch_per_replica)


def sample_keys(seeds: jnp.ndarray) -> jnp.ndarray:
    """Fold per-sample indices into per-replica seeds so each image in a
    replica's sub-batch gets an independent stream (canonical impl lives
    with the samplers)."""
    from comfyui_distributed_tpu.models.samplers import sample_keys as _sk
    return _sk(seeds)


def shard_batch(x: Any, mesh: Mesh, spec: Optional[P] = None) -> jax.Array:
    """Scatter: place a host array on the mesh, batch dim over ``data``.

    The analog of the reference's dispatch fan-out (POST the workflow to every
    worker, ``gpupanel.js:1313-1362``) — except no data moves per-participant;
    XLA lays each shard directly into its device's HBM."""
    spec = spec if spec is not None else shd.mesh_spec(DATA_AXIS)
    return shd.put_on_mesh(x, mesh, spec)


def gather_batch(x: jax.Array) -> np.ndarray:
    """Gather: fetch a (possibly sharded) array to host, preserving axis
    order — the analog of the reference's collector drain + ordered
    ``torch.cat`` (``distributed.py:1281-1459``), with ordering guaranteed by
    construction instead of by sorting worker ids.  This is a device->host
    EDGE and is counted as such (utils.trace)."""
    from comfyui_distributed_tpu.utils.trace import record_transfer
    arr = np.asarray(jax.device_get(x))
    record_transfer("d2h", arr.nbytes)
    return arr


def all_gather_data(x: jax.Array, mesh: Mesh) -> jax.Array:
    """In-program all-gather over the data axis: every participant ends up
    with the full batch (what the reference cannot do — its workers never see
    each other's results)."""
    def f(shard):
        return jax.lax.all_gather(shard, DATA_AXIS, axis=0, tiled=True)
    # check_vma=False: replication over the unused tensor/seq axes (size 1)
    # can't be statically inferred by shard_map's rep checker.
    return shard_map(f, mesh=mesh, in_specs=shd.mesh_spec(DATA_AXIS),
                     out_specs=shd.mesh_spec(), check_vma=False)(x)


def psum_data(x: jax.Array, mesh: Mesh) -> jax.Array:
    """Sum-reduce over the data axis (building block for overlap-add tile
    gathering and for gradient reduction in the train step)."""
    def f(shard):
        return jax.lax.psum(shard, DATA_AXIS)
    return shard_map(f, mesh=mesh, in_specs=shd.mesh_spec(DATA_AXIS),
                     out_specs=shd.mesh_spec(), check_vma=False)(x)


def pad_to_multiple(n: int, m: int) -> int:
    """Smallest multiple of ``m`` >= ``n`` — SPMD needs equal shards where the
    reference tolerated ragged per-worker tile counts via Python loops
    (``distributed_upscale.py:344-357``); we pad-and-mask instead."""
    return ((n + m - 1) // m) * m


def device_put_replicated(x: Any, mesh: Mesh) -> jax.Array:
    return shd.put_on_mesh(x, mesh, shd.mesh_spec())
