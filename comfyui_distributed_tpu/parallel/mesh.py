"""Mesh runtime: device/topology discovery and mesh construction.

TPU-native replacement for the reference's worker topology.  Where the
reference spawns one ComfyUI process per CUDA device and tracks them in
``gpu_config.json`` (``WorkerProcessManager``, reference
``distributed.py:603-1021``), a TPU slice exposes all local chips to one
process; "cluster membership" becomes the shape of a
:class:`jax.sharding.Mesh`.  The reference's *enabled workers* toggle maps to
``data_parallel_size`` — how many mesh slots participate in a fan-out run.

Axes (see ``utils/constants.py``):
    data    replica fan-out + tile scatter (reference's worker axis)
    tensor  intra-op model parallelism (no reference analog; TPU extension)
    seq     sequence/context parallelism for ring attention
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from comfyui_distributed_tpu.utils.constants import (
    DATA_AXIS, MESH_SHAPE_ENV, SEQ_AXIS, TENSOR_AXIS, TP_ENV)
from comfyui_distributed_tpu.utils.logging import debug_log, log

AXIS_ORDER = (DATA_AXIS, TENSOR_AXIS, SEQ_AXIS)


def force_cpu_platform(n_devices: int) -> int:
    """Pin JAX to ``n_devices`` virtual CPU devices WITHOUT ever probing the
    default backend.

    Calling ``jax.devices()`` first would initialize the default (TPU)
    backend, which can hang indefinitely inside ``make_c_api_client`` when
    the chip is held by another process (round-2 dryrun root cause,
    VERDICT.md).  Works even when sitecustomize imported jax at interpreter
    startup (env alone is frozen then — the live config update is the
    reliable switch) and when a CPU backend already initialized with a
    different device count (cleared first so the new count applies).

    Returns the virtual device count actually achieved.  On JAX builds
    without ``jax_num_cpu_devices`` the fallback is ``XLA_FLAGS``, which XLA
    parses ONCE per process at first client creation — ``clear_backends``
    does not re-parse it, so a process whose CPU client already froze a
    SMALLER count cannot honor a larger request in-process.  That used to
    silently proceed on the stale count (a 2-D mesh bench asking for 4
    devices would "succeed" with 1 and fail later at mesh build with a
    misleading divisibility error); now it raises RuntimeError naming the
    real cause.  Achieving MORE devices than requested is allowed — the
    test harness pre-freezes 8 and every smaller request still fits."""
    try:  # drop any backend a host process already initialized
        import jax.extend as jex
        jex.backend.clear_backends()
    except Exception:
        pass
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
        return n_devices
    except AttributeError:
        # older JAX: the option doesn't exist — the XLA flag (read at
        # client creation, i.e. after the clear_backends above) is the
        # portable spelling
        import re
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       os.environ.get("XLA_FLAGS", "")).strip()
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
            .strip())
    # verify the flag actually took: this touches ONLY the cpu backend we
    # just pinned, and freezes the flag we just set (a feature — nothing
    # can sneak a different count in before first real use)
    achieved = len(jax.devices("cpu"))
    if achieved < n_devices:
        raise RuntimeError(
            f"force_cpu_platform({n_devices}) got {achieved} device(s): "
            f"XLA parsed --xla_force_host_platform_device_count at this "
            f"process's first client creation and won't re-read it; "
            f"request the count before any backend init (or from a fresh "
            f"subprocess, as bench.py phase runners do)")
    return achieved


_PROBE_SRC = r"""
import json, sys
import jax
ds = jax.devices()
print(json.dumps({
    "platform": ds[0].platform,
    "kind": getattr(ds[0], "device_kind", "?"),
    "count": len(ds),
}))
"""


def probe_platform_config(platforms: Optional[str], timeout: float):
    """Initialize a backend in a THROWAWAY subprocess with a hard timeout
    — a wedged TPU client kills the child, never this process.

    ``platforms``: value for ``JAX_PLATFORMS`` in the child (``None`` =
    inherit this process's env; ``""`` = unset, let JAX choose).
    Returns ``(ok, info)``: info is the device summary dict on success,
    an error string otherwise."""
    import subprocess
    import sys as _sys
    env = dict(os.environ)
    if platforms is not None:
        if platforms == "":
            env.pop("JAX_PLATFORMS", None)
        else:
            env["JAX_PLATFORMS"] = platforms
    try:
        r = subprocess.run([_sys.executable, "-c", _PROBE_SRC], env=env,
                           capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return False, f"probe hung >{timeout:.0f}s (TPU client wedged?)"
    if r.returncode != 0:
        return False, f"probe rc={r.returncode}: {r.stderr.strip()[-800:]}"
    try:
        import json as _json
        return True, _json.loads(r.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return False, f"probe output unparseable: {r.stdout[-200:]!r}"


def _apply_platforms(value: Optional[str]) -> None:
    """Make the winning probe config this process's config — BEFORE the
    first in-process backend touch."""
    if value is None:
        return  # inherited env config: nothing to change
    if value == "":
        os.environ.pop("JAX_PLATFORMS", None)
        jax.config.update("jax_platforms", None)
    else:
        os.environ["JAX_PLATFORMS"] = value
        jax.config.update("jax_platforms", value)


_backend_checked = False


def claim_window_s() -> float:
    """The server-side accelerator claim window (seconds): a client
    killed INSIDE this window re-wedges the lease.  One source of truth
    for the ladder, bench probe sizing, and the recovery loop
    (override: ``DTPU_CLAIM_WINDOW_S``)."""
    return float(os.environ.get("DTPU_CLAIM_WINDOW_S", "1560"))


def ensure_usable_backend(patience_s: Optional[float] = None,
                          probe_timeout: Optional[float] = None,
                          allow_cpu_fallback: bool = True,
                          force: bool = False,
                          _probe=probe_platform_config) -> Dict[str, Any]:
    """Escape ladder for a wedged accelerator client (rounds 1-3: the TPU
    client can hang indefinitely inside backend init when the chip is held
    or the PJRT server is wedged — ``jax.devices()`` in serve/bench then
    hangs the process).

    Ladder, within a bounded ``patience_s`` budget and escalating sleeps
    (the server-side wedge can outlive short retry bursts):

    1. the env-given config (e.g. ``JAX_PLATFORMS=axon``), retried;
    2. on repeated hangs, alternates: ``""`` (let JAX choose) and
       ``"tpu"`` (direct PJRT), each probed in a throwaway subprocess;
    3. optionally ``cpu`` — guaranteed, loud, last resort (serve path:
       a master that hangs on startup is worse than a CPU master).

    The first config whose probe initializes is applied to THIS process.
    Returns a structured report (every rung's result) for logs/artifacts.
    No-ops once per process unless ``force`` (tests force CPU anyway —
    probing would add a subprocess round-trip to every suite run)."""
    global _backend_checked
    report: Dict[str, Any] = {"attempts": [], "ok": True, "config": "env",
                              "fell_back": False, "skipped": False}
    if _backend_checked and not force:
        report.update(skipped=True)
        return report
    _backend_checked = True
    if os.environ.get("DTPU_SKIP_BACKEND_PROBE"):
        # latency escape hatch for one-shot CLI calls on known-healthy
        # machines: the subprocess probe costs a few seconds of jax import
        report.update(skipped=True, config="unprobed")
        return report
    if (os.environ.get("JAX_PLATFORMS") or "").strip().lower() == "cpu":
        # CPU cannot wedge — but pin the LIVE config as well: a
        # sitecustomize-registered accelerator plugin is still probed by
        # jax.devices() when only the env says cpu (observed: /status on
        # a cpu-env serve hung in the axon plugin's init)
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        report.update(skipped=True, config="cpu")
        return report
    patience_s = float(patience_s if patience_s is not None
                       else os.environ.get("DTPU_INIT_PATIENCE_S", "180"))
    probe_timeout = float(probe_timeout if probe_timeout is not None
                          else os.environ.get("DTPU_INIT_PROBE_TIMEOUT_S",
                                              "60"))
    env_cfg = os.environ.get("JAX_PLATFORMS")
    alternates = [("auto", ""), ("tpu", "tpu")]
    # dedup: an env of '' or 'tpu' already IS that rung
    alternates = [(lbl, v) for lbl, v in alternates if v != (env_cfg or "")]

    # Mid-claim-kill policy (one rule, both rungs): a probe that HUNG was
    # likely inside the server-side claim window (~25 min) — SIGKILLing a
    # client mid-claim re-wedges the lease, so a config that hung is never
    # re-probed unless the remaining budget lets the retry resolve
    # NATURALLY (devices or UNAVAILABLE).  Configs that failed FAST exited
    # on their own (no kill happened) and stay freely retryable — the
    # chip may recover between rounds.  Each alternate gets ONE shot
    # regardless (a different path either comes up fast or tells us
    # nothing more; its first kill is the price of the escape attempt).
    claim_window = claim_window_s()
    hung: Dict[str, bool] = {}

    def _eligible_at(key: str, remaining: float) -> bool:
        if remaining <= 0:
            return False
        return not hung.get(key) or remaining >= claim_window

    def _eligible(key: str) -> bool:
        return _eligible_at(key, deadline - time.monotonic())

    def _probe_once(key, platforms, label_extra=""):
        remaining = deadline - time.monotonic()
        t = min(remaining, probe_timeout if not hung.get(key)
                else max(probe_timeout, claim_window))
        t0 = time.monotonic()
        ok, info = _probe(platforms, max(t, 10.0))
        entry = {"config": key, "ok": ok,
                 "elapsed_s": round(time.monotonic() - t0, 1),
                 "info": info if ok else str(info)}
        if label_extra:
            entry.update(label_extra)
        report["attempts"].append(entry)
        if not ok and str(info).startswith("probe hung"):
            hung[key] = True
        else:
            # resolved NATURALLY (ok, or a clean error like UNAVAILABLE):
            # no kill happened, the lease wasn't poisoned — the config is
            # freely retryable again (a hung-once config whose full-window
            # retry failed clean must not stay gated for the rest of the
            # budget)
            hung.pop(key, None)
        return ok, info

    deadline = time.monotonic() + patience_s
    sleep_s, attempt = 60.0, 0
    while True:
        attempt += 1
        if _eligible("env"):
            ok, info = _probe_once("env", None, {"attempt": attempt})
            if ok and info.get("platform") != "cpu":
                log(f"backend probe ok (env config, attempt {attempt}): "
                    f"{info}")
                return report
            if ok:
                # the env config initialized CPU-ONLY — the accelerator
                # client crashed fast and jax fell back (the round-1/2
                # flake's other face).  Never publish that as an
                # accelerator success: with fallback allowed take CPU
                # now, loudly (a genuinely CPU-only box must not wait out
                # the full patience); for bench (no-fallback) keep
                # laddering — the chip may come back
                log(f"backend probe initialized CPU ONLY (env config, "
                    f"attempt {attempt}): {info}")
                if allow_cpu_fallback:
                    force_cpu_platform(int(os.environ.get(
                        "DTPU_CPU_FALLBACK_DEVICES", "1")))
                    report.update(ok=True, config="cpu", fell_back=True)
                    return report
            else:
                log(f"backend probe failed (env config, attempt "
                    f"{attempt}): {info}")
        # a hang (vs a clean error) suggests the wedge: try the
        # alternates — a different plugin path may come up even while
        # the env one is stuck
        for lbl, val in alternates:
            if not _eligible(lbl):
                continue
            ok, info = _probe_once(lbl, val)
            if ok and info.get("platform") != "cpu":
                # a CPU-only success here is NOT an escape — it means the
                # alternate config just dodged the accelerator entirely;
                # only take it via the explicit fallback below
                log(f"backend escape: JAX_PLATFORMS={val!r} initialized "
                    f"({info}) while the env config is wedged")
                _apply_platforms(val)
                report.update(config=lbl)
                return report
        # sleep only if some config will still be eligible afterwards —
        # otherwise the rest of the budget buys nothing
        keys = ["env"] + [lbl for lbl, _ in alternates]
        after = deadline - (time.monotonic() + sleep_s)
        if after < 10 or not any(_eligible_at(k, after) for k in keys):
            break
        log(f"all configs down; sleeping {sleep_s:.0f}s "
            f"(wedge windows outlive short bursts)")
        time.sleep(sleep_s)
        sleep_s = min(sleep_s * 2, 300.0)
    if allow_cpu_fallback:
        log("backend UNUSABLE after full escape ladder — falling back to "
            "CPU so the control plane stays up (compute will be slow; "
            "restart once the accelerator recovers)")
        force_cpu_platform(int(os.environ.get("DTPU_CPU_FALLBACK_DEVICES",
                                              "1")))
        report.update(ok=True, config="cpu", fell_back=True)
        return report
    report.update(ok=False, config=None)
    return report


def describe_devices(devices: Optional[Sequence[jax.Device]] = None) -> Dict[str, Any]:
    """Topology discovery — the TPU analog of the reference's worker/CUDA
    enumeration (``CUDA_VISIBLE_DEVICES`` handling, reference
    ``distributed.py:672-677``).  Reports platform, counts, per-device
    metadata and multi-host process info."""
    devices = list(devices) if devices is not None else jax.devices()
    descr: List[Dict[str, Any]] = []
    for d in devices:
        entry: Dict[str, Any] = {
            "id": d.id,
            "platform": d.platform,
            "kind": getattr(d, "device_kind", "unknown"),
            "process_index": d.process_index,
        }
        coords = getattr(d, "coords", None)
        if coords is not None:
            entry["coords"] = tuple(coords)
        descr.append(entry)
    return {
        "platform": devices[0].platform if devices else "none",
        "num_devices": len(devices),
        "num_local_devices": jax.local_device_count(),
        "num_processes": jax.process_count(),
        "process_index": jax.process_index(),
        "devices": descr,
    }


def _resolve_axes(axes: Dict[str, int], n_devices: int) -> Dict[str, int]:
    """Resolve -1 ("fill with remaining devices") and validate the product."""
    resolved = {name: int(axes.get(name, 1)) for name in AXIS_ORDER}
    fills = [n for n, v in resolved.items() if v == -1]
    if len(fills) > 1:
        raise ValueError(f"only one axis may be -1, got {fills}")
    fixed = math.prod(v for v in resolved.values() if v != -1)
    if fills:
        if n_devices % fixed != 0:
            raise ValueError(
                f"fixed axes product {fixed} does not divide {n_devices} devices")
        resolved[fills[0]] = n_devices // fixed
    total = math.prod(resolved.values())
    if total != n_devices:
        raise ValueError(
            f"mesh axes {resolved} use {total} devices, have {n_devices}")
    return resolved


def _axis_size(raw: str, where: str) -> int:
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"{where}: axis size must be an integer (or -1 to fill), "
            f"got {raw.strip()!r}") from None


def axes_from_env() -> Optional[Dict[str, int]]:
    """Mesh shape from the serve-path environment (ISSUE 16).

    ``DTPU_MESH_SHAPE`` — full layout, either ``data=2,tensor=2`` pairs or
    positional ``2x2x1`` in AXIS_ORDER (data, tensor, seq); ``-1`` fills.
    ``DTPU_TP`` — shorthand: tensor-axis size, data fills the rest.  Returns
    None when neither is set, so every existing caller keeps the pure
    data-parallel default."""
    shape = os.environ.get(MESH_SHAPE_ENV, "").strip()
    if shape:
        axes: Dict[str, int] = {}
        if "=" in shape:
            for part in shape.split(","):
                part = part.strip()
                if not part:
                    continue
                name, _, val = part.partition("=")
                name = name.strip()
                if name not in AXIS_ORDER:
                    raise ValueError(
                        f"{MESH_SHAPE_ENV}: unknown axis {name!r} "
                        f"(axes: {AXIS_ORDER})")
                axes[name] = _axis_size(val, f"{MESH_SHAPE_ENV} axis {name}")
        else:
            sizes = [_axis_size(v, MESH_SHAPE_ENV)
                     for v in shape.replace("x", ",").split(",")
                     if v.strip()]
            if len(sizes) > len(AXIS_ORDER):
                raise ValueError(
                    f"{MESH_SHAPE_ENV}: {len(sizes)} sizes for "
                    f"{len(AXIS_ORDER)} axes {AXIS_ORDER}")
            axes = dict(zip(AXIS_ORDER, sizes))
        return axes
    tp = os.environ.get(TP_ENV, "").strip()
    if tp and _axis_size(tp, TP_ENV) > 1:
        return {TENSOR_AXIS: _axis_size(tp, TP_ENV), DATA_AXIS: -1}
    return None


def build_mesh(axes: Optional[Dict[str, int]] = None,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Construct a named mesh over the available devices.

    ``axes`` maps axis name -> size; ``-1`` means "all remaining devices"
    (default: ``DTPU_MESH_SHAPE``/``DTPU_TP`` from the environment when set
    — the serve path's 2-D data×tensor switch — else everything on the data
    axis, mirroring the reference's pure data-parallel fan-out)."""
    devices = list(devices) if devices is not None else jax.devices()
    axes = dict(axes if axes is not None else (axes_from_env() or {}))
    axes.setdefault(DATA_AXIS, -1)
    resolved = _resolve_axes(axes, len(devices))
    shape = tuple(resolved[name] for name in AXIS_ORDER)
    arr = np.asarray(devices).reshape(shape)
    debug_log(f"mesh axes={resolved} over {len(devices)} "
              f"{devices[0].platform} device(s)")
    return Mesh(arr, AXIS_ORDER)


@dataclasses.dataclass
class MeshRuntime:
    """The live cluster object: mesh + participation state.

    Capability parity with the reference's notion of "enabled workers"
    (cluster membership lives in UI checkboxes, reference
    ``gpupanel.js:110-116``): here membership is ``num_participants`` — how
    many data-axis slots a fan-out run uses.  Slot 0 is the master
    (ordering parity with reference ``distributed.py:1424-1438``)."""

    mesh: Mesh
    enabled: bool = True

    @property
    def data_size(self) -> int:
        return self.mesh.shape[DATA_AXIS]

    @property
    def num_participants(self) -> int:
        return self.data_size if self.enabled else 1

    def data_sharding(self, spec: Optional[P] = None) -> NamedSharding:
        """Sharding with the leading (batch) dim over the data axis."""
        from comfyui_distributed_tpu.parallel import sharding as shd
        return shd.named(self.mesh,
                         spec if spec is not None else shd.mesh_spec(DATA_AXIS))

    def replicated(self) -> NamedSharding:
        from comfyui_distributed_tpu.parallel import sharding as shd
        return shd.replicated(self.mesh)

    def status(self) -> Dict[str, Any]:
        """Cluster status payload (feeds the control plane's /status route —
        the analog of the reference's 2 s browser poll, ``gpupanel.js:1233``)."""
        topo = describe_devices(list(self.mesh.devices.flat))
        return {
            "enabled": self.enabled,
            "axes": {k: int(v) for k, v in self.mesh.shape.items()},
            "num_participants": self.num_participants,
            **topo,
        }


_runtime: Optional[MeshRuntime] = None
_runtime_lock = threading.Lock()
# True once the TP cache guard below has fired; the disable is STICKY
# for the remainder of the process.
_cc_disabled = False


def _tp_compile_cache_guard(rt: Optional[MeshRuntime]) -> None:
    """XLA CPU cannot round-trip this repo's tensor-parallel serving
    executables through the persistent compilation cache: a cached
    donated SPMD step deserializes into an executable that returns
    garbage rows (observed latents ~1e10) and corrupts the heap (later
    unrelated device_puts segfault).  Fresh compilation of the very
    same HLO is fine — only the serialize/deserialize path is broken
    (jaxlib 0.4.37) — so the first time a tensor>1 serving mesh goes
    live on the cpu backend the cache is switched off FOR THE REST OF
    THE PROCESS.  The disable is deliberately sticky: re-enabling after
    the mesh clears and then loading cached entries reproducibly aborts
    with glibc heap-corruption (even for replicated programs), so a
    process that has ever run the TP serve path never touches the cache
    again.  TPU backends are unaffected.  Callers hold _runtime_lock."""
    global _cc_disabled
    tp_cpu = (rt is not None
              and int(rt.mesh.shape.get(TENSOR_AXIS, 1)) > 1
              and rt.mesh.devices.flat[0].platform == "cpu")
    if tp_cpu and not _cc_disabled:
        _cc_disabled = True
        if bool(jax.config.jax_enable_compilation_cache):
            jax.config.update("jax_enable_compilation_cache", False)
            log("tp: persistent compilation cache disabled for the rest "
                "of this process — a tensor-parallel mesh went live on "
                "cpu (cached sharded executables deserialize corrupt)")


def get_runtime(axes: Optional[Dict[str, int]] = None,
                refresh: bool = False) -> MeshRuntime:
    """Process-wide mesh runtime singleton (the analog of the reference's
    ``WorkerProcessManager`` singleton, ``distributed.py:1021``).

    Passing ``axes`` that conflict with an existing runtime's mesh raises —
    silently returning a differently-shaped mesh would let sharded programs
    run on the wrong topology; use ``refresh=True`` to rebuild."""
    global _runtime
    with _runtime_lock:
        if _runtime is None or refresh:
            _runtime = MeshRuntime(mesh=build_mesh(axes))
            _tp_compile_cache_guard(_runtime)
        elif axes is not None:
            requested = dict(axes)
            requested.setdefault(DATA_AXIS, -1)  # same default build_mesh uses
            want = _resolve_axes(requested, len(list(_runtime.mesh.devices.flat)))
            have = {k: int(v) for k, v in _runtime.mesh.shape.items()}
            if want != have:
                raise ValueError(
                    f"mesh runtime already built with axes {have}, "
                    f"requested {want}; pass refresh=True to rebuild")
        return _runtime


def get_live_runtime() -> Optional[MeshRuntime]:
    """The runtime singleton IF one was set/built — never builds one.
    Hot serving paths use this to ask "is a mesh live?" without paying
    for (or side-effecting) a default mesh construction."""
    with _runtime_lock:
        return _runtime


def set_runtime(rt: Optional[MeshRuntime]) -> None:
    global _runtime
    with _runtime_lock:
        _runtime = rt
        _tp_compile_cache_guard(rt)


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Multi-host (pod) initialization over DCN — the analog of the
    reference's *remote workers* (``README.md:169-202``), but via
    ``jax.distributed`` instead of HTTP dispatch.  No-op when single-host
    env vars are absent and no arguments are given."""
    if coordinator_address is None:
        coordinator_address = os.environ.get("DTPU_COORDINATOR")
    if coordinator_address is None:
        return
    num_processes = num_processes or int(os.environ.get("DTPU_NUM_PROCESSES", "1"))
    process_id = process_id if process_id is not None else int(
        os.environ.get("DTPU_PROCESS_ID", "0"))
    log(f"initializing multihost: coordinator={coordinator_address} "
        f"procs={num_processes} id={process_id}")
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
