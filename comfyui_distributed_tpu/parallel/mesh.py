"""Mesh runtime: device/topology discovery and mesh construction.

TPU-native replacement for the reference's worker topology.  Where the
reference spawns one ComfyUI process per CUDA device and tracks them in
``gpu_config.json`` (``WorkerProcessManager``, reference
``distributed.py:603-1021``), a TPU slice exposes all local chips to one
process; "cluster membership" becomes the shape of a
:class:`jax.sharding.Mesh`.  The reference's *enabled workers* toggle maps to
``data_parallel_size`` — how many mesh slots participate in a fan-out run.

Axes (see ``utils/constants.py``):
    data    replica fan-out + tile scatter (reference's worker axis)
    tensor  intra-op model parallelism (no reference analog; TPU extension)
    seq     sequence/context parallelism for ring attention
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from comfyui_distributed_tpu.utils.constants import DATA_AXIS, SEQ_AXIS, TENSOR_AXIS
from comfyui_distributed_tpu.utils.logging import debug_log, log

AXIS_ORDER = (DATA_AXIS, TENSOR_AXIS, SEQ_AXIS)


def force_cpu_platform(n_devices: int) -> None:
    """Pin JAX to ``n_devices`` virtual CPU devices WITHOUT ever probing the
    default backend.

    Calling ``jax.devices()`` first would initialize the default (TPU)
    backend, which can hang indefinitely inside ``make_c_api_client`` when
    the chip is held by another process (round-2 dryrun root cause,
    VERDICT.md).  Works even when sitecustomize imported jax at interpreter
    startup (env alone is frozen then — the live config update is the
    reliable switch) and when a CPU backend already initialized with a
    different device count (cleared first so the new count applies)."""
    try:  # drop any backend a host process already initialized
        import jax.extend as jex
        jex.backend.clear_backends()
    except Exception:
        pass
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n_devices)


def describe_devices(devices: Optional[Sequence[jax.Device]] = None) -> Dict[str, Any]:
    """Topology discovery — the TPU analog of the reference's worker/CUDA
    enumeration (``CUDA_VISIBLE_DEVICES`` handling, reference
    ``distributed.py:672-677``).  Reports platform, counts, per-device
    metadata and multi-host process info."""
    devices = list(devices) if devices is not None else jax.devices()
    descr: List[Dict[str, Any]] = []
    for d in devices:
        entry: Dict[str, Any] = {
            "id": d.id,
            "platform": d.platform,
            "kind": getattr(d, "device_kind", "unknown"),
            "process_index": d.process_index,
        }
        coords = getattr(d, "coords", None)
        if coords is not None:
            entry["coords"] = tuple(coords)
        descr.append(entry)
    return {
        "platform": devices[0].platform if devices else "none",
        "num_devices": len(devices),
        "num_local_devices": jax.local_device_count(),
        "num_processes": jax.process_count(),
        "process_index": jax.process_index(),
        "devices": descr,
    }


def _resolve_axes(axes: Dict[str, int], n_devices: int) -> Dict[str, int]:
    """Resolve -1 ("fill with remaining devices") and validate the product."""
    resolved = {name: int(axes.get(name, 1)) for name in AXIS_ORDER}
    fills = [n for n, v in resolved.items() if v == -1]
    if len(fills) > 1:
        raise ValueError(f"only one axis may be -1, got {fills}")
    fixed = math.prod(v for v in resolved.values() if v != -1)
    if fills:
        if n_devices % fixed != 0:
            raise ValueError(
                f"fixed axes product {fixed} does not divide {n_devices} devices")
        resolved[fills[0]] = n_devices // fixed
    total = math.prod(resolved.values())
    if total != n_devices:
        raise ValueError(
            f"mesh axes {resolved} use {total} devices, have {n_devices}")
    return resolved


def build_mesh(axes: Optional[Dict[str, int]] = None,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Construct a named mesh over the available devices.

    ``axes`` maps axis name -> size; ``-1`` means "all remaining devices"
    (default: everything on the data axis, mirroring the reference's pure
    data-parallel fan-out)."""
    devices = list(devices) if devices is not None else jax.devices()
    axes = dict(axes or {})
    axes.setdefault(DATA_AXIS, -1)
    resolved = _resolve_axes(axes, len(devices))
    shape = tuple(resolved[name] for name in AXIS_ORDER)
    arr = np.asarray(devices).reshape(shape)
    debug_log(f"mesh axes={resolved} over {len(devices)} "
              f"{devices[0].platform} device(s)")
    return Mesh(arr, AXIS_ORDER)


@dataclasses.dataclass
class MeshRuntime:
    """The live cluster object: mesh + participation state.

    Capability parity with the reference's notion of "enabled workers"
    (cluster membership lives in UI checkboxes, reference
    ``gpupanel.js:110-116``): here membership is ``num_participants`` — how
    many data-axis slots a fan-out run uses.  Slot 0 is the master
    (ordering parity with reference ``distributed.py:1424-1438``)."""

    mesh: Mesh
    enabled: bool = True

    @property
    def data_size(self) -> int:
        return self.mesh.shape[DATA_AXIS]

    @property
    def num_participants(self) -> int:
        return self.data_size if self.enabled else 1

    def data_sharding(self, spec: Optional[P] = None) -> NamedSharding:
        """Sharding with the leading (batch) dim over the data axis."""
        return NamedSharding(self.mesh, spec if spec is not None else P(DATA_AXIS))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def status(self) -> Dict[str, Any]:
        """Cluster status payload (feeds the control plane's /status route —
        the analog of the reference's 2 s browser poll, ``gpupanel.js:1233``)."""
        topo = describe_devices(list(self.mesh.devices.flat))
        return {
            "enabled": self.enabled,
            "axes": {k: int(v) for k, v in self.mesh.shape.items()},
            "num_participants": self.num_participants,
            **topo,
        }


_runtime: Optional[MeshRuntime] = None
_runtime_lock = threading.Lock()


def get_runtime(axes: Optional[Dict[str, int]] = None,
                refresh: bool = False) -> MeshRuntime:
    """Process-wide mesh runtime singleton (the analog of the reference's
    ``WorkerProcessManager`` singleton, ``distributed.py:1021``).

    Passing ``axes`` that conflict with an existing runtime's mesh raises —
    silently returning a differently-shaped mesh would let sharded programs
    run on the wrong topology; use ``refresh=True`` to rebuild."""
    global _runtime
    with _runtime_lock:
        if _runtime is None or refresh:
            _runtime = MeshRuntime(mesh=build_mesh(axes))
        elif axes is not None:
            requested = dict(axes)
            requested.setdefault(DATA_AXIS, -1)  # same default build_mesh uses
            want = _resolve_axes(requested, len(list(_runtime.mesh.devices.flat)))
            have = {k: int(v) for k, v in _runtime.mesh.shape.items()}
            if want != have:
                raise ValueError(
                    f"mesh runtime already built with axes {have}, "
                    f"requested {want}; pass refresh=True to rebuild")
        return _runtime


def set_runtime(rt: Optional[MeshRuntime]) -> None:
    global _runtime
    with _runtime_lock:
        _runtime = rt


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Multi-host (pod) initialization over DCN — the analog of the
    reference's *remote workers* (``README.md:169-202``), but via
    ``jax.distributed`` instead of HTTP dispatch.  No-op when single-host
    env vars are absent and no arguments are given."""
    if coordinator_address is None:
        coordinator_address = os.environ.get("DTPU_COORDINATOR")
    if coordinator_address is None:
        return
    num_processes = num_processes or int(os.environ.get("DTPU_NUM_PROCESSES", "1"))
    process_id = process_id if process_id is not None else int(
        os.environ.get("DTPU_PROCESS_ID", "0"))
    log(f"initializing multihost: coordinator={coordinator_address} "
        f"procs={num_processes} id={process_id}")
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
